#!/usr/bin/env bash
# CI gate for at2_node_tpu — the single-command equivalent of the
# reference's workflow (/root/reference/.github/workflows/rust.yml:9-41:
# check + clippy -D warnings + full test matrix).
#
# Tiers:
#   lint    - syntax/import sanity (ruff when available, else compileall)
#   fast    - unit + integration + e2e tests, minutes  (pytest -m 'not slow')
#   kernel  - differential/interpreter kernel tier      (pytest -m slow)
#
# Usage: scripts/ci.sh [fast|all]   (default: all)

set -euo pipefail
cd "$(dirname "$0")/.."

tier="${1:-all}"

echo "== lint =="
if command -v ruff >/dev/null 2>&1; then
  ruff check at2_node_tpu tests bench.py __graft_entry__.py
else
  # ruff is not in this image: fall back to a compile pass (catches syntax
  # errors and nothing else; keep ruff in real CI)
  python -m compileall -q at2_node_tpu tests bench.py __graft_entry__.py
fi

echo "== native library =="
python - <<'EOF'
from at2_node_tpu.native import native_available
print("native prep library:", "available" if native_available() else
      "UNAVAILABLE (python fallback will be used)")
EOF

echo "== fast tier =="
python -m pytest tests/ -q -m "not slow"

echo "== pipeline smoke gate =="
# Dispatch-pipeline regression (ISSUE 2): 4 overlapped batches on the
# fake device must report sane stats counters (batches, occupancy, zero
# leaked capacity), plus the overlap/backpressure/close-race invariants.
# Fake-device only (no XLA compile), so the gate stays in the fast tier;
# named explicitly so a marker/collection change can never drop it.
python -m pytest tests/test_pipeline.py -q -m "not slow"

echo "== metrics-endpoints smoke gate =="
# Observability regression (ISSUE 3): the registry must stay exact under
# thread + asyncio concurrency, and a live node must serve /metrics,
# /healthz, /statusz (valid Prometheus exposition + JSON) through the
# real PortMux on its public RPC port. Named explicitly so a marker/
# collection change can never drop the endpoints from CI.
python -m pytest tests/test_obs.py -q

echo "== poison-slot chaos gate =="
# Byzantine amplification regression (ISSUE 1): a bad-sig entry per
# ingress batch must not stall slots, fire stall kicks, or trigger
# catchup. Named explicitly so a marker/collection change in the fast
# tier can never silently drop it.
python -m pytest tests/test_faults.py::TestPoisonChaos \
    tests/test_poison_resolution.py -q

echo "== sim determinism gate =="
# Deterministic simulation (ISSUE 4): the same seed must reproduce the
# same campaign hash (sha256 over per-episode wire-trace hashes),
# byte-identical, run to run. PYTHONHASHSEED is pinned because set
# iteration order feeds the schedule.
export PYTHONHASHSEED=0
sim_hash() {
  python -m at2_node_tpu.tools.sim_run --seed 7 --episodes 3 --quiet \
    | sed -n 's/.*hash \([0-9a-f]*\).*/\1/p'
}
h1="$(sim_hash)"
h2="$(sim_hash)"
if [ -z "$h1" ] || [ "$h1" != "$h2" ]; then
  echo "sim determinism gate FAILED: '$h1' != '$h2'" >&2
  exit 1
fi
echo "same-seed campaign hash reproduced: $h1"

echo "== flight-recorder capture gate =="
# Fleet tracing + flight recorder (ISSUE 6): a planted safety breach
# (thresholds below the quorum-intersection bound + a split vote) must
# fail invariants AND the failure artifact must carry its black box —
# one flight-recorder dump per node with events, plus the stitched
# cross-node timeline of the offending tx with straggler attribution.
python - <<'EOF'
from at2_node_tpu.sim.campaign import planted_breach_episode

r = planted_breach_episode(20260805)
assert r.violations, "planted breach must violate invariants"
obs = r.obs
assert obs is not None, "failing episode must attach obs artifact"
recs = obs["recorders"]
assert len(recs) == 4, f"want 4 recorder dumps, got {len(recs)}"
for dump in recs:
    assert dump["recorder"]["events"], (
        f"node {dump['node']}: empty flight-recorder ring"
    )
offending = [tx for tx in obs["stitched"]["txs"] if tx["seq"] == 1]
assert offending, "stitched timeline must contain the offending tx"
assert offending[0]["nodes"] >= 2, "timeline must span multiple nodes"
assert offending[0]["stragglers"], "straggler attribution missing"
print(
    "breach artifact ok: 4 recorder dumps, offending tx stitched across"
    f" {offending[0]['nodes']} nodes"
)
EOF

echo "== sim invariant campaign (50 episodes) =="
# Seeded adversarial campaign on the simulated fabric: 50 episodes of
# the real 4-node f=1 stack under loss, partitions, equivocation, and
# hostile frames — every AT2 invariant (agreement, sieve consistency,
# totality, conservation) checked per episode. Exit nonzero on any
# violation; the printed episode seed is the exact replay recipe.
python -m at2_node_tpu.tools.sim_run --seed 1 --episodes 50 --quiet

echo "== broker roundtrip smoke gate =="
# Broker ingress tier (ISSUE 7): codec roundtrip + native parity, the
# distilled ingress path on the sim fabric (commit/dedup/miss), and a
# real-gRPC broker roundtrip (register + collect + distill + commit +
# directory gossip). Named explicitly so a marker/collection change can
# never drop the broker path from CI.
python -m pytest tests/test_distill.py -q -m "not slow"

echo "== byzantine-broker campaign =="
# Corrupting-collector campaign (ISSUE 7): distilled-frame ingress with
# broker mutations (dup / reorder / garbage / withhold / reseq — the
# last replays a captured signature at a shifted sequence) applied AFTER
# client signing, full AT2 invariant sweep PLUS a forged-commit sweep
# (every committed slot re-verified against its client signature) per
# episode. Run twice: the campaign hash must reproduce byte-identically,
# same contract as the base determinism gate above.
broker_hash() {
  python -m at2_node_tpu.tools.sim_run --seed 11 --episodes 5 --broker \
    --quiet | sed -n 's/.*hash \([0-9a-f]*\).*/\1/p'
}
b1="$(broker_hash)"
b2="$(broker_hash)"
if [ -z "$b1" ] || [ "$b1" != "$b2" ]; then
  echo "byzantine-broker gate FAILED: '$b1' != '$b2'" >&2
  exit 1
fi
echo "same-seed broker campaign hash reproduced: $b1"

echo "== restart-determinism gate =="
# Durable sharded ledger (ISSUE 9): kill/restart cycles under load, on
# real per-node sharded stores (segments + WAL + manifest), with
# mid-catchup partitions, stale-checkpoint restarts, and membership
# reconfigs in the schedule. Every episode runs the full AT2 invariant
# sweep PLUS the no-post-restart-equivocation check (a rebooted node
# must never re-sign a pre-crash slot with different content). Run
# twice: the same seed must reproduce the same campaign hash even
# through crash/restart cycles — recovery is deterministic too.
restart_hash() {
  python -m at2_node_tpu.tools.sim_run --seed 13 --episodes 4 \
    --durability --quiet | sed -n 's/.*hash \([0-9a-f]*\).*/\1/p'
}
r1="$(restart_hash)"
r2="$(restart_hash)"
if [ -z "$r1" ] || [ "$r1" != "$r2" ]; then
  echo "restart-determinism gate FAILED: '$r1' != '$r2'" >&2
  exit 1
fi
echo "same-seed restart campaign hash reproduced: $r1"

echo "== amortized-verification gate =="
# Amortized (RLC) verification (ISSUE 10): first the verdict-agreement
# suite — RLC verdicts must equal per-sig on every adversarial input
# class (small-order / mixed-torsion R and A included), bisection must
# isolate culprits in the expected check counts, and the router policy
# gates must hold. Then the batch-poisoning campaign — a byzantine
# client salts bad signatures into bulk flushes while the shared
# verifier runs amortized — run twice: invariants add bounded
# amortization loss + router convergence for the salting source, and
# the campaign hash must reproduce byte-identically (RLC's random
# coefficients affect internal check counts, never verdicts, so the
# wire trace is deterministic).
python -m pytest tests/test_rlc_verify.py -q -m "not slow"
salting_hash() {
  python -m at2_node_tpu.tools.sim_run --seed 7 --episodes 3 --salting \
    --quiet | sed -n 's/.*hash \([0-9a-f]*\).*/\1/p'
}
s1="$(salting_hash)"
s2="$(salting_hash)"
if [ -z "$s1" ] || [ "$s1" != "$s2" ]; then
  echo "amortized-verification gate FAILED: '$s1' != '$s2'" >&2
  exit 1
fi
echo "same-seed salting campaign hash reproduced: $s1"

echo "== scenario-grid smoke gate =="
# Fleet SLO engine + scenario grid (ISSUE 8): the 2x2 smoke slice
# (lan/wan3 x steady/flash_crowd) must commit every offered transfer,
# pass every per-cell SLO verdict (exit 0), and reproduce its grid hash
# (sha256 over per-cell trace hashes) byte-identically run to run —
# same contract as the campaign determinism gates above.
grid_hash() {
  python -m at2_node_tpu.tools.scenario_grid --seed 7 --smoke \
    --txs 24 --duration 8 --quiet | sed -n 's/.*hash \([0-9a-f]*\).*/\1/p'
}
g1="$(grid_hash)"
g2="$(grid_hash)"
if [ -z "$g1" ] || [ "$g1" != "$g2" ]; then
  echo "scenario-grid gate FAILED: '$g1' != '$g2'" >&2
  exit 1
fi
echo "same-seed scenario grid hash reproduced: $g1"

echo "== wan-finality gate =="
# Sub-second WAN finality (ISSUE 14): replay the [wan]-knobs-on wan3
# steady and cut cells twice each. Each cell must reproduce its trace
# hash byte-identically (the overlap levers are deterministic), and the
# steady cell must clear the sub-second SLO bar: commit p99 < 1000 ms
# with every offered transfer committed.
wan_cell() {  # $1 = FAULTS
  python -m at2_node_tpu.tools.scenario_grid --seed 7 \
    --replay "wan3/steady/$1+wan" --txs 24 --duration 8 --json
}
wan_steady_json=""
for wfaults in none cut; do
  wj1="$(wan_cell "$wfaults")"
  wj2="$(wan_cell "$wfaults")"
  wh1="$(printf '%s' "$wj1" | python -c 'import json,sys; print(json.load(sys.stdin)["trace_hash"])')"
  wh2="$(printf '%s' "$wj2" | python -c 'import json,sys; print(json.load(sys.stdin)["trace_hash"])')"
  if [ -z "$wh1" ] || [ "$wh1" != "$wh2" ]; then
    echo "wan-finality gate FAILED: wan3/steady/$wfaults+wan hash '$wh1' != '$wh2'" >&2
    exit 1
  fi
  echo "wan3/steady/$wfaults+wan hash reproduced: $wh1"
  [ "$wfaults" = none ] && wan_steady_json="$wj1"
done
# the cell JSON rides an env var: the heredoc IS python's stdin here,
# so piping the JSON in as well would race the program text
WAN_STEADY_CELL="$wan_steady_json" python - <<'EOF'
import json, os
cell = json.loads(os.environ["WAN_STEADY_CELL"])
p99 = cell["latency_p99_ms"]
assert cell["committed"] == cell["offered"], (
    f"wan steady cell lost transfers: {cell['committed']}/{cell['offered']}")
assert not cell["violations"], cell["violations"]
assert cell["slo"]["ok"], f"SLO breach: {cell['slo']['breaching']}"
assert p99 < 1000.0, f"sub-second WAN finality missed: p99 {p99} ms"
print(f"wan3 steady +wan: p99 {p99} ms < 1000 ms, SLO ok")
EOF

echo "== overload-control gate =="
# Closed-loop overload control (ISSUE 16), three contracts:
#  1. the A/B claim at smoke scale on the scaled flash crowd, against a
#     finite modeled verifier pool: [overload] off must BREACH the
#     steady-tier client-perceived p99 SLO (the collapse baseline),
#     [overload] on must HOLD it while keeping Jain fairness for the
#     steady (pre-registered) senders at 1.0 >= the 0.8 floor — the
#     tool exits nonzero unless both arms hold their side;
#  2. determinism: the same seed must reproduce the same ab_hash
#     (sha256 over per-cell wire-trace hashes), byte-identical;
#  3. off-identity: a config carrying an all-defaults (disabled)
#     [overload] table must produce a wire trace byte-identical to one
#     with no table at all — same bar as the [wan] knobs.
overload_ab() {
  python -m at2_node_tpu.tools.overload_ab --seed 5 --clients 60 \
    --crowd 40 --txs 80 --workload flash_crowd --quiet
}
v1="$(overload_ab)" || { echo "overload A/B claim FAILED: $v1" >&2; exit 1; }
v2="$(overload_ab)" || { echo "overload A/B claim FAILED: $v2" >&2; exit 1; }
vh1="$(printf '%s' "$v1" | sed -n 's/.*hash \([0-9a-f]*\).*/\1/p')"
vh2="$(printf '%s' "$v2" | sed -n 's/.*hash \([0-9a-f]*\).*/\1/p')"
if [ -z "$vh1" ] || [ "$vh1" != "$vh2" ]; then
  echo "overload-control gate FAILED: ab_hash '$vh1' != '$vh2'" >&2
  exit 1
fi
echo "same-seed overload A/B hash reproduced: $vh1"
python - <<'EOF'
from at2_node_tpu.node.config import OverloadConfig
from at2_node_tpu.sim.scenarios import run_cell

kw = dict(n_tx=24, duration=8.0)
plain = run_cell(7, "lan", "steady", "none", **kw)
tabled = run_cell(7, "lan", "steady", "none", overload=OverloadConfig(), **kw)
assert plain["trace_hash"] == tabled["trace_hash"], (
    f"[overload]-off not byte-identical: {plain['trace_hash'][:12]} != "
    f"{tabled['trace_hash'][:12]}"
)
print("all-knobs-off [overload] table is wire-invisible:",
      plain["trace_hash"][:16])
EOF

echo "== fleet-audit gate =="
# Fleet consistency auditor + capture/replay bridge (ISSUE 15), three
# contracts:
#  1. a planted single-node ledger corruption (consistent across the
#     culprit's own WAL/ring/digest, so only cross-node beacon compare
#     can see it) must be DETECTED by both honest peers within two
#     beacon intervals and ATTRIBUTED to the culprit node and the
#     victim's account-range lane;
#  2. zero false positives: clean adversarial, sharded-plane, and
#     wan-levers episodes must all end with no latched divergence;
#  3. a wire capture taken from a real-socket fleet must replay through
#     the sim bridge to the same verdict hash twice.
python - <<'EOF'
from at2_node_tpu.sim.campaign import planted_divergence_episode
from at2_node_tpu.sim.net import sim_keypairs, sim_client

seed = 20260805
r = planted_divergence_episode(seed)
assert r.violations, "planted divergence must fail the invariant sweep"
culprit = sim_keypairs(seed, 0)[0].public.hex()
victim_lane = sim_client(seed, 1).public[0] >> 4
assert r.audit is not None
honest = r.audit[1:]
for a in honest:
    d = a["divergence"]
    assert d is not None, "honest node failed to latch the divergence"
    assert d["peer"] == culprit, f"wrong attribution: {d['peer'][:12]}"
    assert victim_lane in d["ranges"], (victim_lane, d["ranges"])
    assert d["detected_commits"] - 6 <= 16, d  # two beacon intervals of 8
print("planted divergence: attributed to node 0, lane", victim_lane,
      "at commit", honest[0]["divergence"]["detected_commits"])
EOF
python - <<'EOF'
from at2_node_tpu.node.config import ObservabilityConfig, WanConfig
from at2_node_tpu.sim.campaign import run_episode

obs = {"observability": ObservabilityConfig(audit_every=8)}
cells = {
    "adversarial": dict(config_overrides=dict(obs)),
    "sharded": dict(config_overrides={**obs, "plane_shards": 4}),
    "wan": dict(config_overrides={
        **obs, "wan": WanConfig(overlap_ready=True, region_fanout=True)}),
}
for name, kw in cells.items():
    r = run_episode(11, n_events=12, duration=8.0, settle_horizon=60.0, **kw)
    assert not r.violations, (name, r.violations)
    for a in r.audit:
        assert a["divergence"] is None, (name, a["divergence"])
        assert a["counters"]["diverged"] == 0, (name, a["counters"])
    print(f"clean {name} episode: zero false positives "
          f"({sum(a['counters']['compared'] for a in r.audit)} compares)")
EOF
python - <<'EOF'
import asyncio, time
from at2_node_tpu.broadcast.messages import Payload
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.node.service import Service
from at2_node_tpu.tools._common import make_net_configs, port_counter
from at2_node_tpu.tools.capture_replay import replay_capture, verdict_hash
from at2_node_tpu.types import ThinTransaction

async def capture():
    cfgs = make_net_configs(3, port_counter(28400))
    services = []
    try:
        for c in cfgs:
            services.append(await Service.start(c))
        sender = SignKeyPair.from_hex("66" * 32)
        recipient = SignKeyPair.from_hex("67" * 32).public
        for seq in range(1, 25):
            await services[0].broadcast.broadcast(
                Payload.create(sender, seq, ThinTransaction(recipient, 1)))
        t0 = time.monotonic()
        while any(s.committed < 24 for s in services):
            await asyncio.sleep(0.02)
            assert time.monotonic() - t0 < 120, "fleet did not commit"
        for s in services:
            s._emit_beacon()
        await asyncio.sleep(0.3)
        return services[1].mesh.capture_dump()
    finally:
        for s in services:
            await s.close()

doc = asyncio.run(capture())
assert doc["records"], "capture ring stayed empty"
v1 = replay_capture(doc, 5)
v2 = replay_capture(doc, 5)
h1, h2 = verdict_hash(v1), verdict_hash(v2)
assert h1 == h2, (h1, h2)
assert not v1["violations"], v1["violations"]
print(f"capture of {len(doc['records'])} frames replayed to verdict "
      f"{h1[:16]} twice")
EOF

echo "== observability overhead gate =="
# The full observability tier's cost — tracer, recorder, SLO probes,
# phase accounting, lag probe, sampler, audit beacons, and the inbound
# wire-capture ring — measured as plane throughput with the tier on vs
# off (interleaved arms, best-of-N per arm to shed scheduler noise),
# must stay under the 5% budget. Exit nonzero when the obs-on arm
# regresses past --budget.
python -m at2_node_tpu.tools.plane_bench --compare-obs --nodes 3 \
    --txs 200 --repeat 2 --out /dev/null

echo "== profiler smoke gate =="
# Continuous profiler (ISSUE 11): one short batched firehose with the
# stack sampler live. Fails unless the capture produced folded stacks
# and every exercisable phase counter (plane leaves + plane_total +
# commit_tail + slot_gc) actually ticked — a silent 0 means a marker
# got dropped from a hot path.
python -m at2_node_tpu.tools.plane_bench --smoke-profile --nodes 3 \
    --txs 200 --out /dev/null
# Cross-process observability (ISSUE 18): the same smoke through the
# process executor. Worker processes ship their own phase marks,
# recorder events, and folded stacks over per-shard obs rings; the
# smoke fails unless the merged folded output carries shardN/ frames
# AND every plane leaf phase ticked in at least one worker shard — a
# silent 0 means a worker-side mark (or the shipping lane itself)
# broke. Needs a real second core for the worker process, same policy
# as the scaling smokes.
if [ "$(nproc)" -ge 2 ]; then
  python -m at2_node_tpu.tools.plane_bench --smoke-profile --nodes 3 \
      --txs 200 --shards 2 --executor process --out /dev/null
else
  echo "single-core host: skipping the process-mode profiler smoke"
fi

echo "== sharded-plane gate =="
# Sharded broadcast plane (ISSUE 12): the invariance suite first (named
# explicitly so a marker/collection change can never drop it), then the
# shard-determinism contract straight from the episode driver — the
# same seed must produce ONE campaign hash whether the plane runs
# monolithic or split across 4 shards, and reproduce it run to run.
python -m pytest tests/test_plane_shards.py -q
python - <<'EOF'
from at2_node_tpu.sim.campaign import run_episode

kw = dict(n_events=10, duration=8.0, settle_horizon=60.0)
mono = run_episode(21, **kw)
s4a = run_episode(21, config_overrides={"plane_shards": 4}, **kw)
s4b = run_episode(21, config_overrides={"plane_shards": 4}, **kw)
assert s4a.trace_hash == s4b.trace_hash, "shards=4 not self-deterministic"
assert mono.trace_hash == s4a.trace_hash, (
    f"shard count observable on the wire: {mono.trace_hash[:12]} != "
    f"{s4a.trace_hash[:12]}"
)
print("shard-invariant campaign hash:", mono.trace_hash[:16])
EOF
# 2-core scaling smoke: threaded shards must buy >= 1.5x plane
# throughput over the monolithic loop when there are real cores to
# spread across. A 1-core host cannot measure scaling — skip (the
# banked BENCH_PLANE_SHARDS.json grid is the tracked artifact there).
if [ "$(nproc)" -ge 2 ]; then
  python -m at2_node_tpu.tools.plane_bench --shards-grid 1,2 --cores 2 \
      --nodes 3 --txs 300 --grid-repeat 2 --no-bank \
      --out /tmp/_plane_shards_smoke.json
  python - <<'EOF'
import json

doc = json.load(open("/tmp/_plane_shards_smoke.json"))
speedup = doc["summary"]["peak_speedup_vs_1"]
assert speedup >= 1.5, (
    f"sharded plane speedup {speedup}x < 1.5x on 2 cores"
)
print(f"sharded plane 2-core speedup: {speedup}x")
EOF
else
  echo "single-core host: skipping the 2-core scaling smoke"
fi

echo "== multiprocess-plane gate =="
# Process executor (ISSUE 17): one spawn worker per shard over
# shared-memory rings. The determinism half ALWAYS runs: the same seed
# must produce ONE campaign hash whether [plane] executor says inline,
# thread, or process — the sim clock forces inline placement, and this
# sweep pins that seam so a config-dependent code path can never leak
# into the wire schedule.
python - <<'EOF'
from at2_node_tpu.sim.campaign import run_episode

kw = dict(n_events=8, duration=6.0, settle_horizon=45.0)
for seed in (0, 7):
    hashes = {}
    for shards, ex in ((1, "inline"), (4, "inline"), (4, "thread"),
                       (4, "process")):
        over = (
            {"plane_shards": shards, "plane_executor": ex}
            if shards > 1 else {}
        )
        ep = run_episode(seed, config_overrides=over, **kw)
        assert ep.violations == [], (seed, shards, ex, ep.violations)
        hashes[(shards, ex)] = ep.trace_hash
    assert len(set(hashes.values())) == 1, (
        f"executor observable on the wire at seed {seed}: "
        + ", ".join(f"{k}={v[:12]}" for k, v in hashes.items())
    )
    print(f"seed {seed}: executor-invariant campaign hash "
          f"{next(iter(hashes.values()))[:16]}")
EOF
# 2-core scaling smoke: process-mode shards must buy >= 1.5x plane
# throughput over the monolithic loop when there are real cores to
# spread across (this is the whole point of breaking the GIL). A
# 1-core host cannot measure scaling — skip, same policy as the
# thread-mode smoke above.
if [ "$(nproc)" -ge 2 ]; then
  python -m at2_node_tpu.tools.plane_bench --shards-grid 1,2 --cores 2 \
      --executor process --nodes 3 --txs 300 --grid-repeat 2 --no-bank \
      --out /tmp/_plane_process_smoke.json
  python - <<'EOF'
import json

doc = json.load(open("/tmp/_plane_process_smoke.json"))
speedup = doc["summary"]["peak_speedup_vs_1"]
assert speedup >= 1.5, (
    f"process-mode plane speedup {speedup}x < 1.5x on 2 cores"
)
print(f"process plane 2-core speedup: {speedup}x")
EOF
else
  echo "single-core host: skipping the process-mode scaling smoke"
fi

echo "== finality gate =="
# Succinct finality certificates + stateless light client (ISSUE 20),
# three contracts:
#  1. the planted equivocation campaign — a compromised fleet member
#     co-signs two conflicting digests for the same (epoch, watermark)
#     coordinate, plus stale-epoch and forged-signature floods — must
#     end with ZERO invariant violations, every honest node latching
#     the equivocation with the culprit attributed by public key, and
#     the campaign trace hash reproduced byte-identically run to run;
#  2. a live simulated fleet's certificate chain must verify through
#     the stateless light client (f+1 co-signer threshold) on every
#     node, and the strict full-quorum verifier must reject byte-level
#     mutants (digest flip, bitmap flip, truncated signature blob);
#  3. off-identity: an all-defaults (disabled) [finality] table must
#     produce a wire trace byte-identical to no table at all — same
#     bar as the [wan] and [overload] knobs.
python -m pytest tests/test_finality.py -q -m "not slow"
python - <<'EOF'
from at2_node_tpu.sim.campaign import planted_cert_equivocation_episode
from at2_node_tpu.sim.net import sim_keypairs

seed = 20260807
r1 = planted_cert_equivocation_episode(seed)
r2 = planted_cert_equivocation_episode(seed)
assert r1.trace_hash == r2.trace_hash, (r1.trace_hash, r2.trace_hash)
assert not r1.violations, r1.violations
culprit = sim_keypairs(seed, 4)[0].public.hex()
assert r1.audit is not None
for a in r1.audit:
    fin = a["finality"]
    assert fin is not None and fin["chain_len"] > 0, fin
    eq = fin.get("equivocation")
    assert eq is not None, "equivocation not latched"
    assert eq["origin"] == culprit, eq["origin"][:16]
    assert fin["epoch_skew"] > 0 and fin["bad_sig"] > 0, fin
print("planted equivocation: latched on every node, attributed to",
      culprit[:16] + ", hash", r1.trace_hash[:16])
EOF
python - <<'EOF'
import dataclasses

from at2_node_tpu.finality import LightVerifier, verify_chain
from at2_node_tpu.node.config import FinalityConfig, ObservabilityConfig
from at2_node_tpu.sim.net import SimNet, sim_client, sim_keypairs

seed, nodes = 7, 4
net = SimNet(
    nodes, 1, seed,
    finality=FinalityConfig(enabled=True),
    observability=ObservabilityConfig(audit_every=8),
).start()
try:
    client = sim_client(seed, 0)
    recipient = sim_client(seed, 1).public
    for k in range(24):
        net.submit(k % nodes, client, k + 1, recipient, 1)
    net.settle(horizon=60.0)
    for svc in net.services:
        svc._emit_beacon()
    net.settle(horizon=10.0)
    keys = [sim_keypairs(seed, i)[0].public for i in range(nodes)]
    light = LightVerifier(keys, total=nodes)  # f+1 co-signer threshold
    full = LightVerifier([], members=keys)  # strict: every bitmap bit
    total = 0
    for svc in net.services:
        chain = list(svc.certs.chain)
        assert chain, svc.certs.status()
        assert verify_chain(chain, light)["ok"]
        assert verify_chain(chain, full)["ok"]
        total += len(chain)
    cert = list(net.services[0].certs.chain)[-1]
    mutants = [
        dataclasses.replace(cert, ranges=bytes(x ^ 0xFF
                                               for x in cert.ranges)),
        dataclasses.replace(
            cert, bitmap=bytes([cert.bitmap[0] ^ 0x0F]) + cert.bitmap[1:]
        ),
        dataclasses.replace(cert, sigs=cert.sigs[:-64]),
    ]
    for i, bad in enumerate(mutants):
        assert not full.verify(bad)["ok"], f"mutant {i} accepted"
    assert not net.check_invariants()
finally:
    net.close()
print(f"light client verified {total} live-fleet certificates; "
      "all mutants rejected")
EOF
python - <<'EOF'
from at2_node_tpu.node.config import FinalityConfig
from at2_node_tpu.sim.campaign import run_episode

kw = dict(n_events=10, duration=8.0, settle_horizon=60.0)
plain = run_episode(13, **kw)
tabled = run_episode(
    13, config_overrides={"finality": FinalityConfig()}, **kw
)
assert plain.trace_hash == tabled.trace_hash, (
    f"[finality]-off not byte-identical: {plain.trace_hash[:12]} != "
    f"{tabled.trace_hash[:12]}"
)
print("all-knobs-off [finality] table is wire-invisible:",
      plain.trace_hash[:16])
EOF

echo "== bench-regression sentry gate =="
# regress.py diffs every banked BENCH_*/SCALE_*/MULTICHIP_* artifact
# against its nearest COMPARABLE capture (tunnel/device state must
# match) and exits 1 on a beyond-band drop, 2 on a schema violation.
# Determinism contract: two runs over the same artifacts are
# byte-identical.
python -m at2_node_tpu.tools.regress --dir . > /tmp/_regress1.txt
python -m at2_node_tpu.tools.regress --dir . > /tmp/_regress2.txt
cmp /tmp/_regress1.txt /tmp/_regress2.txt || {
  echo "regression sentry output not deterministic" >&2; exit 1;
}
cat /tmp/_regress1.txt

if [ "$tier" = "all" ]; then
  echo "== native sanitizers (TSAN + ASAN) =="
  # the reference gets race-freedom from Rust; the C++ prep library gets
  # it from disjoint output ranges, proven under TSAN here (SURVEY §5)
  (
    cd at2_node_tpu/native
    mkdir -p build
    g++ -std=c++17 -O1 -g -fsanitize=thread at2_prep.cpp sanitize_test.cpp \
        -o build/sanitize_tsan -lpthread && ./build/sanitize_tsan
    g++ -std=c++17 -O1 -g -fsanitize=address at2_prep.cpp sanitize_test.cpp \
        -o build/sanitize_asan -lpthread && ./build/sanitize_asan
    g++ -std=c++17 -O1 -g -fsanitize=thread at2_ingest.cpp \
        sanitize_ingest_test.cpp -o build/sanitize_ingest_tsan \
        -lpthread -l:libcrypto.so.3 && ./build/sanitize_ingest_tsan
    g++ -std=c++17 -O1 -g -fsanitize=address at2_ingest.cpp \
        sanitize_ingest_test.cpp -o build/sanitize_ingest_asan \
        -lpthread -l:libcrypto.so.3 && ./build/sanitize_ingest_asan
  )

  echo "== kernel tier (slow) =="
  python -m pytest tests/ -q -m "slow"
fi

echo "CI green."
