#!/usr/bin/env python
"""Regenerate at2_node_tpu/proto/at2_pb2.py without protoc.

The image has the protobuf runtime but not the protoc compiler, so this
script maintains the generated module the other way around: it builds the
``FileDescriptorProto`` for at2.proto programmatically (descriptor_pb2 is
itself a protobuf message), serializes it, and rewrites at2_pb2.py in the
exact shape ``protoc --python_out`` emits (AddSerializedFile + builder
calls + the _serialized_start/end offsets, which are byte positions of
each sub-descriptor inside the serialized file proto).

Keep this as the single source of truth for the RPC surface: edit
``build_file()`` below AND the human-readable at2.proto alongside, then
run ``python scripts/gen_pb2.py`` from the repo root.
"""

from __future__ import annotations

import pathlib

from google.protobuf import descriptor_pb2 as dp

OUT = pathlib.Path(__file__).resolve().parent.parent / (
    "at2_node_tpu/proto/at2_pb2.py"
)

# (field) type/label shorthands
T = dp.FieldDescriptorProto


def field(name, number, ftype, label=T.LABEL_OPTIONAL, type_name=None):
    f = dp.FieldDescriptorProto(name=name, number=number, type=ftype, label=label)
    if type_name:
        f.type_name = type_name
    return f


def message(name, *fields):
    m = dp.DescriptorProto(name=name)
    m.field.extend(fields)
    return m


def build_file() -> dp.FileDescriptorProto:
    f = dp.FileDescriptorProto(name="at2.proto", package="at2", syntax="proto3")

    f.message_type.append(
        message(
            "SendAssetRequest",
            field("sender", 1, T.TYPE_BYTES),
            field("sequence", 2, T.TYPE_UINT32),
            field("recipient", 3, T.TYPE_BYTES),
            field("amount", 4, T.TYPE_UINT64),
            field("signature", 5, T.TYPE_BYTES),
        )
    )
    f.message_type.append(message("SendAssetReply"))
    f.message_type.append(
        message(
            "SendAssetBatchRequest",
            field(
                "transactions", 1, T.TYPE_MESSAGE, T.LABEL_REPEATED,
                ".at2.SendAssetRequest",
            ),
        )
    )
    f.message_type.append(
        message("GetBalanceRequest", field("sender", 1, T.TYPE_BYTES))
    )
    f.message_type.append(
        message("GetBalanceReply", field("amount", 1, T.TYPE_UINT64))
    )
    f.message_type.append(
        message("GetLastSequenceRequest", field("sender", 1, T.TYPE_BYTES))
    )
    f.message_type.append(
        message("GetLastSequenceReply", field("sequence", 1, T.TYPE_UINT32))
    )

    full = message(
        "FullTransaction",
        field("timestamp", 1, T.TYPE_STRING),
        field("sender", 2, T.TYPE_BYTES),
        field("recipient", 3, T.TYPE_BYTES),
        field("amount", 4, T.TYPE_UINT64),
        field("state", 5, T.TYPE_ENUM, type_name=".at2.FullTransaction.State"),
        field("sender_sequence", 6, T.TYPE_UINT32),
    )
    st = full.enum_type.add()
    st.name = "State"
    for i, vname in enumerate(("Pending", "Success", "Failure")):
        v = st.value.add()
        v.name = vname
        v.number = i
    f.message_type.append(full)

    f.message_type.append(message("GetLatestTransactionsRequest"))
    f.message_type.append(
        message(
            "GetLatestTransactionsReply",
            field(
                "transactions", 1, T.TYPE_MESSAGE, T.LABEL_REPEATED,
                ".at2.FullTransaction",
            ),
        )
    )

    # Broker ingress tier (ISSUE 7): client registration into the gossiped
    # directory + distilled-batch submission (proto/distill.py wire format
    # travels opaque in `frame`; the node parses it natively).
    f.message_type.append(
        message("RegisterRequest", field("public_key", 1, T.TYPE_BYTES))
    )
    f.message_type.append(
        message("RegisterReply", field("client_id", 1, T.TYPE_UINT64))
    )
    f.message_type.append(
        message("SendDistilledBatchRequest", field("frame", 1, T.TYPE_BYTES))
    )

    svc = f.service.add()
    svc.name = "AT2"
    for mname, req, rep in (
        ("SendAsset", "SendAssetRequest", "SendAssetReply"),
        ("GetBalance", "GetBalanceRequest", "GetBalanceReply"),
        ("GetLastSequence", "GetLastSequenceRequest", "GetLastSequenceReply"),
        (
            "GetLatestTransactions",
            "GetLatestTransactionsRequest",
            "GetLatestTransactionsReply",
        ),
        ("SendAssetBatch", "SendAssetBatchRequest", "SendAssetReply"),
        ("Register", "RegisterRequest", "RegisterReply"),
        ("SendDistilledBatch", "SendDistilledBatchRequest", "SendAssetReply"),
    ):
        m = svc.method.add()
        m.name = mname
        m.input_type = f".at2.{req}"
        m.output_type = f".at2.{rep}"
    return f


def offsets(fdp: dp.FileDescriptorProto, blob: bytes):
    """(_NAME, start, end) tuples, protoc's _serialized_start/end: the
    byte span of each sub-descriptor inside the serialized file proto."""
    out = []

    def locate(sub: bytes) -> tuple:
        start = blob.find(sub)
        assert start >= 0, "sub-descriptor not found in serialized file"
        return start, start + len(sub)

    for msg in fdp.message_type:
        s, e = locate(msg.SerializeToString())
        out.append((f"_{msg.name.upper()}", s, e))
        for en in msg.enum_type:
            es, ee = locate(en.SerializeToString())
            out.append((f"_{msg.name.upper()}_{en.name.upper()}", es, ee))
    for svc in fdp.service:
        s, e = locate(svc.SerializeToString())
        out.append((f"_{svc.name.upper()}", s, e))
    return out


def main() -> None:
    fdp = build_file()
    blob = fdp.SerializeToString()
    lines = [
        "# -*- coding: utf-8 -*-",
        "# Generated by the protocol buffer compiler.  DO NOT EDIT!",
        "# source: at2.proto",
        '"""Generated protocol buffer code."""',
        "from google.protobuf.internal import builder as _builder",
        "from google.protobuf import descriptor as _descriptor",
        "from google.protobuf import descriptor_pool as _descriptor_pool",
        "from google.protobuf import symbol_database as _symbol_database",
        "# @@protoc_insertion_point(imports)",
        "",
        "_sym_db = _symbol_database.Default()",
        "",
        "",
        "",
        "",
        f"DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})",
        "",
        "_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())",
        "_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'at2_pb2', globals())",
        "if _descriptor._USE_C_DESCRIPTORS == False:",
        "",
        "  DESCRIPTOR._options = None",
    ]
    for name, s, e in offsets(fdp, blob):
        lines.append(f"  {name}._serialized_start={s}")
        lines.append(f"  {name}._serialized_end={e}")
    lines.append("# @@protoc_insertion_point(module_scope)")
    OUT.write_text("\n".join(lines) + "\n")
    print(f"wrote {OUT} ({len(blob)} descriptor bytes)")


if __name__ == "__main__":
    main()
