"""Headline benchmark: batched ed25519 verifies/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline = the north-star target from BASELINE.json: 50,000 ed25519
verifies/sec/chip (the reference publishes no numbers — SURVEY.md §6 — so
the target is the yardstick; vs_baseline > 1.0 means the target is beaten).

What is measured (BASELINE config 2's 1k/8k/64k grid):

* ``device_only`` — back-to-back dispatches on device-resident inputs:
  the kernel's compute ceiling.
* ``pipelined`` — the production firehose shape: host prep on a worker
  thread, ONE packed (B,129)-uint8 H2D transfer per batch
  (`ops.ed25519.pack_prepared`), async dispatch chain with
  ``copy_to_host_async`` and deferred materialization. This is the
  steady state of `TpuBatchVerifier` under sustained load.

Transfer analysis (recorded because it sets the pipelined ceiling here):
the chip is reached through a tunnel whose host↔device round trips cost
tens of ms regardless of payload size, transfers cannot overlap compute
(a device_put issued while a program is in flight blocks until the queue
drains), and observed tunnel bandwidth varies by >100x between runs. The
big bucket + single packed transfer + rare-sync pipeline is the design
answer; per-run numbers still inherit the tunnel's mood, so each config
reports the best of ``TRIALS`` trials.
"""

from __future__ import annotations

import json
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

TARGET_PER_CHIP = 50_000.0
GRID = (1024, 8192, 65536)
HEADLINE_BUCKET = 65536
TRIALS = 3
DEPTH = 4  # outstanding batches in the async chain


def _make_batch(n: int):
    from at2_node_tpu.crypto.keys import SignKeyPair

    kp = SignKeyPair.from_hex("5a" * 32)
    pk = kp.public
    msgs = [b"bench message %08d" % i for i in range(n)]
    sigs = [kp.sign(m) for m in msgs]
    return [pk] * n, msgs, sigs


def _rounds_for(bucket: int) -> int:
    # ~0.5M lanes per trial keeps every config's trial a few seconds
    return max(4, min(16, (1 << 19) // bucket))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from at2_node_tpu.ops import ed25519 as kernel

    dev = jax.devices()[0]
    on_tpu = kernel._use_pallas()
    if on_tpu:
        from at2_node_tpu.ops.pallas_verify import (
            _verify_pallas_packed as run_packed,
        )
    else:
        run_packed = kernel._verify_packed_jit

    pool = ThreadPoolExecutor(max_workers=2)
    grid_results = {}
    for bucket in GRID:
        pks, msgs, sigs = _make_batch(bucket)
        packed = kernel.pack_prepared(
            *kernel.prepare_batch(pks, msgs, sigs, bucket)
        )
        rounds = _rounds_for(bucket)

        # warm-up: compile + fault in constants
        dev_in = jax.device_put(packed)
        out = run_packed(dev_in)
        assert bool(np.asarray(out)[:bucket].all()), "warm-up failed to verify"

        best_device, best_pipe = 0.0, 0.0
        for _ in range(TRIALS):
            # 1) device-only ceiling (inputs resident, one final sync)
            t0 = time.perf_counter()
            for _ in range(rounds):
                out = run_packed(dev_in)
            np.asarray(out)
            best_device = max(
                best_device, rounds * bucket / (time.perf_counter() - t0)
            )

            # 2) pipelined production shape: prep worker + packed transfer
            #    + async chain, materialize oldest beyond DEPTH
            next_prep = pool.submit(
                kernel.prepare_batch, pks, msgs, sigs, bucket
            )
            inflight: deque = deque()
            t0 = time.perf_counter()
            for _ in range(rounds):
                prepared = next_prep.result()
                next_prep = pool.submit(
                    kernel.prepare_batch, pks, msgs, sigs, bucket
                )
                host_packed = kernel.pack_prepared(*prepared)
                o = run_packed(jax.device_put(host_packed))
                o.copy_to_host_async()
                inflight.append(o)
                if len(inflight) >= DEPTH:
                    np.asarray(inflight.popleft())
            while inflight:
                np.asarray(inflight.popleft())
            best_pipe = max(
                best_pipe, rounds * bucket / (time.perf_counter() - t0)
            )
            # consume the dangling prep future so it cannot steal CPU from
            # the next trial's timed sections
            next_prep.result()
        grid_results[bucket] = {
            "device_only": round(best_device, 1),
            "pipelined": round(best_pipe, 1),
        }

    # host prep rate (one thread) + CPU (OpenSSL) per-sig baseline
    pks, msgs, sigs = _make_batch(8192)
    t0 = time.perf_counter()
    kernel.prepare_batch(pks, msgs, sigs, 8192)
    prep_rate = 8192 / (time.perf_counter() - t0)

    from at2_node_tpu.crypto.keys import verify_one

    n_cpu = 2000
    t0 = time.perf_counter()
    for i in range(n_cpu):
        verify_one(pks[i], msgs[i], sigs[i])
    cpu_rate = n_cpu / (time.perf_counter() - t0)
    pool.shutdown(wait=False)

    value = grid_results[HEADLINE_BUCKET]["pipelined"]
    print(
        json.dumps(
            {
                "metric": "ed25519_verifies_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "sigs/s",
                "vs_baseline": round(value / TARGET_PER_CHIP, 3),
                "device": str(dev.platform),
                "bucket": HEADLINE_BUCKET,
                "grid": {str(k): v for k, v in grid_results.items()},
                "host_prep_rate": round(prep_rate, 1),
                "cpu_openssl_1core_rate": round(cpu_rate, 1),
                "device_only_rate": grid_results[HEADLINE_BUCKET][
                    "device_only"
                ],
            }
        )
    )


def _guarded() -> None:
    """Run the real bench in a child with a wall-clock bound; the driver
    must ALWAYS get one JSON line even if the device tunnel wedges (a
    hung backend init otherwise turns the round's bench into nothing)."""
    import os
    import subprocess
    import sys

    if os.environ.get("AT2_BENCH_CHILD") == "1":
        main()
        return
    env = dict(os.environ, AT2_BENCH_CHILD="1")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=1500,  # healthy cold-compile run fits in ~10 min
        )
        lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
        if proc.returncode == 0 and lines:
            print(lines[-1])
            return
        error = f"bench child rc={proc.returncode}: {proc.stderr.strip()[-300:]}"
    except subprocess.TimeoutExpired:
        error = "bench child exceeded 1500s (device tunnel unreachable?)"
    print(
        json.dumps(
            {
                "metric": "ed25519_verifies_per_sec_per_chip",
                "value": 0.0,
                "unit": "sigs/s",
                "vs_baseline": 0.0,
                "error": error,
            }
        )
    )


if __name__ == "__main__":
    _guarded()
