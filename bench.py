"""Headline benchmark: batched ed25519 verifies/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline = the north-star target from BASELINE.json: 50,000 ed25519
verifies/sec/chip (the reference publishes no numbers — SURVEY.md §6 — so
the target is the yardstick; vs_baseline > 1.0 means the target is beaten).

Measures the sustained device throughput of the production dispatch path
(`ops.ed25519.verify_kernel`, fixed 8192-lane bucket) with host-side batch
prep overlapped on a worker thread, i.e. the steady state of
`TpuBatchVerifier` under firehose load (BASELINE config 2/3). Also reports
the end-to-end single-stream number (prep + dispatch serialized) and the
CPU (OpenSSL) baseline for context.
"""

from __future__ import annotations

import json
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

TARGET_PER_CHIP = 50_000.0
BUCKET = 8192
ROUNDS = 6


def _make_batch(n: int):
    from at2_node_tpu.crypto.keys import SignKeyPair

    kp = SignKeyPair.from_hex("5a" * 32)
    pk = kp.public
    msgs = [b"bench message %08d" % i for i in range(n)]
    sigs = [kp.sign(m) for m in msgs]
    return [pk] * n, msgs, sigs


def main() -> None:
    import jax

    from at2_node_tpu.ops import ed25519 as kernel

    dev = jax.devices()[0]
    pks, msgs, sigs = _make_batch(BUCKET)
    on_tpu = kernel._use_pallas()

    # Warm-up: compile the bucket's program and fault in constants.
    import jax.numpy as jnp

    if on_tpu:
        from at2_node_tpu.ops.pallas_verify import _verify_pallas as run_prepared
    else:
        run_prepared = kernel._verify_jit
    prepared = kernel.prepare_batch(pks, msgs, sigs, BUCKET)
    dev_args = tuple(jnp.asarray(x) for x in prepared)
    out = run_prepared(*dev_args)
    assert bool(np.asarray(out)[:BUCKET].all()), "warm-up batch failed to verify"

    # 1) Device throughput: dispatch the compiled program back-to-back
    #    (np.asarray forces real completion; block_until_ready does not
    #    synchronize through the tunnel transport).
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        out = run_prepared(*dev_args)
    np.asarray(out)
    device_rate = ROUNDS * BUCKET / (time.perf_counter() - t0)

    # 2) Host prep rate (sha512 + window decomposition, one thread).
    t0 = time.perf_counter()
    kernel.prepare_batch(pks, msgs, sigs, BUCKET)
    prep_rate = BUCKET / (time.perf_counter() - t0)

    # 3) Pipelined steady state: prep on a worker thread, JAX's async
    #    dispatch keeps up to DEPTH batches in flight (transfer of batch
    #    i+1 overlaps compute of batch i) — the TpuBatchVerifier execution
    #    model under firehose load.
    from collections import deque

    DEPTH = 3
    pool = ThreadPoolExecutor(max_workers=2)
    next_prep = pool.submit(kernel.prepare_batch, pks, msgs, sigs, BUCKET)
    inflight: deque = deque()
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        a, r, s_le, h_le, valid = next_prep.result()
        next_prep = pool.submit(kernel.prepare_batch, pks, msgs, sigs, BUCKET)
        inflight.append(
            run_prepared(
                jnp.asarray(a), jnp.asarray(r), jnp.asarray(s_le),
                jnp.asarray(h_le), jnp.asarray(valid),
            )
        )
        if len(inflight) >= DEPTH:
            np.asarray(inflight.popleft())  # fetch results of oldest batch
    while inflight:
        np.asarray(inflight.popleft())
    pipelined_rate = ROUNDS * BUCKET / (time.perf_counter() - t0)
    pool.shutdown(wait=False)

    # 4) CPU baseline (the reference's execution model): OpenSSL, one core.
    from at2_node_tpu.crypto.keys import verify_one

    n_cpu = 2000
    t0 = time.perf_counter()
    for i in range(n_cpu):
        verify_one(pks[i], msgs[i], sigs[i])
    cpu_rate = n_cpu / (time.perf_counter() - t0)

    value = pipelined_rate
    print(
        json.dumps(
            {
                "metric": "ed25519_verifies_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "sigs/s",
                "vs_baseline": round(value / TARGET_PER_CHIP, 3),
                "device": str(dev.platform),
                "bucket": BUCKET,
                "device_only_rate": round(device_rate, 1),
                "host_prep_rate": round(prep_rate, 1),
                "cpu_openssl_1core_rate": round(cpu_rate, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
