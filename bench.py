"""Headline benchmark: batched ed25519 verifies/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Baseline = the north-star target from BASELINE.json: 50,000 ed25519
verifies/sec/chip (the reference publishes no numbers — SURVEY.md §6 — so
the target is the yardstick; vs_baseline > 1.0 means the target is beaten).

What is measured (BASELINE config 2's 1k/8k/64k grid):

* ``device_only`` — back-to-back dispatches on device-resident inputs:
  the kernel's compute ceiling.
* ``pipelined`` — the production firehose shape: host prep on a worker
  thread, ONE packed (B,129)-uint8 H2D transfer per batch
  (`ops.ed25519.pack_prepared`), async dispatch chain with
  ``copy_to_host_async`` and deferred materialization. This is the
  steady state of `TpuBatchVerifier` under sustained load.

Wedge-proofing (round-2 post-mortem: a wedged device tunnel turned the
round's bench artifact into 0.0): the orchestrator never lets a hung
backend produce *nothing* —

1. a tiny PROBE child must initialize the backend and run one op inside
   ``PROBE_TIMEOUT`` or the tunnel is declared dead without spending the
   main budget;
2. the bench child emits ONE JSON line PER BUCKET as it completes
   (headline bucket first), so a mid-run wedge still banks the finished
   buckets;
3. every successful run persists to ``BENCH_LASTGOOD.json``; on any
   failure the orchestrator re-emits those last-good numbers with the
   failure reason and ``tunnel_live_at_write: false`` — provenance
   (``captured_at`` / ``captured_round``: when the value was measured
   on the chip) is reported separately from link state, so a
   same-round capture is never mistaken for a relic (round-4 verdict
   #7; README "Benchmarks").

Transfer analysis (recorded because it sets the pipelined ceiling here):
the chip is reached through a tunnel whose host↔device round trips cost
tens of ms regardless of payload size, transfers cannot overlap compute
(a device_put issued while a program is in flight blocks until the queue
drains), and observed tunnel bandwidth varies by >100x between runs. The
big bucket + single packed transfer + rare-sync pipeline is the design
answer; per-run numbers still inherit the tunnel's mood, so each config
reports the best of ``TRIALS`` trials.
"""

from __future__ import annotations

import json
import os
import sys
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

TARGET_PER_CHIP = 50_000.0
# Headline bucket FIRST: if the tunnel wedges mid-run, the number that
# matters is already banked. AT2_BENCH_GRID/TRIALS/PLATFORM exist so the
# orchestration pipeline itself is testable on CPU with tiny buckets.
GRID = tuple(
    int(x) for x in os.environ.get("AT2_BENCH_GRID", "65536,8192,1024").split(",")
)
HEADLINE_BUCKET = GRID[0]
TRIALS = int(os.environ.get("AT2_BENCH_TRIALS", "3"))
DEPTH = 4  # outstanding batches in the async chain

_REPO = os.path.dirname(os.path.abspath(__file__))
LASTGOOD_PATH = os.path.join(_REPO, "BENCH_LASTGOOD.json")

# env-overridable so CI / a driver on a known-dead tunnel can shrink the
# budget instead of waiting the full production allowance
PROBE_TIMEOUT = int(
    os.environ.get("AT2_BENCH_PROBE_TIMEOUT", "180")
)  # backend init + one tiny compile on a healthy tunnel
BUCKET_TIMEOUT = 900  # cold compile + trials for ONE bucket
TOTAL_TIMEOUT = int(
    os.environ.get("AT2_BENCH_TOTAL_TIMEOUT", "2400")
)  # whole child budget
# dead-tunnel fallback grid: OpenSSL on the host, one trial per bucket
# (the point is a labeled, honest CPU row, not a tuning exercise)
CPU_TRIALS = int(os.environ.get("AT2_BENCH_CPU_TRIALS", "1"))
CPU_TIMEOUT = int(os.environ.get("AT2_BENCH_CPU_TIMEOUT", "600"))


# --------------------------------------------------------------------------
# child: --probe  (tiny tunnel healthcheck)
# --------------------------------------------------------------------------


def _apply_platform_override() -> None:
    """AT2_BENCH_PLATFORM=cpu retargets the backend for pipeline tests.
    Must be jax.config (not env): the environment preloads jax via a .pth
    hook with JAX_PLATFORMS baked in, so env edits are too late."""
    plat = os.environ.get("AT2_BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def probe_main() -> None:
    _apply_platform_override()
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    x = jnp.ones((256, 256), dtype=jnp.float32)
    y = (x @ x).block_until_ready()
    assert float(np.asarray(y)[0, 0]) == 256.0
    print(json.dumps({"probe": "ok", "device": str(dev.platform)}), flush=True)


# --------------------------------------------------------------------------
# child: --child  (the real bench, incremental per-bucket output)
# --------------------------------------------------------------------------


def _make_batch(n: int):
    from at2_node_tpu.crypto.keys import SignKeyPair

    kp = SignKeyPair.from_hex("5a" * 32)
    pk = kp.public
    msgs = [b"bench message %08d" % i for i in range(n)]
    sigs = [kp.sign(m) for m in msgs]
    return [pk] * n, msgs, sigs


def _rounds_for(bucket: int) -> int:
    # ~0.5M lanes per trial keeps every config's trial a few seconds
    return max(4, min(16, (1 << 19) // bucket))


def child_main() -> None:
    _apply_platform_override()
    import jax

    # Persistent compile cache: a healthy-tunnel window must be spent
    # measuring, not re-paying minutes of XLA/Mosaic compilation
    # (tests/conftest.py uses the same cache dir).
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from at2_node_tpu.ops import ed25519 as kernel

    dev = jax.devices()[0]
    print(json.dumps({"stage": "backend_up", "device": str(dev.platform)}), flush=True)
    if kernel._use_pallas():
        from at2_node_tpu.ops.pallas_verify import (
            _verify_pallas_packed as run_packed,
        )
    else:
        run_packed = kernel._verify_packed_jit

    pool = ThreadPoolExecutor(max_workers=2)
    for bucket in GRID:
        pks, msgs, sigs = _make_batch(bucket)
        packed = kernel.pack_prepared(
            *kernel.prepare_batch(pks, msgs, sigs, bucket)
        )
        rounds = _rounds_for(bucket)

        # warm-up: compile + fault in constants — both the device-only
        # program and the pipelined bits-program (donated staging +
        # on-device packbits reduction) so neither trial pays the compiler
        dev_in = jax.device_put(packed)
        out = run_packed(dev_in)
        assert bool(np.asarray(out)[:bucket].all()), "warm-up failed to verify"
        warm = kernel.finish_packed(
            kernel.launch_packed(
                kernel.upload_packed(kernel.prep_packed(pks, msgs, sigs, bucket))
            ),
            bucket,
        )
        assert bool(warm.all()), "pipelined warm-up failed to verify"

        # profiler capture of the device-only shape, headline bucket only
        # (trace path lands in the artifact — VERDICT r2 item 7)
        trace_dir = ""
        if bucket == HEADLINE_BUCKET and str(dev.platform) == "tpu":
            trace_dir = os.path.join(_REPO, ".profile_traces", f"bench_b{bucket}")
            try:
                with jax.profiler.trace(trace_dir):
                    np.asarray(run_packed(dev_in))
            except Exception as exc:  # tunnel-backed profiler may refuse
                trace_dir = f"unavailable: {exc}"

        best_device, best_pipe = 0.0, 0.0
        for _ in range(TRIALS):
            # 1) device-only ceiling (inputs resident, one final sync)
            t0 = time.perf_counter()
            for _ in range(rounds):
                out = run_packed(dev_in)
            np.asarray(out)
            best_device = max(
                best_device, rounds * bucket / (time.perf_counter() - t0)
            )

            # 2) pipelined production shape — the EXACT stage functions
            #    TpuBatchVerifier runs (ops/ed25519.py prep_packed /
            #    upload_packed / launch_packed / finish_packed): pooled
            #    host staging + upload on the worker threads (the round-4
            #    trace attributed the pipelined-vs-device-only gap to
            #    per-batch tunnel transfers serializing with dispatch),
            #    donated device input, on-device packbits reduction so
            #    the per-batch sync materializes B/8 bytes, two prep
            #    futures ahead, finish oldest beyond DEPTH
            def _prep_upload():
                return kernel.upload_packed(
                    kernel.prep_packed(pks, msgs, sigs, bucket)
                )

            preps: deque = deque(
                pool.submit(_prep_upload) for _ in range(2)
            )
            inflight: deque = deque()
            t0 = time.perf_counter()
            for _ in range(rounds):
                staged = preps.popleft().result()
                preps.append(pool.submit(_prep_upload))
                inflight.append(kernel.launch_packed(staged))
                if len(inflight) >= DEPTH:
                    kernel.finish_packed(inflight.popleft(), bucket)
            while inflight:
                out_ok = kernel.finish_packed(inflight.popleft(), bucket)
            best_pipe = max(
                best_pipe, rounds * bucket / (time.perf_counter() - t0)
            )
            assert bool(out_ok.all()), "pipelined trial failed to verify"
            # consume the dangling prep futures so they cannot steal CPU
            # from the next trial's timed sections
            for f in preps:
                f.result()
        line = {
            "bucket": bucket,
            "device_only": round(best_device, 1),
            "pipelined": round(best_pipe, 1),
            "device": str(dev.platform),
        }
        if trace_dir:
            line["trace_dir"] = trace_dir
        print(json.dumps(line), flush=True)

    # host prep rate (one thread) + CPU (OpenSSL) per-sig baseline
    pks, msgs, sigs = _make_batch(8192)
    t0 = time.perf_counter()
    kernel.prepare_batch(pks, msgs, sigs, 8192)
    prep_rate = 8192 / (time.perf_counter() - t0)

    from at2_node_tpu.crypto.keys import verify_one

    n_cpu = 2000
    t0 = time.perf_counter()
    for i in range(n_cpu):
        verify_one(pks[i], msgs[i], sigs[i])
    cpu_rate = n_cpu / (time.perf_counter() - t0)
    pool.shutdown(wait=False)
    print(
        json.dumps(
            {
                "aux": True,
                "host_prep_rate": round(prep_rate, 1),
                "cpu_openssl_1core_rate": round(cpu_rate, 1),
            }
        ),
        flush=True,
    )


# --------------------------------------------------------------------------
# child: --cpu-child  (dead-tunnel fallback: the SAME grid on the host CPU)
# --------------------------------------------------------------------------


def cpu_child_main() -> None:
    """Run the bench grid to completion on the CPU backend (OpenSSL via
    the native ingest library), so a dead tunnel still yields a fresh,
    clearly-labeled measurement instead of only a re-emitted relic.

    Column mapping, honestly labeled per row (``device: cpu-openssl``,
    ``fallback: true``): ``device_only`` is the one-native-call bulk
    verify rate (the host's compute ceiling, no async plumbing);
    ``pipelined`` is the full async CpuVerifier.verify_many path (executor
    hop + chunking) — the same semantic split as the TPU columns. The XLA
    CPU graph is deliberately NOT used here: compiling the crypto graph
    takes 15+ minutes per bucket shape on this host, which is exactly the
    wedge this fallback exists to avoid."""
    import asyncio

    from at2_node_tpu.crypto.verifier import CpuVerifier
    from at2_node_tpu.native import ingest_available, verify_bulk_native

    have_native = ingest_available()  # builds the library if needed
    n_threads = max(1, min(4, os.cpu_count() or 1))
    print(
        json.dumps(
            {
                "stage": "backend_up",
                "device": "cpu-openssl",
                "native": have_native,
            }
        ),
        flush=True,
    )

    from at2_node_tpu.crypto.keys import verify_one

    for bucket in GRID:
        pks, msgs, sigs = _make_batch(bucket)
        items = list(zip(pks, msgs, sigs))
        sampled = False

        best_bulk = 0.0
        for _ in range(CPU_TRIALS):
            t0 = time.perf_counter()
            if have_native:
                ok = verify_bulk_native(items, n_threads)
                n_timed = bucket
            else:
                # no C library: sample with per-sig OpenSSL calls (still a
                # real measurement, marked as such)
                n_timed = min(bucket, 1024)
                ok = np.array(
                    [verify_one(*items[i]) for i in range(n_timed)]
                )
                sampled = True
            dt = time.perf_counter() - t0
            assert bool(np.asarray(ok).all()), "cpu bulk verify failed"
            best_bulk = max(best_bulk, n_timed / dt)

        async def _pipe_once() -> tuple:
            ver = CpuVerifier()
            t0 = time.perf_counter()
            out = await ver.verify_many(items)
            dt = time.perf_counter() - t0
            assert all(out), "cpu pipelined verify failed"
            stats = ver.stats()
            await ver.close()
            return bucket / dt, stats

        best_pipe, pipe_stats = 0.0, {}
        for _ in range(CPU_TRIALS):
            rate, pipe_stats = asyncio.run(_pipe_once())
            best_pipe = max(best_pipe, rate)

        line = {
            "bucket": bucket,
            "device_only": round(best_bulk, 1),
            "pipelined": round(best_pipe, 1),
            "device": "cpu-openssl",
            "fallback": True,
            "verifier_stats": pipe_stats,
        }
        if sampled:
            line["sampled"] = True
        print(json.dumps(line), flush=True)


# --------------------------------------------------------------------------
# orchestrator (default entry): probe -> child -> assemble/fallback
# --------------------------------------------------------------------------


def _run_child(flag: str, timeout: float, on_line=None) -> tuple:
    """Run this file as a subprocess; stream its stdout JSON lines to
    on_line as they arrive. Returns (rc_or_None_if_timeout,
    collected_json_lines, stderr_tail).

    Both pipes get dedicated reader threads: stderr must drain
    concurrently (a cold XLA compile logs more than a pipe buffer —
    an undrained pipe deadlocks the child into a false timeout), and
    blocking line-reads in a thread can never stall the wall-clock loop
    on a partial line or strand buffered lines the way select+readline
    on a TextIOWrapper does."""
    import queue
    import subprocess
    import threading

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), flag],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    out_q: queue.Queue = queue.Queue()
    stderr_chunks: list = []

    def read_stdout() -> None:
        try:
            for line in proc.stdout:
                out_q.put(line)
        except ValueError:
            pass  # pipe closed underneath us at kill time
        out_q.put(None)  # EOF marker AFTER every buffered line

    def read_stderr() -> None:
        try:
            stderr_chunks.append(proc.stderr.read() or "")
        except ValueError:
            stderr_chunks.append("")

    t_out = threading.Thread(target=read_stdout, daemon=True)
    t_err = threading.Thread(target=read_stderr, daemon=True)
    t_out.start()
    t_err.start()

    lines = []

    def consume(item: str) -> None:
        if item.startswith("{"):
            try:
                obj = json.loads(item)
            except ValueError:
                return
            lines.append(obj)
            if on_line is not None:
                on_line(obj)

    deadline = time.monotonic() + timeout
    timed_out = False
    while True:
        budget = deadline - time.monotonic()
        if budget <= 0:
            timed_out = True
            proc.kill()
            break
        try:
            item = out_q.get(timeout=min(budget, 5.0))
        except queue.Empty:
            continue
        if item is None:
            break  # EOF: every line the child ever printed was consumed
        consume(item)
    try:
        proc.wait(timeout=10)
    except Exception:
        pass
    # after a kill, bank whatever completed lines beat the wedge
    t_out.join(timeout=5)
    while True:
        try:
            item = out_q.get_nowait()
        except queue.Empty:
            break
        if item is not None:
            consume(item)
    t_err.join(timeout=5)
    stderr_tail = (stderr_chunks[0] if stderr_chunks else "")[-400:]
    return (None if timed_out else proc.returncode), lines, stderr_tail


def _load_lastgood() -> dict | None:
    try:
        with open(LASTGOOD_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _emit(result: dict) -> None:
    print(json.dumps(result))


def _current_round() -> int | None:
    """Best-effort round number from the driver's PROGRESS.jsonl."""
    try:
        with open(os.path.join(_REPO, "PROGRESS.jsonl")) as f:
            last = None
            for line in f:
                if line.strip():
                    last = line
        return json.loads(last)["round"] if last else None
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _now_utc() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _run_cpu_grid() -> dict:
    """Dead-tunnel path: run the SAME grid on the host CPU (OpenSSL) so
    the round still produces a fresh, labeled measurement. Streamed like
    the TPU child — a completed row is banked even if a later one dies."""
    rows: dict = {}

    def on_line(obj: dict) -> None:
        if "bucket" in obj:
            rows[str(obj["bucket"])] = obj

    rc, _, err = _run_child("--cpu-child", CPU_TIMEOUT, on_line)
    if rc != 0:
        rows["error"] = (
            f"cpu fallback child rc={rc}: {err.strip()[-200:]}"
            if rc is not None
            else f"cpu fallback child exceeded {CPU_TIMEOUT}s"
        )
    return rows


def _fallback(error: str) -> None:
    # Provenance vs link state are SEPARATE facts (round-4 verdict #7):
    # `captured_at`/`captured_round` say when the banked VALUE was
    # measured on the chip; `tunnel_live_at_write: false` says only that
    # the tunnel was dead when THIS artifact was written — and both are
    # carried PER GRID ROW, because a partial run banks row by row. A
    # same-round capture re-emitted through this path is fresh evidence,
    # not a relic — the old single `stale` flag conflated the two.
    last = _load_lastgood()
    if last is None:
        out = {
            "metric": "ed25519_verifies_per_sec_per_chip",
            "value": 0.0,
            "unit": "sigs/s",
            "vs_baseline": 0.0,
            "tunnel_live_at_write": False,
            "error": error,
        }
    else:
        out = dict(last)
        out.pop("stale", None)  # superseded by the split fields
        out["tunnel_live_at_write"] = False
        for row in out.get("grid", {}).values():
            if isinstance(row, dict):
                row["tunnel_live_at_write"] = False
        out["error"] = error
    # the tunnel is dead, the HOST is not: same grid, CPU backend,
    # clearly labeled as the fallback it is
    out["cpu_fallback_grid"] = _run_cpu_grid()
    out["cpu_fallback_captured_at"] = _now_utc()
    _emit(out)


def orchestrate() -> None:
    # 1) fail fast on a dead tunnel: don't burn the bucket budget on a
    #    backend init that will never return
    rc, lines, err = _run_child("--probe", PROBE_TIMEOUT)
    if rc is None:
        _fallback(
            f"device tunnel dead: backend init exceeded {PROBE_TIMEOUT}s probe"
        )
        return
    if rc != 0 or not any(l.get("probe") == "ok" for l in lines):
        _fallback(f"probe child rc={rc}: {err.strip()[-300:]}")
        return
    probed = next(
        (l.get("device", "") for l in lines if l.get("probe") == "ok"), ""
    )
    if probed != "tpu" and not os.environ.get("AT2_BENCH_PLATFORM"):
        # The backend came up but there is no chip behind it (JAX fell
        # back to host CPU): running the XLA grid there would burn the
        # whole budget on 15-minute-per-shape CPU compiles. Treat as a
        # dead tunnel: re-emit last-good + run the OpenSSL fallback grid.
        _fallback(f"no TPU behind tunnel (probe device={probed!r})")
        return

    # 2) the real bench, streamed: every completed bucket is banked even
    #    if a later one wedges
    buckets: dict = {}
    aux: dict = {}
    device = ""

    def on_line(obj: dict) -> None:
        nonlocal device
        if "bucket" in obj:
            buckets[int(obj["bucket"])] = obj
            device = obj.get("device", device)
        elif obj.get("aux"):
            aux.update(obj)

    rc, _, err = _run_child("--child", TOTAL_TIMEOUT, on_line)
    failure = None
    if rc is None:
        failure = f"bench child exceeded {TOTAL_TIMEOUT}s (tunnel wedged mid-run)"
    elif rc != 0:
        failure = f"bench child rc={rc}: {err.strip()[-300:]}"

    if not buckets:
        _fallback(failure or "bench child produced no bucket results")
        return

    # 3) assemble: prefer the headline bucket, else the best completed one.
    # Every freshly measured row carries its OWN provenance + link state
    # (a partial run banks the rows that finished; a later dead-tunnel
    # round re-emits them with tunnel_live_at_write flipped off per row).
    now = _now_utc()
    rnd = _current_round()
    if HEADLINE_BUCKET in buckets:
        headline = buckets[HEADLINE_BUCKET]
    else:
        headline = max(buckets.values(), key=lambda b: b["pipelined"])
    value = headline["pipelined"]
    grid = {
        str(k): {
            "device_only": v["device_only"],
            "pipelined": v["pipelined"],
            "pipelined_vs_device_pct": round(
                100.0 * v["pipelined"] / v["device_only"], 1
            )
            if v["device_only"]
            else 0.0,
            "captured_at": now,
            "captured_round": rnd,
            "tunnel_live_at_write": True,
        }
        for k, v in sorted(buckets.items())
    }
    result = {
        "metric": "ed25519_verifies_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "sigs/s",
        "vs_baseline": round(value / TARGET_PER_CHIP, 3),
        "device": device,
        "bucket": headline["bucket"],
        "grid": grid,
        "device_only_rate": headline["device_only"],
    }
    if "trace_dir" in headline:
        result["trace_dir"] = headline["trace_dir"]
    # roofline: is the device-only rate 10% or 60% of the chip's vector
    # ceiling? (static op-count model derived from the kernel's own
    # constants — at2_node_tpu/ops/roofline.py documents the counting)
    if device == "tpu":
        try:
            from at2_node_tpu.ops.roofline import model as roofline_model

            result["roofline"] = roofline_model(headline["device_only"])
            # Round-4 trace attribution (.profile_traces/bench_b65536,
            # read in round 5): the 64k kernel ran 129.1 ms in-trace
            # (= 496k sigs/s device-side, 55% of the VPU-bound model);
            # the pipelined-vs-device-only gap was per-batch TUNNEL
            # TRANSFERS (~10 MB packed input up + verdicts down, ~126 ms)
            # serializing with dispatch on one thread — round 5 moved
            # pack+device_put onto the prep workers (two ahead). The
            # remaining model-vs-kernel 45% lives INSIDE the Mosaic
            # kernel (attribution needs an xplane-level read or kernel
            # experiments on chip).
            result["roofline"]["transfer_attribution"] = (
                "r4 trace: kernel 129.1ms/64k batch; pipelined loss was "
                "host->device transfer serialized on the dispatch thread; "
                "r5 uploads on prep workers — compare this run's "
                "pipelined/device_only ratio against r4's 0.527"
            )
        except Exception as exc:  # never silently lose the promised block
            result["roofline"] = {"error": str(exc)[:200]}
    for k in ("host_prep_rate", "cpu_openssl_1core_rate"):
        if k in aux:
            result[k] = aux[k]
    if failure:
        result["partial"] = failure  # some buckets missing, headline banked
    # bank as last-good ONLY for runs on the real chip: a CPU-fallback
    # number must never shadow a TPU capture. Banking is a ROW-LEVEL
    # merge: grid rows an interrupted run did not reach keep their older
    # banked values (with their own captured_at), so one wedged bucket
    # no longer evicts the whole last-good grid.
    if device == "tpu":
        last = _load_lastgood() or {}
        merged_grid = dict(grid)
        for k, row in (last.get("grid") or {}).items():
            if k not in merged_grid and isinstance(row, dict):
                old = dict(row)
                old["tunnel_live_at_write"] = False
                merged_grid[k] = old
        banked = dict(result)
        banked["grid"] = merged_grid
        banked["captured_at"] = now
        banked["captured_round"] = rnd
        banked["tunnel_live_at_write"] = True
        result["captured_at"] = now
        result["captured_round"] = rnd
        result["tunnel_live_at_write"] = True
        try:
            with open(LASTGOOD_PATH, "w") as f:
                json.dump(banked, f, indent=1)
        except OSError:
            pass
    _emit(result)


if __name__ == "__main__":
    if "--probe" in sys.argv:
        probe_main()
    elif "--child" in sys.argv:
        child_main()
    elif "--cpu-child" in sys.argv:
        cpu_child_main()
    else:
        orchestrate()
