"""The roofline model's structural counts must track the kernel."""

from at2_node_tpu.ops import field as fe
from at2_node_tpu.ops import pallas_verify, roofline


def test_counts_track_kernel_constants():
    # the model derives from the same constants the kernel compiles with;
    # if the kernel's window count or limb layout changes, the model must
    # be revisited (this test is the tripwire)
    assert roofline.N_WINDOWS == pallas_verify.N_WINDOWS
    assert roofline.CONV_MULS == fe.N_LIMBS * fe.N_LIMBS
    # ~3.9-4.3k field muls/signature: 2 sqrt decompressions + 64-window
    # Straus + final inversion (SURVEY-era estimate the verdict quotes)
    assert 3500 <= roofline.FMUL_PER_SIG <= 4500


def test_model_shape_and_sanity():
    m = roofline.model(392_298.7)  # round-1 measured device-only rate
    for key in (
        "fmul_per_sig",
        "int32_ops_per_sig",
        "achieved_int32_tops",
        "vpu_peak_int32_tops",
        "roofline_pct",
        "vpu_bound_sigs_per_sec",
        "hbm_bound_sigs_per_sec",
    ):
        assert key in m
    assert 0 < m["roofline_pct"] < 100
    # the kernel is compute-bound by orders of magnitude: 130 bytes of
    # traffic against ~4.3M int32 ops per signature
    assert m["compute_vs_memory_bound_ratio"] > 1000
    # rate scales linearly with the model (tolerance absorbs rounding)
    assert (
        abs(roofline.model(2 * 392_298.7)["roofline_pct"] - 2 * m["roofline_pct"])
        < 0.2
    )
