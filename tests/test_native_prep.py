"""Native (C++) batch-prep library: differential tests against hashlib and
the pure-Python prepare_batch (the contract reference).

The library builds on first use with the system g++; if that fails the
whole framework transparently uses the Python path, so these tests skip
rather than fail when no toolchain is present.
"""

import hashlib
import random

import numpy as np
import pytest

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.ops import ed25519 as kernel
from at2_node_tpu.native.prep import (
    mod_l_native,
    native_available,
    prep_batch_native,
    sha512_native,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native prep library unavailable"
)

RNG = random.Random(0xBEEF)


def test_sha512_differential():
    for n in (0, 1, 63, 64, 111, 112, 127, 128, 129, 255, 4096):
        data = RNG.randbytes(n)
        assert sha512_native(data) == hashlib.sha512(data).digest()


def test_mod_l_differential():
    L = kernel.L
    cases = [0, 1, L - 1, L, L + 1, 2 * L, 2 * L - 1, (1 << 512) - 1,
             1 << 252, (1 << 252) - 1, (1 << 448), (1 << 448) - 1]
    cases += [RNG.getrandbits(512) for _ in range(2000)]
    cases += [RNG.getrandbits(bits) for bits in range(0, 512, 7)]
    for v in cases:
        assert mod_l_native(v.to_bytes(64, "little")) == v % L


def test_prep_batch_matches_python():
    kp = SignKeyPair.from_hex("77" * 32)
    n = 200
    msgs = [b"prep parity %d" % i for i in range(n)]
    sigs = [kp.sign(m) for m in msgs]
    pks = [kp.public] * n
    # malformed/edge lanes
    pks[1] = pks[1][:31]
    sigs[2] = sigs[2][:63]
    s = int.from_bytes(sigs[3][32:], "little")
    sigs[3] = sigs[3][:32] + (s + kernel.L).to_bytes(32, "little")
    sigs[4] = sigs[4][:32] + (kernel.L - 1).to_bytes(32, "little")  # in range
    msgs[5] = b""

    py = kernel.prepare_batch_py(pks, msgs, sigs, 256)
    nat = prep_batch_native(pks, msgs, sigs, 256)
    for p, q, name in zip(py, nat, ("a", "r", "s", "h", "valid")):
        assert np.array_equal(p, q), name


def test_prep_batch_variable_length_messages():
    kp = SignKeyPair.from_hex("78" * 32)
    msgs = [RNG.randbytes(RNG.randrange(0, 300)) for _ in range(50)]
    sigs = [kp.sign(m) for m in msgs]
    pks = [kp.public] * 50
    py = kernel.prepare_batch_py(pks, msgs, sigs, 64)
    nat = prep_batch_native(pks, msgs, sigs, 64)
    for p, q in zip(py, nat):
        assert np.array_equal(p, q)
