"""Cross-process observability: the obs shipping lane (ISSUE 18).

The contract being pinned: moving a plane shard into a worker PROCESS
must not blind the diagnosis tier. Each worker runs its own registry
slice (PhaseAccounting, FlightRecorder, TxTrace stamps, StackSampler)
and ships compact DELTA records over a dedicated per-shard obs ring;
the owner folds them into the one registry every surface reads.

* record fidelity — phase ns + histogram deltas, recorder events, and
  trace stamps survive the ring byte-exact: the owner's folded counters
  equal the worker's in-process numbers (the same equivalence
  thread-mode ``shard_view`` provides for free);
* drop accounting — a full obs ring sheds records into the ring's drop
  counter and never corrupts what DID ship; observability loss is
  survivable and visible (``obs_records_dropped``), never fatal;
* crash forensics — on worker death the owner drains the dead shard's
  obs ring post-mortem and attaches the recorder-event tail + last
  phase snapshot to the ``plane_worker_crash`` snapshot, which rides
  /debugz into incident bundles unchanged;
* executor invariance — the sim campaign hash is identical with the
  full obs tier enabled at executor=process (the sim clock forces
  inline execution, so the lane never engages under determinism).
"""

import asyncio
import itertools
import json
import os
import struct
from types import SimpleNamespace

import pytest

from at2_node_tpu.broadcast.shards import ShardedPlane
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.node.config import PlaneConfig
from at2_node_tpu.node.service import Service
from at2_node_tpu.obs.profiler import (
    PHASE_BOUNDS,
    PHASES,
    PLANE_LEAF_PHASES,
    PhaseAccounting,
)
from at2_node_tpu.obs.recorder import FlightRecorder
from at2_node_tpu.obs.registry import Registry
from at2_node_tpu.obs.trace import TxTrace
from at2_node_tpu.parallel import plane_worker as pw
from at2_node_tpu.parallel.ring import ShmRing
from at2_node_tpu.sim.campaign import run_episode
from at2_node_tpu.types import ThinTransaction

from conftest import make_net_configs, wait_until

_ports = itertools.count(29400)
_ring_ids = itertools.count()


def _obs_spec(**kw):
    """The WorkerSpec fields _WorkerObs actually reads — a unit-test
    stand-in for the full picklable spec."""
    base = dict(
        recorder_cap=256,
        trace_sample=1,
        phase_accounting=True,
        profiler_hz=97.0,
        profiler_max_nodes=1000,
        obs_flush_s=0.005,
    )
    base.update(kw)
    return SimpleNamespace(**base)


def _owner_plane(shards=1):
    """An inline ShardedPlane wearing the full owner-side obs kit: the
    fold path (_apply_obs_record) is pure in (sid, kind, payload) and
    does not need live worker processes."""
    reg = Registry()
    plane = ShardedPlane(
        SignKeyPair.random(),
        SimpleNamespace(peers=[], by_sign={}),
        None,
        shards=shards,
        executor="inline",
        registry=reg,
        phases=PhaseAccounting(reg),
        trace=TxTrace(reg, sample_every=1),
        recorder=FlightRecorder(cap=256),
    )
    return plane, reg


def _fresh_ring(slots=256, slot_bytes=1024):
    return ShmRing(
        f"at2obs-test-{os.getpid()}-{next(_ring_ids)}",
        slots=slots,
        slot_bytes=slot_bytes,
        create=True,
    )


def _apply_all(plane, ring, sid=0):
    recs, _ = ring.drain()
    for kind, payload in recs:
        plane._apply_obs_record(sid, kind, payload)
    return len(recs)


def make_payload(keypair, seq=1, amount=10, recipient=b"r" * 32):
    from at2_node_tpu.broadcast.messages import Payload

    return Payload.create(keypair, seq, ThinTransaction(recipient, amount))


# ---------------------------------------------------------------------------
# record fidelity units: worker slice -> ring -> owner fold


class TestObsRecordsOverRing:
    def test_phase_fold_matches_worker_numbers(self):
        """The process-mode equivalent of thread-mode shard_view: after
        the fold, base leaf counters AND the shardN counters carry
        exactly the ns the worker accounted, histograms merge count/sum
        exactly, and the worker's plane_total lands ONLY under its
        shardN name (different denominator, summed by profile_collect)."""
        ring = _fresh_ring()
        try:
            obs = pw._WorkerObs(_obs_spec(), ring)
            marks = {p: (i + 1) * 1_000_000 for i, p in
                     enumerate(PLANE_LEAF_PHASES)}
            for p, ns in marks.items():
                obs.phases.add_ns(p, ns)
            obs.phases.add_ns("plane_total", 99_000_000)
            obs.phases.add_ns("slot_gc", 7_000_000)
            obs.flush()

            plane, reg = _owner_plane()
            assert _apply_all(plane, ring) >= 1
            for p, ns in marks.items():
                assert reg.counter(f"phase_{p}_ns").value == ns
                assert reg.counter(f"phase_{p}_shard0_ns").value == ns
                counts, total_s, count, _mx = reg.histogram(
                    f"phase_{p}", bounds=PHASE_BOUNDS
                ).raw()
                assert count == 1 and sum(counts) == 1
                assert total_s == pytest.approx(ns * 1e-9)
            # slot_gc is not a plane leaf: base only, no shard counter
            assert reg.counter("phase_slot_gc_ns").value == 7_000_000
            assert "phase_slot_gc_shard0_ns" not in reg.snapshot()
            # plane_total: shardN only — the worker drain-cycle span
            # must never inflate the owner's own plane_total
            assert reg.counter("phase_plane_total_ns").value == 0
            assert (
                reg.counter("phase_plane_total_shard0_ns").value
                == 99_000_000
            )
        finally:
            ring.close()

    def test_second_flush_ships_only_deltas(self):
        """Records are DELTAS: a second flush after more marks must add
        exactly the increment, not re-ship the cumulative totals."""
        ring = _fresh_ring()
        try:
            obs = pw._WorkerObs(_obs_spec(), ring)
            plane, reg = _owner_plane()
            obs.phases.add_ns("verify_wait", 5_000_000)
            obs.flush()
            _apply_all(plane, ring)
            obs.phases.add_ns("verify_wait", 3_000_000)
            obs.flush()
            _apply_all(plane, ring)
            assert reg.counter("phase_verify_wait_ns").value == 8_000_000
            assert (
                reg.counter("phase_verify_wait_shard0_ns").value
                == 8_000_000
            )
            _c, _s, count, _m = reg.histogram(
                "phase_verify_wait", bounds=PHASE_BOUNDS
            ).raw()
            assert count == 2
            # an idle flush (nothing changed) ships no phase record
            before = len(ring.drain()[0])
            obs.flush()
            phase_recs = [
                k for k, _ in ring.drain()[0] if k == pw.O_PHASE
            ]
            assert before == 0 and phase_recs == []
        finally:
            ring.close()

    def test_recorder_events_survive_with_shard_prefix(self):
        ring = _fresh_ring()
        try:
            obs = pw._WorkerObs(_obs_spec(), ring)
            obs.recorder.record("echo", (7,))
            obs.recorder.record("ready_quorum", (7,))
            obs.flush()
            plane, _reg = _owner_plane(shards=2)
            _apply_all(plane, ring, sid=1)
            events = plane.worker_events()
            codes = [e[1] for e in events]
            assert codes == ["shard1/echo", "shard1/ready_quorum"]
            # mono timestamps preserved and sorted
            assert events == sorted(events, key=lambda e: e[0])
            # only NEW events ship on the next flush
            obs.recorder.record("stall_kick", ())
            obs.flush()
            _apply_all(plane, ring, sid=1)
            assert [e[1] for e in plane.worker_events()] == [
                "shard1/echo",
                "shard1/ready_quorum",
                "shard1/stall_kick",
            ]
        finally:
            ring.close()

    def test_trace_stamps_replay_on_owner_tracer(self):
        """A worker stage stamp must materialize in the owner's TxTrace
        as a relay-open record at the worker's mono timestamp — the
        exact behavior thread-mode cores get by sharing the tracer."""
        ring = _fresh_ring()
        try:
            obs = pw._WorkerObs(_obs_spec(), ring)
            sender = b"\xab" * 32
            obs.trace.stamp((sender, 3), "delivered", now=123.25)
            obs.flush()
            plane, _reg = _owner_plane()
            _apply_all(plane, ring)
            rec = plane.trace._live.get((sender, 3))
            assert rec is not None
            stages = {s for s, _m, _w in rec[3]}
            assert "delivered" in stages
            mono = [m for s, m, _w in rec[3] if s == "delivered"]
            assert mono == [pytest.approx(123.25)]
        finally:
            ring.close()

    def test_trace_lottery_matches_owner_sampling(self):
        """At sample_every=N the worker applies the SAME keyed lottery
        the owner tracer uses, so shipped stamps are exactly the ones
        the owner would have kept."""
        obs = pw._WorkerObs(_obs_spec(trace_sample=4), ring=None)
        kept = []
        for seq in range(32):
            sender = bytes([seq % 7]) * 32
            obs.trace.stamp((sender, seq), "echoed", now=1.0)
        for sender, seq, _idx, _mono in obs.trace.buf:
            kept.append((sender[0] + seq) % 4)
        assert kept and set(kept) == {0}

    def test_ring_wrap_drops_counted_not_fatal(self):
        """put-never-blocks: a tiny obs ring under a burst sheds records
        into the drop counter; everything that DID ship still folds
        cleanly on the owner."""
        ring = _fresh_ring(slots=8, slot_bytes=256)
        try:
            obs = pw._WorkerObs(_obs_spec(), ring)
            for i in range(64):
                obs.recorder.record("echo", (i, "x" * 40))
                obs.flush()
            assert ring.dropped > 0
            plane, _reg = _owner_plane()
            applied = _apply_all(plane, ring)
            assert applied > 0
            assert plane.worker_events()  # survivors folded fine
        finally:
            ring.close()

    def test_unknown_phase_idx_is_shed(self):
        """Vocabulary drift (a worker from a newer build naming a phase
        this owner doesn't know) sheds the entry instead of crashing the
        flusher."""
        plane, reg = _owner_plane()
        nb = len(PHASE_BOUNDS) + 1
        payload = pw._ophase.pack(250, 1_000_000, 1, 0.001, 0.001)
        payload += struct.pack(f"<{nb}I", *([1] + [0] * (nb - 1)))
        plane._apply_obs_record(0, pw.O_PHASE, payload)
        snap = reg.snapshot()
        # nothing folded anywhere: every phase counter (the inline
        # cores' shard_view pre-creates the shardN names at zero) stays
        # untouched
        assert not any(
            v for k, v in snap.items()
            if k.startswith("phase_") and k.endswith("_ns")
        )

    def test_fold_records_accumulate_samples(self):
        """O_FOLD records are additive increments (the worker resets its
        sampler after each ship): stacks sum, samples sum."""
        payload = (5).to_bytes(8, "little") + b"a;b 3\nc 2"
        plane, _reg = _owner_plane()
        plane._apply_obs_record(0, pw.O_FOLD, payload)
        plane._apply_obs_record(0, pw.O_FOLD, payload)
        assert plane.worker_fold_samples() == 10
        folds = dict(plane.worker_folds())
        assert folds["shard0/"] == {"a;b": 6, "c": 4}


# ---------------------------------------------------------------------------
# end-to-end: live process fleet, surfaces see through the boundary


class TestProcessObsE2E:
    @pytest.mark.asyncio
    async def test_surfaces_see_through_process_boundary(self):
        """One process-mode fleet, four assertions the satellites hang
        off: (1) worker leaf phases (verify_wait included) fold into the
        shardN counters /statusz exports, (2) /debugz interleaves worker
        recorder events by mono time, (3) the fanned-out profiler merges
        shardN/-prefixed worker frames, (4) a crashed worker's snapshot
        carries the post-mortem obs drain into incident bundles."""
        from at2_node_tpu.tools.incident import build_bundle

        cfgs = make_net_configs(
            3, _ports, plane=PlaneConfig(shards=2, executor="process")
        )
        services = [await Service.start(c) for c in cfgs]
        try:
            victim = services[0]
            assert victim.broadcast._obs_ship

            senders = [SignKeyPair.random() for _ in range(4)]
            n_tx = 0
            for sender in senders:
                for seq in (1, 2):
                    await services[0].broadcast.broadcast(
                        make_payload(sender, seq=seq)
                    )
                    n_tx += 1

            async def all_committed():
                return all(s.committed >= n_tx for s in services)

            await wait_until(
                all_committed, timeout=60.0,
                what="commits through the process plane",
            )

            # (1) worker phase accounting folded under shardN names; the
            # verify term runs INSIDE the workers and must be attributed
            async def phases_folded():
                st = victim.snapshot_stats()
                return all(
                    sum(
                        st.get(f"phase_{p}_shard{k}_ns", 0)
                        for k in range(2)
                    ) > 0
                    for p in ("verify_wait", "rx_decode", "ready_deliver")
                )

            await wait_until(
                phases_folded, timeout=15.0,
                what="worker phase deltas fold into shardN counters",
            )
            st = victim.snapshot_stats()
            assert st.get("obs_records_dropped", -1) == 0
            assert (
                sum(
                    st.get(f"phase_plane_total_shard{k}_ns", 0)
                    for k in range(2)
                ) > 0
            )

            # (2) /debugz interleaves worker recorder events by mono t
            async def worker_events_seen():
                rec = victim.debugz()["recorder"]
                return rec.get("worker_events", 0) > 0

            await wait_until(
                worker_events_seen, timeout=15.0,
                what="worker recorder events reach /debugz",
            )
            dump = victim.debugz()["recorder"]
            shard_events = [
                e for e in dump["events"]
                if str(e[1]).startswith("shard")
            ]
            assert shard_events
            ts = [e[0] for e in dump["events"]]
            assert ts == sorted(ts)

            # (3) profiler fan-out: merged folded output carries worker
            # frames under their shardN/ prefix
            plane = victim._plane_obs()
            assert plane is not None and plane.profiler_start()
            deadline_tx = n_tx
            for seq in (3, 4):
                for sender in senders:
                    await services[0].broadcast.broadcast(
                        make_payload(sender, seq=seq)
                    )
                    deadline_tx += 1
            await asyncio.sleep(1.2)
            assert plane.profiler_stop()

            async def folds_shipped():
                return plane.worker_fold_samples() > 0

            await wait_until(
                folds_shipped, timeout=15.0,
                what="worker folded-stack increments ship",
            )
            merged = victim._merged_folded(plane, None)
            assert any(
                line.startswith("shard") for line in merged.splitlines()
            )

            # (4) crash forensics: post-mortem drain + snapshot extra,
            # riding /debugz into a deterministic incident bundle
            victim.broadcast._executor.actions[0].put(
                pw.C_EXIT, bytes([7])
            )

            async def crash_seen():
                return victim.broadcast.worker_crashed == {0: 7}

            await wait_until(
                crash_seen, timeout=30.0,
                what="owner detects the dead worker",
            )
            snaps = [
                s for s in victim.recorder.dump()["snapshots"]
                if s["reason"].startswith("plane_worker_crash:shard=0")
            ]
            assert snaps
            extra = snaps[-1].get("extra")
            assert extra is not None
            assert extra["shard"] == 0 and extra["exit"] == 7
            assert extra["recorder_tail"], "post-mortem tail empty"
            assert any(
                p in extra["phases"] for p in PLANE_LEAF_PHASES
            )
            bundle = build_bundle(
                {"nodes": {"n0:1": {"debugz": victim.debugz()}}},
                reason="test",
            )
            blob = bundle["files"]["n0_1/debugz.json"]
            assert b"plane_worker_crash:shard=0" in blob
            assert b"recorder_tail" in blob
        finally:
            for s in services:
                await s.close()
        # clean shutdown unlinks the obs rings with the others
        for svc in services:
            ex = svc.broadcast._executor
            assert ex.actions == [] and ex.effects == [] and ex.obs == []


# ---------------------------------------------------------------------------
# determinism: the obs lane must not observe-ably exist under the sim


class TestExecutorHashWithObs:
    def test_campaign_hash_invariant_with_obs_tier_on(self):
        """The sim forces inline execution under a non-system clock, so
        the obs shipping lane never engages and the campaign hash stays
        executor-invariant WITH the full observability tier enabled
        (the sim default) — the same seam TestExecutorHashSweep pins,
        re-asserted here because this PR grew what executor=process
        would otherwise do."""
        kw = dict(n_events=6, duration=5.0, settle_horizon=40.0)
        mono = run_episode(3, **kw)
        assert mono.violations == []
        proc = run_episode(
            3,
            config_overrides={
                "plane_shards": 2,
                "plane_executor": "process",
            },
            **kw,
        )
        assert proc.violations == []
        assert proc.trace_hash == mono.trace_hash
