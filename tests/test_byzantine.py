"""Live byzantine NODE test (SURVEY.md §7 hard part 3, round-3 verdict
item 7): a real hostile peer speaking the real encrypted transport
against a net of real `Service` processes.

The broadcast fuzz tier covers equivocation at the state-machine and
schedule level (tests/test_broadcast_fuzz.py); here the adversary is a
live wire-level participant: it is listed in every correct node's config
(so its channels authenticate and its attestations verify — byzantine,
not Sybil), dials them over the real X25519/ChaCha20 transport, and

* gossips two CONFLICTING client-signed payloads for one slot to
  different subsets of the net (a double-spend attempt by an
  equivocating client, amplified by a colluding node);
* double-echoes: votes content A to some peers and content B to others
  for the same slot — the attack sieve's per-origin single-vote rule
  exists for;
* replays its own signed attestations verbatim (dedup must absorb);
* sends Ready votes for a fabricated content hash on a fresh slot.

With n=4 and f=1-tolerant thresholds (echo=ready=2 of 3 peers), two
conflicting quorums would have to share a correct voter, so the correct
trio must agree on at most ONE committed content for the equivocated
slot — and must keep committing honest traffic throughout (the quorums
are reachable from the 2 correct peers alone).

The reference never exercises its stack against a byzantine peer (its
full-quorum config sidesteps faults entirely — rpc.rs:112-120); this
build's thresholds are configurable, so the tolerance is testable.
"""

import asyncio
import itertools

import pytest

from at2_node_tpu.broadcast.messages import ECHO, READY, Attestation, Payload
from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.net import transport
from at2_node_tpu.net.peers import Peer
from at2_node_tpu.node.config import Config
from at2_node_tpu.node.service import Service
from at2_node_tpu.types import ThinTransaction

TICK = 0.1
TIMEOUT = 15.0

_ports = itertools.count(22600)

FAUCET = 100_000


def make_configs(n, **kwargs):
    cfgs = [
        Config(
            node_address=f"127.0.0.1:{next(_ports)}",
            rpc_address=f"127.0.0.1:{next(_ports)}",
            sign_key=SignKeyPair.random(),
            network_key=ExchangeKeyPair.random(),
            **kwargs,
        )
        for _ in range(n)
    ]
    for i, cfg in enumerate(cfgs):
        cfg.nodes = [
            Peer(o.node_address, o.network_key.public, o.sign_key.public)
            for j, o in enumerate(cfgs)
            if j != i
        ]
    return cfgs


async def wait_until(pred, timeout=TIMEOUT, what="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if await pred():
            return
        await asyncio.sleep(TICK)
    raise TimeoutError(f"{what} not reached within {timeout}s")


class _HostileNode:
    """The byzantine participant: authenticated channels to every correct
    node, crafted frames instead of a broadcast state machine."""

    def __init__(self, config: Config):
        self.sign = config.sign_key
        self.network = config.network_key
        self.channels = {}

    async def dial(self, cfgs):
        for i, cfg in enumerate(cfgs):
            host, _, port = cfg.node_address.rpartition(":")
            self.channels[i] = await transport.connect(
                host, int(port), self.network
            )

    async def send(self, node: int, *msgs) -> None:
        await self.channels[node].send(b"".join(m.encode() for m in msgs))

    def attest(self, phase, sender, sequence, chash) -> Attestation:
        sig = self.sign.sign(
            Attestation.signing_bytes(phase, sender, sequence, chash)
        )
        return Attestation(phase, self.sign.public, sender, sequence, chash, sig)

    def close(self):
        for ch in self.channels.values():
            ch.close()


class TestByzantineNode:
    @pytest.mark.asyncio
    async def test_equivocation_double_echo_replay_fabricated_ready(self):
        cfgs = make_configs(4, echo_threshold=2, ready_threshold=2)
        services = [await Service.start(c) for c in cfgs[:3]]
        hostile = _HostileNode(cfgs[3])
        equivocator = SignKeyPair.random()
        r1 = SignKeyPair.random().public
        r2 = SignKeyPair.random().public
        honest = SignKeyPair.random()
        honest_rcpt = SignKeyPair.random().public
        try:
            await hostile.dial(cfgs[:3])

            # -- attack 1: client equivocation amplified by the hostile
            # node: conflicting payloads for slot (equivocator, 1)
            tx_a = ThinTransaction(r1, 10)
            tx_b = ThinTransaction(r2, 99)
            pay_a = Payload(
                equivocator.public, 1, tx_a,
                equivocator.sign(tx_a.signing_bytes()),
            )
            pay_b = Payload(
                equivocator.public, 1, tx_b,
                equivocator.sign(tx_b.signing_bytes()),
            )
            await hostile.send(0, pay_a)
            await hostile.send(1, pay_a)
            await hostile.send(2, pay_b)

            # -- attack 2: double-echo — A to nodes 0/1, B to node 2
            echo_a = hostile.attest(
                ECHO, equivocator.public, 1, pay_a.content_hash()
            )
            echo_b = hostile.attest(
                ECHO, equivocator.public, 1, pay_b.content_hash()
            )
            await hostile.send(0, echo_a)
            await hostile.send(1, echo_a)
            await hostile.send(2, echo_b)

            # -- attack 3: replay the same signed attestation verbatim
            for _ in range(3):
                await hostile.send(0, echo_a)

            # -- attack 4: Ready votes for a fabricated content on a
            # fresh slot (equivocator, 2) nobody gossiped
            fake_ready = hostile.attest(READY, equivocator.public, 2, b"\x42" * 32)
            for i in range(3):
                await hostile.send(i, fake_ready)

            # -- liveness: honest traffic keeps committing on the trio
            # (quorums must be reachable without the byzantine node)
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset(honest, 1, honest_rcpt, 25)

                async def honest_committed():
                    for s in services:
                        if await s.accounts.get_last_sequence(honest.public) < 1:
                            return False
                    return True

                await wait_until(honest_committed, what="honest tx on trio")

                # give the equivocated slot time to settle network-wide
                async def slot_settled():
                    for s in services:
                        if await s.accounts.get_last_sequence(
                            equivocator.public
                        ) < 1:
                            return False
                    return True

                await wait_until(slot_settled, what="equivocated slot settles")

            # -- safety: the correct trio agrees on ONE committed content
            seqs = {
                await s.accounts.get_last_sequence(equivocator.public)
                for s in services
            }
            assert seqs == {1}, seqs
            bal_r1 = {await s.accounts.get_balance(r1) for s in services}
            bal_r2 = {await s.accounts.get_balance(r2) for s in services}
            assert len(bal_r1) == 1 and len(bal_r2) == 1, (bal_r1, bal_r2)
            # exactly one of the conflicting transfers committed — and
            # with these thresholds content A deterministically wins (B
            # can collect at most 1 echo vote at any correct node)
            assert bal_r1 == {FAUCET + 10}, bal_r1
            assert bal_r2 == {FAUCET}, bal_r2
            # the fabricated-content slot never commits anywhere
            for s in services:
                assert (
                    await s.accounts.get_last_sequence(equivocator.public) == 1
                )
            # honest transfer landed everywhere
            for s in services:
                assert await s.accounts.get_balance(honest_rcpt) == FAUCET + 25
        finally:
            hostile.close()
            for s in services:
                await s.close()
