"""Live byzantine NODE test (SURVEY.md §7 hard part 3, round-3 verdict
item 7): a real hostile peer speaking the real encrypted transport
against a net of real `Service` processes.

The broadcast fuzz tier covers equivocation at the state-machine and
schedule level (tests/test_broadcast_fuzz.py); here the adversary is a
live wire-level participant: it is listed in every correct node's config
(so its channels authenticate and its attestations verify — byzantine,
not Sybil), dials them over the real X25519/ChaCha20 transport, and

* gossips two CONFLICTING client-signed payloads for one slot to
  different subsets of the net (a double-spend attempt by an
  equivocating client, amplified by a colluding node);
* double-echoes: votes content A to some peers and content B to others
  for the same slot — the attack sieve's per-origin single-vote rule
  exists for;
* replays its own signed attestations verbatim (dedup must absorb);
* sends Ready votes for a fabricated content hash on a fresh slot.

Threshold math (this build counts votes over PEERS, self excluded —
broadcast/stack.py module docstring): for two correct nodes to deliver
CONFLICTING contents, each needs an echo quorum of t among its
n_peers = n-1 peers, and the two vote sets intersect in at least
2t - (n-1) peers; every correct peer echoes ONE content to everyone, so
each shared voter backing both quorums must be byzantine. Safety against
f byzantine therefore needs 2t - (n-1) > f. With n=5, t=3, f=1:
intersection >= 2 > 1 — equivocation cannot double-commit. Liveness
needs t reachable from correct peers alone: each correct node has
(n-1) - f = 3 = t correct peers. (A 4-node/t=2 config would NOT be
f=1-safe here: 2t - 3 = 1 quorum overlap can be exactly the byzantine
double-voter — one node more than classic BFT is the price of
self-excluded counting.)

The reference never exercises its stack against a byzantine peer (its
full-quorum config sidesteps faults entirely — rpc.rs:112-120); this
build's thresholds are configurable, so the tolerance is testable.
"""

import itertools

import pytest

from at2_node_tpu.broadcast.messages import ECHO, READY, Attestation, Payload
from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.net import transport
from at2_node_tpu.node.config import Config
from at2_node_tpu.node.service import Service
from at2_node_tpu.types import ThinTransaction

from conftest import make_net_configs, wait_until

_ports = itertools.count(22600)

FAUCET = 100_000


class _HostileNode:
    """The byzantine participant: authenticated channels to every correct
    node, crafted frames instead of a broadcast state machine."""

    def __init__(self, config: Config):
        self.sign = config.sign_key
        self.network = config.network_key
        self.channels = {}

    async def dial(self, cfgs):
        for i, cfg in enumerate(cfgs):
            host, _, port = cfg.node_address.rpartition(":")
            self.channels[i] = await transport.connect(
                host, int(port), self.network
            )

    async def send(self, node: int, *msgs) -> None:
        await self.channels[node].send(b"".join(m.encode() for m in msgs))

    def attest(self, phase, sender, sequence, chash) -> Attestation:
        sig = self.sign.sign(
            Attestation.signing_bytes(phase, sender, sequence, chash)
        )
        return Attestation(phase, self.sign.public, sender, sequence, chash, sig)

    def close(self):
        for ch in self.channels.values():
            ch.close()


class TestByzantineNode:
    @pytest.mark.asyncio
    async def test_equivocation_double_echo_replay_fabricated_ready(self):
        # n=5, f=1: 4 correct Services + the hostile node, thresholds 3
        # (see module docstring for why 3-of-4-peers is the f=1-safe
        # configuration under self-excluded vote counting)
        cfgs = make_net_configs(5, _ports, echo_threshold=3, ready_threshold=3)
        services = [await Service.start(c) for c in cfgs[:4]]
        hostile = _HostileNode(cfgs[4])
        equivocator = SignKeyPair.random()
        r1 = SignKeyPair.random().public
        r2 = SignKeyPair.random().public
        honest = SignKeyPair.random()
        honest_rcpt = SignKeyPair.random().public
        try:
            await hostile.dial(cfgs[:4])

            # -- attack 1: client equivocation amplified by the hostile
            # node: conflicting payloads for slot (equivocator, 1) —
            # A to nodes 0-2, B to node 3
            tx_a = ThinTransaction(r1, 10)
            tx_b = ThinTransaction(r2, 99)
            pay_a = Payload.create(equivocator, 1, tx_a)
            pay_b = Payload.create(equivocator, 1, tx_b)
            for i in range(3):
                await hostile.send(i, pay_a)
            await hostile.send(3, pay_b)

            # -- attack 2: double-echo — A to nodes 0/1, B to nodes 2/3
            echo_a = hostile.attest(
                ECHO, equivocator.public, 1, pay_a.content_hash()
            )
            echo_b = hostile.attest(
                ECHO, equivocator.public, 1, pay_b.content_hash()
            )
            await hostile.send(0, echo_a)
            await hostile.send(1, echo_a)
            await hostile.send(2, echo_b)
            await hostile.send(3, echo_b)

            # -- attack 3: replay the same signed attestation verbatim
            for _ in range(3):
                await hostile.send(0, echo_a)

            # -- attack 4: Ready votes for a fabricated content on a
            # fresh slot (equivocator, 2) nobody gossiped — one origin's
            # vote stays far below the ready threshold
            fake_ready = hostile.attest(READY, equivocator.public, 2, b"\x42" * 32)
            for i in range(4):
                await hostile.send(i, fake_ready)

            # -- liveness: honest traffic keeps committing on the
            # correct nodes (echo quorum 3 = the 3 correct peers each
            # node has; the byzantine node contributes nothing)
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset(honest, 1, honest_rcpt, 25)

                async def honest_committed():
                    for s in services:
                        if await s.accounts.get_last_sequence(honest.public) < 1:
                            return False
                    return True

                await wait_until(honest_committed, what="honest tx on correct nodes")

                # the equivocated slot settles: content A deterministically
                # wins (B's echo votes at any correct node top out at
                # {node3, hostile} = 2 < 3, while A gathers the other
                # three correct echoes everywhere; node3 itself
                # sieve-delivers A from {node0,node1,node2} and the Ready
                # quorum {node0,node1,node2,node3} amplifies the rest)
                async def slot_settled():
                    for s in services:
                        if await s.accounts.get_last_sequence(
                            equivocator.public
                        ) < 1:
                            return False
                    return True

                await wait_until(slot_settled, what="equivocated slot settles")

            # -- safety: every correct node committed the SAME content
            seqs = {
                await s.accounts.get_last_sequence(equivocator.public)
                for s in services
            }
            assert seqs == {1}, seqs
            for s in services:
                assert await s.accounts.get_balance(r1) == FAUCET + 10
                assert await s.accounts.get_balance(r2) == FAUCET
                assert await s.accounts.get_balance(honest_rcpt) == FAUCET + 25
            # the fabricated-content slot (equivocator, 2) was never
            # DELIVERED anywhere: each node delivered exactly the honest
            # slot and the equivocated slot
            for s in services:
                assert s.broadcast.stats["delivered"] == 2, (
                    s.broadcast.stats
                )
        finally:
            hostile.close()
            for s in services:
                await s.close()
