"""CLI end-to-end tests: real `server` / `client` processes, TOML over
stdin, output over stdout — the reference's integration tier
(`/root/reference/tests/cli.rs`) and shell tier
(`/root/reference/tests/lib.sh`) translated to this build's binaries.

Network bootstrap follows the reference operator workflow exactly
(`cli.rs:162-208`): generate one config per node, append every OTHER
node's `config get-node` fragment, spawn `server run` with the config on
stdin, wait for the ports to accept connections, then drive everything
through the `client` CLI.
"""

import itertools
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVER = [sys.executable, "-m", "at2_node_tpu.cli.server"]
CLIENT = [sys.executable, "-m", "at2_node_tpu.cli.client"]

# reference's polling budget: cli.rs:24-25
TICK = 0.1
TIMEOUT = 30.0  # interpreter startup is slower than a Rust binary

_ports = itertools.count(21000)


def run_cli(argv, stdin=None, check=True):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        argv, input=stdin, capture_output=True, text=True, env=env, timeout=60
    )
    if check and proc.returncode != 0:
        raise AssertionError(f"{argv} failed: {proc.stderr}")
    return proc


def wait_for_port(port, timeout=TIMEOUT):
    # cli.rs:119-131 wait_until_connect
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            time.sleep(TICK)
    raise TimeoutError(f"port {port} never came up")


class ServerProcess:
    def __init__(self, config, node_port, rpc_port):
        self.config = config
        self.node_port = node_port
        self.rpc_port = rpc_port
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        self.proc = subprocess.Popen(
            SERVER + ["run"],
            stdin=subprocess.PIPE,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        self.proc.stdin.write(config)
        self.proc.stdin.close()

    def stop(self):
        # SIGTERM-then-kill, cli.rs:43-68
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()


def start_network(n):
    ports = [(next(_ports), next(_ports)) for _ in range(n)]
    configs = [
        run_cli(
            SERVER + ["config", "new", f"127.0.0.1:{np}", f"127.0.0.1:{rp}"]
        ).stdout
        for np, rp in ports
    ]
    fragments = [
        run_cli(SERVER + ["config", "get-node"], stdin=cfg).stdout for cfg in configs
    ]
    servers = []
    for i, ((np, rp), cfg) in enumerate(zip(ports, configs)):
        full = cfg + "\n" + "\n".join(f for j, f in enumerate(fragments) if j != i)
        servers.append(ServerProcess(full, np, rp))
    for np, rp in ports:
        wait_for_port(np)
        wait_for_port(rp)
    return servers


@pytest.fixture
def network_3():
    servers = start_network(3)
    yield servers
    for s in servers:
        s.stop()


def new_wallet(rpc_port):
    return run_cli(CLIENT + ["config", "new", f"http://127.0.0.1:{rpc_port}"]).stdout


def wallet_pubkey(wallet):
    return run_cli(CLIENT + ["config", "get-public-key"], stdin=wallet).stdout.strip()


def get_balance(wallet):
    return int(run_cli(CLIENT + ["get-balance"], stdin=wallet).stdout)


def get_last_sequence(wallet):
    return int(run_cli(CLIENT + ["get-last-sequence"], stdin=wallet).stdout)


def wait_for_sequence(wallet, seq):
    # lib.sh:92-101
    deadline = time.monotonic() + TIMEOUT
    while time.monotonic() < deadline:
        if get_last_sequence(wallet) == seq:
            return
        time.sleep(TICK)
    raise TimeoutError(f"sequence {seq} not reached")


class TestConfigPlumbing:
    def test_server_config_roundtrip(self):
        cfg = run_cli(SERVER + ["config", "new", "127.0.0.1:1", "127.0.0.1:2"]).stdout
        fragment = run_cli(SERVER + ["config", "get-node"], stdin=cfg).stdout
        assert '[[nodes]]' in fragment
        assert 'address = "127.0.0.1:1"' in fragment

    def test_client_config_roundtrip(self):
        wallet = new_wallet(9)
        pubkey = wallet_pubkey(wallet)
        assert len(bytes.fromhex(pubkey)) == 32

    def test_double_bind_fails(self):
        # cli.rs:133-160: second server on the same ports must exit nonzero
        np, rp = next(_ports), next(_ports)
        cfg = run_cli(
            SERVER + ["config", "new", f"127.0.0.1:{np}", f"127.0.0.1:{rp}"]
        ).stdout
        first = ServerProcess(cfg, np, rp)
        try:
            wait_for_port(np)
            cfg2 = run_cli(
                SERVER + ["config", "new", f"127.0.0.1:{np}", f"127.0.0.1:{rp}"]
            ).stdout
            second = ServerProcess(cfg2, np, rp)
            assert second.proc.wait(timeout=TIMEOUT) != 0
        finally:
            first.stop()

    def test_dns_names_resolve(self):
        # server-config-resolve-addrs parity: localhost:port works standalone
        np, rp = next(_ports), next(_ports)
        cfg = run_cli(
            SERVER + ["config", "new", f"localhost:{np}", f"localhost:{rp}"]
        ).stdout
        server = ServerProcess(cfg, np, rp)
        try:
            wait_for_port(np)
            wait_for_port(rp)
            wallet = new_wallet(rp)
            assert get_balance(wallet) == 100_000
        finally:
            server.stop()


class TestNetworkE2E:
    def test_transfer_conservation(self, network_3):
        rpc = network_3[0].rpc_port
        sender, receiver = new_wallet(rpc), new_wallet(rpc)
        recv_pub = wallet_pubkey(receiver)
        run_cli(CLIENT + ["send-asset", "1", recv_pub, "100"], stdin=sender)
        wait_for_sequence(sender, 1)
        assert get_balance(sender) == 99_900
        assert get_balance(receiver) == 100_100

    def test_tx_shows_in_latest(self, network_3):
        rpc = network_3[0].rpc_port
        sender, receiver = new_wallet(rpc), new_wallet(rpc)
        recv_pub = wallet_pubkey(receiver)
        run_cli(CLIENT + ["send-asset", "1", recv_pub, "77"], stdin=sender)
        wait_for_sequence(sender, 1)
        out = run_cli(CLIENT + ["get-latest-transactions"], stdin=sender).stdout
        assert "send 77¤" in out
        assert "(success)" in out

    def test_client_against_dead_server_fails(self):
        # cli.rs:215-228
        wallet = new_wallet(1)  # nothing listens on port 1
        proc = run_cli(
            CLIENT + ["send-asset", "1", "ab" * 32, "10"], stdin=wallet, check=False
        )
        assert proc.returncode != 0
