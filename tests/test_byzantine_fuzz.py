"""Seeded wire-level byzantine fuzz campaign (VERDICT r4 #6).

The scripted byzantine test (tests/test_byzantine.py) drives ONE
deterministic interleaving; the broadcast fuzz tier
(tests/test_broadcast_fuzz.py) randomizes schedules but runs ABOVE the
transport. This campaign closes the gap between them: a seeded generator
(`at2_node_tpu.sim.hostile.HostileFrameGen`) drives random HOSTILE
FRAME SEQUENCES against a live 4-node net — valid-but-conflicting
attestations, batch equivocation, random bitmaps, malformed bodies,
replays, catchup-plane junk, interleaved across nodes and schedules —
and asserts the safety invariants after every episode:

* liveness: fresh honest traffic still commits on every correct node;
* agreement: all correct nodes report identical frontiers and balances
  for every identity the episode touched;
* no fabricated content ever reaches the ledger (balances of hostile
  recipients match across nodes — either the one winning content or
  nothing).

The 24-episode campaign runs on the DETERMINISTIC SIM FABRIC
(at2_node_tpu/sim): same real node logic, same frame generators,
virtual time instead of wall-clock waits — plus the full AT2 invariant
sweep (totality, sieve consistency, conservation) at campaign end. One
single-episode campaign stays on the real encrypted transport as the
TRANSPORT-INTEGRATION CANARY (frame framing, AEAD, channel lifecycle
facing hostile bytes), with a native-reader variant when the C++ plane
is available.

Seed discipline: the campaign seed defaults to a fixed value (CI
determinism) and can be overridden with AT2_FUZZ_SEED; every failure
message carries the episode seed for exact replay.

Threshold math: n=5 (4 correct + 1 hostile), echo/ready thresholds 3 —
the f=1-safe configuration under self-excluded vote counting
(tests/test_byzantine.py module docstring).
"""

import asyncio
import itertools
import os
import random

import pytest

from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.net import transport
from at2_node_tpu.node.service import Service
from at2_node_tpu.sim.hostile import HostileFrameGen
from at2_node_tpu.sim.net import SimNet, sim_client

from conftest import make_net_configs, wait_until

_ports = itertools.count(25400)

FAUCET = 100_000
N_EPISODES = 24  # sim-fabric campaign
FRAMES_PER_EPISODE = 40
CANARY_EPISODES = 1  # live-socket transport canary


class _HostileFuzzer(HostileFrameGen):
    """The shared frame generator plus real encrypted transport
    channels — the live-socket canary's byzantine peer."""

    def __init__(self, config, rng: random.Random):
        super().__init__(config.sign_key, rng)
        self.network = config.network_key
        self.channels = {}

    async def dial(self, cfgs):
        for i, cfg in enumerate(cfgs):
            host, _, port = cfg.node_address.rpartition(":")
            self.channels[i] = await transport.connect(
                host, int(port), self.network
            )

    def close(self):
        for ch in self.channels.values():
            ch.close()

    async def episode(self, n_frames: int) -> None:
        rng = self.rng
        for _ in range(n_frames):
            frame = self.next_frame()
            targets = rng.sample(
                list(self.channels), rng.randint(1, len(self.channels))
            )
            for t in targets:
                try:
                    await self.channels[t].send(frame)
                except (transport.ChannelClosed, ConnectionError):
                    pass  # correct nodes never close on bad frames, but be safe
            if rng.random() < 0.3:
                await asyncio.sleep(0)  # schedule churn


async def _agreement(services, identities):
    """All correct nodes agree on frontier and balance for every key."""
    for key in identities:
        seqs = {await s.accounts.get_last_sequence(key) for s in services}
        assert len(seqs) == 1, f"frontier divergence for {key.hex()[:16]}: {seqs}"
        bals = {await s.accounts.get_balance(key) for s in services}
        assert len(bals) == 1, f"balance divergence for {key.hex()[:16]}: {bals}"


class TestByzantineWireFuzz:
    def test_seeded_campaign_sim_fabric(self):
        """The full 24-episode campaign on the deterministic simulated
        fabric: virtual time, seeded delivery jitter, exact replay from
        (AT2_FUZZ_SEED). SYNC test: it owns the virtual event loop."""
        campaign_seed = int(os.environ.get("AT2_FUZZ_SEED", "20260731"))
        rng = random.Random(campaign_seed)
        net = SimNet(
            n=4,
            f=1,
            seed=campaign_seed,
            hostile=1,
            echo_threshold=3,
            ready_threshold=3,
        ).start()
        honest = sim_client(campaign_seed, 100)
        honest_rcpt = sim_client(campaign_seed, 101).public
        try:
            hostile = HostileFrameGen(net.hostile_configs[0].sign_key, rng)
            node_signs = [c.sign_key.public for c in net.configs[:4]]

            def frontier(key):
                return [
                    net.loop.run_until_complete(
                        s.accounts.get_last_sequence(key)
                    )
                    for s in net.services
                ]

            for ep in range(N_EPISODES):
                ep_seed = rng.getrandbits(32)
                hostile.rng.seed(ep_seed)
                try:
                    for _ in range(FRAMES_PER_EPISODE):
                        frame = hostile.next_frame()
                        targets = hostile.rng.sample(
                            range(4), hostile.rng.randint(1, 4)
                        )
                        for t in targets:
                            net.fabric.inject(
                                hostile.sign.public, node_signs[t], frame
                            )
                        net.run_for(0.02)
                    # liveness: honest traffic commits everywhere
                    seq = ep + 1
                    err = net.submit(0, honest, seq, honest_rcpt, 1)
                    assert err is None, f"honest tx rejected: {err}"
                    for _ in range(240):
                        net.run_for(0.5)
                        if all(fr >= seq for fr in frontier(honest.public)):
                            break
                    else:
                        raise AssertionError(
                            "honest tx did not commit on all nodes: "
                            f"{frontier(honest.public)}"
                        )
                    # agreement on everything the episode touched
                    touched = (
                        [c.public for c in hostile.clients]
                        + list(hostile.recipients)
                        + [honest.public, honest_rcpt]
                    )
                    net.loop.run_until_complete(
                        _agreement(net.services, touched)
                    )
                except AssertionError as exc:
                    raise AssertionError(
                        f"episode {ep} (seed {ep_seed}, campaign "
                        f"{campaign_seed}): {exc}"
                    ) from exc
            # hostile frames never killed a correct node's inbound plane
            for s in net.services:
                assert s.broadcast.stats["delivered"] >= N_EPISODES
            # beyond the live test: settle and sweep the FULL invariant
            # set (agreement + sieve consistency + totality +
            # conservation) across everything the campaign committed
            net.settle(horizon=90.0)
            violations = net.check_invariants()
            assert violations == [], violations
        finally:
            net.close()

    @pytest.mark.asyncio
    async def test_live_socket_canary(self):
        """One episode over the REAL encrypted transport: the
        integration the sim fabric abstracts away (framing, AEAD,
        channel lifecycle) still faces hostile bytes every CI run."""
        await self._live_campaign()

    @pytest.mark.asyncio
    async def test_live_socket_canary_native_reader_plane(self, monkeypatch):
        """Same canary with the C++ channel readers forced on: the
        native inbound plane (socket reads, AEAD, frame assembly, wake
        batching, chained delivery) faces the hostile frame generator
        too."""
        from at2_node_tpu.native.reader import _lib_with_reader

        if _lib_with_reader() is None:
            pytest.skip("native reader library unavailable")
        monkeypatch.setenv("AT2_FORCE_NATIVE_READER", "1")
        await self._live_campaign(seed_offset=1)

    async def _live_campaign(self, seed_offset: int = 0):
        campaign_seed = (
            int(os.environ.get("AT2_FUZZ_SEED", "20260731")) + seed_offset
        )
        cfgs = make_net_configs(5, _ports, echo_threshold=3, ready_threshold=3)
        services = [await Service.start(c) for c in cfgs[:4]]
        rng = random.Random(campaign_seed)
        hostile = _HostileFuzzer(cfgs[4], rng)
        honest_seq = 0
        honest = SignKeyPair.random()
        honest_rcpt = SignKeyPair.random().public
        try:
            await hostile.dial(cfgs[:4])
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                for ep in range(CANARY_EPISODES):
                    ep_seed = rng.getrandbits(32)
                    hostile.rng.seed(ep_seed)
                    try:
                        await hostile.episode(FRAMES_PER_EPISODE)
                        # liveness: honest traffic commits everywhere
                        honest_seq += 1
                        await client.send_asset(
                            honest, honest_seq, honest_rcpt, 1
                        )
                        target = honest_seq

                        async def honest_committed():
                            for s in services:
                                got = await s.accounts.get_last_sequence(
                                    honest.public
                                )
                                if got < target:
                                    return False
                            return True

                        await wait_until(
                            honest_committed,
                            what=f"honest tx after episode {ep}",
                        )
                        # agreement on everything the episode touched
                        touched = (
                            [c.public for c in hostile.clients]
                            + list(hostile.recipients)
                            + [honest.public, honest_rcpt]
                        )
                        await _agreement(services, touched)
                    except AssertionError as exc:
                        raise AssertionError(
                            f"episode {ep} (seed {ep_seed}, campaign "
                            f"{campaign_seed}): {exc}"
                        ) from exc
            # channel health: the hostile peer's bad frames must never
            # have killed a correct node's inbound plane for OTHER peers
            # (honest commits above prove it transitively); and no node
            # crashed (all four answered every round)
            for s in services:
                st = s.broadcast.stats
                assert st["delivered"] >= CANARY_EPISODES  # honest slots
        finally:
            hostile.close()
            for s in services:
                await s.close()
