"""Seeded wire-level byzantine fuzz campaign (VERDICT r4 #6).

The scripted byzantine test (tests/test_byzantine.py) drives ONE
deterministic interleaving; the broadcast fuzz tier
(tests/test_broadcast_fuzz.py) randomizes schedules but runs ABOVE the
transport. This campaign closes the gap between them: a seeded generator
drives random HOSTILE FRAME SEQUENCES over the real encrypted transport
against a live 4-node net — valid-but-conflicting attestations, batch
equivocation, random bitmaps, malformed bodies, replays, catchup-plane
junk, interleaved across nodes and schedules — and asserts the safety
invariants after every episode:

* liveness: fresh honest traffic still commits on every correct node;
* agreement: all correct nodes report identical frontiers and balances
  for every identity the episode touched;
* no fabricated content ever reaches the ledger (balances of hostile
  recipients match across nodes — either the one winning content or
  nothing).

Seed discipline: the campaign seed defaults to a fixed value (CI
determinism) and can be overridden with AT2_FUZZ_SEED; every failure
message carries the episode seed for exact replay.

Threshold math: n=5 (4 correct + 1 hostile), echo/ready thresholds 3 —
the f=1-safe configuration under self-excluded vote counting
(tests/test_byzantine.py module docstring).
"""

import asyncio
import itertools
import os
import random
import struct

import pytest

from at2_node_tpu.broadcast.messages import (
    BATCH_ECHO,
    BATCH_READY,
    ECHO,
    READY,
    Attestation,
    BatchAttestation,
    BatchContentRequest,
    ContentRequest,
    HistoryBatch,
    HistoryIndexRequest,
    HistoryRequest,
    Payload,
    TxBatch,
)
from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.net import transport
from at2_node_tpu.node.service import Service
from at2_node_tpu.types import ThinTransaction

from conftest import make_net_configs, wait_until

_ports = itertools.count(25400)

FAUCET = 100_000
N_EPISODES = 24
FRAMES_PER_EPISODE = 40


class _HostileFuzzer:
    """Authenticated byzantine peer emitting seeded random frame salvos."""

    def __init__(self, config, rng: random.Random):
        self.sign = config.sign_key
        self.network = config.network_key
        self.rng = rng
        self.channels = {}
        self.sent_log = []  # replay source
        # identities this fuzzer signs client payloads with
        self.clients = [SignKeyPair.random() for _ in range(3)]
        self.recipients = [SignKeyPair.random().public for _ in range(3)]
        self.batches = []  # real TxBatches sent: targets for oversized bitmaps

    async def dial(self, cfgs):
        for i, cfg in enumerate(cfgs):
            host, _, port = cfg.node_address.rpartition(":")
            self.channels[i] = await transport.connect(
                host, int(port), self.network
            )

    def close(self):
        for ch in self.channels.values():
            ch.close()

    # -- frame builders ---------------------------------------------------

    def _payload(self, client, seq, recipient, amount, good_sig=True):
        tx = ThinTransaction(recipient, amount)
        sig = (
            client.sign(tx.signing_bytes())
            if good_sig
            else bytes(self.rng.getrandbits(8) for _ in range(64))
        )
        return Payload(client.public, seq, tx, sig)

    def _rand_payload(self):
        rng = self.rng
        return self._payload(
            rng.choice(self.clients),
            rng.randint(1, 4),
            rng.choice(self.recipients),
            rng.randint(1, 50),
            good_sig=rng.random() > 0.25,
        )

    def _rand_batch(self):
        rng = self.rng
        entries = b"".join(
            self._rand_payload().encode()[1:]
            for _ in range(rng.randint(1, 6))
        )
        batch = TxBatch.create(self.sign, rng.randint(1, 5), entries)
        self.batches.append(batch)
        return batch

    def _poison_batch(self):
        """A batch GUARANTEED to carry at least one never-verifiable
        entry among honest-looking ones — the poison-slot resolution
        path's bread and butter (slot must retire, never stall)."""
        rng = self.rng
        payloads = [self._rand_payload() for _ in range(rng.randint(1, 4))]
        payloads.insert(
            rng.randrange(len(payloads) + 1),
            self._payload(
                rng.choice(self.clients),
                rng.randint(1, 4),
                rng.choice(self.recipients),
                rng.randint(1, 50),
                good_sig=False,
            ),
        )
        entries = b"".join(p.encode()[1:] for p in payloads)
        batch = TxBatch.create(self.sign, rng.randint(1, 5), entries)
        self.batches.append(batch)
        return batch

    def _oversized_batch_attestation(self):
        """A correctly signed attestation for a REAL previously-sent
        batch whose bitmap claims far more entries than the batch has:
        exercises the width clamp (phantom bits must not grow nbits or
        spuriously quorate). Falls back to a random one before any batch
        exists."""
        rng = self.rng
        if not self.batches:
            return self._rand_batch_attestation()
        batch = rng.choice(self.batches)
        phase = rng.choice((BATCH_ECHO, BATCH_READY))
        bitmap = bytes(
            rng.getrandbits(8) | 1 for _ in range(rng.choice((16, 64, 128)))
        )
        sig = self.sign.sign(
            BatchAttestation.signing_bytes(
                phase, batch.origin, batch.batch_seq, batch.content_hash(), bitmap
            )
        )
        return BatchAttestation(
            phase,
            self.sign.public,
            batch.origin,
            batch.batch_seq,
            batch.content_hash(),
            bitmap,
            sig,
        )

    def _rand_attestation(self):
        rng = self.rng
        phase = rng.choice((ECHO, READY))
        sender = rng.choice(self.clients).public
        seq = rng.randint(1, 4)
        chash = (
            self._rand_payload().content_hash()
            if rng.random() < 0.6
            else bytes(rng.getrandbits(8) for _ in range(32))
        )
        sig = self.sign.sign(
            Attestation.signing_bytes(phase, sender, seq, chash)
        )
        return Attestation(phase, self.sign.public, sender, seq, chash, sig)

    def _rand_batch_attestation(self):
        rng = self.rng
        phase = rng.choice((BATCH_ECHO, BATCH_READY))
        b_origin = self.sign.public
        b_seq = rng.randint(1, 5)
        b_hash = bytes(rng.getrandbits(8) for _ in range(32))
        bitmap = bytes(
            rng.getrandbits(8) for _ in range(rng.choice((1, 2, 16, 128)))
        )
        sig = self.sign.sign(
            BatchAttestation.signing_bytes(phase, b_origin, b_seq, b_hash, bitmap)
        )
        return BatchAttestation(
            phase, self.sign.public, b_origin, b_seq, b_hash, bitmap, sig
        )

    def _rand_catchup_junk(self):
        rng = self.rng
        kind = rng.randrange(4)
        if kind == 0:
            return HistoryIndexRequest(rng.getrandbits(64))
        if kind == 1:
            return HistoryRequest(
                rng.getrandbits(64),
                rng.choice(self.clients).public,
                1,
                rng.randint(1, 1 << 20),  # absurd range: server must clamp
            )
        if kind == 2:
            return HistoryBatch(
                rng.getrandbits(64),
                tuple(self._rand_payload() for _ in range(rng.randint(1, 4))),
            )
        return ContentRequest(
            rng.choice(self.clients).public,
            rng.randint(1, 4),
            bytes(rng.getrandbits(8) for _ in range(32)),
        )

    def _malformed(self) -> bytes:
        rng = self.rng
        choice = rng.randrange(4)
        if choice == 0:  # unknown kind
            return bytes([rng.randint(13, 255)]) + bytes(
                rng.getrandbits(8) for _ in range(rng.randint(0, 64))
            )
        if choice == 1:  # truncated known message
            full = self._rand_payload().encode()
            return full[: rng.randint(1, len(full) - 1)]
        if choice == 2:  # batch header with an absurd count field
            b = bytearray(self._rand_batch().encode())
            b[41:45] = struct.pack("<I", rng.randint(1025, 1 << 30))
            return bytes(b)
        # random garbage
        return bytes(rng.getrandbits(8) for _ in range(rng.randint(1, 200)))

    def next_frame(self) -> bytes:
        rng = self.rng
        roll = rng.random()
        if roll < 0.22:
            msgs = [self._rand_payload() for _ in range(rng.randint(1, 3))]
            frame = b"".join(m.encode() for m in msgs)
        elif roll < 0.34:
            frame = self._rand_batch().encode()
        elif roll < 0.42:
            frame = self._poison_batch().encode()
        elif roll < 0.58:
            frame = self._rand_attestation().encode()
        elif roll < 0.68:
            frame = self._rand_batch_attestation().encode()
        elif roll < 0.75:
            frame = self._oversized_batch_attestation().encode()
        elif roll < 0.84:
            frame = self._rand_catchup_junk().encode()
        elif roll < 0.93 and self.sent_log:
            frame = rng.choice(self.sent_log)  # verbatim replay
        else:
            frame = self._malformed()
        self.sent_log.append(frame)
        return frame

    async def episode(self, n_frames: int) -> None:
        rng = self.rng
        for _ in range(n_frames):
            frame = self.next_frame()
            targets = rng.sample(
                list(self.channels), rng.randint(1, len(self.channels))
            )
            for t in targets:
                try:
                    await self.channels[t].send(frame)
                except (transport.ChannelClosed, ConnectionError):
                    pass  # correct nodes never close on bad frames, but be safe
            if rng.random() < 0.3:
                await asyncio.sleep(0)  # schedule churn


async def _agreement(services, identities):
    """All correct nodes agree on frontier and balance for every key."""
    for key in identities:
        seqs = {await s.accounts.get_last_sequence(key) for s in services}
        assert len(seqs) == 1, f"frontier divergence for {key.hex()[:16]}: {seqs}"
        bals = {await s.accounts.get_balance(key) for s in services}
        assert len(bals) == 1, f"balance divergence for {key.hex()[:16]}: {bals}"


class TestByzantineWireFuzz:
    @pytest.mark.asyncio
    async def test_seeded_campaign(self):
        await self._campaign()

    @pytest.mark.asyncio
    async def test_seeded_campaign_native_reader_plane(self, monkeypatch):
        """Same campaign with the C++ channel readers forced on: the
        native inbound plane (socket reads, AEAD, frame assembly, wake
        batching, chained delivery) faces the hostile frame generator
        too."""
        from at2_node_tpu.native.reader import _lib_with_reader

        if _lib_with_reader() is None:
            pytest.skip("native reader library unavailable")
        monkeypatch.setenv("AT2_FORCE_NATIVE_READER", "1")
        await self._campaign(seed_offset=1)

    async def _campaign(self, seed_offset: int = 0):
        campaign_seed = (
            int(os.environ.get("AT2_FUZZ_SEED", "20260731")) + seed_offset
        )
        cfgs = make_net_configs(5, _ports, echo_threshold=3, ready_threshold=3)
        services = [await Service.start(c) for c in cfgs[:4]]
        rng = random.Random(campaign_seed)
        hostile = _HostileFuzzer(cfgs[4], rng)
        honest_seq = 0
        honest = SignKeyPair.random()
        honest_rcpt = SignKeyPair.random().public
        try:
            await hostile.dial(cfgs[:4])
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                for ep in range(N_EPISODES):
                    ep_seed = rng.getrandbits(32)
                    hostile.rng.seed(ep_seed)
                    try:
                        await hostile.episode(FRAMES_PER_EPISODE)
                        # liveness: honest traffic commits everywhere
                        honest_seq += 1
                        await client.send_asset(
                            honest, honest_seq, honest_rcpt, 1
                        )
                        target = honest_seq

                        async def honest_committed():
                            for s in services:
                                got = await s.accounts.get_last_sequence(
                                    honest.public
                                )
                                if got < target:
                                    return False
                            return True

                        await wait_until(
                            honest_committed,
                            what=f"honest tx after episode {ep}",
                        )
                        # agreement on everything the episode touched
                        touched = (
                            [c.public for c in hostile.clients]
                            + list(hostile.recipients)
                            + [honest.public, honest_rcpt]
                        )
                        await _agreement(services, touched)
                    except AssertionError as exc:
                        raise AssertionError(
                            f"episode {ep} (seed {ep_seed}, campaign "
                            f"{campaign_seed}): {exc}"
                        ) from exc
            # channel health: the hostile peer's bad frames must never
            # have killed a correct node's inbound plane for OTHER peers
            # (honest commits above prove it transitively); and no node
            # crashed (all four answered every round)
            for s in services:
                st = s.broadcast.stats
                assert st["delivered"] >= N_EPISODES  # honest slots
        finally:
            hostile.close()
            for s in services:
                await s.close()
