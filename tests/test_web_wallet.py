"""Pins for the browser wallet page (web/wallet.html).

No JS runtime exists in this image, so the page cannot be executed in
CI; its wire behavior (grpc-web-text framing, protobuf shapes, CORS) is
what the interop tier pins with stock HTTP clients. What CAN be checked
offline, is checked here:

* the PKCS8 prefix the page uses to import raw Ed25519 seeds into
  WebCrypto is byte-identical to the real PKCS8 encoding `cryptography`
  produces — the single most fragile constant on the page (a wrong
  prefix silently derives a different key);
* the signed byte layout the page builds (recipient || amount LE, no
  sequence) matches types.ThinTransaction.signing_bytes, so a browser
  signature verifies server-side;
* the page references the correct service path and content type.
"""

import os
import re

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric import ed25519

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.types import ThinTransaction

PAGE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "web",
    "wallet.html",
)


def _page() -> str:
    with open(PAGE, encoding="utf-8") as f:
        return f.read()


def test_pkcs8_prefix_matches_real_encoding():
    match = re.search(r'PKCS8_PREFIX = hexToBytes\("([0-9a-f]+)"\)', _page())
    assert match, "PKCS8 prefix constant missing from the page"
    page_prefix = bytes.fromhex(match.group(1))

    seed = bytes(range(32))
    key = ed25519.Ed25519PrivateKey.from_private_bytes(seed)
    pkcs8 = key.private_bytes(
        serialization.Encoding.DER,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    assert pkcs8 == page_prefix + seed, (
        "the page's PKCS8 wrapper diverges from the real encoding; "
        "WebCrypto importKey would build a different key"
    )


def test_signing_layout_matches_canonical():
    page = _page()
    # the page signs concat(recipient, amountLe) with LE u64 — the same
    # canonical form ThinTransaction.signing_bytes defines
    assert "setBigUint64(0, amount, true)" in page  # little-endian
    assert "concat(recipient, amountLe)" in page
    thin = ThinTransaction(b"\x07" * 32, 513)
    assert thin.signing_bytes() == b"\x07" * 32 + (513).to_bytes(8, "little")
    # a signature over that layout verifies with the repo's own keys
    kp = SignKeyPair.from_hex("2b" * 32)
    sig = kp.sign(thin.signing_bytes())
    from at2_node_tpu.crypto.keys import verify_one

    assert verify_one(kp.public, thin.signing_bytes(), sig)


def test_page_targets_the_served_surface():
    page = _page()
    assert "/at2.AT2/" in page
    assert "application/grpc-web-text" in page
    # field numbers used for SendAsset match at2.proto's
    # (sender=1, sequence=2, recipient=3, amount=4, signature=5)
    assert "pbBytes(1, keyPair.publicKey)" in page
    assert "pbUint(2, sequence)" in page
    assert "pbBytes(3, recipient)" in page
    assert "pbUint(4, amount)" in page
    assert "pbBytes(5, signature)" in page
    # FullTransaction decode uses the right field map (timestamp=1,
    # sender=2, recipient=3, amount=4, state=5 — proto/at2.proto:61-75)
    assert "t[3] ? bytesToHex(t[3][0])" in page  # recipient
    assert "stateNames[Number(t[5]" in page  # state
