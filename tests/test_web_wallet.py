"""Pins for the browser wallet page (web/wallet.html).

No JS runtime exists in this image, so the page cannot be executed in
CI. The byte-level codec check therefore runs in two halves that meet at
a golden-vector block embedded in the page:

* this test REGENERATES every vector from the server's own protobuf
  bindings (at2_pb2) plus the canonical grpc-web-text framing, and
  byte-compares against the block between the page's GOLDEN-BEGIN/END
  markers — any drift between page, proto, or framing fails CI;
* the page runs `selfTest()` at load, driving its real encoder/decoder
  functions (varint, pbBytes/pbUint, frameB64, pbDecode,
  parseGrpcWebBody) against the same vectors, and DISABLES the wallet
  on mismatch — so the JS half of the contract is enforced by the only
  JS executor in the loop, the user's browser, before any signing.

Also pinned here (pre-existing):

* the PKCS8 prefix the page uses to import raw Ed25519 seeds into
  WebCrypto is byte-identical to the real PKCS8 encoding `cryptography`
  produces — the single most fragile constant on the page (a wrong
  prefix silently derives a different key);
* the signed byte layout the page builds (tag || sender || sequence LE
  || recipient || amount LE) matches types.transfer_signing_bytes, so a
  browser signature verifies server-side;
* the page references the correct service path and content type.
"""

import base64
import json
import os
import re

import pytest

# this module mimics the browser's WebCrypto key handling (PKCS8/SPKI DER),
# which the pure-python fallback deliberately does not implement
cryptography = pytest.importorskip(
    "cryptography", reason="wallet test needs the real cryptography wheel"
)
from cryptography.hazmat.primitives import serialization  # noqa: E402
from cryptography.hazmat.primitives.asymmetric import ed25519  # noqa: E402

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.proto import at2_pb2 as pb
from at2_node_tpu.types import TRANSFER_SIG_TAG, transfer_signing_bytes

PAGE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "web",
    "wallet.html",
)


def _page() -> str:
    with open(PAGE, encoding="utf-8") as f:
        return f.read()


def test_pkcs8_prefix_matches_real_encoding():
    match = re.search(r'PKCS8_PREFIX = hexToBytes\("([0-9a-f]+)"\)', _page())
    assert match, "PKCS8 prefix constant missing from the page"
    page_prefix = bytes.fromhex(match.group(1))

    seed = bytes(range(32))
    key = ed25519.Ed25519PrivateKey.from_private_bytes(seed)
    pkcs8 = key.private_bytes(
        serialization.Encoding.DER,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    assert pkcs8 == page_prefix + seed, (
        "the page's PKCS8 wrapper diverges from the real encoding; "
        "WebCrypto importKey would build a different key"
    )


def test_signing_layout_matches_canonical():
    page = _page()
    # the page signs tag || sender || seqLe || recipient || amountLe with
    # LE u32/u64 — the same canonical v2 form transfer_signing_bytes
    # defines, sequence fetched BEFORE signing so it lands in the preimage
    assert 'TextEncoder().encode("at2-node-tpu/transfer/v2")' in page
    assert "setUint32(0, Number(sequence), true)" in page  # little-endian
    assert "setBigUint64(0, amount, true)" in page  # little-endian
    assert (
        "concat(\n    TRANSFER_SIG_TAG, keyPair.publicKey, seqLe, "
        "recipient, amountLe)" in page
    )
    kp = SignKeyPair.from_hex("2b" * 32)
    pre = transfer_signing_bytes(kp.public, 513, b"\x07" * 32, 9)
    assert pre == (
        TRANSFER_SIG_TAG
        + kp.public
        + (513).to_bytes(4, "little")
        + b"\x07" * 32
        + (9).to_bytes(8, "little")
    )
    # a signature over that layout verifies with the repo's own keys
    sig = kp.sign(pre)
    from at2_node_tpu.crypto.keys import verify_one

    assert verify_one(kp.public, pre, sig)


def _expected_golden() -> dict:
    """The vectors as the SERVER's own bindings produce them — the
    oracle the page's embedded block must match byte-for-byte."""
    sender = bytes(range(32))
    recipient = bytes(range(32, 64))
    signature = bytes(range(64, 128))
    sequence = 300
    amount = (1 << 32) + 5

    sa = pb.SendAssetRequest(
        sender=sender, sequence=sequence, recipient=recipient,
        amount=amount, signature=signature,
    ).SerializeToString()
    frame = b"\x00" + len(sa).to_bytes(4, "big") + sa
    reply = pb.GetBalanceReply(amount=100_000).SerializeToString()
    tx = pb.FullTransaction(
        timestamp="2026-07-31T00:00:00Z", sender=sender, recipient=recipient,
        amount=7, state=1, sender_sequence=9,
    ).SerializeToString()
    trailer = b"grpc-status:0\r\n"
    resp_body = (
        b"\x00" + len(reply).to_bytes(4, "big") + reply
        + b"\x80" + len(trailer).to_bytes(4, "big") + trailer
    )

    def var(n: int) -> bytes:
        out = bytearray()
        while True:
            b = n & 0x7F
            n >>= 7
            out.append(b | (0x80 if n else 0))
            if not n:
                return bytes(out)

    return {
        "send_asset": {
            "sender": sender.hex(),
            "sequence": sequence,
            "recipient": recipient.hex(),
            "amount": str(amount),
            "signature": signature.hex(),
            "expect": sa.hex(),
            "expect_frame_b64": base64.b64encode(frame).decode(),
        },
        "get_balance_request": {
            "expect": pb.GetBalanceRequest(sender=sender)
            .SerializeToString()
            .hex()
        },
        "balance_reply": {"bytes": reply.hex(), "amount": "100000"},
        "full_transaction": {
            "bytes": tx.hex(),
            "timestamp": "2026-07-31T00:00:00Z",
            "sender": sender.hex(),
            "recipient": recipient.hex(),
            "amount": "7",
            "state": 1,
            "sender_sequence": "9",
        },
        "response_body_b64": {
            "b64": base64.b64encode(resp_body).decode(),
            "data": reply.hex(),
            "status": 0,
        },
        "varints": [
            [str(n), var(n).hex()]
            for n in [0, 1, 127, 128, 300, (1 << 32) + 5, (1 << 64) - 1]
        ],
    }


def test_golden_vectors_match_at2_pb2_byte_for_byte():
    match = re.search(
        r"/\* GOLDEN-BEGIN \*/\s*(\{.*?\})\s*/\* GOLDEN-END \*/",
        _page(),
        re.DOTALL,
    )
    assert match, "GOLDEN vector block missing from the page"
    embedded = json.loads(match.group(1))
    assert embedded == _expected_golden(), (
        "the page's golden vectors diverge from at2_pb2's byte output — "
        "regenerate the block (tests/test_web_wallet.py _expected_golden)"
    )


def test_self_test_gates_the_wallet():
    """The page must run selfTest() BEFORE wiring any button, and a
    failure must disable the UI — the vectors are only load-bearing if
    their check actually gates operation."""
    page = _page()
    assert "selfTest();" in page
    gate = page.index("selfTest();")
    wiring = page.index('["load", loadKey]')
    assert gate < wiring, "self-test must run before the UI is wired"
    assert '$(id).disabled = true' in page
    # every codec function the wallet uses at runtime appears in the test
    for fn in ("varint(", "pbBytes(", "pbUint(", "pbDecode(",
               "frameB64(", "parseGrpcWebBody("):
        body = page[page.index("function selfTest()"):page.index("try {")]
        assert fn in body, f"selfTest does not exercise {fn}"


def test_page_targets_the_served_surface():
    page = _page()
    assert "/at2.AT2/" in page
    assert "application/grpc-web-text" in page
    # field numbers used for SendAsset match at2.proto's
    # (sender=1, sequence=2, recipient=3, amount=4, signature=5)
    assert "pbBytes(1, keyPair.publicKey)" in page
    assert "pbUint(2, sequence)" in page
    assert "pbBytes(3, recipient)" in page
    assert "pbUint(4, amount)" in page
    assert "pbBytes(5, signature)" in page
    # FullTransaction decode uses the right field map (timestamp=1,
    # sender=2, recipient=3, amount=4, state=5 — proto/at2.proto:61-75)
    assert "t[3] ? bytesToHex(t[3][0])" in page  # recipient
    assert "stateNames[Number(t[5]" in page  # state
