"""Aggregate (quorum-certificate) RLC verification tests.

Small n keeps the CPU XLA compile of the two-table Straus graph bounded;
the n=64 BASELINE shape runs on real hardware (validated there, same
graph modulo batch size).
"""

import numpy as np

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.ops.aggregate import aggregate_verify, verify_certificate

N = 4


def _cert(n=N):
    keys = [SignKeyPair.random() for _ in range(n)]
    msgs = [b"attestation %d" % i for i in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return [k.public for k in keys], msgs, sigs


def test_aggregate_accepts_valid_and_rejects_tampered():
    pks, msgs, sigs = _cert()
    # fixed z: deterministic, compile once for both calls
    z = [3, 5, 7, 11]
    assert aggregate_verify(pks, msgs, sigs, _z_override=z) is True
    bad = list(sigs)
    bad[2] = bad[2][:32] + bytes([bad[2][32] ^ 1]) + bad[2][33:]
    assert aggregate_verify(pks, msgs, bad, _z_override=z) is False


def test_aggregate_rejects_malformed_without_device_work():
    pks, msgs, sigs = _cert()
    assert aggregate_verify(pks[:1], msgs[:1], [sigs[0][:10]]) is False


def test_verify_certificate_culprit_fallback():
    pks, msgs, sigs = _cert()
    sigs[1] = sigs[1][:32] + bytes([sigs[1][32] ^ 1]) + sigs[1][33:]
    out = verify_certificate(pks, msgs, sigs)
    assert out.tolist() == [True, False, True, True]


def test_aggregate_empty():
    assert aggregate_verify([], [], []) is True
