"""Aggregate (quorum-certificate) RLC verification tests.

Small n keeps the CPU XLA compile of the two-table Straus graph bounded;
the n=64 BASELINE shape runs on real hardware (validated there, same
graph modulo batch size).
"""

import hashlib

import numpy as np
import pytest

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.ops import ed25519 as base
from at2_node_tpu.ops import edwards as ed
from at2_node_tpu.ops import field as fe
from at2_node_tpu.ops.aggregate import aggregate_verify, verify_certificate

# The two-table Straus + torsion-check graph is a minutes-scale XLA compile
# on CPU (cached across runs via the persistent compilation cache, but the
# cold path belongs in the kernel tier, not the fast dev loop).
pytestmark = pytest.mark.slow

N = 4


def _cert(n=N):
    keys = [SignKeyPair.random() for _ in range(n)]
    msgs = [b"attestation %d" % i for i in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return [k.public for k in keys], msgs, sigs


def _affine_scalar_mult(k: int, p: tuple) -> tuple:
    acc = (0, 1)
    while k:
        if k & 1:
            acc = ed.affine_add_ints(acc, p)
        p = ed.affine_add_ints(p, p)
        k >>= 1
    return acc


def _compress(pt: tuple) -> bytes:
    x, y = pt
    enc = bytearray(y.to_bytes(32, "little"))
    if x & 1:
        enc[31] |= 0x80
    return bytes(enc)


def _torsion_point() -> tuple:
    """A nonzero small-order point: [L]Q for an arbitrary curve point Q."""
    for y in range(2, 60):
        try:
            x = ed._recover_x(y, 0)
        except ValueError:
            continue
        t = _affine_scalar_mult(base.L, (x, y))
        if t != (0, 1):
            return t
    raise AssertionError("no torsion point found")


def test_aggregate_accepts_valid_and_rejects_tampered():
    pks, msgs, sigs = _cert()
    # fixed z: deterministic, compile once for both calls
    z = [3, 5, 7, 11]
    assert aggregate_verify(pks, msgs, sigs, _z_override=z) is True
    bad = list(sigs)
    bad[2] = bad[2][:32] + bytes([bad[2][32] ^ 1]) + bad[2][33:]
    assert aggregate_verify(pks, msgs, bad, _z_override=z) is False


def test_aggregate_rejects_malformed_without_device_work():
    pks, msgs, sigs = _cert()
    assert aggregate_verify(pks[:1], msgs[:1], [sigs[0][:10]]) is False


def test_verify_certificate_culprit_fallback():
    pks, msgs, sigs = _cert()
    sigs[1] = sigs[1][:32] + bytes([sigs[1][32] ^ 1]) + sigs[1][33:]
    out = verify_certificate(pks, msgs, sigs)
    assert out.tolist() == [True, False, True, True]


def test_aggregate_empty():
    assert aggregate_verify([], [], []) is True


def test_small_order_rlc_cancellation_rejected():
    """A byzantine signer who knows its private scalar can plant an
    8-torsion component in R: the residual e = [S]B - R - [h]A is then the
    small-order point -T, and adversarial coefficients with z1 + z2 == 0
    (mod 8) cancel the naive RLC sum even though every per-signature
    cofactorless verifier rejects these signatures. The subgroup
    (torsion-free) check must reject the certificate (ADVICE round-1
    medium finding)."""
    torsion = _torsion_point()
    base_pt = (ed.BX_INT, ed.BY_INT)
    a_scalar = 987654321987654321987654321 % base.L
    a_pub = _compress(_affine_scalar_mult(a_scalar, base_pt))

    pks, msgs, sigs = [], [], []
    for i, r_nonce in enumerate((11111, 22222)):
        msg = b"small-order attack %d" % i
        r_pt = ed.affine_add_ints(_affine_scalar_mult(r_nonce, base_pt), torsion)
        r_bytes = _compress(r_pt)
        h = (
            int.from_bytes(
                hashlib.sha512(r_bytes + a_pub + msg).digest(), "little"
            )
            % base.L
        )
        s = (r_nonce + h * a_scalar) % base.L
        pks.append(a_pub)
        msgs.append(msg)
        sigs.append(r_bytes + s.to_bytes(32, "little"))
    # two honest filler lanes keep the batch at the shared compiled shape
    filler_keys = [SignKeyPair.random() for _ in range(2)]
    for i, k in enumerate(filler_keys):
        msg = b"honest filler %d" % i
        pks.append(k.public)
        msgs.append(msg)
        sigs.append(k.sign(msg))

    # every per-signature cofactorless path rejects the attack signatures
    assert base.verify_batch(pks, msgs, sigs).tolist() == [
        False,
        False,
        True,
        True,
    ]
    # z1=1, z2=7: torsion residues cancel ([8]T = identity) so the naive
    # RLC equation HOLDS — only the subgroup check stands in the way
    assert aggregate_verify(pks, msgs, sigs, _z_override=[1, 7, 3, 5]) is False
