"""[overload] closed-loop control unit tests (ISSUE 16).

Pure-function coverage of the pieces the flash-crowd A/B bench
(tools/overload_ab.py) exercises end-to-end: admission token buckets
under clock skew, the OverloadController's deterministic ladder (ramp
math, debt accumulators, CoDel arming, fast-attack/slow-release EWMA,
strict registered-tier priority), typed retry_after_ms hints, and the
client RetryPolicy math with an injected rng — plus one sim-backed test
that a shed is typed RESOURCE_EXHAUSTED and charges NOTHING to the
sender's signature fail bucket."""

import grpc
import pytest

from at2_node_tpu.client import RetryPolicy
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.node.config import OverloadConfig
from at2_node_tpu.node.overload import (
    LEVELS,
    OverloadController,
    broker_retry_after_ms,
    format_shed_details,
    parse_retry_after_ms,
)
from at2_node_tpu.node.service import Service
from at2_node_tpu.sim.net import SimNet, SimRpcError


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def monotonic(self) -> float:
        return self.t


def _cfg(**kw) -> OverloadConfig:
    """Enabled config with a small, test-legible ladder: ramp over
    [0.5, 0.9], instant EWMA, zero-rate-limit sampling."""
    base = dict(
        enabled=True,
        sample_interval=1e-9,
        smoothing=1.0,
        queue_target=10,
        sojourn_target_ms=100.0,
        sojourn_arm_s=1.0,
        shed_start=0.5,
        shed_full=0.9,
        registered_grace=0.2,
        retry_after_ms=100,
        retry_after_max_ms=1000,
    )
    base.update(kw)
    return OverloadConfig(**base)


class TestBucketClockSkew:
    """Service._bucket_refill: the shared token-bucket primitive behind
    the [admission] fail and register buckets. limit=4 over window=4s
    (rate 1 token/s) throughout."""

    def _refill(self, buckets, now, limit=4.0, window=4.0):
        return Service._bucket_refill(buckets, "src", now, limit, window)

    def test_burst_at_window_edge_never_exceeds_limit(self):
        b = {}
        bucket = self._refill(b, 0.0)
        assert bucket[0] == 4.0  # fresh bucket starts full
        bucket[0] = 0.0  # fully drained by failures at t=0
        # continuous refill: 3s elapsed -> 3 tokens, not a cliff at the
        # window edge
        assert self._refill(b, 3.0)[0] == pytest.approx(3.0)
        # an arbitrarily long gap caps at the limit — crossing the
        # window boundary mints at most one window's worth, ever
        assert self._refill(b, 400.0)[0] == 4.0

    def test_refill_after_idle_resumes_from_the_spend(self):
        b = {}
        self._refill(b, 0.0)
        # long idle (bucket pinned at the cap), then a spend
        bucket = self._refill(b, 100.0)
        bucket[0] = 1.0
        # refill resumes at the configured rate from the spend point
        assert self._refill(b, 101.5)[0] == pytest.approx(2.5)

    def test_backwards_clock_neither_mints_nor_drains(self):
        b = {}
        bucket = self._refill(b, 100.0)
        bucket[0] = 1.0
        # clock steps back 50s (NTP slew): a negative delta must not
        # drain tokens, and the stamp must hold — re-crediting the
        # interval the bucket already refilled over would mint tokens
        back = self._refill(b, 50.0)
        assert back[0] == pytest.approx(1.0)
        assert back[1] == 100.0
        # once the clock catches back up, refill credits only the time
        # past the held stamp: 2 real seconds -> 2 tokens, not 52
        assert self._refill(b, 102.0)[0] == pytest.approx(3.0)


class TestControllerLadder:
    def _ctl(self, cfg=None, depth=0, **kw):
        box = {"queue_depth": depth}
        ctl = OverloadController(
            cfg or _cfg(),
            FakeClock(),
            verifier_stats=lambda: box,
            **kw,
        )
        ctl._depth_box = box  # test handle, not API
        return ctl

    def test_shed_fraction_linear_ramp(self):
        ctl = self._ctl()
        ctl._signals = {"occupancy": 2.0}
        for p, want in ((0.4, 0.0), (0.5, 0.0), (0.7, 0.5), (0.9, 1.0),
                        (2.0, 1.0)):
            ctl.pressure = p
            assert ctl.shed_fraction(registered=False) == pytest.approx(want)

    def test_registered_grace_shifts_the_ramp(self):
        ctl = self._ctl()
        # queue past target and growing: the registered exemption is off
        ctl._signals = {"occupancy": 1.5}
        ctl.draining = False
        ctl.pressure = 0.8  # grace 0.2: registered ramp starts at 0.7
        assert ctl.shed_fraction(registered=False) == pytest.approx(0.75)
        assert ctl.shed_fraction(registered=True) == pytest.approx(0.25)

    def test_registered_exempt_unless_queue_growing_past_target(self):
        ctl = self._ctl()
        ctl.pressure = 2.0  # saturated
        # sub-target queue: the fleet absorbs registered marginal load
        ctl._signals = {"occupancy": 0.9}
        ctl.draining = False
        assert ctl.shed_fraction(registered=True) == 0.0
        # draining queue: saturation is the ghost of the crowd's burst
        ctl._signals = {"occupancy": 2.0}
        ctl.draining = True
        assert ctl.shed_fraction(registered=True) == 0.0
        # growing AND past target: now the registered ramp engages
        ctl.draining = False
        assert ctl.shed_fraction(registered=True) == 1.0
        # the crowd ramp never had the exemption
        ctl.draining = True
        assert ctl.shed_fraction(registered=False) == 1.0

    def test_debt_accumulator_is_exact_and_deterministic(self):
        # depth 6 / target 10 -> raw 0.6 every sample; smoothing 1.0
        # pins pressure at 0.6 -> new-tier shed fraction 0.25
        a = self._ctl(depth=6)
        b = self._ctl(depth=6)
        da = [a.admit(registered=False, now=float(i)) for i in range(100)]
        db = [b.admit(registered=False, now=float(i)) for i in range(100)]
        assert da == db  # no RNG anywhere in the decision
        shed = [i for i, r in enumerate(da) if r is not None]
        # the long-run rate is exact up to fp rounding of the fraction
        # ((0.6-0.5)/0.4 lands a hair under 0.25), and the cadence is
        # perfectly periodic — one shed every 4 decisions
        assert len(shed) == 24
        assert {b - a for a, b in zip(shed, shed[1:])} == {4}

    def test_retry_after_scales_with_pressure_and_clamps(self):
        ctl = self._ctl()
        ctl.pressure = 0.5
        assert ctl.retry_after_ms() == 100  # at the ramp start: base
        ctl.pressure = 0.9
        # 100 * (1 + 4 * 0.4) = 260
        assert ctl.retry_after_ms() == 260
        ctl.pressure = 2.0
        # 100 * (1 + 4 * 1.5) = 700, still under the 1000 cap
        assert ctl.retry_after_ms() == 700
        ctl.cfg.retry_after_max_ms = 500
        assert ctl.retry_after_ms() == 500  # clamped

    def test_registered_hint_stays_flat(self):
        # a registered shed is a transient growth-window event: the
        # sender should come right back, not queue behind the crowd's
        # pressure-scaled hold-offs
        ctl = self._ctl()
        ctl.pressure = 2.0
        assert ctl.retry_after_ms(registered=True) == 100

    def test_broker_hint_same_ladder_shape(self):
        cfg = _cfg()
        assert broker_retry_after_ms(cfg, 0.0) == 100
        assert broker_retry_after_ms(cfg, 0.5) == 300
        assert broker_retry_after_ms(cfg, 1.0) == 500
        assert broker_retry_after_ms(cfg, 5.0) == 500  # ratio clamped

    def test_disabled_controller_is_inert(self):
        ctl = self._ctl(cfg=_cfg(enabled=False), depth=1000)
        for i in range(50):
            assert ctl.admit(registered=False, now=float(i)) is None
        ctl.maybe_sample(99.0)
        assert ctl.samples == 0
        assert ctl.pressure == 0.0
        assert not ctl.overloaded

    def test_maybe_sample_rate_limit(self):
        ctl = self._ctl(cfg=_cfg(sample_interval=1.0))
        ctl.maybe_sample(0.0)
        ctl.maybe_sample(0.5)
        assert ctl.samples == 1
        ctl.maybe_sample(1.0)
        assert ctl.samples == 2

    def test_level_transitions_fire_callback(self):
        seen = []
        ctl = self._ctl(
            on_transition=lambda old, new, p: seen.append((old, new))
        )
        for depth, level in ((0, 0), (4, 1), (6, 2), (10, 3), (10, 3)):
            ctl._depth_box["queue_depth"] = depth
            ctl.sample(float(len(seen)))
            assert LEVELS[ctl.level] == LEVELS[level]
        assert seen == [
            ("normal", "elevated"),
            ("elevated", "shedding"),
            ("shedding", "saturated"),
        ]
        assert ctl.overloaded  # level >= shedding

    def test_fast_attack_slow_release(self):
        ctl = self._ctl(cfg=_cfg(smoothing=0.5), depth=20)  # occupancy 2.0
        ctl.sample(0.0)
        assert ctl.pressure == pytest.approx(1.0)  # attack at full alpha
        ctl.sample(1.0)
        assert ctl.pressure == pytest.approx(1.5)
        # load vanishes: release runs at a quarter of the attack rate,
        # so one quiet tick cannot re-open admission
        ctl._depth_box["queue_depth"] = 0
        ctl.sample(2.0)
        assert ctl.pressure == pytest.approx(1.5 * (1 - 0.5 * 0.25))
        assert ctl.draining

    def test_codel_arming_and_empty_queue_disarm(self):
        hist = {"count": 0.0, "sum_ms": 0.0}
        depth = {"queue_depth": 5}
        ctl = OverloadController(
            _cfg(),
            FakeClock(),
            verifier_stats=lambda: depth,
            stage_hists=lambda: {"queue_wait": dict(hist)},
        )
        ctl.sample(0.0)  # primes the histogram snapshot
        assert not ctl.armed
        # sustained 500ms sojourn (target 100): over, but not yet armed
        hist.update(count=10.0, sum_ms=5000.0)
        ctl.sample(0.5)
        assert not ctl.armed
        assert ctl._signals["sojourn"] == 0.0  # unarmed signal is muted
        # still over after sojourn_arm_s of continuous breach: armed
        hist.update(count=20.0, sum_ms=10000.0)
        ctl.sample(1.6)
        assert ctl.armed
        assert ctl._signals["sojourn"] == 2.0  # 500/100 capped at 2.0
        # queue fully drained, no completions: the stale high reading
        # must not hold the signal armed forever
        depth["queue_depth"] = 0
        ctl.sample(2.0)
        assert not ctl.armed
        assert ctl._signals["sojourn"] == 0.0

    def test_standing_queue_keeps_last_reading(self):
        hist = {"count": 10.0, "sum_ms": 5000.0}
        depth = {"queue_depth": 5}
        ctl = OverloadController(
            _cfg(sojourn_arm_s=0.0),
            FakeClock(),
            verifier_stats=lambda: depth,
            stage_hists=lambda: {"queue_wait": dict(hist)},
        )
        ctl.sample(0.0)
        hist.update(count=20.0, sum_ms=10000.0)
        ctl.sample(1.0)
        assert ctl.armed
        # no completions but work still queued: no fresh evidence either
        # way — the armed reading holds
        ctl.sample(2.0)
        assert ctl.armed
        assert ctl._signals["sojourn"] == 2.0


class TestTypedHints:
    def test_format_parse_round_trip(self):
        details = format_shed_details("ingress shed under overload", 260)
        assert details.endswith("retry_after_ms=260")
        assert parse_retry_after_ms(details) == 260

    def test_parse_tolerates_hintless_details(self):
        assert parse_retry_after_ms(None) is None
        assert parse_retry_after_ms("") is None
        assert parse_retry_after_ms("too many invalid signatures") is None


class TestRetryPolicy:
    def test_delay_math_with_injected_rng(self):
        p = RetryPolicy(budget=4, base_ms=100.0, max_ms=5000.0,
                        multiplier=2.0, jitter=0.5, rng=lambda: 0.5)
        # rng 0.5 makes the jitter spread exactly 1.0
        assert p.delay_s(0) == pytest.approx(0.1)
        assert p.delay_s(2) == pytest.approx(0.4)
        assert p.delay_s(10) == pytest.approx(5.0)  # capped at max_ms

    def test_server_hint_raises_the_floor(self):
        p = RetryPolicy(jitter=0.5, rng=lambda: 0.5)
        assert p.delay_s(0, hint_ms=1000) == pytest.approx(1.0)
        # the hint is a floor, not a ceiling: a longer computed backoff
        # stands
        assert p.delay_s(6, hint_ms=1000) >= 1.0

    def test_jitter_spread_bounds(self):
        lo = RetryPolicy(base_ms=100.0, jitter=0.5, rng=lambda: 0.0)
        hi = RetryPolicy(base_ms=100.0, jitter=0.5, rng=lambda: 1.0)
        assert lo.delay_s(0) == pytest.approx(0.075)
        assert hi.delay_s(0) == pytest.approx(0.125)


class TestShedChargesNothing:
    """Sim-backed: a shed aborts RESOURCE_EXHAUSTED with a parseable
    hint, counts in overload_stats, and never charges the sender's
    [admission] fail bucket — refusing valid work under pressure is the
    node's state, not evidence against the sender."""

    def test_shed_typed_and_fail_bucket_untouched(self):
        net = SimNet(
            2,
            0,
            seed=5,
            overload=_cfg(sample_interval=1000.0),
        )
        try:
            net.start()
            svc = net.services[0]
            ov = svc.overload
            # force saturation and freeze the sampler (the huge
            # sample_interval keeps maybe_sample from overwriting it)
            ov.pressure = 2.0
            ov._signals = {"occupancy": 2.0}
            ov.draining = False
            ov._last_sample = net.clock.monotonic()
            kp = SignKeyPair.random()
            err = net.submit(0, kp, 1, b"r" * 32, 1)
            assert isinstance(err, SimRpcError)
            assert err.code == grpc.StatusCode.RESOURCE_EXHAUSTED
            hint = parse_retry_after_ms(err.details)
            assert hint is not None and hint >= ov.cfg.retry_after_ms
            assert svc.overload_stats["overload_shed_requests"] == 1
            assert svc.overload_stats["overload_shed_entries"] == 1
            # the shed aborted BEFORE admission: no bucket was created,
            # no signature rejection was recorded
            assert svc._admission_buckets == {}
            snap = svc.snapshot_stats()
            assert snap["rejected_at_ingress"] == 0
            assert snap["admission_throttled"] == 0
            # pressure drains: the very same sender is admitted — a shed
            # left no throttling state behind
            ov.pressure = 0.0
            ov._signals = {}
            assert net.submit(0, kp, 1, b"r" * 32, 1) is None
        finally:
            net.close()
