"""Broker ingress tier: distilled wire format, client directory, node
handler, broker roundtrip, and the byzantine-broker campaign.

The codec tests pin the distilled frame's safety-relevant shape: within-
frame duplicate (sender, seq) pairs are *unrepresentable* (strictly
increasing deltas), malformed frames reject wholesale, and the Python
and native parsers accept the exact same language (differential fuzz
over `mutate_distilled_frame` mutants). The ingress tests then assert
the trust argument end to end: a broker can censor or duplicate but a
forged or altered entry never commits, on the real gRPC surface and in
the simulated byzantine campaign.
"""

import asyncio
import itertools
import random

import pytest

from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.ledger import checkpoint
from at2_node_tpu.ledger.accounts import Accounts
from at2_node_tpu.ledger.recent import RecentTransactions
from at2_node_tpu.node.directory import ClientDirectory
from at2_node_tpu.proto import distill
from at2_node_tpu.proto.distill import (
    DISTILL_MAX_ENTRIES,
    DistillError,
    DistilledEntry,
)
from at2_node_tpu.sim.hostile import mutate_distilled_frame
from at2_node_tpu.types import transfer_signing_bytes

_ports = itertools.count(26600)

_U32_MAX = (1 << 32) - 1
_U64_MAX = (1 << 64) - 1


def _sig(rng: random.Random) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(64))


def _rand_entries(rng: random.Random, n: int, *, max_id: int = 500):
    """n entries with unique (sender_id, sequence) pairs and a mix of
    directory-id and raw-key recipients."""
    pairs = set()
    while len(pairs) < n:
        pairs.add((rng.randrange(max_id), rng.randint(1, 200)))
    out = []
    for sid, seq in pairs:
        recipient = (
            rng.randrange(1 << 20)
            if rng.random() < 0.5
            else bytes(rng.getrandbits(8) for _ in range(32))
        )
        out.append(
            DistilledEntry(sid, seq, recipient, rng.randrange(1 << 40), _sig(rng))
        )
    return out


class TestDistillCodec:
    def test_roundtrip_property(self):
        rng = random.Random(1)
        for trial in range(50):
            entries = _rand_entries(rng, rng.randint(1, 64))
            frame, dropped = distill.distill(entries)
            assert dropped == 0
            decoded = distill.decode(frame)
            expect = sorted(entries, key=lambda e: (e.sender_id, e.sequence))
            assert decoded == expect, f"trial {trial}"

    def test_single_entry_edge(self):
        e = DistilledEntry(0, 1, b"\x07" * 32, 0, b"\x01" * 64)
        frame, _ = distill.distill([e])
        assert distill.decode(frame) == [e]

    def test_max_gap_edges(self):
        # widest representable deltas in one frame: id 0 -> u64 max
        # (group delta), seq jumping straight to u32 max, amount u64 max
        entries = [
            DistilledEntry(0, _U32_MAX, 0, _U64_MAX, b"\x01" * 64),
            DistilledEntry(_U64_MAX, 1, _U64_MAX - 1, 0, b"\x02" * 64),
        ]
        frame, _ = distill.distill(entries)
        assert distill.decode(frame) == entries

    def test_distill_drops_exact_duplicates(self):
        rng = random.Random(2)
        e = DistilledEntry(3, 9, 0, 5, _sig(rng))
        later = DistilledEntry(3, 9, 1, 6, _sig(rng))  # same slot, new body
        frame, dropped = distill.distill([e, later, e])
        assert dropped == 2
        assert distill.decode(frame) == [e]  # first submission wins

    def test_duplicate_slot_unrepresentable(self):
        # a second entry on the same (sender, seq) needs a zero delta,
        # which the decoder rejects outright — a byzantine broker cannot
        # even ENCODE a within-frame duplicate
        for dup_kind in ("seq", "sender"):
            head = bytearray([distill.MAGIC, distill.VERSION])
            if dup_kind == "seq":
                distill._write_varint(head, 1)  # n_groups
                distill._write_varint(head, 2)  # n_entries
                distill._write_varint(head, 5)  # sender id
                distill._write_varint(head, 2)  # group size
                for delta in (1, 0):  # second seq repeats the first
                    distill._write_varint(head, delta)
                    distill._write_varint(head, 1)  # rtag: directory id 0
                    distill._write_varint(head, 1)  # amount
            else:
                distill._write_varint(head, 2)  # n_groups
                distill._write_varint(head, 2)  # n_entries
                for gid_delta in (5, 0):  # second group repeats the id
                    distill._write_varint(head, gid_delta)
                    distill._write_varint(head, 1)
                    distill._write_varint(head, 1)
                    distill._write_varint(head, 1)
                    distill._write_varint(head, 1)
            frame = bytes(head) + b"\x00" * 128
            with pytest.raises(DistillError):
                distill.decode(frame)

    def test_bounds(self):
        with pytest.raises(DistillError):
            distill.encode([])
        sig = b"\x00" * 64
        too_many = [
            DistilledEntry(0, s + 1, 0, 1, sig)
            for s in range(DISTILL_MAX_ENTRIES + 1)
        ]
        with pytest.raises(DistillError):
            distill.encode(too_many)
        exact = too_many[:DISTILL_MAX_ENTRIES]
        assert len(distill.decode(distill.encode(exact))) == DISTILL_MAX_ENTRIES

    def test_strict_rejects(self):
        frame, _ = distill.distill(
            [DistilledEntry(1, 1, b"\x05" * 32, 7, b"\x09" * 64)]
        )
        for bad in (
            b"",
            frame[:3],
            frame[:-1],  # truncated signature block
            frame + b"\x00",  # trailing byte
            b"\x00" + frame[1:],  # bad magic
            bytes([frame[0], 0x7F]) + frame[2:],  # bad version
        ):
            with pytest.raises(DistillError):
                distill.decode(bad)


class TestClientDirectory:
    def test_strided_assignment_disjoint_and_idempotent(self):
        a = ClientDirectory(rank=0, total=3)
        b = ClientDirectory(rank=2, total=3)
        keys = [bytes([i + 1]) * 32 for i in range(8)]
        ids_a = [a.assign(k)[0] for k in keys[:4]]
        ids_b = [b.assign(k)[0] for k in keys[4:]]
        assert ids_a == [0, 3, 6, 9]
        assert ids_b == [2, 5, 8, 11]
        assert a.assign(keys[0]) == (0, False)  # idempotent re-register
        assert a.get(0) == keys[0] and a.id_of(keys[0]) == 0
        assert a.get(1) is None  # other strides unknown until gossip

    def test_apply_stride_and_first_binding(self):
        d = ClientDirectory(rank=0, total=2)
        key, other = b"\x11" * 32, b"\x22" * 32
        assert d.apply(1, key, rank=1) is True  # rank 1's stride
        assert d.apply(3, key, rank=0) is False  # id 3 is NOT rank 0's
        assert d.apply(1, other, rank=1) is False  # rebind: first wins
        assert d.get(1) == key
        assert d.apply(1, key, rank=1) is True  # matching re-announce ok
        assert d.apply(5, b"\x00" * 32, rank=1) is False  # zero key
        # gossip into our own stride advances the assign cursor past it
        assert d.apply(4, other, rank=0) is True
        cid, created = d.assign(b"\x33" * 32)
        assert created and cid == 6

    def test_export_import_roundtrip(self):
        d = ClientDirectory(rank=1, total=2)
        keys = [bytes([i + 1]) * 32 for i in range(5)]
        for k in keys[:3]:
            d.assign(k)
        d.apply(0, keys[3], rank=0)
        restored = ClientDirectory(rank=1, total=2)
        assert restored.import_(d.export()) == 4
        for cid in (0, 1, 3, 5):
            assert restored.get(cid) == d.get(cid)
        assert restored.export() == d.export()

    @pytest.mark.asyncio
    async def test_checkpoint_roundtrip(self, tmp_path):
        accounts, recent = Accounts(), RecentTransactions()
        d = ClientDirectory(rank=0, total=2)
        keys = [bytes([i + 9]) * 32 for i in range(3)]
        ids = [d.assign(k)[0] for k in keys]
        path = str(tmp_path / "ledger.json")
        await checkpoint.save(path, accounts, recent, d)
        restored = ClientDirectory(rank=0, total=2)
        ok = await checkpoint.load(
            path, Accounts(), RecentTransactions(), restored
        )
        assert ok is True
        assert [restored.id_of(k) for k in keys] == ids
        # stride cursor restored: the next assign must not collide
        cid, created = restored.assign(b"\x77" * 32)
        assert created and cid not in ids

    @pytest.mark.asyncio
    async def test_checkpoint_without_directory_still_loads(self, tmp_path):
        path = str(tmp_path / "ledger.json")
        await checkpoint.save(path, Accounts(), RecentTransactions())
        d = ClientDirectory()
        ok = await checkpoint.load(path, Accounts(), RecentTransactions(), d)
        assert ok is True and len(d) == 0


class TestNativeParity:
    """The native distilled parser and `expand_py` must accept the exact
    same frame language and expand to identical bytes."""

    @pytest.fixture(autouse=True)
    def _need_native(self):
        from at2_node_tpu.native.ingest import ingest_available

        if not ingest_available():
            pytest.skip("native ingest library unavailable")

    def _assert_parity(self, frame: bytes, directory: ClientDirectory):
        from at2_node_tpu.native.ingest import distill_parse_native

        table, limit = directory.keys_view()
        native = distill_parse_native(frame, table, limit)
        try:
            bodies, ids, ok = distill.expand_py(frame, directory.get)
        except DistillError:
            assert native is None, "native accepted a frame python rejects"
            return
        assert native is not None, "native rejected a frame python accepts"
        n_bodies, n_ids, n_ok = native
        assert bytes(bodies) == bytes(n_bodies)
        assert ids == list(int(i) for i in n_ids)
        assert ok == list(bool(o) for o in n_ok)

    def test_differential_fuzz(self):
        rng = random.Random(3)
        directory = ClientDirectory(rank=1, total=3)
        for i in range(40):
            directory.assign(bytes([i + 1]) * 32)
        for trial in range(120):
            entries = _rand_entries(rng, rng.randint(1, 32), max_id=80)
            frame, _ = distill.distill(entries)
            self._assert_parity(frame, directory)
            # and a hostile mutant of the same frame
            self._assert_parity(mutate_distilled_frame(frame, rng), directory)

    def test_miss_positions_agree(self):
        directory = ClientDirectory(rank=0, total=2)
        known = directory.assign(b"\x0a" * 32)[0]
        entries = [
            DistilledEntry(known, 1, b"\x0b" * 32, 1, b"\x01" * 64),
            DistilledEntry(known + 1, 1, known, 1, b"\x02" * 64),  # both miss
            DistilledEntry(10**9, 3, b"\x0c" * 32, 2, b"\x03" * 64),  # miss
        ]
        frame, _ = distill.distill(entries)
        _, _, ok = distill.expand_py(frame, directory.get)
        assert ok == [True, False, False]
        self._assert_parity(frame, directory)


class TestDistilledIngress:
    """The node-side handler on the simulated fabric: commit via
    distilled frames, replay dedup, directory misses, and the
    never-forge property at the ledger."""

    def _net(self, seed: int):
        from at2_node_tpu.sim.net import SimNet

        return SimNet(4, 1, seed, hostile=0).start()

    def _frame(self, cid: int, client, rows):
        entries = []
        for seq, recipient, amount in rows:
            entries.append(
                DistilledEntry(
                    cid, seq, recipient, amount,
                    client.sign(
                        transfer_signing_bytes(
                            client.public, seq, recipient, amount
                        )
                    ),
                )
            )
        frame, _ = distill.distill(entries)
        return frame

    def test_commit_dedup_and_miss(self):
        from at2_node_tpu.sim.net import sim_client

        net = self._net(901)
        try:
            run = net.loop.run_until_complete
            client = sim_client(901, 0)
            cid = run(net.aregister(0, client.public))
            assert cid is not None
            rcpt = sim_client(901, 1).public
            frame = self._frame(cid, client, [(s, rcpt, 2) for s in (1, 2, 3)])
            assert run(net.asubmit_distilled(0, frame)) is None
            net.settle(horizon=60.0)
            for s in net.services:
                assert run(s.accounts.get_last_sequence(client.public)) == 3
                assert run(s.accounts.get_balance(rcpt)) == 100_006
            svc = net.services[0]
            assert svc.distill_stats["distilled_batches_rx"] == 1
            # exact replay: every slot already ingested -> dedup drops
            assert run(net.asubmit_distilled(0, frame)) is None
            net.settle(horizon=30.0)
            assert svc.distill_stats["dedup_drops"] == 3
            assert run(svc.accounts.get_last_sequence(client.public)) == 3
            # unknown sender id -> directory miss, no state change
            bogus = distill.distill(
                [DistilledEntry(cid + 10**6, 1, rcpt, 1, b"\x05" * 64)]
            )[0]
            assert run(net.asubmit_distilled(1, bogus)) is None
            assert net.services[1].distill_stats["directory_misses"] == 1
            # malformed frame -> whole-frame rejection at the RPC
            err = run(net.asubmit_distilled(0, b"\xd5\x01junk"))
            assert err is not None
            net.touched.update((client.public, rcpt))
            assert net.check_invariants() == []
        finally:
            net.close()

    def test_forged_entries_never_commit(self):
        from at2_node_tpu.sim.net import sim_client

        net = self._net(902)
        try:
            run = net.loop.run_until_complete
            client = sim_client(902, 0)
            cid = run(net.aregister(0, client.public))
            rcpt = sim_client(902, 1).public
            # a "broker" that forges: valid frame shape, garbage
            # signature (it never had the client's secret key)
            forged = distill.distill(
                [DistilledEntry(cid, 1, rcpt, 50, b"\x0f" * 64)]
            )[0]
            assert run(net.asubmit_distilled(0, forged)) is None  # ACKed...
            # ...but never admitted: signature verification is the gate
            net.settle(horizon=40.0)
            for s in net.services:
                assert run(s.accounts.get_last_sequence(client.public)) == 0
                assert run(s.accounts.get_balance(rcpt)) == 100_000
            assert net.services[0].admission_stats["rejected_at_ingress"] >= 1
            # an ALTERED entry (signature from a different body) is the
            # same story: the broker cannot redirect or reprice a transfer
            altered = distill.distill(
                [
                    DistilledEntry(
                        cid, 1, rcpt, 9999,
                        client.sign(
                            transfer_signing_bytes(client.public, 1, rcpt, 1)
                        ),
                    )
                ]
            )[0]
            assert run(net.asubmit_distilled(1, altered)) is None
            net.settle(horizon=40.0)
            for s in net.services:
                assert run(s.accounts.get_last_sequence(client.public)) == 0
        finally:
            net.close()


class TestBrokerRoundtrip:
    """Real gRPC: clients -> broker -> distilled frames -> node -> commit."""

    @pytest.mark.asyncio
    async def test_collect_distill_commit(self):
        from at2_node_tpu.broker import Broker
        from at2_node_tpu.client import Client
        from at2_node_tpu.crypto.keys import ExchangeKeyPair
        from at2_node_tpu.net.peers import Peer
        from at2_node_tpu.node.config import Config
        from at2_node_tpu.node.service import Service

        cfgs = [
            Config(
                node_address=f"127.0.0.1:{next(_ports)}",
                rpc_address=f"127.0.0.1:{next(_ports)}",
                sign_key=SignKeyPair.random(),
                network_key=ExchangeKeyPair.random(),
            )
            for _ in range(2)
        ]
        for i, cfg in enumerate(cfgs):
            cfg.nodes = [
                Peer(o.node_address, o.network_key.public, o.sign_key.public)
                for j, o in enumerate(cfgs)
                if j != i
            ]
        services = [await Service.start(c) for c in cfgs]
        broker_addr = f"127.0.0.1:{next(_ports)}"
        broker = await Broker.start(
            f"http://{cfgs[0].rpc_address}",
            broker_addr,
            max_entries=16,
            window=0.01,
        )
        try:
            kp = SignKeyPair.random()
            async with Client(f"http://{broker_addr}") as c:
                cid = await c.register(kp.public)
                assert await c.register(kp.public) == cid  # idempotent
                await c.send_asset_many(
                    kp, [(s, kp.public, 1) for s in range(1, 21)]
                )
                # the broker proxies reads, so commit is observable on it
                deadline = asyncio.get_event_loop().time() + 15.0
                while asyncio.get_event_loop().time() < deadline:
                    if await c.get_last_sequence(kp.public) == 20:
                        break
                    await asyncio.sleep(0.1)
                assert await c.get_last_sequence(kp.public) == 20
            # totality: node1, which the broker never talked to, converges
            async with Client(f"http://{cfgs[1].rpc_address}") as c1:
                deadline = asyncio.get_event_loop().time() + 15.0
                while asyncio.get_event_loop().time() < deadline:
                    if await c1.get_last_sequence(kp.public) == 20:
                        break
                    await asyncio.sleep(0.1)
                assert await c1.get_last_sequence(kp.public) == 20
            # and the directory gossip reached node1
            deadline = asyncio.get_event_loop().time() + 10.0
            while asyncio.get_event_loop().time() < deadline:
                if services[1].directory.get(cid) == kp.public:
                    break
                await asyncio.sleep(0.1)
            assert services[1].directory.get(cid) == kp.public
            assert services[0].distill_stats["distilled_batches_rx"] >= 1
            assert broker.stats["broker_entries_tx"] == 20
            assert broker.stats["broker_batches_tx"] >= 1

            # broker-hop causal tracing: the broker's relay spans carry
            # the same (sender, seq) keys as the node spans, so stitch()
            # joins client→broker→node→commit and decomposes the hop
            import json

            from at2_node_tpu.tools.trace_collect import stitch

            status, _, body = broker.obs_http("/tracez")
            assert status == 200
            broker_dump = json.loads(body)
            assert broker_dump["node"] == f"broker:{broker.node_uri}"
            st = stitch([s.tracez() for s in services] + [broker_dump])
            assert st["coverage"]["with_broker"] >= 1
            hop_txs = [t for t in st["txs"] if "broker_hop" in t]
            assert hop_txs
            hop = hop_txs[0]["broker_hop"]
            # queue (rx→flush) + handoff (flush→ingress) + plane
            # (ingress→commit) cover the end-to-end total
            assert {"queue_ms", "handoff_ms", "plane_ms", "total_ms",
                    "bottleneck"} <= set(hop)
            assert hop["queue_ms"] >= 0 and hop["handoff_ms"] >= 0
            assert hop["total_ms"] >= hop["plane_ms"] > 0
            segs = st["broker_hop"]["segments"]
            assert segs["total_ms"]["count"] == len(hop_txs)

            # broker health: ok far from PENDING_CAP, verdict embedded
            # in /statusz for the top.py broker row
            status, _, body = broker.obs_http("/healthz")
            assert status == 200
            hv = json.loads(body)
            assert hv["status"] == "ok" and hv["backpressure"] is False
            assert hv["role"] == "broker" and hv["pending"] == 0
            status, _, body = broker.obs_http("/statusz")
            sz = json.loads(body)
            assert sz["role"] == "broker"
            assert sz["health"]["status"] == "ok"
            assert sz["flush"]["count"] >= 1

            # satellite recorder codes: broker flush decisions, node
            # distilled-ingress events
            broker_codes = {e[1] for e in broker.recorder.dump()["events"]}
            assert "flush" in broker_codes
            node_codes = {
                e[1] for e in services[0].recorder.dump()["events"]
            }
            assert "distill_rx" in node_codes
        finally:
            await broker.close()
            for s in services:
                await s.close()


class TestByzantineBrokerCampaign:
    def test_campaign_green_and_replays(self):
        from at2_node_tpu.sim.campaign import run_episode

        first = run_episode(
            424, broker=True, n_events=12, duration=8.0, settle_horizon=60.0
        )
        assert first.violations == []
        assert sum(first.committed) > 0, "no distilled traffic committed"
        again = run_episode(
            424, broker=True, n_events=12, duration=8.0, settle_horizon=60.0
        )
        assert again.trace_hash == first.trace_hash  # exact-seed replay
        assert again.committed == first.committed

    def test_generator_covers_mutations(self):
        from at2_node_tpu.sim.campaign import (
            BROKER_MUTATIONS,
            generate_broker_events,
        )

        rng = random.Random(5)
        seen = set()
        for _ in range(30):
            for t, kind, args in generate_broker_events(rng, n_events=20):
                if kind == "bsub":
                    seen.add(args["mutation"])
        assert seen == set(BROKER_MUTATIONS)


class TestReviewHardening:
    """Regressions for the ingress-tier review findings: signature
    replay at a shifted sequence, unbounded directory allocation,
    unthrottled registration, and the broker buffer-cap race."""

    def test_directory_apply_bounds(self):
        from at2_node_tpu.node.directory import (
            APPLY_GAP_SLACK,
            MAX_CLIENTS_PER_RANK,
        )

        d = ClientDirectory(rank=0, total=2)
        # an announce naming an astronomical id in the announcer's OWN
        # stride must be refused BEFORE any allocation: accepting it
        # would materialize an exabyte-scale dense key array
        huge = 1 + 2 * (1 << 60)
        assert d.apply(huge, b"\x11" * 32, rank=1) is False
        assert len(d) == 0
        # per-stride hard cap, independent of the gap slack
        at_cap = 1 + 2 * MAX_CLIENTS_PER_RANK
        assert d.apply(at_cap, b"\x11" * 32, rank=1) is False
        # within the slack an id may run ahead of installed count...
        assert d.apply(1 + 2 * APPLY_GAP_SLACK, b"\x12" * 32, rank=1) is True
        # ...but one past the (now advanced) slack is refused
        beyond = 1 + 2 * (APPLY_GAP_SLACK + 1 + APPLY_GAP_SLACK + 1)
        assert d.apply(beyond, b"\x13" * 32, rank=1) is False
        # honest in-order announces are unaffected
        assert d.apply(1, b"\x14" * 32, rank=1) is True

    def test_directory_assign_cap(self, monkeypatch):
        from at2_node_tpu.node import directory as dir_mod

        monkeypatch.setattr(dir_mod, "MAX_CLIENTS_PER_RANK", 2)
        d = ClientDirectory(rank=0, total=1)
        assert d.assign(b"\x01" * 32) == (0, True)
        assert d.assign(b"\x02" * 32) == (1, True)
        with pytest.raises(dir_mod.DirectoryFullError):
            d.assign(b"\x03" * 32)
        # idempotent lookup of a known key still works at the cap
        assert d.assign(b"\x01" * 32) == (0, False)

    def test_replay_at_shifted_sequence_rejected(self):
        """A byzantine broker re-encoding a captured client signature at
        the sender's next sequence must die at ingress: the v2 preimage
        (types.transfer_signing_bytes) binds sender and sequence."""
        from at2_node_tpu.sim.net import SimNet, sim_client

        net = SimNet(4, 1, 903, hostile=0).start()
        try:
            run = net.loop.run_until_complete
            client = sim_client(903, 0)
            cid = run(net.aregister(0, client.public))
            assert cid is not None
            rcpt = sim_client(903, 1).public

            def frame(rows):
                entries = [
                    DistilledEntry(
                        cid, seq, rcpt, amount,
                        client.sign(
                            transfer_signing_bytes(
                                client.public, seq, rcpt, amount
                            )
                        ),
                    )
                    for seq, amount in rows
                ]
                return distill.distill(entries)[0]

            assert run(net.asubmit_distilled(0, frame([(1, 5), (2, 5)]))) is None
            net.settle(horizon=60.0)
            for s in net.services:
                assert run(s.accounts.get_last_sequence(client.public)) == 2
            # replay seq-2's signature at seq 3, identical recipient and
            # amount — exactly the repeated-spend re-encoding
            captured = distill.decode(frame([(2, 5)]))[0]
            replay = distill.distill(
                [DistilledEntry(cid, 3, rcpt, 5, captured.signature)]
            )[0]
            assert run(net.asubmit_distilled(1, replay)) is None
            net.settle(horizon=30.0)
            assert net.services[1].admission_stats["rejected_at_ingress"] >= 1
            for s in net.services:
                assert run(s.accounts.get_last_sequence(client.public)) == 2
            # the slot is not burned: the client's own seq-3 commits
            assert run(net.asubmit_distilled(0, frame([(3, 7)]))) is None
            net.settle(horizon=60.0)
            for s in net.services:
                assert run(s.accounts.get_last_sequence(client.public)) == 3
            net.touched.update((client.public, rcpt))
            assert net.check_invariants() == []
        finally:
            net.close()

    def test_register_throttle_and_stride_gated_announce(self):
        from at2_node_tpu.broadcast.messages import DIR_ANNOUNCE
        from at2_node_tpu.sim.net import SimNet, sim_client

        net = SimNet(2, 0, 904, hostile=0).start()
        try:
            run = net.loop.run_until_complete
            svc0 = net.services[0]
            # throttle: new assignments charge the per-source register
            # bucket; re-registration of a known key stays free
            svc0.config.admission.register_limit = 2
            svc0.config.admission.register_window = 10_000.0
            k1, k2, k3 = (sim_client(904, i).public for i in range(3))
            cid1 = run(net.aregister(0, k1))
            assert cid1 is not None
            assert run(net.aregister(0, k2)) is not None
            assert run(net.aregister(0, k3)) is None  # bucket drained
            assert svc0.admission_stats["admission_throttled"] >= 1
            assert run(net.aregister(0, k1)) == cid1  # lookup: free
            # stride gate: node 1 learned (cid1 -> k1) via gossip; a
            # Register for the same key on node 1 must return the id
            # WITHOUT re-announcing it under node 1's origin (receivers
            # would drop the out-of-stride announce anyway)
            net.settle(horizon=30.0)
            assert net.services[1].directory.get(cid1) == k1
            sent = []
            mesh1 = net.services[1].mesh
            orig = mesh1.broadcast

            def spy(frame, *a, **kw):
                sent.append(bytes(frame))
                return orig(frame, *a, **kw)

            mesh1.broadcast = spy
            assert run(net.aregister(1, k1)) == cid1
            assert not any(f and f[0] == DIR_ANNOUNCE for f in sent)
            # a genuinely new key on node 1 still announces its own id
            k4 = sim_client(904, 9).public
            assert run(net.aregister(1, k4)) is not None
            assert any(f and f[0] == DIR_ANNOUNCE for f in sent)
        finally:
            net.close()

    @pytest.mark.asyncio
    async def test_broker_collect_recheck_after_awaits(self, monkeypatch):
        """Two _collect calls interleaving at the Register await must
        not overshoot PENDING_CAP: the capacity check re-runs with no
        await point before the buffer extend."""
        from at2_node_tpu import broker as broker_mod
        from at2_node_tpu.proto import at2_pb2 as pb

        monkeypatch.setattr(broker_mod, "PENDING_CAP", 3)
        br = broker_mod.Broker("http://127.0.0.1:1", window=60.0)
        gate = asyncio.Event()

        async def slow_client_id(pubkey):
            await gate.wait()
            return 1

        br._client_id = slow_client_id

        class Ctx:
            def peer(self):
                return "test"

            async def abort(self, code, details=""):
                raise RuntimeError(f"abort {code}: {details}")

        kp = SignKeyPair.random()

        def reqs(base):
            return [
                pb.SendAssetRequest(
                    sender=kp.public, sequence=base + i,
                    recipient=kp.public, amount=1, signature=b"\x01" * 64,
                )
                for i in range(2)
            ]

        try:
            tasks = [
                asyncio.ensure_future(br._collect(reqs(b), Ctx()))
                for b in (1, 10)
            ]
            await asyncio.sleep(0)  # both pass the pre-check, both stall
            gate.set()
            results = await asyncio.gather(*tasks, return_exceptions=True)
            aborted = [r for r in results if isinstance(r, RuntimeError)]
            assert len(aborted) == 1, results
            assert len(br._buf) == 2  # never overshot the cap of 3
            assert br.stats["broker_overflow_drops"] == 2
        finally:
            br._buf.clear()
            if br._flush_task is not None:
                br._flush_task.cancel()
            await br.close()
