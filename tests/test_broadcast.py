"""Broadcast-layer tests: wire codec, transport crypto, and the
three-phase state machine driven through an in-memory mesh.

Mirrors the byzantine-ish property tests SURVEY.md §7 calls for (hard
part #3): equivocation filtering and totality are exercised with injected
duplicates and conflicting payloads — cases the reference never tests
because its thresholds=n config sidesteps faults.
"""

import asyncio

import pytest

from at2_node_tpu.broadcast.messages import (
    ECHO,
    READY,
    Attestation,
    ContentRequest,
    Payload,
    WireError,
    parse_frame,
)
from at2_node_tpu.broadcast.stack import Broadcast
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.crypto.verifier import CpuVerifier
from at2_node_tpu.net.peers import Peer
from at2_node_tpu.types import ThinTransaction


def make_payload(keypair, seq=1, amount=10, recipient=b"r" * 32):
    return Payload.create(keypair, seq, ThinTransaction(recipient, amount))


class TestWire:
    def test_payload_roundtrip(self):
        kp = SignKeyPair.random()
        p = make_payload(kp, seq=7, amount=123)
        [decoded] = parse_frame(p.encode())
        assert decoded == p
        assert decoded.content_hash() == p.content_hash()

    def test_attestation_roundtrip(self):
        kp = SignKeyPair.random()
        chash = b"h" * 32
        sig = kp.sign(Attestation.signing_bytes(ECHO, b"s" * 32, 3, chash))
        att = Attestation(ECHO, kp.public, b"s" * 32, 3, chash, sig)
        [decoded] = parse_frame(att.encode())
        assert decoded == att

    def test_coalesced_frame(self):
        kp = SignKeyPair.random()
        p = make_payload(kp)
        sig = kp.sign(Attestation.signing_bytes(READY, kp.public, 1, b"h" * 32))
        att = Attestation(READY, kp.public, kp.public, 1, b"h" * 32, sig)
        msgs = parse_frame(p.encode() + att.encode() + p.encode())
        assert msgs == [p, att, p]

    def test_echo_not_replayable_as_ready(self):
        assert Attestation.signing_bytes(
            ECHO, b"s" * 32, 1, b"h" * 32
        ) != Attestation.signing_bytes(READY, b"s" * 32, 1, b"h" * 32)

    def test_truncated_frame_rejected(self):
        kp = SignKeyPair.random()
        with pytest.raises(WireError):
            parse_frame(make_payload(kp).encode()[:-1])
        with pytest.raises(WireError):
            parse_frame(b"\xff" + b"x" * 200)


class FakeMesh:
    """In-memory mesh: records outbound frames, exposes peer maps."""

    def __init__(self, peers):
        self.peers = peers
        self.by_sign = {p.sign_public: p for p in peers}
        self.by_exchange = {p.exchange_public: p for p in peers}
        self.sent = []
        self.unicast = []  # (peer, frame) pairs from Mesh.send

    def broadcast(self, frame, exclude=()):
        self.sent.append(frame)

    def send(self, peer, frame):
        self.unicast.append((peer, frame))

    def sent_messages(self):
        return [m for f in self.sent for m in parse_frame(f)]


async def inject(bcast, msg, peer=None):
    """Feed one message into the broadcast inbox as the workers expect it
    ((peer, msg); peer=None models local submission)."""
    await bcast._inbox.put((peer, msg))


def make_net(n_peers):
    """A local broadcast endpoint plus n_peers signing identities."""
    peer_keys = [SignKeyPair.random() for _ in range(n_peers)]
    peers = [
        Peer(f"127.0.0.1:{9000+i}", bytes([i]) * 32, kp.public)
        for i, kp in enumerate(peer_keys)
    ]
    mesh = FakeMesh(peers)
    node_key = SignKeyPair.random()
    bcast = Broadcast(node_key, mesh, CpuVerifier(), workers=4)
    return bcast, mesh, peer_keys


async def start(bcast):
    await bcast.start()
    return bcast


def echo_from(peer_kp, payload, phase=ECHO, chash=None):
    chash = chash if chash is not None else payload.content_hash()
    sig = peer_kp.sign(
        Attestation.signing_bytes(phase, payload.sender, payload.sequence, chash)
    )
    return Attestation(
        phase, peer_kp.public, payload.sender, payload.sequence, chash, sig
    )


async def settle(bcast, timeout=2.0):
    """Wait until the broadcast inbox fully drains."""
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if bcast._inbox.empty():
            await asyncio.sleep(0.05)
            if bcast._inbox.empty():
                return
        await asyncio.sleep(0.01)


class TestStateMachine:
    @pytest.mark.asyncio
    async def test_single_node_delivers_immediately(self):
        # empty peer list => thresholds 0 (the reference's standalone-node
        # mode, tests/server-config-resolve-addrs)
        bcast, mesh, _ = make_net(0)
        await start(bcast)
        sender = SignKeyPair.random()
        await bcast.broadcast(make_payload(sender))
        delivered = await asyncio.wait_for(bcast.delivered.get(), 2)
        assert delivered.sender == sender.public
        await bcast.close()

    @pytest.mark.asyncio
    async def test_full_quorum_delivers(self):
        bcast, mesh, peer_keys = make_net(3)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        await bcast.broadcast(payload)
        for kp in peer_keys:
            await inject(bcast, echo_from(kp, payload, ECHO))
        for kp in peer_keys:
            await inject(bcast, echo_from(kp, payload, READY))
        delivered = await asyncio.wait_for(bcast.delivered.get(), 2)
        assert delivered == payload
        # the node itself gossiped, echoed, and readied
        kinds = [type(m).__name__ for m in mesh.sent_messages()]
        assert "Payload" in kinds
        phases = [m.phase for m in mesh.sent_messages() if hasattr(m, "phase")]
        assert ECHO in phases and READY in phases
        await bcast.close()

    @pytest.mark.asyncio
    async def test_below_threshold_does_not_deliver(self):
        bcast, mesh, peer_keys = make_net(3)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        await bcast.broadcast(payload)
        for kp in peer_keys[:2]:  # 2 of 3 echoes: below threshold
            await inject(bcast, echo_from(kp, payload, ECHO))
        await settle(bcast)
        assert bcast.delivered.empty()
        await bcast.close()

    @pytest.mark.asyncio
    async def test_invalid_payload_signature_dropped(self):
        bcast, mesh, _ = make_net(0)
        await start(bcast)
        sender = SignKeyPair.random()
        thin = ThinTransaction(b"r" * 32, 10)
        bad = Payload(sender.public, 1, thin, b"\x01" * 64)
        await bcast.broadcast(bad)
        await settle(bcast)
        assert bcast.delivered.empty()
        assert bcast.stats["invalid_sig"] == 1
        await bcast.close()

    @pytest.mark.asyncio
    async def test_attestation_from_unknown_origin_ignored(self):
        bcast, mesh, peer_keys = make_net(1)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        await bcast.broadcast(payload)
        outsider = SignKeyPair.random()  # not in the peer set
        await inject(bcast, echo_from(outsider, payload, ECHO))
        await inject(bcast, echo_from(outsider, payload, READY))
        await settle(bcast)
        assert bcast.delivered.empty()
        await bcast.close()

    @pytest.mark.asyncio
    async def test_duplicate_votes_count_once(self):
        bcast, mesh, peer_keys = make_net(2)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        await bcast.broadcast(payload)
        # one peer echoes three times; the other stays silent
        for _ in range(3):
            await inject(bcast, echo_from(peer_keys[0], payload, ECHO))
        await settle(bcast)
        assert bcast.delivered.empty()  # 1 distinct echo < threshold 2
        await bcast.close()

    @pytest.mark.asyncio
    async def test_equivocating_sender_delivers_at_most_one(self):
        # byzantine client: two conflicting payloads for the same slot
        bcast, mesh, peer_keys = make_net(2)
        await start(bcast)
        sender = SignKeyPair.random()
        pay_a = make_payload(sender, amount=10)
        pay_b = make_payload(sender, amount=99)
        await bcast.broadcast(pay_a)
        await bcast.broadcast(pay_b)
        await settle(bcast)
        # the node must have echoed only ONE content for the slot
        echoes = [
            m
            for m in mesh.sent_messages()
            if isinstance(m, Attestation) and m.phase == ECHO
        ]
        assert len(echoes) == 1
        # full quorum on content A only
        for kp in peer_keys:
            await inject(bcast, echo_from(kp, pay_a, ECHO))
        for kp in peer_keys:
            await inject(bcast, echo_from(kp, pay_a, READY))
        delivered = await asyncio.wait_for(bcast.delivered.get(), 2)
        assert delivered == pay_a
        await settle(bcast)
        assert bcast.delivered.empty()  # B never delivers
        await bcast.close()

    @pytest.mark.asyncio
    async def test_ready_amplification_totality(self):
        # a node that saw NO echoes still delivers once it sees a full
        # Ready quorum (plus the payload) — contagion's totality property
        bcast, mesh, peer_keys = make_net(2)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        await bcast.broadcast(payload)  # payload known, but no echoes arrive
        for kp in peer_keys:
            await inject(bcast, echo_from(kp, payload, READY))
        delivered = await asyncio.wait_for(bcast.delivered.get(), 2)
        assert delivered == payload
        # and the node joined the Ready quorum itself (amplification)
        phases = [m.phase for m in mesh.sent_messages() if hasattr(m, "phase")]
        assert READY in phases
        await bcast.close()

    @pytest.mark.asyncio
    async def test_forged_attestation_does_not_shadow_real_vote(self):
        # an attacker relays a badly-signed echo claiming a peer's origin;
        # the peer's real echo must still count afterwards
        bcast, mesh, peer_keys = make_net(1)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        await bcast.broadcast(payload)
        forged = Attestation(
            ECHO,
            peer_keys[0].public,
            payload.sender,
            payload.sequence,
            payload.content_hash(),
            b"\x02" * 64,
        )
        await inject(bcast, forged)
        await settle(bcast)
        await inject(bcast, echo_from(peer_keys[0], payload, ECHO))
        await inject(bcast, echo_from(peer_keys[0], payload, READY))
        delivered = await asyncio.wait_for(bcast.delivered.get(), 2)
        assert delivered == payload
        await bcast.close()

    @pytest.mark.asyncio
    async def test_missing_content_pulled_on_ready_quorum(self):
        # totality catch-up: the node sees a full Ready quorum but the
        # payload gossip never arrived — it must pull the content from the
        # Ready voters and deliver once a voter serves it
        bcast, mesh, peer_keys = make_net(2)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        for kp in peer_keys:  # quorum with NO payload
            await inject(bcast, echo_from(kp, payload, READY))
        await settle(bcast)
        assert bcast.delivered.empty()
        requests = [
            m
            for _, f in mesh.unicast
            for m in parse_frame(f)
            if isinstance(m, ContentRequest)
        ]
        assert requests, "node never requested the missing content"
        req = requests[0]
        assert req.sender == payload.sender
        assert req.content_hash == payload.content_hash()
        # a voter serves the payload over its authenticated channel
        await inject(bcast, payload, peer=mesh.peers[0])
        delivered = await asyncio.wait_for(bcast.delivered.get(), 2)
        assert delivered == payload
        await bcast.close()

    @pytest.mark.asyncio
    async def test_equivocating_peer_votes_count_for_one_content_only(self):
        # a byzantine PEER echoes two different contents for one slot; only
        # its first verified vote may count (echo_by_origin), so neither
        # content can assemble a quorum from one voter
        bcast, mesh, peer_keys = make_net(2)
        await start(bcast)
        sender = SignKeyPair.random()
        pay_a = make_payload(sender, amount=1)
        pay_b = make_payload(sender, amount=2)
        await bcast.broadcast(pay_a)
        await bcast.broadcast(pay_b)
        await settle(bcast)
        # peer 0 equivocates: echoes BOTH contents; peer 1 echoes only A
        await inject(bcast, echo_from(peer_keys[0], pay_a, ECHO))
        await inject(bcast, echo_from(peer_keys[0], pay_b, ECHO))
        await inject(bcast, echo_from(peer_keys[1], pay_a, ECHO))
        await settle(bcast)
        state = bcast._slots[pay_a.slot]
        assert len(state.echoes[pay_a.content_hash()]) == 2
        assert len(state.echoes[pay_b.content_hash()]) == 0  # vote discarded
        await bcast.close()

    @pytest.mark.asyncio
    async def test_replayed_attestation_not_reverified(self):
        # exact duplicate (same signature) is dropped by the dedup set
        # BEFORE hitting the verifier (capacity protection)
        bcast, mesh, peer_keys = make_net(2)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        att = echo_from(peer_keys[0], payload, ECHO)
        for _ in range(5):
            await inject(bcast, att)
        await settle(bcast)
        verifier_calls = bcast.verifier.signatures_verified
        assert verifier_calls == 1, f"verified {verifier_calls} times"
        await bcast.close()

    @pytest.mark.asyncio
    async def test_delivered_slot_gossip_suppressed_after_compaction(self):
        # once a slot is delivered and compacted, late gossip for it is
        # dropped without re-creating state (memory bound after GC)
        bcast, mesh, peer_keys = make_net(0)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        await bcast.broadcast(payload)
        await asyncio.wait_for(bcast.delivered.get(), 2)
        # simulate GC compaction
        bcast._delivered_slots.add(payload.slot)
        del bcast._slots[payload.slot]
        await inject(bcast, payload, peer=None)
        await settle(bcast)
        assert payload.slot not in bcast._slots
        assert bcast.delivered.empty()
        await bcast.close()

    @pytest.mark.asyncio
    async def test_quorate_content_admitted_past_content_cap(self):
        # a byzantine equivocator fills the per-slot content cap with junk;
        # the content the honest quorum actually voted for must still be
        # admitted when it arrives (pull response or retransmission) —
        # otherwise the slot can never deliver (round-2 review finding)
        from at2_node_tpu.broadcast.stack import MAX_CONTENTS_PER_SLOT

        bcast, mesh, peer_keys = make_net(2)
        await start(bcast)
        sender = SignKeyPair.random()
        for i in range(MAX_CONTENTS_PER_SLOT):
            await inject(bcast, make_payload(sender, amount=100 + i))
        await settle(bcast)
        target = make_payload(sender, amount=999)  # not stored: cap is full
        for kp in peer_keys:
            await inject(bcast, echo_from(kp, target, READY))
        await settle(bcast)
        assert bcast.delivered.empty()
        await inject(bcast, target, peer=mesh.peers[0])
        delivered = await asyncio.wait_for(bcast.delivered.get(), 2)
        assert delivered == target
        await bcast.close()

    @pytest.mark.asyncio
    async def test_content_request_served_from_held_content(self):
        # the serving side: a node that HAS the payload answers a peer's
        # ContentRequest with a unicast copy
        bcast, mesh, peer_keys = make_net(2)
        await start(bcast)
        sender = SignKeyPair.random()
        payload = make_payload(sender)
        await bcast.broadcast(payload)
        await settle(bcast)
        req = ContentRequest(
            payload.sender, payload.sequence, payload.content_hash()
        )
        await inject(bcast, req, peer=mesh.peers[1])
        await settle(bcast)
        served = [
            (p, m)
            for p, f in mesh.unicast
            for m in parse_frame(f)
            if isinstance(m, Payload)
        ]
        assert served and served[0][0] == mesh.peers[1]
        assert served[0][1] == payload
        assert bcast.stats["content_served"] == 1
        await bcast.close()
