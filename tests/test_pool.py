"""Sharded verifier pool tests on the virtual 8-device CPU mesh.

Mirrors the reference's approach of testing distribution on localhost
(`/root/reference/tests/cli.rs:162-208`): real sharding machinery, virtual
devices. The conftest forces 8 CPU devices, so every sharded program here
compiles and runs exactly as it would across a v5e-8 slice (minus ICI).
"""

import asyncio

import numpy as np
import pytest

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.parallel import pool


def _sigs(n, tamper_every=None):
    kp = SignKeyPair.from_hex("11" * 32)
    pks, msgs, sigs = [], [], []
    for i in range(n):
        msg = b"pool message %d" % i
        sig = kp.sign(msg)
        if tamper_every and i % tamper_every == 0:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        pks.append(kp.public)
        msgs.append(msg)
        sigs.append(sig)
    return pks, msgs, sigs


def test_mesh_spans_all_devices():
    mesh = pool.make_mesh()
    assert mesh.devices.size == 8


def test_pool_bucket_rounds_to_device_multiple():
    assert pool.pool_bucket_for(10, 8) == 64
    assert pool.pool_bucket_for(65, 8) == 256
    # a size not dividing 8 is skipped in favor of the next divisible bucket
    assert pool.pool_bucket_for(3, 3) == 66


@pytest.mark.slow
def test_sharded_verify_matches_ground_truth():
    pks, msgs, sigs = _sigs(24, tamper_every=5)
    out = pool.verify_batch_sharded(pks, msgs, sigs)
    expected = np.array([i % 5 != 0 for i in range(24)])
    assert out.shape == (24,)
    assert (out == expected).all()


@pytest.mark.slow
def test_sharded_count_collective():
    """The replicated valid-count output exercises the cross-device
    reduction (AllReduce on real hardware)."""
    pks, msgs, sigs = _sigs(16, tamper_every=4)
    mesh = pool.make_mesh()
    import jax.numpy as jnp

    from at2_node_tpu.ops import ed25519 as kernel

    a, r, s_w, h_w, valid = kernel.prepare_batch(pks, msgs, sigs, 64)
    ok, count = pool._count_fn(mesh)(
        jnp.asarray(a), jnp.asarray(r), jnp.asarray(s_w),
        jnp.asarray(h_w), jnp.asarray(valid),
    )
    assert int(count) == 12  # 16 - 4 tampered
    assert np.asarray(ok)[:16].sum() == 12


@pytest.mark.slow
@pytest.mark.asyncio
async def test_pool_verifier_async():
    pks, msgs, sigs = _sigs(20, tamper_every=7)
    v = pool.PoolVerifier(batch_size=64, max_delay=0.01)
    try:
        results = await v.verify_many(list(zip(pks, msgs, sigs)))
        assert results == [i % 7 != 0 for i in range(20)]
        assert v.signatures_verified == 20
        assert v.batches_dispatched >= 1
    finally:
        await v.close()


@pytest.mark.slow
@pytest.mark.asyncio
async def test_two_node_net_shares_one_pool_verifier():
    """BASELINE config-5 shape at test scale: two full nodes inject all
    their broadcast signature checks into ONE sharded pool
    (Service.start(verifier=...)) and a transfer still commits."""
    import itertools

    from at2_node_tpu.client import Client
    from at2_node_tpu.crypto.keys import ExchangeKeyPair
    from at2_node_tpu.net.peers import Peer
    from at2_node_tpu.node.config import Config
    from at2_node_tpu.node.service import Service

    ports = itertools.count(45800)
    shared = pool.PoolVerifier(batch_size=64, max_delay=0.005)
    await shared.warmup()
    cfgs = [
        Config(
            node_address=f"127.0.0.1:{next(ports)}",
            rpc_address=f"127.0.0.1:{next(ports)}",
            sign_key=SignKeyPair.random(),
            network_key=ExchangeKeyPair.random(),
        )
        for _ in range(2)
    ]
    for i, cfg in enumerate(cfgs):
        cfg.nodes = [
            Peer(o.node_address, o.network_key.public, o.sign_key.public)
            for j, o in enumerate(cfgs)
            if j != i
        ]
    services = []
    try:
        for cfg in cfgs:
            services.append(await Service.start(cfg, verifier=shared))
        async with Client(f"http://{cfgs[0].rpc_address}") as client:
            sender, recipient = SignKeyPair.random(), SignKeyPair.random()
            await client.send_asset(sender, 1, recipient.public, 40)
            deadline = asyncio.get_event_loop().time() + 30
            while asyncio.get_event_loop().time() < deadline:
                if await client.get_last_sequence(sender.public) == 1:
                    break
                await asyncio.sleep(0.1)
            assert await client.get_balance(sender.public) == 99_960
        assert shared.signatures_verified > 0
        assert shared.batches_dispatched > 0
    finally:
        for s in services:
            await s.close()
        await shared.close()


@pytest.mark.slow
def test_make_verifier_pool_kind():
    from at2_node_tpu.crypto.verifier import make_verifier

    async def run():
        v = make_verifier("pool", batch_size=64)
        try:
            pks, msgs, sigs = _sigs(3)
            assert await v.verify(pks[0], msgs[0], sigs[0]) is True
            assert await v.verify(pks[1], b"wrong", sigs[1]) is False
        finally:
            await v.close()

    asyncio.run(run())
