"""Finality-certificate subsystem (finality/, wire kind 16).

Covers the full externally-verifiable evidence chain:

* kind-16 co-signature wire roundtrip (and the native-parser
  differential when the C++ ingest library is buildable);
* CertAssembler quorum assembly, counters, the equivocation latch
  (attribution requires a VALID signature — forged frames cannot
  implicate a member), and export/restore through the store manifest's
  JSON seam;
* the stateless LightVerifier in both subset (f+1 known keys) and
  full (complete member list) modes, including byte-level mutants and
  the chain monotonicity rule;
* config gating: a fleet without the ``[finality]`` table keeps the
  subsystem fully inert;
* the sim lane: a finality-enabled episode produces verifiable
  certificates on every node, hostile certificate frames fuzz through
  the capture-replay bridge deterministically, and the planted
  equivocation campaign latches with attribution (slow tier — CI runs
  it twice via scripts/ci.sh for the determinism half).
"""

from __future__ import annotations

import dataclasses
import json
import random

import pytest

from at2_node_tpu.broadcast.messages import (
    CERT_SIG,
    CERT_SIG_WIRE,
    CertSig,
    parse_frame,
)
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.finality import (
    CertAssembler,
    Certificate,
    LightVerifier,
    verify_chain,
)
from at2_node_tpu.finality.light import default_threshold
from at2_node_tpu.native import ingest_available


def _keypairs(n: int, seed: int = 0):
    rng = random.Random(seed)
    return [
        SignKeyPair(bytes(rng.getrandbits(8) for _ in range(32)))
        for _ in range(n)
    ]


def _digests(seed: int = 1):
    rng = random.Random(seed)
    wm = bytes(rng.getrandbits(8) for _ in range(16))
    ranges = bytes(rng.getrandbits(8) for _ in range(128))
    dird = bytes(rng.getrandbits(8) for _ in range(8))
    return wm, ranges, dird


def _assemble(kps, *, epoch: int = 0, seed: int = 1):
    """Run every keypair's co-signature through a fresh assembler."""
    asm = CertAssembler([kp.public for kp in kps], epoch=epoch)
    wm, ranges, dird = _digests(seed)
    cert = None
    for i, kp in enumerate(kps):
        got = asm.add(CertSig.create(kp, epoch, 50 + i, wm, ranges, dird))
        cert = got or cert
    assert cert is not None
    return asm, cert


# -- wire ----------------------------------------------------------------


def test_cert_sig_wire_roundtrip():
    kp = _keypairs(1)[0]
    wm, ranges, dird = _digests()
    cosig = CertSig.create(kp, 3, 1234, wm, ranges, dird)
    frame = cosig.encode()
    assert len(frame) == CERT_SIG_WIRE
    assert frame[0] == CERT_SIG
    (back,) = parse_frame(frame)
    assert back == cosig
    # commits rides OUTSIDE the signed preimage (node-local coordinate)
    other = CertSig.create(kp, 3, 9999, wm, ranges, dird)
    assert other.signature == cosig.signature
    # epoch/wm/ranges/dir are all inside it
    assert CertSig.create(kp, 4, 1234, wm, ranges, dird).signature != (
        cosig.signature
    )


def test_certificate_roundtrip():
    kps = _keypairs(4)
    _, cert = _assemble(kps)
    raw = cert.encode()
    assert Certificate.decode(raw) == cert
    # the manifest seam is JSON: to_doc must survive dumps/loads
    doc = json.loads(json.dumps(cert.to_doc()))
    assert Certificate.from_doc(doc) == cert
    assert cert.signer_count() >= 3  # 2f+1 of 4


@pytest.mark.skipif(
    not ingest_available(), reason="native ingest library unavailable"
)
def test_native_parse_differential_kind16():
    from at2_node_tpu.native import parse_frames_native
    from at2_node_tpu.sim.hostile import mutate_cert_frame

    kp = _keypairs(1)[0]
    wm, ranges, dird = _digests()
    good = CertSig.create(kp, 0, 7, wm, ranges, dird).encode()
    rng = random.Random(11)
    frames = [good] + [mutate_cert_frame(good, rng) for _ in range(32)]
    native, frame_ok = parse_frames_native(frames)
    for fi, ok in enumerate(frame_ok):
        try:
            py = parse_frame(frames[fi])
            py_ok = True
        except Exception:
            py, py_ok = [], False
        assert bool(ok) == py_ok, f"frame {fi}: native {ok} != python"
        if py_ok:
            got = [msg for gi, msg in native if gi == fi]
            assert got == py, f"frame {fi}: parse mismatch"


# -- assembler -----------------------------------------------------------


def test_assembler_quorum_counters_and_duplicates():
    kps = _keypairs(4)
    asm = CertAssembler([kp.public for kp in kps])
    assert asm.quorum == 3  # 2f+1, f = (4-1)//3
    wm, ranges, dird = _digests()
    sigs = [CertSig.create(kp, 0, 10 + i, wm, ranges, dird)
            for i, kp in enumerate(kps)]
    assert asm.add(sigs[0]) is None
    assert asm.add(sigs[0]) is None  # duplicate
    assert asm.add(sigs[1]) is None
    cert = asm.add(sigs[2])  # third distinct signer => quorum
    assert cert is not None and cert.signer_count() == 3
    assert asm.add(sigs[3]) is None  # late cosig, cert already out
    assert asm.counters["duplicates"] == 1
    assert asm.counters["assembled"] == 1
    # non-member cosig
    outsider = SignKeyPair(bytes(range(32)))
    asm.add(CertSig.create(outsider, 0, 1, wm, ranges, dird))
    assert asm.counters["foreign"] == 1
    # stale epoch
    asm.add(CertSig.create(kps[0], 9, 1, wm, ranges, dird))
    assert asm.counters["epoch_skew"] == 1
    # forged signature
    bad = dataclasses.replace(sigs[3], signature=bytes(64))
    asm.add(bad)
    assert asm.counters["bad_sig"] == 1
    assert asm.latest == cert


def test_equivocation_latch_requires_valid_signature():
    kps = _keypairs(4)
    asm = CertAssembler([kp.public for kp in kps])
    wm, ranges, dird = _digests()
    first = CertSig.create(kps[0], 0, 5, wm, ranges, dird)
    asm.add(first)
    # a FORGED conflicting cosig must not implicate the member
    conflicting = CertSig.create(
        kps[0], 0, 5, wm, bytes(x ^ 0xFF for x in ranges), dird
    )
    forged = dataclasses.replace(conflicting, signature=bytes(64))
    asm.add(forged)
    assert asm.equivocation is None
    assert asm.counters["bad_sig"] == 1
    # the genuinely signed conflict latches with attribution
    asm.add(conflicting)
    eq = asm.equivocation
    assert eq is not None
    assert eq["origin"] == kps[0].public.hex()
    assert eq["first"]["ranges"] != eq["second"]["ranges"]
    # the latch never self-clears, even across later clean quorums
    wm2, ranges2, dird2 = _digests(seed=2)
    for i, kp in enumerate(kps):
        asm.add(CertSig.create(kp, 0, 20 + i, wm2, ranges2, dird2))
    assert asm.latest is not None
    assert asm.equivocation is not None


def test_assembler_export_restore_roundtrip():
    kps = _keypairs(4)
    asm, cert = _assemble(kps)
    # plant a latched equivocation so the evidence survives too
    wm, ranges, dird = _digests(seed=3)
    asm.add(CertSig.create(kps[1], 0, 1, wm, ranges, dird))
    asm.add(CertSig.create(kps[1], 0, 1, wm, bytes(128), dird))
    assert asm.equivocation is not None
    # the store manifest is JSON — exported state must survive the trip
    doc = json.loads(json.dumps(asm.export()))
    fresh = CertAssembler([kp.public for kp in kps])
    fresh.restore(doc)
    assert fresh.latest == cert
    assert fresh.chain == asm.chain
    assert fresh.equivocation == asm.equivocation
    # counters are runtime telemetry, deliberately NOT persisted
    assert fresh.counters["assembled"] == 0


# -- light client --------------------------------------------------------


def test_light_verifier_subset_full_and_mutants():
    kps = _keypairs(4)
    _, cert = _assemble(kps)
    keys = [kp.public for kp in kps]
    need = default_threshold(4)
    assert need == 2  # f+1 of 4
    subset = LightVerifier(keys[:need], total=4)
    full = LightVerifier([], members=keys)
    for verifier in (subset, full):
        got = verifier.verify(cert)
        assert got["ok"], got
    # preimage mutations kill every co-signature: both modes reject
    preimage_mutants = [
        dataclasses.replace(cert, ranges=bytes(x ^ 0xFF
                                               for x in cert.ranges)),
        dataclasses.replace(cert, wm_digest=bytes(16)),
        dataclasses.replace(cert, epoch=cert.epoch + 1),
    ]
    for i, bad in enumerate(preimage_mutants):
        for verifier in (subset, full):
            assert not verifier.verify(bad)["ok"], f"mutant {i} accepted"
    # structural mutations (bitmap bits, sig-blob shape) are full mode's
    # job — subset mode matches trusted keys against the blob directly
    # and by design never reads the bitmap
    structural_mutants = [
        dataclasses.replace(
            cert,
            bitmap=bytes([cert.bitmap[0] ^ 0x0F]) + cert.bitmap[1:],
        ),
        dataclasses.replace(cert, sigs=cert.sigs[:-64]),
        dataclasses.replace(cert, sigs=cert.sigs[64:] + cert.sigs[:64]),
    ]
    for i, bad in enumerate(structural_mutants):
        assert not full.verify(bad)["ok"], f"structural mutant {i} accepted"


def test_verify_chain_monotonicity():
    kps = _keypairs(4)
    _, c1 = _assemble(kps, seed=1)
    asm2 = CertAssembler([kp.public for kp in kps])
    wm, ranges, dird = _digests(seed=2)
    c2 = None
    for i, kp in enumerate(kps):
        got = asm2.add(CertSig.create(kp, 0, 200 + i, wm, ranges, dird))
        c2 = got or c2
    assert c2 is not None and c2.commits > c1.commits
    full = LightVerifier([], members=[kp.public for kp in kps])
    assert verify_chain([c1, c2], full)["ok"]
    # a regressing commit frontier is not a valid chain
    back = verify_chain([c2, c1], full)
    assert not back["ok"] and back["index"] == 1


# -- config gating + sim lane --------------------------------------------


def test_finality_disabled_is_inert():
    from at2_node_tpu.sim.net import SimNet

    net = SimNet(3, 0, 5).start()
    try:
        for svc in net.services:
            assert svc.certs is None
            assert svc._finality_status() == {"enabled": False}
    finally:
        net.close()


def test_sim_fleet_produces_verifiable_chain():
    from at2_node_tpu.node.config import FinalityConfig, ObservabilityConfig
    from at2_node_tpu.sim.net import SimNet, sim_client, sim_keypairs

    seed, nodes = 7, 4
    net = SimNet(
        nodes, 1, seed,
        finality=FinalityConfig(enabled=True),
        observability=ObservabilityConfig(audit_every=8),
    ).start()
    try:
        client = sim_client(seed, 0)
        recipient = sim_client(seed, 1).public
        for k in range(16):
            net.submit(k % nodes, client, k + 1, recipient, 1)
        net.settle(horizon=60.0)
        for svc in net.services:
            svc._emit_beacon()
        net.settle(horizon=10.0)
        keys = [sim_keypairs(seed, i)[0].public for i in range(nodes)]
        # stateless client: all genesis keys known, f+1 valid co-signers
        # required (a 2f+1 cert only guarantees ONE overlap with an
        # arbitrary f+1 key subset — the signer set is arrival-order)
        subset = LightVerifier(keys, total=nodes)
        assert subset.threshold == default_threshold(nodes)
        for svc in net.services:
            chain = list(svc.certs.chain)
            assert chain, svc.certs.status()
            assert verify_chain(chain, subset)["ok"]
            assert svc.certs.equivocation is None
        assert not net.check_invariants()
    finally:
        net.close()


def test_capture_replay_fuzzes_kind16_frames():
    """Hostile certificate frames ride the capture→replay bridge like
    any other wire kind: a synthetic capture of valid + mutated kind-16
    frames must replay to the same verdict hash twice, crash-free."""
    from at2_node_tpu.sim.hostile import mutate_cert_frame
    from at2_node_tpu.tools.capture_replay import replay_capture, verdict_hash

    kp = _keypairs(1)[0]
    wm, ranges, dird = _digests()
    good = CertSig.create(kp, 0, 7, wm, ranges, dird).encode()
    rng = random.Random(23)
    records = []
    for i in range(24):
        frame = good if i % 4 == 0 else mutate_cert_frame(good, rng)
        records.append([i * 5_000_000, "fuzz", CERT_SIG, frame.hex()])
    doc = {"cap": 256, "captured": len(records), "records": records}
    v1 = replay_capture(doc, 9)
    v2 = replay_capture(doc, 9)
    assert verdict_hash(v1) == verdict_hash(v2)
    assert not v1["violations"], v1["violations"]


def test_mutate_cert_frame_deterministic():
    from at2_node_tpu.sim.hostile import mutate_cert_frame

    kp = _keypairs(1)[0]
    wm, ranges, dird = _digests()
    good = CertSig.create(kp, 0, 7, wm, ranges, dird).encode()
    a = [mutate_cert_frame(good, random.Random(3)) for _ in range(16)]
    b = [mutate_cert_frame(good, random.Random(3)) for _ in range(16)]
    assert a == b
    assert all(m != good for m in a)


def test_generate_cert_events_shape():
    from at2_node_tpu.sim.campaign import generate_cert_events

    events = generate_cert_events(random.Random(1), n_events=20)
    kinds = [e[1] for e in events]
    assert kinds.count("cert_equiv") == 3
    assert kinds.count("cert_stale") == 2
    assert kinds.count("cert_forge") == 2
    assert [e[0] for e in events] == sorted(e[0] for e in events)
    assert generate_cert_events(random.Random(1), n_events=20) == events


@pytest.mark.slow
def test_planted_cert_equivocation_episode():
    from at2_node_tpu.sim.campaign import planted_cert_equivocation_episode
    from at2_node_tpu.sim.net import sim_keypairs

    seed = 20260807
    r = planted_cert_equivocation_episode(seed)
    assert not r.violations, r.violations
    culprit = sim_keypairs(seed, 4)[0].public.hex()
    assert r.audit is not None
    for a in r.audit:
        fin = a["finality"]
        assert fin is not None and fin["chain_len"] > 0, fin
        assert fin["equivocation"]["origin"] == culprit
        assert fin["epoch_skew"] > 0 and fin["bad_sig"] > 0, fin
