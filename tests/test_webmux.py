"""grpc-web / HTTP1 / CORS browser-surface tests (reference parity:
`/root/reference/src/bin/server/main.rs:110-114` serves tonic-web with
`accept_http1(true)` and CORS allow-all on the same port as native gRPC).

The calls here speak raw HTTP/1.1 + grpc-web framing over a plain TCP
socket — exactly what a browser grpc-web client emits — against the same
public RPC port the native gRPC tests use (the PortMux splices the two
protocols)."""

import asyncio
import base64
import itertools

from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.net.peers import Peer
from at2_node_tpu.net.webmux import _DATA_FRAME, _TRAILER_FRAME, _frame, _parse_frames
from at2_node_tpu.node.config import Config
from at2_node_tpu.node.service import Service
from at2_node_tpu.proto import at2_pb2 as pb
from at2_node_tpu.types import transfer_signing_bytes

_ports = itertools.count(25100)


def _single_node_config():
    return Config(
        node_address=f"127.0.0.1:{next(_ports)}",
        rpc_address=f"127.0.0.1:{next(_ports)}",
        sign_key=SignKeyPair.random(),
        network_key=ExchangeKeyPair.random(),
    )


async def _http1(addr: str, request: bytes) -> tuple:
    """One raw HTTP/1.1 exchange; returns (status_line, headers, body)."""
    host, _, port = addr.rpartition(":")
    reader, writer = await asyncio.open_connection(host, int(port))
    writer.write(request)
    await writer.drain()
    raw = await reader.read(-1)  # server closes after responding
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    headers = {}
    for line in lines[1:]:
        if ":" in line:
            k, v = line.split(":", 1)
            headers[k.strip().lower()] = v.strip()
    return lines[0], headers, body


async def _grpc_web_call(addr: str, method: str, request_msg, text=False):
    """Unary grpc-web call; returns (grpc_status, reply_bytes|None)."""
    body = _frame(request_msg.SerializeToString())
    ctype = "application/grpc-web+proto"
    if text:
        body = base64.b64encode(body)
        ctype = "application/grpc-web-text+proto"
    req = (
        f"POST /at2.AT2/{method} HTTP/1.1\r\n"
        f"Host: node\r\nContent-Type: {ctype}\r\n"
        f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
    ).encode() + body
    status_line, headers, payload = await _http1(addr, req)
    assert "200" in status_line, status_line
    assert headers.get("access-control-allow-origin") == "*"
    if text:
        payload = base64.b64decode(payload)
    reply = None
    grpc_status = None
    for flags, data in _parse_frames(payload):
        if flags == _DATA_FRAME:
            reply = data
        elif flags == _TRAILER_FRAME:
            for line in data.decode().split("\r\n"):
                if line.lower().startswith("grpc-status:"):
                    grpc_status = int(line.split(":", 1)[1])
    return grpc_status, reply


class TestGrpcWeb:
    async def test_cors_preflight(self):
        cfg = _single_node_config()
        service = await Service.start(cfg)
        try:
            req = (
                "OPTIONS /at2.AT2/SendAsset HTTP/1.1\r\nHost: node\r\n"
                "Origin: http://example.com\r\n"
                "Access-Control-Request-Method: POST\r\n"
                "Connection: close\r\n\r\n"
            ).encode()
            status_line, headers, _ = await _http1(cfg.rpc_address, req)
            assert "204" in status_line
            assert headers["access-control-allow-origin"] == "*"
            assert "post" in headers["access-control-allow-methods"].lower()
        finally:
            await service.close()

    async def test_send_asset_and_read_back_over_grpc_web(self):
        cfg = _single_node_config()
        service = await Service.start(cfg)
        try:
            sender, recipient = SignKeyPair.random(), SignKeyPair.random()
            request = pb.SendAssetRequest(
                sender=sender.public,
                sequence=1,
                recipient=recipient.public,
                amount=77,
                signature=sender.sign(
                    transfer_signing_bytes(
                        sender.public, 1, recipient.public, 77
                    )
                ),
            )
            status, reply = await _grpc_web_call(
                cfg.rpc_address, "SendAsset", request
            )
            assert status == 0 and reply is not None

            # poll commit via grpc-web GetLastSequence (binary mode)
            deadline = asyncio.get_event_loop().time() + 10
            seq = 0
            while asyncio.get_event_loop().time() < deadline:
                status, reply = await _grpc_web_call(
                    cfg.rpc_address,
                    "GetLastSequence",
                    pb.GetLastSequenceRequest(sender=sender.public),
                )
                assert status == 0
                seq = pb.GetLastSequenceReply.FromString(reply).sequence
                if seq == 1:
                    break
                await asyncio.sleep(0.1)
            assert seq == 1

            # GetLatestTransactions over grpc-web-TEXT mode (the framing a
            # browser uses when fetch streaming is unavailable)
            status, reply = await _grpc_web_call(
                cfg.rpc_address,
                "GetLatestTransactions",
                pb.GetLatestTransactionsRequest(),
                text=True,
            )
            assert status == 0
            txs = pb.GetLatestTransactionsReply.FromString(reply).transactions
            assert len(txs) == 1 and txs[0].amount == 77
        finally:
            await service.close()

    async def test_native_grpc_still_served_on_same_port(self):
        # the splice path: a stock gRPC client on the muxed public port
        cfg = _single_node_config()
        service = await Service.start(cfg)
        try:
            async with Client(f"http://{cfg.rpc_address}") as client:
                user = SignKeyPair.random()
                assert await client.get_balance(user.public) == 100_000
        finally:
            await service.close()

    async def test_grpc_web_error_paths(self):
        cfg = _single_node_config()
        service = await Service.start(cfg)
        try:
            # unknown method -> UNIMPLEMENTED (12) in the trailers
            status, reply = await _grpc_web_call(
                cfg.rpc_address, "NoSuchMethod", pb.GetBalanceRequest()
            )
            assert status == 12 and reply is None
            # handler abort -> INVALID_ARGUMENT (3)
            bad = pb.SendAssetRequest(
                sender=b"short", sequence=1, recipient=b"r" * 32,
                amount=1, signature=b"s" * 64,
            )
            status, _ = await _grpc_web_call(cfg.rpc_address, "SendAsset", bad)
            assert status == 3
        finally:
            await service.close()


class TestConnectionBounds:
    async def test_idle_splice_flood_does_not_starve_http1(self, monkeypatch):
        """Fill the splice budget with idle native-gRPC-preface
        connections: excess splices are rejected, and grpc-web service
        on the same port keeps working throughout."""
        from at2_node_tpu.net import webmux as webmux_mod

        monkeypatch.setattr(webmux_mod, "_MAX_SPLICES", 4)
        cfg = _single_node_config()
        service = await Service.start(cfg)
        host, _, port = cfg.rpc_address.rpartition(":")
        writers = []
        try:
            # 8 idle splices against a cap of 4: all hold only the
            # 4-byte preface so the mux pins pump tasks for each
            for _ in range(8):
                reader, writer = await asyncio.open_connection(host, int(port))
                writer.write(b"PRI ")
                await writer.drain()
                writers.append(writer)
            await asyncio.sleep(0.2)  # let the mux route/reject them
            # exactly the cap: 4 accepted (proving the splice path DID
            # engage), 4 rejected
            assert service._mux._n_splices == 4

            # a real grpc-web call on the same port is unaffected
            status, reply = await _grpc_web_call(
                cfg.rpc_address, "GetBalance",
                pb.GetBalanceRequest(sender=b"\x01" * 32),
            )
            assert status == 0
            assert pb.GetBalanceReply.FromString(reply).amount == 100_000
        finally:
            for w in writers:
                w.close()
            await service.close()

    async def test_http1_conn_cap_answers_503(self, monkeypatch):
        from at2_node_tpu.net import webmux as webmux_mod

        monkeypatch.setattr(webmux_mod, "_MAX_HTTP1_CONNS", 2)
        cfg = _single_node_config()
        service = await Service.start(cfg)
        host, _, port = cfg.rpc_address.rpartition(":")
        holders = []
        try:
            # two keep-alive connections occupy the whole budget
            for _ in range(2):
                reader, writer = await asyncio.open_connection(host, int(port))
                writer.write(b"XGET")  # non-PRI head: routed to HTTP/1
                await writer.drain()
                holders.append(writer)
            await asyncio.sleep(0.2)
            # the third is turned away with 503, not hung or crashed
            reader, writer = await asyncio.open_connection(host, int(port))
            writer.write(b"XGET")
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), timeout=5)
            assert b"503" in line
            writer.close()
        finally:
            for w in holders:
                w.close()
            await service.close()
