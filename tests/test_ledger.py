"""Ledger semantics tests.

Ports of the reference's own unit tests, which pin the observable ledger
quirks (`/root/reference/src/bin/server/accounts/account.rs:56-91`,
`accounts/mod.rs:216-301`, `recent_transactions.rs:203-249`).
"""

import asyncio

import pytest

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.ledger import (
    Account,
    AccountModificationError,
    Accounts,
    INITIAL_BALANCE,
    RecentTransactions,
)
from at2_node_tpu.ledger.account import AccountError, AccountException
from at2_node_tpu.types import ThinTransaction, TransactionState


# -- Account state machine (account.rs:56-91) --


def test_debit_too_much_fails_but_bumps_sequence():
    account = Account()
    old_seq = account.last_sequence
    with pytest.raises(AccountException) as exc:
        account.debit(1, INITIAL_BALANCE + 1)
    assert exc.value.kind == AccountError.UNDERFLOW
    assert account.last_sequence > old_seq
    assert account.balance == INITIAL_BALANCE


def test_debit_increases_sequence():
    account = Account()
    old_seq = account.last_sequence
    account.debit(1, 1)
    assert account.last_sequence > old_seq


def test_credit_doesnt_change_sequence():
    account = Account()
    old_seq = account.last_sequence
    account.credit(1)
    assert account.last_sequence == old_seq


def test_debit_requires_consecutive_sequence():
    account = Account()
    with pytest.raises(AccountException) as exc:
        account.debit(2, 1)
    assert exc.value.kind == AccountError.INCONSECUTIVE_SEQUENCE
    assert account.last_sequence == 0


def test_credit_overflow():
    account = Account()
    with pytest.raises(AccountException) as exc:
        account.credit((1 << 64) - 1)
    assert exc.value.kind == AccountError.OVERFLOW


# -- Accounts actor (accounts/mod.rs:216-301) --


async def _balance_and_sequence(accounts, user):
    return await accounts.get_balance(user), await accounts.get_last_sequence(user)


def test_new_account_is_the_same_as_unknown_account():
    async def run():
        accounts = Accounts()
        user = SignKeyPair.random().public
        balance, sequence = await _balance_and_sequence(accounts, user)
        fresh = Account()
        assert balance == fresh.balance
        assert sequence == fresh.last_sequence
        accounts.close()

    asyncio.run(run())


def test_transfer_to_themselves_increments_sequence_and_keeps_balance():
    async def run():
        accounts = Accounts()
        user = SignKeyPair.random().public
        balance0, seq0 = await _balance_and_sequence(accounts, user)
        await accounts.transfer(user, 1, user, 10)
        balance1, seq1 = await _balance_and_sequence(accounts, user)
        assert balance0 == balance1
        assert seq0 < seq1
        accounts.close()

    asyncio.run(run())


def test_transfer_too_much_fails_and_increases_sequence():
    async def run():
        accounts = Accounts()
        first = SignKeyPair.random().public
        second = SignKeyPair.random().public
        fb0, fs0 = await _balance_and_sequence(accounts, first)
        sb0, ss0 = await _balance_and_sequence(accounts, second)
        with pytest.raises(AccountModificationError):
            await accounts.transfer(first, 1, second, fb0 + 1)
        fb1, fs1 = await _balance_and_sequence(accounts, first)
        sb1, ss1 = await _balance_and_sequence(accounts, second)
        assert fb0 == fb1
        assert fs0 < fs1
        assert sb0 == sb1
        assert ss0 == ss1
        accounts.close()

    asyncio.run(run())


def test_transfer_conserves_total_balance():
    async def run():
        accounts = Accounts()
        alice = SignKeyPair.random().public
        bob = SignKeyPair.random().public
        await accounts.transfer(alice, 1, bob, 1000)
        assert await accounts.get_balance(alice) == INITIAL_BALANCE - 1000
        assert await accounts.get_balance(bob) == INITIAL_BALANCE + 1000
        accounts.close()

    asyncio.run(run())


def test_transfer_sequence_gap_is_retryable_error():
    async def run():
        accounts = Accounts()
        alice = SignKeyPair.random().public
        bob = SignKeyPair.random().public
        with pytest.raises(AccountModificationError) as exc:
            await accounts.transfer(alice, 2, bob, 1)
        assert exc.value.source.kind == AccountError.INCONSECUTIVE_SEQUENCE
        # gap filled: now 1 then 2 work
        await accounts.transfer(alice, 1, bob, 1)
        await accounts.transfer(alice, 2, bob, 1)
        assert await accounts.get_last_sequence(alice) == 2
        accounts.close()

    asyncio.run(run())


# -- RecentTransactions ring (recent_transactions.rs:203-249) --


def test_put_transactions_show_in_get_all():
    async def run():
        recent = RecentTransactions()
        sender = SignKeyPair.random().public
        recipient = SignKeyPair.random().public
        txs = [
            ThinTransaction(recipient=recipient, amount=10),
            ThinTransaction(recipient=sender, amount=3),
        ]
        for seq, thin in enumerate(txs, start=1):
            await recent.put(sender, seq, thin)

        got = await recent.get_all()
        assert len(got) == len(txs)
        for seq, (thin, full) in enumerate(zip(txs, got), start=1):
            assert full.sender == sender
            assert full.sender_sequence == seq
            assert full.amount == thin.amount
            assert full.recipient == thin.recipient
            assert full.state == TransactionState.PENDING

    asyncio.run(run())


def test_put_dedups_by_sender_and_sequence():
    async def run():
        recent = RecentTransactions()
        sender = SignKeyPair.random().public
        thin = ThinTransaction(recipient=sender, amount=1)
        await recent.put(sender, 1, thin)
        await recent.put(sender, 1, thin)
        assert len(await recent.get_all()) == 1

    asyncio.run(run())


def test_ring_caps_at_ten_and_update_missing_is_nop():
    async def run():
        recent = RecentTransactions()
        sender = SignKeyPair.random().public
        thin = ThinTransaction(recipient=sender, amount=1)
        for seq in range(1, 13):
            await recent.put(sender, seq, thin)
        got = await recent.get_all()
        assert len(got) == 10
        assert got[0].sender_sequence == 3  # oldest two evicted

        # updating an evicted (or never-seen) tx is a NOP
        await recent.update(sender, 1, TransactionState.SUCCESS)
        await recent.update(sender, 5, TransactionState.SUCCESS)
        got = await recent.get_all()
        states = {tx.sender_sequence: tx.state for tx in got}
        assert states[5] == TransactionState.SUCCESS
        assert states[4] == TransactionState.PENDING

    asyncio.run(run())


# -- shared types --


def test_signing_bytes_layout():
    from at2_node_tpu.types import TRANSFER_SIG_TAG, transfer_signing_bytes

    sender = bytes(range(32, 64))
    recipient = bytes(range(32))
    assert transfer_signing_bytes(sender, 3, recipient, 5) == (
        TRANSFER_SIG_TAG
        + sender
        + (3).to_bytes(4, "little")
        + recipient
        + (5).to_bytes(8, "little")
    )


def test_sign_verify_roundtrip():
    from at2_node_tpu.crypto.keys import verify_one
    from at2_node_tpu.types import transfer_signing_bytes

    keypair = SignKeyPair.random()
    recipient = SignKeyPair.random().public
    msg = transfer_signing_bytes(keypair.public, 1, recipient, 42)
    sig = keypair.sign(msg)
    assert verify_one(keypair.public, msg, sig)
    assert not verify_one(keypair.public, b"other message", sig)
    # sequence is bound: the same signature fails at a shifted slot
    shifted = transfer_signing_bytes(keypair.public, 2, recipient, 42)
    assert not verify_one(keypair.public, shifted, sig)


# -- bulk ring/ledger operations (round 5: one lock round-trip per batch) --


@pytest.mark.asyncio
async def test_put_many_matches_per_item_put_semantics():
    ring = RecentTransactions()
    s1, s2 = b"\x01" * 32, b"\x02" * 32
    thin = ThinTransaction(b"\x03" * 32, 5)
    # dedup inside one bulk call AND against prior entries
    await ring.put(s1, 1, thin)
    await ring.put_many([(s1, 1, thin), (s1, 2, thin), (s2, 1, thin), (s1, 2, thin)])
    txs = await ring.get_all()
    assert [(t.sender, t.sender_sequence) for t in txs] == [
        (s1, 1), (s1, 2), (s2, 1)
    ]
    assert all(t.state is TransactionState.PENDING for t in txs)


@pytest.mark.asyncio
async def test_apply_many_order_and_unless_success():
    ring = RecentTransactions()
    s = b"\x04" * 32
    thin = ThinTransaction(b"\x05" * 32, 5)
    await ring.put_many([(s, 1, thin), (s, 2, thin)])
    # ordered application: FAILURE then SUCCESS for seq 1 -> final SUCCESS;
    # unless_success on seq 1 afterwards must NOT flip it back; seq 2's
    # unless_success (still PENDING) must mark FAILURE
    await ring.apply_many(
        [
            ("update", s, 1, TransactionState.FAILURE),
            ("update", s, 1, TransactionState.SUCCESS),
            ("unless_success", s, 1),
            ("unless_success", s, 2),
        ]
    )
    states = {t.sender_sequence: t.state for t in await ring.get_all()}
    assert states == {
        1: TransactionState.SUCCESS,
        2: TransactionState.FAILURE,
    }


@pytest.mark.asyncio
async def test_run_exclusive_applies_and_returns():
    accounts = Accounts()
    a, b = b"\x06" * 32, b"\x07" * 32

    def txn(acc):
        acc._transfer(a, 1, b, 100)
        try:
            acc._transfer(a, 1, b, 100)  # duplicate sequence
        except AccountModificationError as exc:
            return exc
        return None

    err = await accounts.run_exclusive(txn)
    assert isinstance(err, AccountModificationError)
    assert await accounts.get_balance(b) == INITIAL_BALANCE + 100
    assert await accounts.get_last_sequence(a) == 1
