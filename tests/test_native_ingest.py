"""Native message-plane ingest (at2_ingest.cpp): differential parity.

The C++ path must be bit-identical with the Python plane it replaces:
* at2_parse_frames vs broadcast.messages.parse_frame (incl. malformed
  frames dropping whole, content hashes, every message kind);
* at2_verify_bulk vs crypto.keys.verify_one (same libcrypto underneath,
  so verdicts must match on valid, corrupted, and degenerate inputs);
* Broadcast._parse_chunk native and Python paths produce identical
  message streams.
"""

import random

import pytest

from at2_node_tpu.broadcast.messages import (
    ECHO,
    READY,
    Attestation,
    ContentRequest,
    HistoryBatch,
    HistoryIndex,
    HistoryIndexRequest,
    HistoryRequest,
    Payload,
    parse_frame,
)
from at2_node_tpu.crypto.keys import SignKeyPair, verify_one
from at2_node_tpu.native import ingest_available
from at2_node_tpu.types import ThinTransaction

pytestmark = pytest.mark.skipif(
    not ingest_available(), reason="native ingest library unavailable"
)


def _rand_payload(rng: random.Random) -> Payload:
    kp = SignKeyPair.from_hex(f"{rng.randrange(1, 255):02x}" * 32)
    tx = ThinTransaction(rng.randbytes(32), rng.randrange(1 << 64))
    return Payload.create(kp, rng.randrange(1 << 32), tx)


def _rand_attestation(rng: random.Random) -> Attestation:
    kp = SignKeyPair.from_hex(f"{rng.randrange(1, 255):02x}" * 32)
    phase = rng.choice((ECHO, READY))
    sender = rng.randbytes(32)
    seq = rng.randrange(1 << 32)
    chash = rng.randbytes(32)
    sig = kp.sign(Attestation.signing_bytes(phase, sender, seq, chash))
    return Attestation(phase, kp.public, sender, seq, chash, sig)


def test_parse_differential_fuzz():
    from at2_node_tpu.native import parse_frames_native

    rng = random.Random(7)
    frames = []
    for _ in range(40):
        msgs = []
        for _ in range(rng.randrange(1, 6)):
            roll = rng.random()
            if roll < 0.35:
                msgs.append(_rand_payload(rng))
            elif roll < 0.7:
                msgs.append(_rand_attestation(rng))
            elif roll < 0.8:
                msgs.append(
                    ContentRequest(rng.randbytes(32), rng.randrange(1 << 32), rng.randbytes(32))
                )
            elif roll < 0.85:
                msgs.append(HistoryIndexRequest(rng.randrange(1 << 64)))
            elif roll < 0.9:
                msgs.append(
                    HistoryRequest(
                        rng.randrange(1 << 64),
                        rng.randbytes(32),
                        rng.randrange(1 << 32),
                        rng.randrange(1 << 32),
                    )
                )
            elif roll < 0.95:
                msgs.append(
                    HistoryIndex(
                        rng.randrange(1 << 64),
                        tuple(
                            (rng.randbytes(32), rng.randrange(1 << 32))
                            for _ in range(rng.randrange(0, 5))
                        ),
                    )
                )
            else:
                msgs.append(
                    HistoryBatch(
                        rng.randrange(1 << 64),
                        tuple(_rand_payload(rng) for _ in range(rng.randrange(0, 4))),
                    )
                )
        frames.append(b"".join(m.encode() for m in msgs))
    native, frame_ok = parse_frames_native(frames)
    assert frame_ok.all()
    by_frame: dict = {}
    for fi, msg in native:
        by_frame.setdefault(fi, []).append(msg)
    for i, frame in enumerate(frames):
        ref = parse_frame(frame)
        got = by_frame.get(i, [])
        assert got == ref
        for g, r in zip(got, ref):
            if isinstance(g, Payload):
                assert g.content_hash() == r.content_hash()


def test_parse_malformed_frames_drop_whole():
    from at2_node_tpu.native import parse_frames_native

    rng = random.Random(9)
    good = _rand_payload(rng)
    batch = HistoryBatch(7, (good, _rand_payload(rng)))
    # one message past the coalescing cap: drops whole on BOTH paths
    dense = HistoryIndexRequest(1).encode() * 4097
    cases = [
        good.encode(),
        b"\xff" + good.encode(),  # unknown kind
        good.encode()[:-1],  # truncated tail message
        good.encode() + b"\x02" + b"\x00" * 10,  # truncated attestation
        b"",  # empty frame parses to zero messages
        batch.encode()[:-1],  # truncated history batch (count > entries)
        b"\x06" + b"\x00" * 5,  # truncated history header
        batch.encode(),
        dense,  # exceeds MAX_MSGS_PER_FRAME
    ]
    with pytest.raises(Exception):
        parse_frame(dense)
    native, frame_ok = parse_frames_native(cases)
    assert frame_ok.tolist() == [
        True, False, False, False, True, False, False, True, False,
    ]
    assert [fi for fi, _ in native] == [0, 7]
    assert native[0][1] == good
    assert native[1][1] == batch


def test_verify_bulk_parity_and_threads():
    from at2_node_tpu.native import verify_bulk_native

    rng = random.Random(11)
    items, expect = [], []
    for i in range(64):
        kp = SignKeyPair.from_hex(f"{i + 1:02x}" * 32)
        msg = rng.randbytes(rng.randrange(1, 200))
        sig = kp.sign(msg)
        pk = kp.public
        mutate = i % 4
        if mutate == 1:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        elif mutate == 2:
            msg = msg + b"x"
        elif mutate == 3 and i % 8 == 3:
            pk = rng.randbytes(32)
        items.append((pk, msg, sig))
        expect.append(verify_one(pk, msg, sig))
    for n_threads in (1, 3, 8):
        assert verify_bulk_native(items, n_threads).tolist() == expect


def test_verify_bulk_degenerate_inputs():
    from at2_node_tpu.native import verify_bulk_native

    kp = SignKeyPair.from_hex("aa" * 32)
    sig = kp.sign(b"m")
    items = [
        (b"", b"m", sig),  # empty pk
        (kp.public[:31], b"m", sig),  # short pk
        (kp.public, b"m", sig[:63]),  # short sig
        (kp.public, b"", kp.sign(b"")),  # empty message, valid
        (b"\x00" * 32, b"m", b"\x00" * 64),  # degenerate key/sig
    ]
    got = verify_bulk_native(items, 2).tolist()
    assert got == [False, False, False, True, False]
    # the python oracle agrees on the well-formed-length cases
    assert verify_one(kp.public, b"", items[3][2]) is True
    assert verify_one(b"\x00" * 32, b"m", b"\x00" * 64) is False


def test_parse_chunk_native_vs_python(monkeypatch):
    """Broadcast._parse_chunk yields the same stream on both paths."""
    from at2_node_tpu.broadcast import stack as stack_mod
    from at2_node_tpu.broadcast.stack import Broadcast

    from types import SimpleNamespace

    rng = random.Random(13)
    # frame 0 is large enough that the chunk crosses _parse_chunk's
    # native-path byte threshold — the whole point is comparing the
    # NATIVE branch against the Python one
    frames = [
        b"".join(
            m.encode()
            for m in (
                *(_rand_payload(rng) for _ in range(16)),
                *(_rand_attestation(rng) for _ in range(16)),
            )
        ),
        _rand_attestation(rng).encode(),
        b"\xee junk",
    ]
    assert sum(len(f) for f in frames) >= 4096
    local = _rand_payload(rng)
    peers = [SimpleNamespace(address=f"peer{i}") for i in range(3)]
    chunk = [
        (peers[0], frames[0]),
        (None, local),
        (peers[1], frames[1]),
        (peers[2], frames[2]),
    ]

    bc = Broadcast.__new__(Broadcast)  # _parse_chunk touches no instance state
    native_out = bc._parse_chunk(list(chunk))

    import at2_node_tpu.native as native_pkg

    monkeypatch.setattr(native_pkg, "ingest_ready_or_kick", lambda: False)
    python_out = bc._parse_chunk(list(chunk))

    def key(pairs):
        return [(p, m) for p, m in pairs]

    assert sorted(map(repr, key(native_out))) == sorted(map(repr, key(python_out)))
    # frame 0's 16 payloads + the local submission survive on both paths
    assert sum(1 for _, m in native_out if isinstance(m, Payload)) == 17
