"""Differential tests: JAX GF(2^255-19) limb arithmetic vs python ints."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from at2_node_tpu.ops import field as fe

RNG = np.random.default_rng(0xA72)

# Eager per-primitive dispatch is orders of magnitude slower than the jitted
# graphs the real kernels use; jit everything under test once here.
f_add = jax.jit(fe.add)
f_sub = jax.jit(fe.sub)
f_neg = jax.jit(fe.neg)
f_mul = jax.jit(fe.mul)
f_square = jax.jit(fe.square)
f_invert = jax.jit(fe.invert)
f_pow22523 = jax.jit(fe.pow22523)
f_canonical = jax.jit(fe.canonical)
f_eq = jax.jit(fe.eq)
f_step = jax.jit(lambda acc, A: f_mul(f_add(acc, A), f_sub(acc, A)))
f_bytes_to_limbs = jax.jit(fe.bytes_to_limbs)
f_limbs_to_bytes = jax.jit(fe.limbs_to_bytes)


def rand_ints(n, below=fe.P):
    return [int.from_bytes(RNG.bytes(40), "little") % below for _ in range(n)]


def to_batch(ints):
    return jnp.asarray(np.stack([fe.int_to_limbs(x) for x in ints]))


def from_batch(limbs):
    arr = np.asarray(limbs)
    return [fe.limbs_to_int(arr[i]) for i in range(arr.shape[0])]


N = 64


def test_limb_roundtrip():
    xs = rand_ints(N) + [0, 1, fe.P - 1, 2**255 - 20]
    assert from_batch(to_batch(xs)) == [x % fe.P for x in xs]


def test_add_sub_neg():
    a, b = rand_ints(N), rand_ints(N)
    A, B = to_batch(a), to_batch(b)
    assert from_batch(f_add(A, B)) == [(x + y) % fe.P for x, y in zip(a, b)]
    assert from_batch(f_sub(A, B)) == [(x - y) % fe.P for x, y in zip(a, b)]
    assert from_batch(f_neg(A)) == [(-x) % fe.P for x in a]


def test_mul_square():
    a, b = rand_ints(N), rand_ints(N)
    A, B = to_batch(a), to_batch(b)
    assert from_batch(f_mul(A, B)) == [(x * y) % fe.P for x, y in zip(a, b)]
    assert from_batch(f_square(A)) == [(x * x) % fe.P for x in a]


def test_mul_worst_case_limbs():
    # all-ones limbs (max magnitude) exercise the int32 overflow bound
    worst = (1 << 255) - 1
    xs = [worst, fe.P - 1, fe.P + 5 - fe.P]  # note: reduced on input
    A = to_batch(xs)
    assert from_batch(f_mul(A, A)) == [(x % fe.P) ** 2 % fe.P for x in xs]


def test_chained_ops_stay_reduced():
    # long chains must not overflow int32 lanes
    a = rand_ints(8)
    A = to_batch(a)
    acc, ref = A, list(a)
    for _ in range(25):
        acc = f_step(acc, A)
        ref = [((r + x) * (r - x)) % fe.P for r, x in zip(ref, a)]
    assert from_batch(acc) == ref


def test_invert():
    a = rand_ints(N)
    A = to_batch(a)
    assert from_batch(f_invert(A)) == [pow(x, fe.P - 2, fe.P) for x in a]
    # invert(0) == 0
    assert from_batch(f_invert(to_batch([0]))) == [0]


def test_pow22523():
    a = rand_ints(16)
    A = to_batch(a)
    assert from_batch(f_pow22523(A)) == [pow(x, (fe.P - 5) // 8, fe.P) for x in a]


def test_canonical_and_eq():
    a = rand_ints(16)
    A = to_batch(a)
    assert bool(jnp.all(f_eq(f_add(A, to_batch([0] * 16)), A)))
    # x + p == x (different representations, same value)
    shifted = A + jnp.asarray(fe.int_to_limbs(0))  # same limbs
    assert bool(jnp.all(f_eq(shifted, A)))
    assert not bool(jnp.any(f_eq(f_add(A, to_batch([1] * 16)), A)))
    # canonical of p and 2^255-1
    assert from_batch(f_canonical(to_batch([fe.P - 1]))) == [fe.P - 1]


def test_bytes_roundtrip():
    xs = rand_ints(N)
    raw = np.stack(
        [np.frombuffer(x.to_bytes(32, "little"), dtype=np.uint8) for x in xs]
    )
    limbs = f_bytes_to_limbs(jnp.asarray(raw))
    assert from_batch(limbs) == xs
    back = np.asarray(f_limbs_to_bytes(limbs))
    assert back.tolist() == raw.tolist()


def test_constants():
    assert fe.limbs_to_int(fe.SQRT_M1) ** 2 % fe.P == fe.P - 1
    # d = -121665/121666
    assert (fe.D_INT * 121666 + 121665) % fe.P == 0
