"""grpc-web interop against STOCK client stacks (round-3 VERDICT item 5).

The reference's browser story is a wasm client talking tonic-web
(`/root/reference/src/client.rs:45-61`, `main.rs:110-114`). This build's
PortMux serves the same single-port surface; earlier tests drove it with
frames hand-built by this repo's own code. This tier closes the loop
with client bytes this repo did NOT craft:

* live calls through four independent real-world HTTP stacks —
  `requests` (urllib3), `httpx`, `aiohttp`, and the `curl` binary —
  in both grpc-web binary and base64 text modes, plus a chunked
  transfer-encoded unary call (curl/httpx streaming bodies really send
  these; the mux must decode them, not silently treat the body as
  empty);
* replay of PINNED transcripts captured from curl's and requests' own
  network stacks against a recording proxy (tests/data/*.raw) — byte
  streams emitted by those clients, immune to this repo's framing code
  drifting in lockstep with a server bug.

(No browser binary nor the official grpc-web JS npm package exists in
this image, so the protobuf payload inside the live-call frames comes
from the protoc-generated encoder — the same encoder family the official
clients embed — while the HTTP layer is fully third-party.)
"""

import asyncio
import base64
import itertools
import os
import shutil
import subprocess

import pytest

from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.node.config import Config
from at2_node_tpu.node.service import Service
from at2_node_tpu.proto import at2_pb2 as pb

_ports = itertools.count(22400)

# the pinned transcripts query this sender (baked into their bytes)
PINNED_SENDER = bytes.fromhex(
    "d759793bbc13a2819a827c76adb6fba8a49aee007f49f2d0992d99b825ad2c48"
)
FAUCET = 100_000
DATA_DIR = os.path.join(os.path.dirname(__file__), "data")


def _frame(msg: bytes) -> bytes:
    return bytes([0]) + len(msg).to_bytes(4, "big") + msg


def _parse_balance(body: bytes) -> int:
    assert body and body[0] == 0, body[:10]
    ln = int.from_bytes(body[1:5], "big")
    assert b"grpc-status: 0" in body, body
    return pb.GetBalanceReply.FromString(body[5 : 5 + ln]).amount


class node:
    """Async context manager yielding a running single node's Config
    (the repo's pytest harness has no async-fixture support)."""

    async def __aenter__(self):
        self.cfg = Config(
            node_address=f"127.0.0.1:{next(_ports)}",
            rpc_address=f"127.0.0.1:{next(_ports)}",
            sign_key=SignKeyPair.random(),
            network_key=ExchangeKeyPair.random(),
        )
        self.svc = await Service.start(self.cfg)
        return self.cfg

    async def __aexit__(self, *exc):
        await self.svc.close()


def _url(cfg) -> str:
    return f"http://{cfg.rpc_address}/at2.AT2/GetBalance"


def _request_frame() -> bytes:
    return _frame(
        pb.GetBalanceRequest(sender=PINNED_SENDER).SerializeToString()
    )


class TestLiveClientStacks:
    @pytest.mark.asyncio
    async def test_requests_binary(self):
      async with node() as cfg:
        import requests

        def call():
            return requests.post(
                _url(cfg),
                data=_request_frame(),
                headers={"Content-Type": "application/grpc-web+proto"},
                timeout=10,
            )

        r = await asyncio.get_event_loop().run_in_executor(None, call)
        assert r.status_code == 200
        assert r.headers["Access-Control-Allow-Origin"] == "*"
        assert _parse_balance(r.content) == FAUCET

    @pytest.mark.asyncio
    async def test_httpx_text_mode(self):
      async with node() as cfg:
        import httpx

        def call():
            return httpx.post(
                _url(cfg),
                content=base64.b64encode(_request_frame()),
                headers={"Content-Type": "application/grpc-web-text"},
                timeout=10,
            )

        r = await asyncio.get_event_loop().run_in_executor(None, call)
        assert r.status_code == 200
        assert "grpc-web-text" in r.headers["content-type"]
        assert _parse_balance(base64.b64decode(r.content)) == FAUCET

    @pytest.mark.asyncio
    async def test_httpx_chunked_transfer_encoding(self):
      async with node() as cfg:
        """A streaming-body unary call (Transfer-Encoding: chunked) must
        decode the REAL request — before round 3 the mux read an empty
        body and answered the default account's balance."""
        import httpx

        frame = _request_frame()

        def call():
            def gen():
                yield frame[:7]
                yield frame[7:]

            return httpx.post(
                _url(cfg),
                content=gen(),
                headers={"Content-Type": "application/grpc-web+proto"},
                timeout=10,
            )

        r = await asyncio.get_event_loop().run_in_executor(None, call)
        assert r.status_code == 200
        assert _parse_balance(r.content) == FAUCET

    @pytest.mark.asyncio
    async def test_aiohttp_binary(self):
      async with node() as cfg:
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.post(
                _url(cfg),
                data=_request_frame(),
                headers={"Content-Type": "application/grpc-web+proto"},
            ) as resp:
                assert resp.status == 200
                assert _parse_balance(await resp.read()) == FAUCET

    @pytest.mark.asyncio
    @pytest.mark.skipif(shutil.which("curl") is None, reason="no curl binary")
    async def test_curl_binary_and_preflight(self, tmp_path):
      async with node() as cfg:
        frame_path = tmp_path / "frame.bin"
        frame_path.write_bytes(_request_frame())

        def run_curl(args):
            return subprocess.run(
                ["curl", "-s", "-m", "10", *args],
                capture_output=True,
                timeout=15,
            )

        loop = asyncio.get_event_loop()
        post = await loop.run_in_executor(
            None,
            run_curl,
            [
                "-X", "POST",
                "-H", "Content-Type: application/grpc-web+proto",
                "-H", "X-Grpc-Web: 1",
                "--data-binary", f"@{frame_path}",
                _url(cfg),
            ],
        )
        assert post.returncode == 0
        assert _parse_balance(post.stdout) == FAUCET

        preflight = await loop.run_in_executor(
            None,
            run_curl,
            [
                "-D", "-", "-o", "/dev/null",
                "-X", "OPTIONS",
                "-H", "Origin: http://example.com",
                "-H", "Access-Control-Request-Method: POST",
                _url(cfg),
            ],
        )
        head = preflight.stdout.decode("latin-1")
        assert "204" in head.splitlines()[0]
        assert "Access-Control-Allow-Origin: *" in head


class TestHttp1EdgeCases:
    @pytest.mark.asyncio
    async def test_expect_100_continue_answered(self):
        """curl stalls ~1s per request if 100-continue goes unanswered."""
        async with node() as cfg:
            host, _, port = cfg.rpc_address.rpartition(":")
            frame = _request_frame()
            reader, writer = await asyncio.open_connection(host, int(port))
            writer.write(
                b"POST /at2.AT2/GetBalance HTTP/1.1\r\n"
                b"Host: x\r\nContent-Type: application/grpc-web+proto\r\n"
                b"Expect: 100-continue\r\nConnection: close\r\n"
                + f"Content-Length: {len(frame)}\r\n\r\n".encode()
            )
            await writer.drain()
            interim = await asyncio.wait_for(reader.readline(), timeout=5)
            assert b"100 Continue" in interim
            await reader.readline()  # blank line after the interim response
            writer.write(frame)
            await writer.drain()
            resp = await asyncio.wait_for(reader.read(), timeout=5)
            writer.close()
            head, _, body = resp.partition(b"\r\n\r\n")
            assert b"200 OK" in head.split(b"\r\n")[0]
            assert _parse_balance(body) == FAUCET

    @pytest.mark.asyncio
    async def test_chunked_oversize_is_413_and_junk_is_400(self):
        async with node() as cfg:
            host, _, port = cfg.rpc_address.rpartition(":")

            async def chunked_post(chunks: bytes) -> bytes:
                reader, writer = await asyncio.open_connection(host, int(port))
                writer.write(
                    b"POST /at2.AT2/GetBalance HTTP/1.1\r\n"
                    b"Host: x\r\nContent-Type: application/grpc-web+proto\r\n"
                    b"Transfer-Encoding: chunked\r\n\r\n" + chunks
                )
                await writer.drain()
                resp = await asyncio.wait_for(reader.read(), timeout=10)
                writer.close()
                return resp.split(b"\r\n")[0]

            # one declared 8MB chunk: over _MAX_BODY -> 413 (parity with
            # the Content-Length path), not 400
            assert b"413" in await chunked_post(b"800000\r\n")
            # RFC 9112 chunk-size is hex digits only
            assert b"400" in await chunked_post(b"+3\r\nabc\r\n0\r\n\r\n")
            assert b"400" in await chunked_post(b"0x3\r\nabc\r\n0\r\n\r\n")


class TestPinnedTranscripts:
    """Replay byte streams captured from real clients' network stacks
    (recording proxy between the stock client and a live node). The
    transcripts carry a Host header for the capture-time port; HTTP/1.1
    routing here ignores Host, so they replay against any port."""

    TRANSCRIPTS = [
        ("grpcweb_curl_post_binary.raw", b"200 OK", True),
        ("grpcweb_curl_post_text.raw", b"200 OK", True),
        ("grpcweb_curl_preflight.raw", b"204 No Content", False),
        ("grpcweb_requests_post_binary.raw", b"200 OK", True),
    ]

    @pytest.mark.asyncio
    @pytest.mark.parametrize("name,status,has_balance", TRANSCRIPTS)
    async def test_replay(self, name, status, has_balance):
      async with node() as cfg:
        raw = open(os.path.join(DATA_DIR, name), "rb").read()
        host, _, port = cfg.rpc_address.rpartition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        writer.write(raw)
        await writer.drain()
        resp = await asyncio.wait_for(_read_response(reader), timeout=10)
        writer.close()
        head, _, body = resp.partition(b"\r\n\r\n")
        assert status in head.split(b"\r\n")[0], head[:100]
        if has_balance:
            if b"grpc-web-text" in head:
                body = base64.b64decode(body)
            assert _parse_balance(body) == FAUCET


async def _read_response(reader) -> bytes:
    """Read exactly one HTTP response (headers + Content-Length body);
    the server keeps connections alive, so EOF never delimits."""
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = await reader.read(4096)
        if not chunk:
            return data
        data += chunk
    head, _, rest = data.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":", 1)[1])
    while len(rest) < length:
        chunk = await reader.read(4096)
        if not chunk:
            break
        rest += chunk
    return head + b"\r\n\r\n" + rest[:length]


class TestKeepAlive:
    @pytest.mark.asyncio
    async def test_two_calls_one_connection(self):
        """HTTP/1.1 keep-alive: a stock client's second unary call rides
        the SAME connection (tonic parity; previously every call paid a
        reconnect)."""
        async with node() as cfg:
            host, _, port = cfg.rpc_address.rpartition(":")
            frame = _request_frame()
            req = (
                b"POST /at2.AT2/GetBalance HTTP/1.1\r\n"
                b"Host: x\r\nContent-Type: application/grpc-web+proto\r\n"
                + f"Content-Length: {len(frame)}\r\n\r\n".encode()
                + frame
            )
            reader, writer = await asyncio.open_connection(host, int(port))
            for _ in range(2):
                writer.write(req)
                await writer.drain()
                resp = await asyncio.wait_for(_read_response(reader), timeout=10)
                head, _, body = resp.partition(b"\r\n\r\n")
                assert b"200 OK" in head.split(b"\r\n")[0]
                assert b"connection: keep-alive" in head.lower()
                assert _parse_balance(body) == FAUCET
            writer.close()

    @pytest.mark.asyncio
    async def test_requests_session_reuses_connection(self):
        """urllib3 session pooling works end-to-end against the mux —
        asserted by the SERVER's accepted-connection counter, so a
        regression to close-per-response (which urllib3 would silently
        absorb by reconnecting) fails the test."""
        import requests

        ctx = node()
        async with ctx as cfg:

            def calls():
                with requests.Session() as s:
                    out = []
                    for _ in range(3):
                        r = s.post(
                            _url(cfg),
                            data=_request_frame(),
                            headers={
                                "Content-Type": "application/grpc-web+proto"
                            },
                            timeout=10,
                        )
                        out.append((r.status_code, _parse_balance(r.content)))
                    return out

            results = await asyncio.get_event_loop().run_in_executor(None, calls)
            assert results == [(200, FAUCET)] * 3
            assert ctx.svc._mux._http1_accepted == 1, (
                f"expected one reused connection, server accepted "
                f"{ctx.svc._mux._http1_accepted}"
            )
