"""Crash-restart, catchup-to-live, and membership reconfiguration under
the deterministic simulator (ISSUE 9).

Sync tests on purpose, like tests/test_sim.py: each test owns a SimNet
(which owns a virtual-time SimScheduler) and drives it explicitly.

Covers the acceptance properties of the durability subsystem END TO END
— real Service, real ShardedStore on a real tmpdir, sim transport:

* a node killed under load restarts from its sharded checkpoint,
  replays the WAL, catches up to live, and the fleet's invariants
  (agreement, sieve totality, conservation, and the new
  no-post-restart-equivocation check) stay green;
* payloads DELIVERED but parked at the sequence gate survive the crash
  — the restarted node re-enqueues them and they commit once their
  predecessor arrives (broadcast never retransmits a delivered slot);
* an admin-signed ConfigTx removes a member fleet-wide: epoch bumps
  everywhere, the evicted identity leaves every mesh after the grace
  window, quorum thresholds re-weight, and stale-epoch transactions
  are rejected;
* the applied epoch survives a restart through the store manifest;
* seeded durability episodes (kill/restart cycles, mid-catchup
  partitions, stale-checkpoint restarts, racing reconfigs) pass the
  invariant sweep, and the same seed reproduces the same campaign
  hash — the CI restart-determinism gate's contract.
"""

from at2_node_tpu.sim.campaign import run_campaign, run_episode
from at2_node_tpu.sim.net import SimNet, sim_client
from at2_node_tpu.tools.top import once_verdict, render_frame


class TestCrashRestart:
    def test_kill_restart_under_load_stays_green(self):
        net = SimNet(n=4, f=1, seed=101, durable=True).start()
        try:
            clients = [sim_client(101, i) for i in range(3)]
            for c in clients:
                net.submit(0, c, 1, clients[0].public, 5)
            net.run_for(3.0)
            net.crash(2)
            for c in clients:
                net.submit(0, c, 2, clients[1].public, 3)
            net.run_for(3.0)
            net.restart(2)
            net.settle(horizon=60.0)

            net.assert_invariants()
            assert net.attest_violations == []
            # the invariant actually observed signatures across both
            # incarnations, it did not pass vacuously
            assert any(key[0] == 2 for key in net._attest)
            svc = net.services[2]
            assert svc.recovery.state == "live"
            # ledger state (not the per-incarnation committed counter):
            # every node holds every client's seq-2 commit
            for s in net.services:
                state = s.store.accounts_state()
                for c in clients:
                    assert state[c.public.hex()][0] == 2
        finally:
            net.close()

    def test_restart_loads_segments_then_replays_wal(self):
        net = SimNet(n=4, f=1, seed=102, durable=True).start()
        try:
            c = sim_client(102, 0)
            net.submit(0, c, 1, sim_client(102, 1).public, 7)
            net.settle(horizon=30.0)
            net.flush_store(3)  # segments hold seq 1
            net.submit(0, c, 2, sim_client(102, 1).public, 7)
            net.settle(horizon=30.0)  # seq 2 only in node 3's WAL
            net.crash(3)
            svc = net.restart(3)

            assert svc.store.segments_loaded > 0
            assert svc.store.wal_replayed > 0
            assert svc.recovery.segments_loaded > 0
            assert svc.recovery.wal_records_replayed > 0
            # the restart restored both slots from disk alone — neither
            # catchup nor re-delivery has anything left to transfer
            # (svc.committed counts THIS incarnation's commits only)
            assert svc.store.accounts_state()[c.public.hex()][0] == 2
            assert svc.store.history_count() == 2
            net.settle(horizon=30.0)
            assert svc.committed == 0
            net.assert_invariants()
            assert svc.recovery.state == "live"
        finally:
            net.close()

    def test_parked_payload_survives_restart(self):
        """Seq 2 delivered while seq 1 is still unsent parks at the
        sequence gate; the parked record must survive the crash because
        the broadcast will never retransmit a delivered slot."""
        net = SimNet(n=4, f=1, seed=103, durable=True).start()
        try:
            c = sim_client(103, 0)
            net.submit(0, c, 2, sim_client(103, 1).public, 9)
            net.run_for(5.0)  # delivered fleet-wide, committed nowhere
            assert [s.committed for s in net.services] == [0, 0, 0, 0]
            assert net.services[1].store.parked_count() == 1

            net.crash(1)
            svc = net.restart(1)
            assert svc.store.parked_count() == 1  # restored from WAL

            net.submit(0, c, 1, sim_client(103, 1).public, 9)
            net.settle(horizon=60.0)
            net.assert_invariants()
            assert [s.committed for s in net.services] == [2, 2, 2, 2]
            # committing pruned the parked set everywhere
            assert all(s.store.parked_count() == 0 for s in net.services)
        finally:
            net.close()


class TestReconfiguration:
    def test_remove_hostile_reweights_and_evicts(self):
        net = SimNet(
            n=4, f=1, seed=104, hostile=1, durable=True,
            membership_grace=1.0,
        ).start()
        try:
            evicted = net.hostile_configs[0].sign_key.public
            # n_peers drops 4 -> 3; crash-fault thresholds for f=1
            tx = net.reconfig(0, {
                "remove": [evicted.hex()],
                "echo_threshold": 2,
                "ready_threshold": 2,
            })
            assert tx.epoch == 1
            net.settle(horizon=30.0)  # gossip + grace expiry + sweep

            for s in net.services:
                assert s.membership.epoch == 1
                assert s.broadcast.ready_threshold == 2
                # post-grace: the identity is out of the mesh, so its
                # frames die at the fabric's by_sign lookup
                assert evicted not in s.mesh.by_sign
                assert s.membership.stats()["evicted_final"] == 1

            # traffic still flows at the re-weighted quorum
            c = sim_client(104, 0)
            net.submit(0, c, 1, sim_client(104, 1).public, 4)
            net.settle(horizon=30.0)
            net.assert_invariants()
            assert [s.committed for s in net.services] == [1, 1, 1, 1]
        finally:
            net.close()

    def test_stale_epoch_config_rejected(self):
        net = SimNet(
            n=4, f=1, seed=105, durable=True, membership_grace=1.0
        ).start()
        try:
            net.reconfig(0, {})  # epoch 0 -> 1
            svc = net.services[0]
            assert svc.membership.epoch == 1
            before = svc.membership.stats()["rejected"]
            # a replayed epoch is normal gossip echo: ignored, not
            # counted; a GAPPED future epoch is rejected outright
            net.reconfig(0, {}, epoch=1)
            net.reconfig(0, {}, epoch=5)
            assert svc.membership.epoch == 1
            assert svc.membership.stats()["applied"] == 1
            assert svc.membership.stats()["rejected"] == before + 1
        finally:
            net.close()

    def test_epoch_persists_across_restart(self):
        net = SimNet(
            n=4, f=1, seed=106, durable=True, membership_grace=1.0
        ).start()
        try:
            net.reconfig(0, {})
            net.settle(horizon=20.0)
            for i in range(4):
                net.flush_store(i)
            net.crash(2)
            svc = net.restart(2)
            assert svc.store.epoch == 1
            assert svc.membership.epoch == 1  # seeded from the manifest
            assert svc.health_verdict()["epoch"] == 1
            net.settle(horizon=20.0)
            net.assert_invariants()
        finally:
            net.close()


class TestDurabilityCampaign:
    def test_durability_episode_green(self):
        r = run_episode(3, durability=True, n_events=20, duration=18.0)
        assert r.ok, r.violations
        assert sum(r.committed) > 0

    def test_same_seed_same_campaign_hash(self):
        kw = dict(durability=True, n_events=15, duration=15.0)
        a = run_campaign(7, 2, **kw)
        b = run_campaign(7, 2, **kw)
        assert a["failures"] == 0
        assert a["campaign_hash"] == b["campaign_hash"]
        assert a["durability"] is True


class TestTopRecoverySurface:
    """tools/top.py renders the recovery machine and gates --once on
    the recovering deadline (pure-function tests, no sockets)."""

    def _row(self, status, recovery):
        return ("n1:1", {
            "health": {
                "status": status, "epoch": 3, "committed": 10,
                "peers_connected": 3, "peers_configured": 3,
            },
            "recovery": recovery,
            "stats": {}, "tx_lifecycle": {}, "verifier_stages": {},
        })

    def test_frame_shows_recovery_progress_and_epoch(self):
        frame = render_frame(
            [self._row("recovering",
                       {"state": "catchup", "catchup_lag": 7})],
            1.0, {},
        )
        assert "recovering" in frame
        assert "catchup lag 7" in frame
        assert "epoch" in frame.splitlines()[0]

    def test_once_tolerates_recovering_within_deadline(self):
        rows = [self._row("recovering",
                          {"state": "replaying_wal", "elapsed_s": 30.0})]
        assert once_verdict(rows, 120.0) == []

    def test_once_fails_recovering_past_deadline(self):
        rows = [self._row("recovering",
                          {"state": "catchup", "elapsed_s": 500.0})]
        bad = once_verdict(rows, 120.0)
        assert len(bad) == 1 and "deadline" in bad[0]

    def test_once_still_fails_down_and_degraded(self):
        rows = [
            ("dead:1", ConnectionRefusedError("nope")),
            self._row("degraded", {"state": "live"}),
            self._row("ok", {"state": "live"}),
        ]
        bad = once_verdict(rows, 120.0)
        assert len(bad) == 2
        assert any("down" in b for b in bad)
        assert any("degraded" in b for b in bad)
