"""Shell e2e tier: run every executable script in tests/shell/ with the
framework's CLI shims on PATH — the reference's tests/execs.rs harness
(`/root/reference/tests/execs.rs:11-60`) rebuilt for this package.

Scripts use bash (for $RANDOM and /dev/tcp) + the lib.sh helpers and
drive real server/client processes over localhost; a script passes iff
it exits 0.
"""

import os
import stat
import subprocess

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SHELL_DIR = os.path.join(HERE, "shell")


def _scripts():
    out = []
    for name in sorted(os.listdir(SHELL_DIR)):
        path = os.path.join(SHELL_DIR, name)
        if os.path.isfile(path) and os.stat(path).st_mode & stat.S_IXUSR:
            out.append(name)
    return out


@pytest.mark.parametrize("script", _scripts())
def test_shell_script(script):
    env = dict(
        os.environ,
        PATH=os.path.join(REPO, "bin") + os.pathsep + os.environ["PATH"],
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        ["/bin/bash", os.path.join(SHELL_DIR, script)],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"{script} failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
