"""Verifier boundary tests: CPU path, TPU batch path, adaptive flush."""

import asyncio

import pytest

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.crypto.verifier import CpuVerifier, TpuBatchVerifier, make_verifier


def _signed(n, msg=b"hello"):
    keys = [SignKeyPair.random() for _ in range(n)]
    return [(k.public, msg, k.sign(msg)) for k in keys]


def test_cpu_verifier():
    async def run():
        ver = CpuVerifier()
        items = _signed(4)
        assert await ver.verify(*items[0])
        assert not await ver.verify(items[0][0], b"other", items[0][2])
        results = await ver.verify_many(items)
        assert results == [True] * 4
        await ver.close()

    asyncio.run(run())


def test_batch_verifier_flushes_on_timeout():
    async def run():
        # small bucket: the flush-on-timeout semantics don't depend on the
        # bucket size, and the 8-lane XLA graph compiles ~10x faster than
        # the production 256 bucket (round-1 weak item: 78s per process)
        ver = TpuBatchVerifier(batch_size=8, max_delay=0.01)
        items = _signed(3)
        items.append((items[0][0], b"tampered", items[0][2]))
        results = await ver.verify_many(items)
        assert results == [True, True, True, False]
        assert ver.batches_dispatched == 1  # one padded dispatch, not four
        await ver.close()

    asyncio.run(run())


def test_batch_verifier_flushes_on_size():
    async def run():
        # same bucket shape as the timeout test: one compiled program (and
        # one compilation-cache entry) serves both
        ver = TpuBatchVerifier(batch_size=8, max_delay=10.0)
        items = _signed(8)
        results = await ver.verify_many(items)
        assert results == [True] * 8
        assert ver.batches_dispatched == 1
        await ver.close()

    asyncio.run(run())


def test_make_verifier():
    async def run():
        assert isinstance(make_verifier("cpu"), CpuVerifier)
        tpu = make_verifier("tpu", batch_size=64)
        assert isinstance(tpu, TpuBatchVerifier)
        await tpu.close()
        with pytest.raises(ValueError):
            make_verifier("gpu")

    asyncio.run(run())


def test_dispatch_pipeline_overlaps_batches():
    """Consecutive batches must overlap across the prep/launch/finish
    stages. Structural assertion (not wall-clock, which is flaky on a
    loaded single-core host): some batch's prep must START before an
    earlier batch's finish has ENDED."""
    import time as _time

    import numpy as _np

    async def run():
        events = []  # (stage, "start"/"end", batch_tag, t)

        class SlowStages(TpuBatchVerifier):
            def _prep(self, pks, msgs, sigs, bucket):
                tag = len(events)
                events.append(("prep", "start", tag, _time.monotonic()))
                _time.sleep(0.02)
                events.append(("prep", "end", tag, _time.monotonic()))
                return len(pks)

            def _launch(self, prepared):
                return prepared

            def _finish(self, handle, n):
                events.append(("finish", "start", None, _time.monotonic()))
                _time.sleep(0.05)
                events.append(("finish", "end", None, _time.monotonic()))
                return _np.ones(n, dtype=bool)

        ver = SlowStages(batch_size=4, max_delay=0.001)
        items = [(b"p" * 32, b"m", b"s" * 64)] * 32  # 8 batches of 4
        out = await ver.verify_many(items)
        assert out == [True] * 32
        assert ver.batches_dispatched == 8
        prep_starts = sorted(
            t for s, k, _, t in events if s == "prep" and k == "start"
        )
        finish_ends = sorted(
            t for s, k, _, t in events if s == "finish" and k == "end"
        )
        # pipelined: the LAST prep begins while finishes are still
        # outstanding (serial execution would order every finish-end
        # before the next prep-start)
        assert prep_starts[-1] < finish_ends[-1], "stages never overlapped"
        overlapping = sum(
            1 for t in prep_starts if t < finish_ends[0]
        )
        assert overlapping >= 2, f"only {overlapping} preps before first finish end"
        await ver.close()

    asyncio.run(run())


def test_close_with_inflight_completions_resolves_everything():
    """close() while batches sit between launch and finish must resolve
    every caller (success or 'verifier closed'), never hang."""
    import time as _time

    import numpy as _np

    async def run():
        class SlowFinish(TpuBatchVerifier):
            def _prep(self, pks, msgs, sigs, bucket):
                return len(pks)

            def _launch(self, prepared):
                return prepared

            def _finish(self, handle, n):
                _time.sleep(0.15)
                return _np.ones(n, dtype=bool)

        ver = SlowFinish(batch_size=4, max_delay=0.001)
        futs = [
            asyncio.ensure_future(ver.verify(b"p" * 32, b"m", b"s" * 64))
            for _ in range(8)
        ]
        await asyncio.sleep(0.05)  # let at least one batch pass launch
        await asyncio.wait_for(ver.close(), timeout=5)
        results = await asyncio.gather(*futs, return_exceptions=True)
        for r in results:
            assert r is True or isinstance(r, RuntimeError), r

    asyncio.run(run())
