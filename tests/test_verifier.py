"""Verifier boundary tests: CPU path, TPU batch path, adaptive flush."""

import asyncio

import pytest

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.crypto.verifier import CpuVerifier, TpuBatchVerifier, make_verifier


def _signed(n, msg=b"hello"):
    keys = [SignKeyPair.random() for _ in range(n)]
    return [(k.public, msg, k.sign(msg)) for k in keys]


def test_cpu_verifier():
    async def run():
        ver = CpuVerifier()
        items = _signed(4)
        assert await ver.verify(*items[0])
        assert not await ver.verify(items[0][0], b"other", items[0][2])
        results = await ver.verify_many(items)
        assert results == [True] * 4
        await ver.close()

    asyncio.run(run())


def test_batch_verifier_flushes_on_timeout():
    async def run():
        # small bucket: the flush-on-timeout semantics don't depend on the
        # bucket size, and the 8-lane XLA graph compiles ~10x faster than
        # the production 256 bucket (round-1 weak item: 78s per process)
        ver = TpuBatchVerifier(batch_size=8, max_delay=0.01)
        items = _signed(3)
        items.append((items[0][0], b"tampered", items[0][2]))
        results = await ver.verify_many(items)
        assert results == [True, True, True, False]
        assert ver.batches_dispatched == 1  # one padded dispatch, not four
        await ver.close()

    asyncio.run(run())


def test_batch_verifier_flushes_on_size():
    async def run():
        # same bucket shape as the timeout test: one compiled program (and
        # one compilation-cache entry) serves both
        ver = TpuBatchVerifier(batch_size=8, max_delay=10.0)
        items = _signed(8)
        results = await ver.verify_many(items)
        assert results == [True] * 8
        assert ver.batches_dispatched == 1
        await ver.close()

    asyncio.run(run())


def test_make_verifier():
    async def run():
        assert isinstance(make_verifier("cpu"), CpuVerifier)
        tpu = make_verifier("tpu", batch_size=64)
        assert isinstance(tpu, TpuBatchVerifier)
        await tpu.close()
        with pytest.raises(ValueError):
            make_verifier("gpu")

    asyncio.run(run())
