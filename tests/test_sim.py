"""Deterministic simulation harness tests (at2_node_tpu/sim).

Everything here is a SYNC test on purpose: each test owns a
``SimScheduler`` (a virtual-time asyncio loop) and drives it with
``run_until_complete`` — the conftest's coroutine-test wrapper would
fight over the running loop.

Covers the acceptance properties of the harness itself:

* virtual time: sleeps advance the clock without wall-clock cost, the
  executor seam is inline, lost-wakeup bugs deadlock loudly;
* the fabric: latency/loss/duplication, partitions and heals,
  kind-selective interposition, the transport-channel surface;
* determinism: the same ``(seed, config, events)`` replays to a
  byte-identical wire trace hash, across full adversarial episodes;
* invariants: a seeded multi-episode campaign of the REAL stack
  (equivocation, hostile frames, partitions, drop windows) stays green;
* the round-5 stalled-slot overload (attestations dropped under a
  burst) reproduces and heals in-sim from a fixed seed;
* a deliberately injected safety bug (echo/ready threshold below the
  quorum-intersection bound) is caught by the invariant checker and
  minimized to a handful of events.
"""

import asyncio

import pytest

from at2_node_tpu.node.config import BatchingConfig
from at2_node_tpu.sim.campaign import (
    apply_events,
    minimize_events,
    planted_breach_episode,
    run_campaign,
    run_episode,
)
from at2_node_tpu.sim.fabric import LinkModel, SimChannel, SimFabric
from at2_node_tpu.sim.net import SimNet, sim_client, sim_keypairs
from at2_node_tpu.sim.scheduler import (
    SIM_START,
    SimClock,
    SimDeadlockError,
    SimScheduler,
)


class TestScheduler:
    def test_virtual_sleep_advances_no_wall_time(self):
        loop = SimScheduler()
        try:
            t0 = loop.time()
            assert t0 == SIM_START

            async def nap():
                await asyncio.sleep(3600.0)
                return loop.time()

            import time

            wall0 = time.monotonic()
            end = loop.run_until_complete(nap())
            assert end == pytest.approx(t0 + 3600.0)
            assert time.monotonic() - wall0 < 1.0  # an hour in an instant
        finally:
            loop.close()

    def test_timer_ordering_is_schedule_order(self):
        loop = SimScheduler()
        try:
            order = []
            loop.call_later(0.3, order.append, "c")
            loop.call_later(0.1, order.append, "a")
            loop.call_later(0.2, order.append, "b")
            loop.call_later(0.2, order.append, "b2")  # tie: insertion order
            loop.run_for(1.0)
            assert order == ["a", "b", "b2", "c"]
        finally:
            loop.close()

    def test_executor_runs_inline(self):
        loop = SimScheduler()
        try:
            import threading

            main = threading.get_ident()

            async def offload():
                return await loop.run_in_executor(
                    None, lambda: threading.get_ident()
                )

            assert loop.run_until_complete(offload()) == main
        finally:
            loop.close()

    def test_lost_wakeup_deadlocks_loudly(self):
        loop = SimScheduler()
        try:
            with pytest.raises(SimDeadlockError):
                loop.run_until_complete(asyncio.Event().wait())
        finally:
            loop.close()

    def test_clock_binds_to_loop(self):
        loop = SimScheduler()
        try:
            clock = SimClock(loop)
            w0, m0 = clock.wall(), clock.monotonic()
            loop.run_for(12.5)
            assert clock.monotonic() - m0 == pytest.approx(12.5)
            assert clock.wall() - w0 == pytest.approx(12.5)
        finally:
            loop.close()


class TestFabric:
    def _fabric(self, seed=0, **link):
        loop = SimScheduler()
        asyncio.set_event_loop(loop)
        fabric = SimFabric(loop, seed=seed, default_link=LinkModel(**link))
        return loop, fabric

    def _mesh_pair(self, loop, fabric):
        from at2_node_tpu.net.peers import Peer
        from at2_node_tpu.sim.fabric import SimMesh

        ka, xa = sim_keypairs(0, 0)
        kb, xb = sim_keypairs(0, 1)
        pa = Peer("sim-a:0", xa.public, ka.public)
        pb = Peer("sim-b:0", xb.public, kb.public)
        got_a, got_b = [], []

        async def on_a(peer, frame):
            got_a.append(frame)

        async def on_b(peer, frame):
            got_b.append(frame)

        mesh_a = SimMesh(fabric, ka.public, [pb], on_a)
        mesh_b = SimMesh(fabric, kb.public, [pa], on_b)
        return mesh_a, mesh_b, pa, pb, got_a, got_b

    def test_delivery_and_partition(self):
        loop, fabric = self._fabric()
        try:
            mesh_a, mesh_b, pa, pb, got_a, got_b = self._mesh_pair(loop, fabric)
            mesh_a.send(pb, b"\x01hello")
            loop.run_for(1.0)
            assert got_b == [b"\x01hello"]
            fabric.partition(pa.sign_public, pb.sign_public)
            mesh_a.send(pb, b"\x01cut")
            loop.run_for(1.0)
            assert got_b == [b"\x01hello"]  # blackholed
            fabric.heal(pa.sign_public, pb.sign_public)
            mesh_b.send(pa, b"\x01back")
            loop.run_for(1.0)
            assert got_a == [b"\x01back"]
        finally:
            loop.close()
            asyncio.set_event_loop(None)

    def test_loss_and_duplication_are_seeded(self):
        loop, fabric = self._fabric(seed=3, loss=0.5, dup=0.3)
        try:
            mesh_a, mesh_b, pa, pb, _, got_b = self._mesh_pair(loop, fabric)
            for i in range(40):
                mesh_a.send(pb, bytes([1, i]))
            loop.run_for(2.0)
            # lossy and duplicating: SOME dropped, SOME duplicated, and
            # the exact counts are a pure function of the seed
            assert 0 < len(got_b) != 40
            assert fabric.dropped > 0
        finally:
            loop.close()
            asyncio.set_event_loop(None)

    def test_interposer_drops_by_kind(self):
        loop, fabric = self._fabric()
        try:
            mesh_a, mesh_b, pa, pb, _, got_b = self._mesh_pair(loop, fabric)
            fabric.interposer = (
                lambda src, dst, frame: [] if frame[0] == 2 else None
            )
            mesh_a.send(pb, b"\x01keep")
            mesh_a.send(pb, b"\x02drop")
            mesh_a.send(pb, b"\x03keep")
            loop.run_for(1.0)
            assert sorted(got_b) == [b"\x01keep", b"\x03keep"]
        finally:
            loop.close()
            asyncio.set_event_loop(None)

    def test_sim_channel_surface(self):
        from at2_node_tpu.net.transport import ChannelClosed

        loop = SimScheduler()
        asyncio.set_event_loop(loop)
        try:
            a_end, b_end = SimChannel.pair(loop, b"A" * 32, b"B" * 32, 0.01)
            assert a_end.peer_public == b"B" * 32
            assert b_end.peer_public == b"A" * 32

            async def roundtrip():
                await a_end.send(b"ping")
                got = await b_end.recv()
                await b_end.send(b"pong")
                return got, await a_end.recv()

            assert loop.run_until_complete(roundtrip()) == (b"ping", b"pong")
            a_end.close()
            with pytest.raises(ChannelClosed):
                loop.run_until_complete(b_end.recv())
        finally:
            loop.close()
            asyncio.set_event_loop(None)


class TestDeterminism:
    def test_same_seed_same_trace_hash(self):
        results = [
            run_episode(77, n_events=12, duration=6.0, settle_horizon=45.0)
            for _ in range(2)
        ]
        assert results[0].trace_hash == results[1].trace_hash
        assert results[0].committed == results[1].committed
        assert results[0].events == results[1].events

    def test_different_seeds_diverge(self):
        a = run_episode(1, n_events=8, duration=4.0, settle_horizon=30.0)
        b = run_episode(2, n_events=8, duration=4.0, settle_horizon=30.0)
        assert a.trace_hash != b.trace_hash


class TestScenarioGrid:
    def test_same_seed_same_cell(self):
        """A WAN grid cell is pure in (seed, params): same wire-trace
        hash AND byte-identical measurement JSON on re-run."""
        import json

        from at2_node_tpu.sim.scenarios import run_cell

        kw = dict(nodes=3, n_clients=3, n_tx=8, duration=3.0,
                  settle_horizon=60.0)
        a = run_cell(31, "wan3", "flash_crowd", "none", **kw)
        b = run_cell(31, "wan3", "flash_crowd", "none", **kw)
        assert a["trace_hash"] == b["trace_hash"]
        a.pop("wall_seconds"), b.pop("wall_seconds")
        assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
        # the cell is also a valid measurement: clean commits, SLO
        # verdict attached, fairness in (0, 1]
        assert a["violations"] == []
        assert a["committed"] == a["offered"]
        assert 0.0 < a["fairness"] <= 1.0
        assert a["slo"]["ok"] is True
        assert a["latency_p99_ms"] >= a["latency_p50_ms"] > 0.0

    def test_different_topologies_diverge(self):
        from at2_node_tpu.sim.scenarios import run_cell

        kw = dict(nodes=3, n_clients=3, n_tx=6, duration=2.0,
                  settle_horizon=60.0)
        lan = run_cell(32, "lan", "steady", "none", **kw)
        wan = run_cell(32, "wan3", "steady", "none", **kw)
        assert lan["trace_hash"] != wan["trace_hash"]
        # regional long-haul links must show up in the tail
        assert wan["latency_p99_ms"] > lan["latency_p99_ms"]


class TestInvariantCampaign:
    def test_seeded_campaign_stays_green(self):
        """4-node f=1, hostile identity live, equivocation + partitions
        + drop windows: every episode's invariants must hold."""
        campaign = run_campaign(20260805, 3, n_events=18, duration=10.0)
        assert campaign["failures"] == 0, campaign["results"]
        # and the campaign fingerprint replays
        again = run_campaign(20260805, 3, n_events=18, duration=10.0)
        assert campaign["campaign_hash"] == again["campaign_hash"]


class TestScenarios:
    def test_stalled_slot_overload_heals(self):
        """Round-5 regression shape, from a fixed seed: a burst lands
        while every batch-echo attestation is being dropped — all slots
        stall; once the blackout lifts, budgeted retransmission heals
        every slot without client retries."""
        net = SimNet(n=4, f=1, seed=5).start()
        try:
            clients = [sim_client(5, i) for i in range(2)]
            events = [[0.0, "drop", {"src": None, "kinds": [10], "duration": 8.0}]]
            events += [
                [
                    0.5 + 0.05 * i,
                    "tx",
                    {"node": i % 4, "client": 0, "seq": i + 1, "to": 1, "amount": 1},
                ]
                for i in range(10)
            ]
            apply_events(net, events, clients, None)
            net.run_for(6.0)  # deep inside the blackout
            assert [s.committed for s in net.services] == [0, 0, 0, 0]
            net.run_for(10.0)  # blackout ends at t=8; retransmit heals
            assert [s.committed for s in net.services] == [10, 10, 10, 10]
            net.settle(horizon=40.0)
            assert net.check_invariants() == []
        finally:
            net.close()

    def test_injected_threshold_bug_caught_and_minimized(self):
        """Safety-bug detection end to end: thresholds forced to 1
        (below the quorum-intersection bound), per-tx plane, honest
        attestations between the two target nodes suppressed — an
        equivocating client splits the net into divergent commits. The
        invariant checker must flag it, replay must reproduce it, and
        minimization must shrink the schedule to a few events."""
        from at2_node_tpu.broadcast.messages import (
            ECHO,
            READY,
            Attestation,
            Payload,
        )
        from at2_node_tpu.types import ThinTransaction

        seed = 20260805
        clients = [sim_client(seed, i) for i in range(4)]
        hostile_sign, _ = sim_keypairs(seed, 4)  # identity 4: hostile peer

        def payload(to_i, amount):
            tx = ThinTransaction(clients[to_i].public, amount)
            return Payload.create(clients[0], 1, tx)

        def att_frames(chash):
            out = []
            for phase in (ECHO, READY):
                sig = hostile_sign.sign(
                    Attestation.signing_bytes(
                        phase, clients[0].public, 1, chash
                    )
                )
                out.append(
                    Attestation(
                        phase,
                        hostile_sign.public,
                        clients[0].public,
                        1,
                        chash,
                        sig,
                    ).encode().hex()
                )
            return out

        echo_a, ready_a = att_frames(payload(1, 5).content_hash())
        echo_b, ready_b = att_frames(payload(2, 6).content_hash())

        # honest attestations suppressed net-wide; the hostile peer then
        # hand-delivers a split vote: content A's quorum to node 0,
        # content B's quorum to node 1
        events = [
            [0.0, "drop", {"src": s, "kinds": [2, 3], "duration": 60.0}]
            for s in range(4)
        ] + [
            [
                0.2,
                "equiv",
                {
                    "node_a": 0,
                    "node_b": 1,
                    "client": 0,
                    "seq": 1,
                    "to_a": 1,
                    "to_b": 2,
                    "amount_a": 5,
                    "amount_b": 6,
                },
            ],
            [0.6, "inject", {"src_hostile": 1, "target": 0, "frame": echo_a}],
            [0.6, "inject", {"src_hostile": 1, "target": 0, "frame": ready_a}],
            [0.6, "inject", {"src_hostile": 1, "target": 1, "frame": echo_b}],
            [0.6, "inject", {"src_hostile": 1, "target": 1, "frame": ready_b}],
        ]

        def run(evs):
            return run_episode(
                seed,
                events=evs,
                echo_threshold=1,
                ready_threshold=1,
                config_overrides={"batching": BatchingConfig(enabled=False)},
                settle_horizon=40.0,
            )

        first = run(events)
        assert first.violations, "threshold bug must violate agreement"
        assert any("sieve violation" in v for v in first.violations)
        # exact replay: same violations, same wire trace
        again = run(events)
        assert again.violations == first.violations
        assert again.trace_hash == first.trace_hash
        # minimization: down to a <= 25-event (here: tiny) schedule
        minimal = minimize_events(
            events, lambda evs: bool(run(evs).violations)
        )
        assert len(minimal) <= 25
        assert len(minimal) < len(events)
        assert run(minimal).violations

    def test_correct_thresholds_survive_the_same_schedule(self):
        """The counterfactual: the same suppression + equivocation
        shape, with the real f=1-safe thresholds, commits at most one
        content — invariants green. The bug, not the schedule, was the
        problem."""
        events = [
            [0.0, "drop", {"src": s, "kinds": [2, 3], "duration": 60.0}]
            for s in range(4)
        ] + [
            [
                0.2,
                "equiv",
                {
                    "node_a": 0,
                    "node_b": 1,
                    "client": 0,
                    "seq": 1,
                    "to_a": 1,
                    "to_b": 2,
                    "amount_a": 5,
                    "amount_b": 6,
                },
            ]
        ]
        result = run_episode(
            20260805,
            events=events,
            config_overrides={"batching": BatchingConfig(enabled=False)},
            settle_horizon=40.0,
        )
        assert result.violations == []


class TestObsCapture:
    def test_episode_stitches_deterministically(self):
        """Fleet tracing acceptance: a 4-node honest episode stitches
        every sampled committed tx across multiple nodes with straggler
        attribution, and two same-seed runs produce a byte-identical
        stitched artifact (virtual clocks make the join exact)."""
        import json

        def go():
            return run_episode(
                7, nodes=4, hostile=0, n_events=20, duration=10.0,
                capture_obs=True,
            )

        a, b = go(), go()
        assert a.violations == []
        cov = a.obs["stitched"]["coverage"]
        assert cov["committed"] > 0
        assert cov["stitched_committed"] / cov["committed"] >= 0.95
        assert cov["with_origin"] == cov["txs"]
        # straggler attribution names a node for every delivered stage
        for tx in a.obs["stitched"]["txs"]:
            if tx["terminal"] == "committed":
                assert "ready_quorum" in tx["stragglers"]
        assert json.dumps(a.obs, sort_keys=True) == json.dumps(
            b.obs, sort_keys=True
        )

    def test_planted_breach_attaches_recorder_and_timeline(self):
        """Failing episodes carry their black box: per-node flight
        recorder dumps plus the stitched cross-node timeline of the
        offending tx (the artifact scripts/ci.sh gates on)."""
        r = planted_breach_episode()
        assert r.violations
        assert any("sieve violation" in v for v in r.violations)
        obs = r.obs
        assert obs is not None
        assert len(obs["recorders"]) == 4
        for dump in obs["recorders"]:
            rec = dump["recorder"]
            assert rec["recorded"] > 0 and rec["events"]
            assert rec["snapshots"]  # episode capture froze the ring
        offending = [
            tx for tx in obs["stitched"]["txs"] if tx["seq"] == 1
        ]
        assert offending, "the equivocated tx must appear in the timeline"
        assert offending[0]["nodes"] >= 2  # genuinely cross-node
        assert offending[0]["stragglers"]
        # the artifact round-trips through to_dict (banked as JSON by
        # tools/sim_run.py next to the minimized schedule)
        import json

        blob = json.loads(json.dumps(r.to_dict()))
        assert blob["obs"]["stitched"]["coverage"]["txs"] >= 1


class TestServiceInSim:
    def test_health_and_stats_surface(self):
        """The real observability surface works under the sim mesh."""
        net = SimNet(n=4, f=1, seed=11).start()
        try:
            net.run_for(1.0)
            for s in net.services:
                verdict = s.health_verdict()
                assert verdict["status"] == "ok", verdict
                snap = s.snapshot_stats()
                assert snap["mesh_channels"] == 3
        finally:
            net.close()

    def test_admission_runs_in_sim(self):
        """A bad client signature is rejected at the real admission
        gate, never reaching the gossip plane."""
        net = SimNet(n=4, f=1, seed=13).start()
        try:
            client = sim_client(13, 0)
            rcpt = sim_client(13, 1).public
            err = net.submit(0, client, 1, rcpt, 5, good_sig=False)
            assert err is not None  # SimRpcError from context.abort
            net.settle(horizon=30.0)
            assert [s.committed for s in net.services] == [0, 0, 0, 0]
            assert (
                net.services[0].snapshot_stats()["rejected_at_ingress"] == 1
            )
        finally:
            net.close()


class TestFleetAuditSim:
    """Episode-level contracts for the fleet consistency auditor
    (obs/audit.py) and the capture->replay bridge: detection +
    attribution on a planted corruption, digest invariance across the
    sharded plane and the [wan] levers, and deterministic replay."""

    def test_planted_divergence_detected_and_attributed(self):
        from at2_node_tpu.sim.campaign import planted_divergence_episode

        seed = 20260805
        r = planted_divergence_episode(seed)
        # the fork is real, so the invariant sweep fails by design
        assert r.violations
        culprit = sim_keypairs(seed, 0)[0].public.hex()
        victim_lane = sim_client(seed, 1).public[0] >> 4
        assert r.audit is not None
        for a in r.audit[1:]:  # both honest nodes latch it
            d = a["divergence"]
            assert d is not None
            assert d["peer"] == culprit
            assert victim_lane in d["ranges"]
            # caught within two audit_every=8 beacon intervals of the
            # corruption (armed just before commit ~6)
            assert d["detected_commits"] - 6 <= 16, d
        # the culprit symmetrically sees itself diverged from a peer
        assert r.audit[0]["divergence"] is not None

    def test_digests_invariant_across_plane_shards(self):
        from at2_node_tpu.node.config import ObservabilityConfig

        kw = dict(
            n_events=10,
            duration=8.0,
            settle_horizon=60.0,
            config_overrides={
                "observability": ObservabilityConfig(audit_every=8)
            },
        )
        mono = run_episode(13, **kw)
        shard = run_episode(
            13,
            **{
                **kw,
                "config_overrides": {
                    **kw["config_overrides"],
                    "plane_shards": 4,
                },
            },
        )
        assert mono.trace_hash == shard.trace_hash
        for a, b in zip(mono.audit, shard.audit):
            assert a["wm"] == b["wm"]
            assert a["ranges"] == b["ranges"]
            assert a["divergence"] is None and b["divergence"] is None

    def test_digests_invariant_across_wan_levers(self):
        """Digest equality under [wan] on/off needs a schedule where
        both runs commit the same SET (the digest is a pure function of
        committed state, not of timing) — so: serialized benign
        traffic. Adversarial schedules can commit different sets under
        the wan timing levers (TTL races), which is a scheduling
        difference, not a digest defect."""
        from at2_node_tpu.node.config import ObservabilityConfig, WanConfig

        events = [
            [0.5 + 0.4 * k, "tx",
             {"node": k % 3, "client": 0, "seq": k + 1, "to": 1,
              "amount": 1}]
            for k in range(24)
        ]
        obs = ObservabilityConfig(audit_every=8)
        base = dict(
            nodes=3, f=0, hostile=0, events=events, settle_horizon=60.0
        )
        off = run_episode(
            17, **base, config_overrides={"observability": obs}
        )
        on = run_episode(
            17,
            **base,
            config_overrides={
                "observability": obs,
                "wan": WanConfig(overlap_ready=True, region_fanout=True),
            },
        )
        assert not off.violations and not on.violations
        assert off.committed == on.committed == [24, 24, 24]
        for a, b in zip(off.audit, on.audit):
            assert a["wm"] == b["wm"]
            assert a["ranges"] == b["ranges"]
            assert a["dir"] == b["dir"]
            assert a["divergence"] is None and b["divergence"] is None

    def test_capture_replay_verdict_is_deterministic(self):
        from at2_node_tpu.broadcast.messages import StateBeacon
        from at2_node_tpu.crypto.keys import SignKeyPair
        from at2_node_tpu.tools.capture_replay import (
            replay_capture,
            verdict_hash,
        )

        # a synthetic capture: one well-formed signed beacon from a key
        # the sim fleet does not know, plus junk — the bridge must
        # replay hostile/unknown bytes, not only friendly traffic
        kp = SignKeyPair.from_hex("aa" * 32)
        beacon = StateBeacon.create(
            kp, 0, 3, (99).to_bytes(16, "little"), b"\x01" * 128,
            b"\x02" * 8, b"\x03" * 32,
        )
        doc = {
            "cap": 16,
            "captured": 3,
            "records": [
                [1_000_000, "ab" * 32, 15, beacon.encode().hex()],
                [51_000_000, "ab" * 32, 222, "deadbeef"],
                [101_000_000, "ab" * 32, 0, "00" * 40],
            ],
        }
        v1 = replay_capture(doc, 7, nodes=4)
        v2 = replay_capture(doc, 7, nodes=4)
        assert verdict_hash(v1) == verdict_hash(v2)
        assert v1["injected"] == 3
        assert not v1["violations"]
