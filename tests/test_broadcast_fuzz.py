"""Randomized-schedule broadcast fuzz: safety under adversarial delivery.

The targeted state-machine tests (test_broadcast.py) pin known scenarios;
this tier drives FULL Broadcast instances for every node of a simulated
net through a seeded adversarial network — arbitrary interleaving,
duplication, and (in the consistency runs) message loss — and asserts the
AT2 safety invariants that must hold under ANY schedule:

* **consistency** (sieve): no two nodes ever deliver different contents
  for one (sender, sequence) slot, even when a byzantine client
  equivocates two signed contents for the same slot;
* **no double delivery**: a node delivers a slot at most once;
* **validity**: only client-signed payloads are ever delivered;
* **totality** (loss-free runs): every node delivers every honest slot.

The reference never tests these (its thresholds=n config sidesteps
faults entirely — SURVEY.md §7 hard part 3)."""

import asyncio
import random

import pytest

from at2_node_tpu.broadcast.messages import Payload, TxBatch
from at2_node_tpu.broadcast.stack import Broadcast
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.crypto.verifier import CpuVerifier
from at2_node_tpu.net.peers import Peer
from at2_node_tpu.types import ThinTransaction


class _CountingVerifier(CpuVerifier):
    """CpuVerifier that tracks in-flight verifications, so quiescence
    detection can't race a worker parked inside an executor round-trip."""

    def __init__(self):
        super().__init__()
        self.inflight = 0

    async def verify_many(self, items):
        self.inflight += 1
        try:
            return await super().verify_many(items)
        finally:
            self.inflight -= 1


class AdversarialNet:
    """N Broadcast endpoints joined by a network the test schedules."""

    def __init__(self, n, rng, dup=0.2, drop=0.0, threshold=None):
        self.rng = rng
        self.dup = dup
        self.drop = drop
        self.n = n
        self.keys = [SignKeyPair.random() for _ in range(n)]
        exchange = [bytes([i + 1]) * 32 for i in range(n)]
        self.all_peers = [
            Peer(f"sim{i}", exchange[i], self.keys[i].public) for i in range(n)
        ]
        self.pending = []  # (dst_node, src_peer_as_seen_by_dst, frame)
        self.bcasts = []
        for i in range(n):
            peers = [p for j, p in enumerate(self.all_peers) if j != i]
            mesh = _RoutedMesh(self, i, peers)
            self.bcasts.append(
                Broadcast(
                    self.keys[i],
                    mesh,
                    _CountingVerifier(),
                    echo_threshold=threshold,
                    ready_threshold=threshold,
                    workers=2,
                )
            )

    def route(self, src: int, dst_peer: Peer, frame: bytes) -> None:
        dst = next(
            i for i, p in enumerate(self.all_peers) if p is dst_peer
        )
        if self.rng.random() < self.drop:
            return
        src_as_seen = self.all_peers[src]
        self.pending.append((dst, src_as_seen, frame))
        if self.rng.random() < self.dup:
            self.pending.append((dst, src_as_seen, frame))

    async def start(self):
        for b in self.bcasts:
            await b.start()

    async def close(self):
        for b in self.bcasts:
            await b.close()
            await b.verifier.close()

    def _endpoints_idle(self) -> bool:
        """Every inbox drained and no worker parked inside a verifier
        executor round-trip. A worker holds its chunk synchronously from
        inbox-get to the verify await (no other awaits between, single
        event loop), so inbox-empty + inflight==0 cannot race a chunk
        into invisibility. The routed-frame queue (self.pending) is NOT
        part of this check — relays refill it by design; the outer loop
        consumes it."""
        return all(b._inbox.empty() for b in self.bcasts) and all(
            b.verifier.inflight == 0 for b in self.bcasts
        )

    async def run_to_quiescence(self, max_steps=1000):
        """Deliver pending frames in seeded-random order (relays refill
        the queue) until the network and every endpoint are drained."""
        for _ in range(max_steps):
            if self.pending:
                self.rng.shuffle(self.pending)
                k = self.rng.randrange(1, len(self.pending) + 1)
                batch, self.pending = self.pending[:k], self.pending[k:]
                for dst, peer, frame in batch:
                    await self.bcasts[dst].on_frame(peer, frame)
            # let workers drain what they have (they may emit new frames)
            for _ in range(1000):
                if self._endpoints_idle():
                    break
                await asyncio.sleep(0.005)
            if self._endpoints_idle() and not self.pending:
                await asyncio.sleep(0.01)
                if self._endpoints_idle() and not self.pending:
                    return
        raise AssertionError("network never quiesced")

    def delivered(self, i):
        out = []
        q = self.bcasts[i].delivered
        while not q.empty():
            out.append(q.get_nowait())
        return out


class _RoutedMesh:
    def __init__(self, net, index, peers):
        self.net = net
        self.index = index
        self.peers = peers
        self.by_sign = {p.sign_public: p for p in peers}
        self.by_exchange = {p.exchange_public: p for p in peers}

    def broadcast(self, frame, exclude=()):
        for p in self.peers:
            if p.exchange_public not in exclude:
                self.net.route(self.index, p, frame)

    def send(self, peer, frame):
        self.net.route(self.index, peer, frame)


def _signed_payload(client, seq, amount=5):
    return Payload.create(client, seq, ThinTransaction(b"r" * 32, amount))


def _check_safety(per_node_deliveries, honest_sigs):
    """The invariants that must hold under EVERY schedule."""
    chosen = {}  # slot -> content hash the network agreed on
    for node, payloads in enumerate(per_node_deliveries):
        seen_slots = set()
        for p in payloads:
            slot = (p.sender, p.sequence)
            assert slot not in seen_slots, f"node {node} delivered {slot} twice"
            seen_slots.add(slot)
            assert p.signature in honest_sigs[p.sender], (
                f"node {node} delivered an unsigned payload"
            )
            agreed = chosen.setdefault(slot, p.content_hash())
            assert agreed == p.content_hash(), (
                f"consistency violation at {slot}: two contents delivered"
            )


@pytest.mark.parametrize("seed", [1, 7, 23, 51])
async def test_totality_and_consistency_lossless_schedules(seed):
    """Dup + arbitrary reordering, no loss: every node must deliver every
    honest slot exactly once, with network-wide agreement. async-def so
    conftest's hang watchdog (with task-stack dumps) covers a wedge."""
    if True:
        rng = random.Random(seed)
        net = AdversarialNet(4, rng, dup=0.25, drop=0.0)
        await net.start()
        clients = [SignKeyPair.random() for _ in range(2)]
        slots = []
        honest_sigs = {}
        try:
            for client in clients:
                for seq in rng.sample(range(1, 4), 3):  # out-of-order seqs
                    p = _signed_payload(client, seq, amount=seq)
                    honest_sigs.setdefault(client.public, set()).add(p.signature)
                    slots.append((client.public, seq))
                    # submission lands at a random node
                    await net.bcasts[rng.randrange(net.n)].broadcast(p)
            await net.run_to_quiescence()
            deliveries = [net.delivered(i) for i in range(net.n)]
            _check_safety(deliveries, honest_sigs)
            for node, payloads in enumerate(deliveries):
                got = {(p.sender, p.sequence) for p in payloads}
                assert got == set(slots), (
                    f"node {node} missed slots: {set(slots) - got}"
                )
        finally:
            await net.close()


@pytest.mark.parametrize("seed", [3, 13, 37, 91])
async def test_consistency_under_loss_and_equivocation(seed):
    """Random loss + a byzantine client equivocating two contents for the
    SAME slot: totality is forfeit (loss), but consistency and validity
    must survive every schedule."""
    if True:
        rng = random.Random(seed)
        # default thresholds (= all peers): echo quorums must intersect, so
        # consistency is a real guarantee of this config — threshold 2 of
        # 3 peers would permit disjoint echo quorums and the invariant
        # would be violable by schedule, not by bug
        net = AdversarialNet(4, rng, dup=0.2, drop=0.15, threshold=None)
        await net.start()
        honest = SignKeyPair.random()
        byz = SignKeyPair.random()
        honest_sigs = {}
        try:
            for seq in (1, 2):
                p = _signed_payload(honest, seq)
                honest_sigs.setdefault(honest.public, set()).add(p.signature)
                await net.bcasts[rng.randrange(net.n)].broadcast(p)
            # equivocation: two validly-signed contents, one slot,
            # submitted at different nodes
            for amount, node in ((111, 0), (222, 2)):
                p = Payload.create(byz, 1, ThinTransaction(b"r" * 32, amount))
                honest_sigs.setdefault(byz.public, set()).add(p.signature)
                await net.bcasts[node].broadcast(p)
            await net.run_to_quiescence()
            _check_safety(
                [net.delivered(i) for i in range(net.n)], honest_sigs
            )
        finally:
            await net.close()


@pytest.mark.parametrize("seed", [7, 29, 61, 83])
async def test_batch_plane_consistency_under_loss_and_equivocation(seed):
    """The batched plane under the same adversarial schedules: random
    loss + dup, a byzantine client racing conflicting same-(sender, seq)
    entries through TWO different nodes' batch slots AND a third
    conflicting content over the per-tx plane. The cross-plane entry
    registry + per-entry quorum counting must keep consistency (at most
    one content per slot network-wide) under every schedule; totality is
    forfeit to loss by design."""
    if True:
        rng = random.Random(seed)
        net = AdversarialNet(4, rng, dup=0.2, drop=0.15, threshold=None)
        await net.start()
        honest = SignKeyPair.random()
        byz = SignKeyPair.random()
        honest_sigs = {}
        try:
            # an honest 3-entry batch slot from node 0
            entries = []
            for seq in (1, 2, 3):
                p = _signed_payload(honest, seq)
                honest_sigs.setdefault(honest.public, set()).add(p.signature)
                entries.append(p)
            raw = b"".join(p.encode()[1:] for p in entries)
            await net.bcasts[0].broadcast_batch(
                TxBatch.create(net.keys[0], 1, raw)
            )
            # byzantine client: conflicting (byz, 1) entries ride two
            # different honest nodes' batch slots
            for amount, node in ((111, 1), (222, 2)):
                p = Payload.create(byz, 1, ThinTransaction(b"r" * 32, amount))
                honest_sigs.setdefault(byz.public, set()).add(p.signature)
                await net.bcasts[node].broadcast_batch(
                    TxBatch.create(net.keys[node], 7, p.encode()[1:])
                )
            # ...and a third conflicting content over the per-tx plane
            p = Payload.create(byz, 1, ThinTransaction(b"r" * 32, 333))
            honest_sigs[byz.public].add(p.signature)
            await net.bcasts[3].broadcast(p)
            await net.run_to_quiescence()
            _check_safety(
                [net.delivered(i) for i in range(net.n)], honest_sigs
            )
        finally:
            await net.close()
