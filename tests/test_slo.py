"""SLO burn-rate engine unit tests (obs/slo.py): window edges, empty
snapshots, flap suppression via the multi-window AND, idle guards, and
the offline single-point evaluation the scenario grid banks."""

import pytest

from at2_node_tpu.obs.slo import (
    BURN_CAP,
    Objective,
    SloEngine,
    default_objectives,
    evaluate_point,
)


def _lat(pairs, count):
    """Histogram.buckets() shape: (cumulative (le, cum) pairs incl +Inf,
    sum_seconds, count)."""
    return (pairs, 0.0, count)


def _sample(t, committed=0, rejected=0, pending=0, stalled=False,
            latency=None):
    return {
        "t": t,
        "committed": committed,
        "rejected": rejected,
        "pending": pending,
        "stalled": stalled,
        "latency": latency,
    }


class TestObjective:
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            Objective("x", "latency_p42", 1.0)

    def test_nonpositive_target_rejected(self):
        with pytest.raises(ValueError):
            Objective("x", "latency_p99", 0.0)

    def test_default_objectives_disable_on_nonpositive(self):
        kinds = {o.kind for o in default_objectives()}
        # the throughput floor defaults OFF (0.0): an idle node has no
        # committed rate to hold
        assert kinds == {"latency_p99", "rejection_ratio", "stall_budget"}
        kinds = {o.kind for o in default_objectives(latency_p99_ms=0.0)}
        assert "latency_p99" not in kinds
        kinds = {o.kind for o in default_objectives(throughput_floor_tps=2.0)}
        assert "throughput_floor" in kinds


class TestEngineWindows:
    def test_bad_windows_rejected(self):
        with pytest.raises(ValueError):
            SloEngine([], windows=(0.0, 30.0))
        with pytest.raises(ValueError):
            SloEngine([], windows=())

    def test_empty_engine_reports_no_data_and_never_breaches(self):
        e = SloEngine(default_objectives(), windows=(30.0, 300.0))
        ev = e.evaluate(now=1000.0)
        assert ev["samples"] == 0 and ev["breaching"] == []
        assert {o["status"] for o in ev["objectives"]} == {"no_data"}
        # one sample is still not a window: deltas need two endpoints
        e.observe(_sample(999.0))
        assert {o["status"] for o in e.evaluate(now=1000.0)["objectives"]} \
            == {"no_data"}

    def test_window_edge_is_inclusive(self):
        obj = [Objective("lat", "latency_p99", 150.0)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        empty = _lat([(0.05, 0), (0.1, 0), (float("inf"), 0)], 0)
        ten = _lat([(0.05, 0), (0.1, 10), (float("inf"), 10)], 10)
        # old sample sits EXACTLY on the fast-window cutoff (100 - 30):
        # it must count, so the fast window has a valid delta
        e.observe(_sample(70.0, latency=empty))
        e.observe(_sample(100.0, committed=10, latency=ten))
        (o,) = e.evaluate(now=100.0)["objectives"]
        fast, slow = o["windows"]
        assert fast["status"] == "ok"
        # all 10 completions landed in the 0.1s bucket: windowed p99 is
        # that bucket's upper bound, in ms
        assert fast["value"] == 100.0
        assert fast["burn"] == round(100.0 / 150.0, 6)
        assert slow["status"] == "ok"
        assert o["status"] == "ok"

    def test_sample_just_outside_window_reports_no_data(self):
        obj = [Objective("lat", "latency_p99", 150.0)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        e.observe(_sample(69.9, latency=_lat([(float("inf"), 0)], 0)))
        e.observe(
            _sample(100.0, latency=_lat([(0.1, 5), (float("inf"), 5)], 5))
        )
        (o,) = e.evaluate(now=100.0)["objectives"]
        fast, slow = o["windows"]
        assert fast["status"] == "no_data"  # one sample inside the window
        assert slow["status"] == "ok"
        # any-window no_data dominates: a half-blind verdict is not ok
        assert o["status"] == "no_data"
        assert e.evaluate(now=100.0)["breaching"] == []

    def test_empty_histogram_snapshots_read_idle(self):
        obj = [Objective("lat", "latency_p99", 150.0)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        # latency=None (tracer off) and zero-count buckets both mean "no
        # completions this window" — idle, never breaching
        e.observe(_sample(70.0))
        e.observe(_sample(100.0))
        (o,) = e.evaluate(now=100.0)["objectives"]
        assert o["status"] == "idle"
        empty = _lat([(0.05, 0), (float("inf"), 0)], 0)
        e2 = SloEngine(obj, windows=(30.0, 300.0))
        e2.observe(_sample(70.0, latency=empty))
        e2.observe(_sample(100.0, latency=empty))
        (o2,) = e2.evaluate(now=100.0)["objectives"]
        assert o2["status"] == "idle"

    def test_overflow_bucket_p99_doubles_last_finite_bound(self):
        obj = [Objective("lat", "latency_p99", 150.0)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        e.observe(_sample(70.0, latency=_lat([(0.05, 0), (float("inf"), 0)], 0)))
        e.observe(
            _sample(
                100.0,
                committed=5,
                latency=_lat([(0.05, 0), (float("inf"), 5)], 5),
            )
        )
        (o,) = e.evaluate(now=100.0)["objectives"]
        # every completion overflowed the finite buckets: report 2x the
        # last finite bound (conservative, JSON-safe)
        assert o["windows"][0]["value"] == 100.0

    def test_samples_pruned_past_slow_window(self):
        e = SloEngine([], windows=(30.0, 300.0))
        for t in range(0, 1000, 10):
            e.observe(_sample(float(t)))
        # bounded by slow window span / probe interval (+1s slack)
        assert e.sample_count <= 32


class TestFlapSuppression:
    def test_fast_spike_alone_does_not_breach(self):
        obj = [Objective("floor", "throughput_floor", 0.5)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        e.observe(_sample(0.0, committed=0))
        e.observe(_sample(60.0, committed=100))
        # commits stopped with work pending: the fast window burns...
        e.observe(_sample(75.0, committed=100, pending=5))
        e.observe(_sample(99.0, committed=100, pending=5))
        (o,) = e.evaluate(now=100.0)["objectives"]
        fast, slow = o["windows"]
        assert fast["status"] == "breaching" and fast["burn"] == BURN_CAP
        # ...but the slow window still shows healthy rate: no alert
        assert slow["status"] == "ok"
        assert o["status"] == "ok"
        assert e.evaluate(now=100.0)["breaching"] == []

    def test_sustained_degradation_trips_both_windows(self):
        obj = [Objective("floor", "throughput_floor", 0.5)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        for t in (0.0, 150.0, 280.0, 299.0):
            e.observe(_sample(t, committed=0, pending=5))
        ev = e.evaluate(now=300.0)
        (o,) = ev["objectives"]
        assert [w["status"] for w in o["windows"]] == [
            "breaching", "breaching",
        ]
        assert o["status"] == "breaching"
        assert ev["breaching"] == ["floor"]

    def test_idle_node_never_burns_the_floor(self):
        obj = [Objective("floor", "throughput_floor", 0.5)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        e.observe(_sample(70.0))
        e.observe(_sample(100.0))
        (o,) = e.evaluate(now=100.0)["objectives"]
        assert o["status"] == "idle"


class TestRatioAndStall:
    def test_rejection_ratio_idle_under_min_events(self):
        obj = [Objective("rej", "rejection_ratio", 0.95)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        e.observe(_sample(70.0))
        # 1 reject out of 1 attempt is one unlucky request, not a
        # 100%-rejection incident
        e.observe(_sample(100.0, rejected=1))
        (o,) = e.evaluate(now=100.0)["objectives"]
        assert o["status"] == "idle"

    def test_rejection_ratio_breaches_when_everything_bounces(self):
        obj = [Objective("rej", "rejection_ratio", 0.95)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        e.observe(_sample(70.0))
        e.observe(_sample(100.0, rejected=20))
        (o,) = e.evaluate(now=100.0)["objectives"]
        assert o["status"] == "breaching"
        fast = o["windows"][0]
        assert fast["value"] == 1.0
        assert fast["burn"] == round(1.0 / 0.95, 6)

    def test_stall_budget_counts_flagged_samples(self):
        obj = [Objective("stall", "stall_budget", 0.5)]
        e = SloEngine(obj, windows=(30.0, 300.0))
        for t, stalled in ((72.0, True), (80.0, True), (90.0, True),
                           (99.0, False)):
            e.observe(_sample(t, stalled=stalled))
        (o,) = e.evaluate(now=100.0)["objectives"]
        assert o["status"] == "breaching"
        assert o["windows"][0]["value"] == 0.75
        assert o["windows"][0]["burn"] == 1.5


class TestEvaluatePoint:
    def test_clean_cell_reads_ok(self):
        objs = default_objectives(
            latency_p99_ms=500.0, throughput_floor_tps=0.2,
            rejection_ratio_max=0.02, stall_budget=0.25,
        )
        res = evaluate_point(
            objs,
            {
                "throughput_tps": 2.5,
                "latency_p99_ms": 120.0,
                "rejection_ratio": 0.0,
                "stall_fraction": 0.0,
            },
        )
        assert res["ok"] and res["breaching"] == []
        assert {o["status"] for o in res["objectives"]} == {"ok"}

    def test_breaches_and_burns(self):
        objs = default_objectives(
            latency_p99_ms=500.0, throughput_floor_tps=0.2,
        )
        res = evaluate_point(
            objs,
            {
                "throughput_tps": 0.0,
                "latency_p99_ms": 750.0,
                "rejection_ratio": 0.0,
                "stall_fraction": 0.0,
            },
        )
        assert not res["ok"]
        assert set(res["breaching"]) == {
            "commit_latency_p99", "throughput_floor",
        }
        by_name = {o["name"]: o for o in res["objectives"]}
        assert by_name["commit_latency_p99"]["burn"] == 1.5
        # zero rate against a floor is a capped burn, not a ZeroDivision
        assert by_name["throughput_floor"]["burn"] == BURN_CAP

    def test_missing_measure_is_no_data_not_breach(self):
        objs = default_objectives(latency_p99_ms=500.0)
        res = evaluate_point(objs, {"rejection_ratio": 0.0})
        by_kind = {o["kind"]: o for o in res["objectives"]}
        assert by_kind["latency_p99"]["status"] == "no_data"
        assert res["ok"]
