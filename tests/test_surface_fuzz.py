"""Public-surface robustness fuzz: junk bytes must never crash a node.

Two surfaces take untrusted bytes directly from the network:

* the RPC port (PortMux): seeded random junk — truncated HTTP, binary
  garbage, oversized headers, malformed grpc-web bodies, abrupt
  disconnects — must always end in a clean 4xx/close, never an
  unhandled exception (the generic handler logs full tracebacks, so a
  crash-per-junk-request floods the logs on the public port), and the
  node must keep serving real clients afterwards;
* the node mesh (transport): random corruption of AEAD-framed
  ciphertext must terminate the channel, never deliver altered
  plaintext (ChaCha20-Poly1305 integrity, pinned here under seeds
  rather than the single tamper case in test_node.py).
"""

import asyncio
import itertools
import logging
import random

import pytest

from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.net import transport
from at2_node_tpu.node.config import Config
from at2_node_tpu.node.service import Service
from at2_node_tpu.proto import at2_pb2 as pb

_ports = itertools.count(24100)


def _junk_requests(rng: random.Random):
    """A zoo of malformed inputs for the public HTTP/1 surface."""
    yield rng.randbytes(rng.randrange(1, 64))  # pure binary garbage
    yield b"GET "  # truncated request line, then disconnect
    yield b"POST /at2.AT2/GetBalance HTTP/1.1\r\n" + b"X: y\r\n" * 40
    yield (
        b"POST /at2.AT2/GetBalance HTTP/1.1\r\nHost: x\r\n"
        b"Content-Type: application/grpc-web+proto\r\n"
        b"Content-Length: 99999999999999999999\r\n\r\n"
    )
    yield (
        b"POST /%s HTTP/1.1\r\nHost: x\r\nContent-Type: application/grpc-web+proto\r\n"
        b"Content-Length: 4\r\n\r\nabcd" % rng.randbytes(8).hex().encode()
    )
    yield (
        b"POST /at2.AT2/GetBalance HTTP/1.1\r\nHost: x\r\n"
        b"Content-Type: application/grpc-web-text\r\n"
        b"Content-Length: 7\r\n\r\nnot=b64"
    )
    yield (
        b"POST /at2.AT2/GetBalance HTTP/1.1\r\nHost: x\r\n"
        b"Content-Type: application/grpc-web+proto\r\n"
        b"Transfer-Encoding: chunked\r\n\r\n" + rng.randbytes(20)
    )
    # random mutation of a VALID request
    frame = bytes([0, 0, 0, 0, 2, 0x0A, 0x00])
    good = (
        b"POST /at2.AT2/GetBalance HTTP/1.1\r\nHost: x\r\n"
        b"Content-Type: application/grpc-web+proto\r\n"
        + b"Content-Length: %d\r\n\r\n" % len(frame)
        + frame
    )
    mutated = bytearray(good)
    for _ in range(rng.randrange(1, 6)):
        mutated[rng.randrange(len(mutated))] = rng.randrange(256)
    yield bytes(mutated)


@pytest.mark.parametrize("seed", [4, 19, 42])
async def test_rpc_port_survives_junk_flood(seed, caplog):
    cfg = Config(
        node_address=f"127.0.0.1:{next(_ports)}",
        rpc_address=f"127.0.0.1:{next(_ports)}",
        sign_key=SignKeyPair.random(),
        network_key=ExchangeKeyPair.random(),
    )
    svc = await Service.start(cfg)
    rng = random.Random(seed)
    host, _, port = cfg.rpc_address.rpartition(":")
    try:
        with caplog.at_level(logging.ERROR, logger="at2_node_tpu.net.webmux"):
            for junk in _junk_requests(rng):
                try:
                    reader, writer = await asyncio.open_connection(
                        host, int(port)
                    )
                    writer.write(junk)
                    await writer.drain()
                    if rng.random() < 0.5:
                        writer.close()  # abrupt disconnect mid-request
                    else:
                        await asyncio.wait_for(
                            reader.read(4096), timeout=2
                        )
                        writer.close()
                except (ConnectionError, asyncio.TimeoutError, OSError):
                    pass
        # junk must not generate ANY error-level record (connection-level
        # OR handler-level tracebacks both count as spam)
        errors = [r for r in caplog.records if r.levelno >= logging.ERROR]
        assert not errors, [r.message for r in errors]

        # and the node still serves a real client cleanly
        reader, writer = await asyncio.open_connection(host, int(port))
        msg = pb.GetBalanceRequest(sender=b"\x01" * 32).SerializeToString()
        frame = bytes([0]) + len(msg).to_bytes(4, "big") + msg
        writer.write(
            b"POST /at2.AT2/GetBalance HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/grpc-web+proto\r\n"
            b"Connection: close\r\n"
            + b"Content-Length: %d\r\n\r\n" % len(frame)
            + frame
        )
        await writer.drain()
        resp = await asyncio.wait_for(reader.read(-1), timeout=10)
        writer.close()
        assert b"200 OK" in resp.split(b"\r\n")[0]
        assert b"grpc-status: 0" in resp
    finally:
        await svc.close()


@pytest.mark.parametrize("seed", [8, 33, 77])
async def test_transport_rejects_random_corruption(seed):
    """Bit-flipped AEAD records: the receiving channel must error out,
    never surface altered plaintext."""
    rng = random.Random(seed)
    server_kp, client_kp = ExchangeKeyPair.random(), ExchangeKeyPair.random()
    received = []
    accepted = asyncio.get_event_loop().create_future()
    handler_done = asyncio.Event()

    async def on_conn(reader, writer):
        try:
            channel = await transport.accept(reader, writer, server_kp)
            accepted.set_result(channel)
            while True:
                received.append(await channel.recv())
        except (transport.ChannelClosed, transport.HandshakeError, ConnectionError):
            pass
        except Exception as exc:  # pragma: no cover
            received.append(("UNEXPECTED", repr(exc)))
        finally:
            handler_done.set()

    port = next(_ports)
    server = await asyncio.start_server(on_conn, "127.0.0.1", port)
    try:
        channel = await transport.connect("127.0.0.1", port, client_kp)
        await channel.send(b"legit-before")
        srv_channel = await asyncio.wait_for(accepted, timeout=5)

        # inject a corrupted sealed frame through the channel's raw
        # socket: seal a frame with the SAME counter the receiver expects
        # next, then flip random bits before writing
        import struct

        nonce = struct.pack("<Q", channel._send_ctr) + b"\x00\x00\x00\x00"
        ct = channel._send_aead.encrypt(nonce, b"attacker-target", None)
        sealed = struct.pack("<I", len(ct)) + ct
        corrupt = bytearray(sealed)
        for _ in range(rng.randrange(1, 5)):
            corrupt[4 + rng.randrange(len(ct))] ^= 1 << rng.randrange(8)
        if bytes(corrupt) == sealed:
            corrupt[4] ^= 0xFF
        channel.writer.write(bytes(corrupt))
        await channel.writer.drain()

        # the receiver must tear down (handler exits via ChannelClosed)
        # without delivering the forgery
        await asyncio.wait_for(handler_done.wait(), timeout=5)
        assert b"legit-before" in received
        assert not any(
            isinstance(r, bytes) and b"attacker" in r for r in received
        ), "corrupted frame surfaced as plaintext"
        assert not any(isinstance(r, tuple) for r in received), received
        channel.close()
        srv_channel.close()
    finally:
        server.close()
        await server.wait_closed()
