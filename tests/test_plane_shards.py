"""Sharded broadcast plane (broadcast/shards.py + parallel/plane.py).

The contract being pinned: partitioning slot state per origin key across
N shard cores is a CAPACITY change, not a BEHAVIOR change. Concretely:

* same-seed campaign invariance — the sim wire-trace hash is identical
  at ``plane_shards=1`` and ``plane_shards=4`` (arrival-order inline
  execution + the global birth-ordered GC pass make shard count
  unobservable on the wire);
* flash-crowd conservation — a burst workload spread across multiple
  origins commits green with slots genuinely living on >= 2 distinct
  cores, and every observed slot sits on exactly the core
  ``shard_of(origin)`` names (non-vacuous: the test fails if routing
  ever lands a slot off its owning shard OR if everything collapsed
  onto one core);
* poison resolution on the owning shard — a never-deliverable entry
  retires through the owning core's GC, and no other core ever
  materializes state for that origin;
* crash mid-flight + WAL replay — a durable node killed while sharded
  slots are in flight restarts through the PR 9 store and converges;
* native kernel differential — the shard-local tally/quorum kernels
  (at2_counts_add / at2_quorum_mask) agree with the pure-Python
  counting they replace.
"""

import asyncio
import itertools
import random

import pytest

from at2_node_tpu.broadcast.shards import ShardedPlane, shard_of
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.node.config import PlaneConfig
from at2_node_tpu.node.service import Service
from at2_node_tpu.sim.campaign import apply_events, run_episode
from at2_node_tpu.sim.net import SimNet, sim_client
from at2_node_tpu.sim.scenarios import flash_crowd_workload
from at2_node_tpu.types import ThinTransaction

from conftest import make_net_configs, wait_until

_ports = itertools.count(28900)

SHARDS = 4


# ---------------------------------------------------------------------------
# routing + config units


class TestShardRouting:
    def test_shard_of_stable_and_in_range(self):
        rng = random.Random(7)
        keys = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(256)]
        for shards in (1, 2, 4, 8):
            seen = set()
            for k in keys:
                sid = shard_of(k, shards)
                assert 0 <= sid < shards
                assert shard_of(k, shards) == sid  # pure in (key, shards)
                seen.add(sid)
            # 256 uniform keys must spread: an all-on-one-core hash
            # would make the whole module a no-op
            assert len(seen) == shards

    def test_shard_of_one_shard_is_identity_zero(self):
        assert shard_of(b"\x00" * 32, 1) == 0
        assert shard_of(b"\xff" * 32, 1) == 0

    def test_plane_config_default_is_monolithic(self):
        cfg = PlaneConfig()
        assert cfg.shards == 1
        with pytest.raises(ValueError):
            PlaneConfig(shards=0)
        with pytest.raises(ValueError):
            PlaneConfig(shards=2, executor="fork")

    def test_sharded_plane_rejects_zero_shards(self):
        with pytest.raises(ValueError):
            ShardedPlane(SignKeyPair.random(), None, None, shards=0)


# ---------------------------------------------------------------------------
# native kernel differential


class TestNativeShardKernels:
    def test_counts_and_quorum_match_python(self):
        from at2_node_tpu.native import (
            counts_add_native,
            ingest_available,
            quorum_mask_native,
        )

        if not ingest_available():
            pytest.skip("native ingest kernels not built on this host")
        np = pytest.importorskip("numpy")

        rng = random.Random(3)
        for trial in range(20):
            nbits = rng.randrange(1, 130)
            counts = np.zeros(nbits, dtype=np.int32)
            expect = [0] * nbits
            for _ in range(rng.randrange(1, 8)):
                bits = [rng.random() < 0.4 for _ in range(nbits)]
                bitmap = int(
                    "".join("1" if b else "0" for b in reversed(bits)), 2
                ).to_bytes((nbits + 7) // 8, "little")
                folded = counts_add_native(bitmap, counts)
                assert folded == sum(bits)
                for i, b in enumerate(bits):
                    expect[i] += int(b)
            assert counts.tolist() == expect

            thr = rng.randrange(1, 6)
            mask = quorum_mask_native(counts, thr, nbits)
            pure = 0
            for i, c in enumerate(expect):
                if c >= thr:
                    pure |= 1 << i
            assert mask == pure

    def test_quorum_mask_empty_and_clamped(self):
        from at2_node_tpu.native import ingest_available, quorum_mask_native

        if not ingest_available():
            pytest.skip("native ingest kernels not built on this host")
        np = pytest.importorskip("numpy")
        counts = np.array([5, 0, 5], dtype=np.int32)
        assert quorum_mask_native(counts, 1, 0) == 0
        # nbits beyond the tally is clamped, not read past the end
        assert quorum_mask_native(counts, 1, 64) == 0b101


# ---------------------------------------------------------------------------
# tentpole: shard count must be unobservable on the sim wire


class TestCampaignShardInvariance:
    @pytest.mark.parametrize("seed", [0, 7])
    def test_same_seed_same_hash_shards_1_vs_4(self, seed):
        """The whole determinism story in one assert: a full episode
        (clients, hostile traffic, settle, invariants) produces the SAME
        wire-trace hash whether the plane runs monolithic or split
        across 4 inline shards."""
        kw = dict(n_events=10, duration=8.0, settle_horizon=60.0)
        mono = run_episode(seed, **kw)
        sharded = run_episode(
            seed, config_overrides={"plane_shards": SHARDS}, **kw
        )
        assert mono.violations == []
        assert sharded.violations == []
        assert sharded.trace_hash == mono.trace_hash
        assert sharded.committed == mono.committed
        assert sharded.delivered == mono.delivered

    def test_sharded_episode_is_self_deterministic(self):
        kw = dict(n_events=8, duration=6.0, settle_horizon=45.0)
        a = run_episode(11, config_overrides={"plane_shards": SHARDS}, **kw)
        b = run_episode(11, config_overrides={"plane_shards": SHARDS}, **kw)
        assert a.trace_hash == b.trace_hash
        assert a.committed == b.committed


# ---------------------------------------------------------------------------
# flash crowd: conservation + commit ordering, non-vacuously sharded


class TestFlashCrowdSharded:
    def test_flash_crowd_conserves_across_real_shards(self):
        nodes, n_clients, n_tx, duration = 4, 6, 40, 8.0
        seed = 5
        net = SimNet(nodes, 1, seed, hostile=0, plane_shards=SHARDS)
        net.start()
        try:
            clients = [sim_client(seed, i) for i in range(n_clients)]
            rng = random.Random(seed)
            events = flash_crowd_workload(
                rng, nodes=nodes, n_clients=n_clients, n_tx=n_tx,
                duration=duration,
            )
            events.sort(key=lambda e: (e[0], e[1]))
            apply_events(net, events, clients, None)
            last_t = max(e[0] for e in events)
            net.run_for(last_t + 1.0)

            # mid-run, before settle compacts everything: every slot a
            # core holds must be the one shard_of names, and the load
            # must genuinely span cores
            occupied = set()
            for svc in net.services:
                cores = svc.broadcast._cores
                assert len(cores) == SHARDS
                for sid, core in enumerate(cores):
                    for (sender, _seq) in core._slots:
                        assert shard_of(sender, SHARDS) == sid
                    for (origin, _bseq) in core._batch_slots:
                        assert shard_of(origin, SHARDS) == sid
                    if core._slots or core._batch_slots or core._delivered_slots:
                        occupied.add(sid)
            assert len(occupied) >= 2, (
                "flash crowd collapsed onto one shard — test is vacuous"
            )

            net.settle(horizon=90.0)
            net.assert_invariants()
            committed = [s.committed for s in net.services]
            assert min(committed) > 0
            # commit-tail totality: every correct node commits the same
            # count once settled (ordering divergence would show up as
            # an invariant violation above, count divergence here)
            assert len(set(committed)) == 1
        finally:
            net.close()


# ---------------------------------------------------------------------------
# poison resolution happens on the owning shard


def make_payload(keypair, seq=1, amount=10, recipient=b"r" * 32):
    from at2_node_tpu.broadcast.messages import Payload

    return Payload.create(keypair, seq, ThinTransaction(recipient, amount))


def bad_payload(public, seq=1, amount=10, recipient=b"r" * 32):
    from at2_node_tpu.broadcast.messages import Payload

    return Payload(public, seq, ThinTransaction(recipient, amount), b"\x01" * 64)


async def submit(service, payload):
    await service.recent.put(payload.sender, payload.sequence, payload.transaction)
    service._batch_buf.append(payload)


class TestPoisonOnOwningShard:
    @pytest.mark.asyncio
    async def test_poison_batch_retires_on_owning_core(self, monkeypatch):
        import at2_node_tpu.broadcast.shards as shards_mod
        import at2_node_tpu.broadcast.stack as stack_mod

        monkeypatch.setattr(stack_mod, "GC_INTERVAL", 0.2)
        monkeypatch.setattr(shards_mod, "GC_INTERVAL", 0.2)
        monkeypatch.setattr(stack_mod, "DELIVERED_RETENTION", 0.4)
        monkeypatch.setattr(stack_mod, "RETRANSMIT_AFTER", 1.0)
        monkeypatch.setattr(stack_mod, "STALLED_CATCHUP_AFTER", 1.0)

        cfgs = make_net_configs(
            3, _ports, plane=PlaneConfig(shards=SHARDS, executor="inline")
        )
        services = [await Service.start(c) for c in cfgs]
        try:
            for svc in services:
                assert isinstance(svc.broadcast, ShardedPlane)
            origin = cfgs[0].sign_key.public
            owner = shard_of(origin, SHARDS)

            sender = SignKeyPair.random()
            poisoner = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            for seq in range(1, 6):
                await submit(
                    services[0], make_payload(sender, seq=seq, recipient=recipient)
                )
            await submit(services[0], bad_payload(poisoner.public, seq=1))
            await services[0]._flush_batch()

            # record where batch-slot state materializes while we wait;
            # asserted against the routing contract afterwards
            occupancy = set()  # (service idx, core idx, slot origin)

            def scan():
                for i, svc in enumerate(services):
                    for sid, core in enumerate(svc.broadcast._cores):
                        for (slot_origin, _bseq) in core._batch_slots:
                            occupancy.add((i, sid, slot_origin))

            async def resolved_everywhere():
                scan()
                for svc in services:
                    st = svc.broadcast.stats
                    if st["slots_retired"] < 1 or st["poison_resolved"] < 1:
                        return False
                    if any(c._batch_slots for c in svc.broadcast._cores):
                        return False
                return True

            await wait_until(
                resolved_everywhere, what="poison slot retires on every node"
            )
            assert all(s.committed >= 5 for s in services)
            # the slot existed somewhere (non-vacuous) ...
            assert any(sid == owner for _i, sid, _o in occupancy)
            # ... and ONLY ever on the owning core
            for _i, sid, slot_origin in occupancy:
                assert slot_origin == origin
                assert sid == owner
        finally:
            for s in services:
                await s.close()


# ---------------------------------------------------------------------------
# crash mid-flight: sharded slots replay through the durable store


class TestShardedCrashRestart:
    def test_kill_midstream_replays_wal_and_converges(self):
        net = SimNet(
            n=4, f=1, seed=13, hostile=0, durable=True, plane_shards=SHARDS
        )
        net.start()
        try:
            clients = [sim_client(13, i) for i in range(3)]
            recipient = SignKeyPair.random().public
            seq = {i: 0 for i in range(3)}

            def burst(target):
                for ci, client in enumerate(clients):
                    seq[ci] += 1
                    net.submit(target, client, seq[ci], recipient, 3)

            burst(0)
            net.run_for(2.0)
            net.flush_store(2)
            net.crash(2)
            # traffic keeps flowing while node 2 is down — these slots
            # are in flight across the survivors' shards
            burst(1)
            burst(0)
            net.run_for(3.0)
            svc = net.restart(2)
            assert isinstance(svc.broadcast, ShardedPlane)
            # the pre-crash flush put burst 1 in segments; restart loads
            # them back through the PR 9 store
            assert svc.store.segments_loaded > 0
            burst(3)
            net.settle(horizon=120.0)
            net.assert_invariants()
            # `committed` is per-incarnation; convergence is LEDGER
            # state — every node (including the restarted one) holds
            # every client's final sequence
            for s in net.services:
                state = s.store.accounts_state()
                for client in clients:
                    assert state[client.public.hex()][0] == 4
            assert net.services[2].recovery.state == "live"
        finally:
            net.close()
