"""Sharded broadcast plane (broadcast/shards.py + parallel/plane.py).

The contract being pinned: partitioning slot state per origin key across
N shard cores is a CAPACITY change, not a BEHAVIOR change. Concretely:

* same-seed campaign invariance — the sim wire-trace hash is identical
  at ``plane_shards=1`` and ``plane_shards=4`` (arrival-order inline
  execution + the global birth-ordered GC pass make shard count
  unobservable on the wire);
* flash-crowd conservation — a burst workload spread across multiple
  origins commits green with slots genuinely living on >= 2 distinct
  cores, and every observed slot sits on exactly the core
  ``shard_of(origin)`` names (non-vacuous: the test fails if routing
  ever lands a slot off its owning shard OR if everything collapsed
  onto one core);
* poison resolution on the owning shard — a never-deliverable entry
  retires through the owning core's GC, and no other core ever
  materializes state for that origin;
* crash mid-flight + WAL replay — a durable node killed while sharded
  slots are in flight restarts through the PR 9 store and converges;
* native kernel differential — the shard-local tally/quorum kernels
  (at2_counts_add / at2_quorum_mask) agree with the pure-Python
  counting they replace.
"""

import asyncio
import itertools
import random

import pytest

from at2_node_tpu.broadcast.shards import ShardedPlane, shard_of
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.node.config import PlaneConfig
from at2_node_tpu.node.service import Service
from at2_node_tpu.sim.campaign import apply_events, run_episode
from at2_node_tpu.sim.net import SimNet, sim_client
from at2_node_tpu.sim.scenarios import flash_crowd_workload
from at2_node_tpu.types import ThinTransaction

from conftest import make_net_configs, wait_until

_ports = itertools.count(28900)

SHARDS = 4


# ---------------------------------------------------------------------------
# routing + config units


class TestShardRouting:
    def test_shard_of_stable_and_in_range(self):
        rng = random.Random(7)
        keys = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(256)]
        for shards in (1, 2, 4, 8):
            seen = set()
            for k in keys:
                sid = shard_of(k, shards)
                assert 0 <= sid < shards
                assert shard_of(k, shards) == sid  # pure in (key, shards)
                seen.add(sid)
            # 256 uniform keys must spread: an all-on-one-core hash
            # would make the whole module a no-op
            assert len(seen) == shards

    def test_shard_of_one_shard_is_identity_zero(self):
        assert shard_of(b"\x00" * 32, 1) == 0
        assert shard_of(b"\xff" * 32, 1) == 0

    def test_plane_config_default_is_monolithic(self):
        cfg = PlaneConfig()
        assert cfg.shards == 1
        with pytest.raises(ValueError):
            PlaneConfig(shards=0)
        with pytest.raises(ValueError):
            PlaneConfig(shards=2, executor="fork")

    def test_sharded_plane_rejects_zero_shards(self):
        with pytest.raises(ValueError):
            ShardedPlane(SignKeyPair.random(), None, None, shards=0)


# ---------------------------------------------------------------------------
# native kernel differential


class TestNativeShardKernels:
    def test_counts_and_quorum_match_python(self):
        from at2_node_tpu.native import (
            counts_add_native,
            ingest_available,
            quorum_mask_native,
        )

        if not ingest_available():
            pytest.skip("native ingest kernels not built on this host")
        np = pytest.importorskip("numpy")

        rng = random.Random(3)
        for trial in range(20):
            nbits = rng.randrange(1, 130)
            counts = np.zeros(nbits, dtype=np.int32)
            expect = [0] * nbits
            for _ in range(rng.randrange(1, 8)):
                bits = [rng.random() < 0.4 for _ in range(nbits)]
                bitmap = int(
                    "".join("1" if b else "0" for b in reversed(bits)), 2
                ).to_bytes((nbits + 7) // 8, "little")
                folded = counts_add_native(bitmap, counts)
                assert folded == sum(bits)
                for i, b in enumerate(bits):
                    expect[i] += int(b)
            assert counts.tolist() == expect

            thr = rng.randrange(1, 6)
            mask = quorum_mask_native(counts, thr, nbits)
            pure = 0
            for i, c in enumerate(expect):
                if c >= thr:
                    pure |= 1 << i
            assert mask == pure

    def test_quorum_mask_empty_and_clamped(self):
        from at2_node_tpu.native import ingest_available, quorum_mask_native

        if not ingest_available():
            pytest.skip("native ingest kernels not built on this host")
        np = pytest.importorskip("numpy")
        counts = np.array([5, 0, 5], dtype=np.int32)
        assert quorum_mask_native(counts, 1, 0) == 0
        # nbits beyond the tally is clamped, not read past the end
        assert quorum_mask_native(counts, 1, 64) == 0b101


# ---------------------------------------------------------------------------
# tentpole: shard count must be unobservable on the sim wire


class TestCampaignShardInvariance:
    @pytest.mark.parametrize("seed", [0, 7])
    def test_same_seed_same_hash_shards_1_vs_4(self, seed):
        """The whole determinism story in one assert: a full episode
        (clients, hostile traffic, settle, invariants) produces the SAME
        wire-trace hash whether the plane runs monolithic or split
        across 4 inline shards."""
        kw = dict(n_events=10, duration=8.0, settle_horizon=60.0)
        mono = run_episode(seed, **kw)
        sharded = run_episode(
            seed, config_overrides={"plane_shards": SHARDS}, **kw
        )
        assert mono.violations == []
        assert sharded.violations == []
        assert sharded.trace_hash == mono.trace_hash
        assert sharded.committed == mono.committed
        assert sharded.delivered == mono.delivered

    def test_sharded_episode_is_self_deterministic(self):
        kw = dict(n_events=8, duration=6.0, settle_horizon=45.0)
        a = run_episode(11, config_overrides={"plane_shards": SHARDS}, **kw)
        b = run_episode(11, config_overrides={"plane_shards": SHARDS}, **kw)
        assert a.trace_hash == b.trace_hash
        assert a.committed == b.committed


# ---------------------------------------------------------------------------
# flash crowd: conservation + commit ordering, non-vacuously sharded


class TestFlashCrowdSharded:
    def test_flash_crowd_conserves_across_real_shards(self):
        nodes, n_clients, n_tx, duration = 4, 6, 40, 8.0
        seed = 5
        net = SimNet(nodes, 1, seed, hostile=0, plane_shards=SHARDS)
        net.start()
        try:
            clients = [sim_client(seed, i) for i in range(n_clients)]
            rng = random.Random(seed)
            events = flash_crowd_workload(
                rng, nodes=nodes, n_clients=n_clients, n_tx=n_tx,
                duration=duration,
            )
            events.sort(key=lambda e: (e[0], e[1]))
            apply_events(net, events, clients, None)
            last_t = max(e[0] for e in events)
            net.run_for(last_t + 1.0)

            # mid-run, before settle compacts everything: every slot a
            # core holds must be the one shard_of names, and the load
            # must genuinely span cores
            occupied = set()
            for svc in net.services:
                cores = svc.broadcast._cores
                assert len(cores) == SHARDS
                for sid, core in enumerate(cores):
                    for (sender, _seq) in core._slots:
                        assert shard_of(sender, SHARDS) == sid
                    for (origin, _bseq) in core._batch_slots:
                        assert shard_of(origin, SHARDS) == sid
                    if core._slots or core._batch_slots or core._delivered_slots:
                        occupied.add(sid)
            assert len(occupied) >= 2, (
                "flash crowd collapsed onto one shard — test is vacuous"
            )

            net.settle(horizon=90.0)
            net.assert_invariants()
            committed = [s.committed for s in net.services]
            assert min(committed) > 0
            # commit-tail totality: every correct node commits the same
            # count once settled (ordering divergence would show up as
            # an invariant violation above, count divergence here)
            assert len(set(committed)) == 1
        finally:
            net.close()


# ---------------------------------------------------------------------------
# poison resolution happens on the owning shard


def make_payload(keypair, seq=1, amount=10, recipient=b"r" * 32):
    from at2_node_tpu.broadcast.messages import Payload

    return Payload.create(keypair, seq, ThinTransaction(recipient, amount))


def bad_payload(public, seq=1, amount=10, recipient=b"r" * 32):
    from at2_node_tpu.broadcast.messages import Payload

    return Payload(public, seq, ThinTransaction(recipient, amount), b"\x01" * 64)


async def submit(service, payload):
    await service.recent.put(payload.sender, payload.sequence, payload.transaction)
    service._batch_buf.append(payload)


class TestPoisonOnOwningShard:
    @pytest.mark.asyncio
    async def test_poison_batch_retires_on_owning_core(self, monkeypatch):
        import at2_node_tpu.broadcast.shards as shards_mod
        import at2_node_tpu.broadcast.stack as stack_mod

        monkeypatch.setattr(stack_mod, "GC_INTERVAL", 0.2)
        monkeypatch.setattr(shards_mod, "GC_INTERVAL", 0.2)
        monkeypatch.setattr(stack_mod, "DELIVERED_RETENTION", 0.4)
        monkeypatch.setattr(stack_mod, "RETRANSMIT_AFTER", 1.0)
        monkeypatch.setattr(stack_mod, "STALLED_CATCHUP_AFTER", 1.0)

        cfgs = make_net_configs(
            3, _ports, plane=PlaneConfig(shards=SHARDS, executor="inline")
        )
        services = [await Service.start(c) for c in cfgs]
        try:
            for svc in services:
                assert isinstance(svc.broadcast, ShardedPlane)
            origin = cfgs[0].sign_key.public
            owner = shard_of(origin, SHARDS)

            sender = SignKeyPair.random()
            poisoner = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            for seq in range(1, 6):
                await submit(
                    services[0], make_payload(sender, seq=seq, recipient=recipient)
                )
            await submit(services[0], bad_payload(poisoner.public, seq=1))
            await services[0]._flush_batch()

            # record where batch-slot state materializes while we wait;
            # asserted against the routing contract afterwards
            occupancy = set()  # (service idx, core idx, slot origin)

            def scan():
                for i, svc in enumerate(services):
                    for sid, core in enumerate(svc.broadcast._cores):
                        for (slot_origin, _bseq) in core._batch_slots:
                            occupancy.add((i, sid, slot_origin))

            async def resolved_everywhere():
                scan()
                for svc in services:
                    st = svc.broadcast.stats
                    if st["slots_retired"] < 1 or st["poison_resolved"] < 1:
                        return False
                    if any(c._batch_slots for c in svc.broadcast._cores):
                        return False
                return True

            await wait_until(
                resolved_everywhere, what="poison slot retires on every node"
            )
            assert all(s.committed >= 5 for s in services)
            # the slot existed somewhere (non-vacuous) ...
            assert any(sid == owner for _i, sid, _o in occupancy)
            # ... and ONLY ever on the owning core
            for _i, sid, slot_origin in occupancy:
                assert slot_origin == origin
                assert sid == owner
        finally:
            for s in services:
                await s.close()


# ---------------------------------------------------------------------------
# crash mid-flight: sharded slots replay through the durable store


class TestShardedCrashRestart:
    def test_kill_midstream_replays_wal_and_converges(self):
        net = SimNet(
            n=4, f=1, seed=13, hostile=0, durable=True, plane_shards=SHARDS
        )
        net.start()
        try:
            clients = [sim_client(13, i) for i in range(3)]
            recipient = SignKeyPair.random().public
            seq = {i: 0 for i in range(3)}

            def burst(target):
                for ci, client in enumerate(clients):
                    seq[ci] += 1
                    net.submit(target, client, seq[ci], recipient, 3)

            burst(0)
            net.run_for(2.0)
            net.flush_store(2)
            net.crash(2)
            # traffic keeps flowing while node 2 is down — these slots
            # are in flight across the survivors' shards
            burst(1)
            burst(0)
            net.run_for(3.0)
            svc = net.restart(2)
            assert isinstance(svc.broadcast, ShardedPlane)
            # the pre-crash flush put burst 1 in segments; restart loads
            # them back through the PR 9 store
            assert svc.store.segments_loaded > 0
            burst(3)
            net.settle(horizon=120.0)
            net.assert_invariants()
            # `committed` is per-incarnation; convergence is LEDGER
            # state — every node (including the restarted one) holds
            # every client's final sequence
            for s in net.services:
                state = s.store.accounts_state()
                for client in clients:
                    assert state[client.public.hex()][0] == 4
            assert net.services[2].recovery.state == "live"
        finally:
            net.close()


# ---------------------------------------------------------------------------
# shared-memory ring (parallel/ring.py): the process-mode handoff lane


class TestShmRing:
    def _mk(self, name, **kw):
        from at2_node_tpu.parallel.ring import ShmRing

        return ShmRing(name, create=True, **kw)

    def test_roundtrip_and_wrap_preserves_order(self):
        import os as _os

        ring = self._mk(f"at2t-{_os.getpid()}-wrap", slots=16, slot_bytes=32)
        try:
            rng = random.Random(5)
            # far more traffic than the ring holds at once: every record
            # crosses the wrap boundary many times, sizes span 1 slot to
            # several, and order must survive exactly
            for batch in range(100):
                sent = []
                for i in range(rng.randrange(1, 4)):
                    payload = bytes(
                        rng.randrange(256) for _ in range(rng.randrange(0, 40))
                    )
                    kind = 1 + (batch + i) % 7
                    assert ring.put(kind, payload)
                    sent.append((kind, payload))
                got, worst = ring.drain()
                assert got == sent
                assert worst >= 0
            assert ring.dropped == 0
        finally:
            ring.close()

    def test_full_ring_drops_with_producer_accounting(self):
        import os as _os

        ring = self._mk(f"at2t-{_os.getpid()}-full", slots=4, slot_bytes=32)
        try:
            # each 20-byte payload needs ceil((16+20)/32) = 2 slots
            assert ring.put(1, b"x" * 20)
            assert ring.put(2, b"y" * 20)
            assert len(ring) == 4
            # full: refused WITHOUT blocking and WITHOUT overwriting
            assert not ring.put(3, b"z" * 20)
            assert ring.dropped == 1
            assert not ring.put(3, b"z" * 20)
            assert ring.dropped == 2
            # a record larger than the whole ring can never fit
            assert not ring.put(4, b"w" * 4096)
            assert ring.dropped == 3
            # draining frees capacity; the drop counter is cumulative
            got, _ = ring.drain()
            assert [k for k, _p in got] == [1, 2]
            assert ring.put(5, b"q" * 20)
            got, _ = ring.drain()
            assert got == [(5, b"q" * 20)]
            assert ring.dropped == 3
        finally:
            ring.close()

    def test_stale_segment_reclaimed_on_create(self):
        import os as _os

        from at2_node_tpu.parallel.ring import ShmRing

        name = f"at2t-{_os.getpid()}-stale"
        dead = self._mk(name, slots=8, slot_bytes=32)
        dead.put(1, b"predecessor state")
        # simulate an owner that died uncleanly: detach WITHOUT unlink,
        # leaving the segment (and its queued record) in /dev/shm
        dead._owner = False
        dead.close()
        # an owner restart creating the same name must reclaim the stale
        # segment and start empty — never attach to predecessor state
        reborn = ShmRing(name, slots=8, slot_bytes=32, create=True)
        try:
            assert len(reborn) == 0
            assert reborn.drain() == ([], 0)
            assert reborn.put(2, b"fresh")
            assert reborn.drain()[0] == [(2, b"fresh")]
        finally:
            reborn.close()


# ---------------------------------------------------------------------------
# process-mode state protocol: the counter vocabulary must stay aligned


class TestWorkerStatKeys:
    def test_stat_keys_exist_in_both_counter_groups(self):
        """E_STATS records are positional u64 deltas in STAT_KEYS order;
        a key that drifts out of either counter group would silently
        misattribute every shard worker's counters."""
        import types

        from at2_node_tpu.broadcast.stack import Broadcast
        from at2_node_tpu.parallel.plane_worker import STAT_KEYS

        kp = SignKeyPair.random()
        mesh = types.SimpleNamespace(peers=[], by_sign={})
        plane = ShardedPlane(kp, mesh, None, shards=2, executor="inline")
        core = Broadcast(kp, mesh, None, workers=0)
        assert len(STAT_KEYS) == len(set(STAT_KEYS))
        for key in STAT_KEYS:
            plane.stats[key]  # raises KeyError on drift
            core.stats[key]


# ---------------------------------------------------------------------------
# native one-call drain: parse + shard routing must match the Python path


class TestNativePlaneDrain:
    def _mixed_frames(self, rng, n=64):
        from at2_node_tpu.broadcast.messages import (
            Attestation,
            ContentRequest,
            ECHO,
            READY,
        )

        frames, msgs = [], []
        senders = [SignKeyPair.random() for _ in range(4)]
        origin = SignKeyPair.random()
        for i in range(n):
            pick = rng.randrange(3)
            if pick == 0:
                m = make_payload(senders[i % 4], seq=i + 1)
            elif pick == 1:
                phase = ECHO if i % 2 else READY
                chash = bytes(rng.randrange(256) for _ in range(32))
                sender = senders[i % 4].public
                sig = origin.sign(
                    Attestation.signing_bytes(phase, sender, i + 1, chash)
                )
                m = Attestation(phase, origin.public, sender, i + 1, chash, sig)
            else:
                m = ContentRequest(
                    senders[i % 4].public,
                    i + 1,
                    bytes(rng.randrange(256) for _ in range(32)),
                )
            msgs.append(m)
            frames.append(m.encode())
        return frames, msgs

    def test_routing_matches_python_shard_of(self):
        from at2_node_tpu.native import ingest_available, plane_drain_native

        if not ingest_available():
            pytest.skip("native ingest kernels not built on this host")
        rng = random.Random(17)
        frames, msgs = self._mixed_frames(rng)
        for shards in (1, 2, 4):
            items, frame_ok, counts = plane_drain_native(frames, shards)
            assert len(items) == len(frames)
            assert all(frame_ok)
            tally = [0] * shards
            for fidx, sid, msg in items:
                # every message kind here routes by the slot's sender key
                assert sid == shard_of(msgs[fidx].sender, shards)
                assert type(msg) is type(msgs[fidx])
                assert msg.encode() == frames[fidx]
                tally[sid] += 1
            assert list(counts) == tally
            if shards > 1:
                assert len([t for t in tally if t]) > 1, "routing collapsed"

    def test_want_objects_false_wire_roundtrip(self):
        """Process-mode dispatch ships raw wire bytes to workers; the
        reconstructed per-message frames must be byte-identical to the
        originals (the worker re-parses them)."""
        from at2_node_tpu.native import ingest_available, plane_drain_native

        if not ingest_available():
            pytest.skip("native ingest kernels not built on this host")
        rng = random.Random(23)
        frames, _msgs = self._mixed_frames(rng, n=48)
        items, frame_ok, _counts = plane_drain_native(
            frames, 4, want_objects=False
        )
        assert all(frame_ok)
        objs, _, _ = plane_drain_native(frames, 4)
        assert len(items) == len(objs)
        for (fidx, sid, kind, wire), (ofidx, osid, _msg) in zip(items, objs):
            assert (fidx, sid) == (ofidx, osid)
            assert wire == frames[fidx]
            assert wire[0] == kind


# ---------------------------------------------------------------------------
# tentpole: multiprocess plane over real services — delivery, crash
# detection, degraded health with shard attribution, clean shutdown


class TestProcessPlaneE2E:
    @pytest.mark.asyncio
    async def test_process_executor_delivers_then_survives_worker_crash(self):
        from at2_node_tpu.parallel import plane_worker as pw

        cfgs = make_net_configs(
            3, _ports, plane=PlaneConfig(shards=2, executor="process")
        )
        services = [await Service.start(c) for c in cfgs]
        try:
            for svc in services:
                assert isinstance(svc.broadcast, ShardedPlane)
                info = svc.broadcast.plane_info()
                assert info["executor"] == "process"
                assert all(
                    svc.broadcast._executor.alive(sid) for sid in range(2)
                )

            # enough distinct senders that both shards carry slots
            senders = [SignKeyPair.random() for _ in range(4)]
            n_tx = 0
            for sender in senders:
                for seq in (1, 2):
                    await services[0].broadcast.broadcast(
                        make_payload(sender, seq=seq)
                    )
                    n_tx += 1
            async def all_committed():
                return all(s.committed >= n_tx for s in services)

            await wait_until(
                all_committed,
                timeout=60.0,
                what="all payloads commit through the process plane",
            )
            assert {shard_of(s.public, 2) for s in senders} == {0, 1}

            # kill shard 0's worker on node 0 mid-flight (C_EXIT is the
            # crash-injection record; exit code 7 must surface verbatim)
            victim = services[0]
            victim.broadcast._executor.actions[0].put(pw.C_EXIT, bytes([7]))

            async def crash_seen():
                return victim.broadcast.worker_crashed == {0: 7}

            await wait_until(
                crash_seen,
                timeout=30.0,
                what="owner detects the dead worker",
            )
            # degraded — never hung — with shard attribution everywhere
            # an operator looks: /healthz, /statusz plane block, and a
            # flight-recorder snapshot for the post-mortem
            hv = victim.health_verdict()
            assert hv["status"] == "degraded"
            assert hv["plane_workers_crashed"] == {"0": 7}
            assert victim.broadcast.plane_info()["worker_crashed"] == {"0": 7}
            assert any(
                s["reason"].startswith("plane_worker_crash:shard=0")
                for s in victim.recorder.dump()["snapshots"]
            )
            # the other shard's worker is untouched and the crash is
            # reported exactly once
            assert victim.broadcast._executor.alive(1)
            assert victim.broadcast._executor.poll_crashed() == []
            # healthy nodes stay healthy
            assert services[1].health_verdict()["status"] == "ok"
        finally:
            for s in services:
                await s.close()
        # clean shutdown reaps every worker process and unlinks the rings
        for svc in services:
            ex = svc.broadcast._executor
            assert all(not p.is_alive() for p in ex._procs)
            assert ex.actions == [] and ex.effects == []


# ---------------------------------------------------------------------------
# tentpole gate: the configured executor must be unobservable on the wire


class TestExecutorHashSweep:
    @pytest.mark.parametrize("seed", [0, 7])
    def test_campaign_hash_identical_across_executors(self, seed):
        """`[plane] executor` is a RUNTIME placement choice, never a
        protocol change: under the sim clock the service forces inline
        execution whatever the config says, so one monolithic episode
        and three sharded episodes configured inline/thread/process must
        produce the identical wire-trace hash. This is the seam the CI
        multiprocess-plane gate pins."""
        kw = dict(n_events=8, duration=6.0, settle_horizon=45.0)
        mono = run_episode(seed, **kw)
        assert mono.violations == []
        hashes = {"mono1": mono.trace_hash}
        for ex in ("inline", "thread", "process"):
            ep = run_episode(
                seed,
                config_overrides={
                    "plane_shards": SHARDS,
                    "plane_executor": ex,
                },
                **kw,
            )
            assert ep.violations == []
            assert ep.committed == mono.committed
            assert ep.delivered == mono.delivered
            hashes[ex] = ep.trace_hash
        assert len(set(hashes.values())) == 1, hashes
