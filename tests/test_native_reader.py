"""Native channel reader (at2_ingest.cpp reader section + native/reader.py).

Differential against the transport spec: frames encrypted exactly as
`transport.Channel.send` produces them (u32-LE length || ChaCha20-
Poly1305 ciphertext, LE-counter nonce) must come back from the C++
reader thread byte-identical and in order; a tampered frame must kill
the channel with a protocol-error status (the ChannelClosed parity), and
the batched wake pipe must signal exactly when frames are pending.

The mesh-level integration is exercised by every multi-node test in the
suite (the mesh picks the native inbound plane automatically when the
library is available); here the A/B seam is pinned too: with
AT2_NO_NATIVE_READER=1 the mesh serves inbound connections on the
asyncio path and still converges.
"""

import asyncio
import itertools
import os
import select
import socket
import struct

import pytest

from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.native.reader import (
    STATUS_EOF,
    STATUS_OPEN,
    STATUS_PROTOCOL_ERROR,
    NativeChannelReader,
    _lib_with_reader,
)

from conftest import make_net_configs, wait_until

# Gate on the LIBRARY being buildable, not on reader_available(): the
# core-count heuristic turns the reader off on 1-core CI hosts, but
# these tests exist to exercise the native plane — each forces it on
# via the fixture below (except the heuristic tests, which manage the
# env themselves).
pytestmark = pytest.mark.skipif(
    _lib_with_reader() is None, reason="native reader library unavailable"
)


@pytest.fixture(autouse=True)
def _force_native(monkeypatch):
    monkeypatch.setenv("AT2_FORCE_NATIVE_READER", "1")

_ports = itertools.count(23600)


def _encrypt_frame(aead, ctr: int, payload: bytes) -> bytes:
    nonce = struct.pack("<Q", ctr) + b"\x00\x00\x00\x00"
    ct = aead.encrypt(nonce, payload, None)
    return struct.pack("<I", len(ct)) + ct


def _drain(reader, rfd, timeout=5.0):
    """Wait for the wake pipe, then take everything pending."""
    frames = []
    status = STATUS_OPEN
    r, _, _ = select.select([rfd], [], [], timeout)
    assert r, "reader never woke the pipe"
    os.read(rfd, 65536)
    while True:
        batch, status, _drops = reader.take()
        frames.extend(batch)
        if not batch:
            break
    return frames, status


def test_reader_differential_and_tamper():
    # independent AEAD implementation for the differential; images without
    # the cryptography wheel can't run it (the reader itself has a
    # pure-python fallback and is covered by the transport tests)
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

    key = bytes(range(32))
    aead = ChaCha20Poly1305(key)
    a, b = socket.socketpair()
    rfd, wfd = os.pipe()
    os.set_blocking(rfd, False)
    reader = NativeChannelReader(b.fileno(), key, wfd)
    try:
        payloads = [
            b"",  # empty frame (tag-only ciphertext) is legal
            b"x" * 1,
            os.urandom(1000),
            os.urandom(5 * 1024 * 1024),  # exceeds the 4 MiB take buffer
        ]
        blob = b"".join(
            _encrypt_frame(aead, i, p) for i, p in enumerate(payloads)
        )
        a.sendall(blob)
        got = []
        while len(got) < len(payloads):
            frames, status = _drain(reader, rfd)
            got.extend(frames)
            assert status == STATUS_OPEN
        assert got == payloads  # byte-identical, in order

        # tampered ciphertext: channel-fatal protocol error, like
        # transport.Channel.recv's InvalidTag -> ChannelClosed
        bad = bytearray(_encrypt_frame(aead, len(payloads), b"evil"))
        bad[7] ^= 0x01
        a.sendall(bytes(bad))
        frames, status = _drain(reader, rfd)
        assert frames == []
        assert status == STATUS_PROTOCOL_ERROR
    finally:
        reader.stop()
        os.close(rfd)
        os.close(wfd)
        a.close()
        b.close()


def test_reader_clean_eof():
    key = os.urandom(32)
    a, b = socket.socketpair()
    rfd, wfd = os.pipe()
    os.set_blocking(rfd, False)
    reader = NativeChannelReader(b.fileno(), key, wfd)
    try:
        a.close()
        frames, status = _drain(reader, rfd)
        assert frames == []
        assert status == STATUS_EOF
    finally:
        reader.stop()
        os.close(rfd)
        os.close(wfd)
        b.close()


def test_reader_oversized_length_is_protocol_error():
    key = os.urandom(32)
    a, b = socket.socketpair()
    rfd, wfd = os.pipe()
    os.set_blocking(rfd, False)
    reader = NativeChannelReader(b.fileno(), key, wfd)
    try:
        a.sendall(struct.pack("<I", 17 * 1024 * 1024))  # > MAX_FRAME
        frames, status = _drain(reader, rfd)
        assert frames == []
        assert status == STATUS_PROTOCOL_ERROR
    finally:
        reader.stop()
        os.close(rfd)
        os.close(wfd)
        a.close()
        b.close()


async def _converge_two_nodes():
    from at2_node_tpu.client import Client
    from at2_node_tpu.node.service import Service

    cfgs = make_net_configs(2, _ports, echo_threshold=1, ready_threshold=1)
    services = [await Service.start(c) for c in cfgs]
    sender = SignKeyPair.random()
    recipient = SignKeyPair.random().public
    try:
        async with Client(f"http://{cfgs[0].rpc_address}") as client:
            await client.send_asset(sender, 1, recipient, 10)

            async def committed():
                for s in services:
                    if await s.accounts.get_last_sequence(sender.public) < 1:
                        return False
                return True

            await wait_until(committed, what="2-node commit")
        return [s.mesh.stats() for s in services]
    finally:
        for s in services:
            await s.close()


@pytest.mark.asyncio
async def test_mesh_uses_native_readers_and_converges(monkeypatch):
    # force past the core-count heuristic: the CI host may be 1-core
    monkeypatch.setenv("AT2_FORCE_NATIVE_READER", "1")
    stats = await _converge_two_nodes()
    # both nodes accepted their inbound connection onto the native plane
    assert all(s["native_readers"] >= 1 for s in stats), stats


@pytest.mark.asyncio
async def test_mesh_asyncio_fallback_converges(monkeypatch):
    monkeypatch.setenv("AT2_NO_NATIVE_READER", "1")
    stats = await _converge_two_nodes()
    assert all(s["native_readers"] == 0 for s in stats), stats


class TestPlaneSelectionHeuristic:
    """VERDICT r4 #5: the inbound plane self-selects by host shape —
    native reader threads default OFF on a 1-core host (the
    measured-penalty shape, BENCH_E2E.json round4_note) and ON
    otherwise; env vars override in both directions."""

    @staticmethod
    def _pin_cores(monkeypatch, n: int) -> None:
        """The heuristic reads the AFFINITY mask (cgroup/taskset aware),
        falling back to cpu_count — pin both."""
        from at2_node_tpu.native import reader

        monkeypatch.setattr(
            reader.os, "sched_getaffinity", lambda pid: set(range(n)),
            raising=False,
        )
        monkeypatch.setattr(reader.os, "cpu_count", lambda: n)

    def test_single_core_defaults_off(self, monkeypatch):
        from at2_node_tpu.native import reader

        monkeypatch.delenv("AT2_NO_NATIVE_READER", raising=False)
        monkeypatch.delenv("AT2_FORCE_NATIVE_READER", raising=False)
        self._pin_cores(monkeypatch, 1)
        assert not reader.reader_default_on()
        assert not reader.reader_available()

    def test_single_core_force_overrides(self, monkeypatch):
        from at2_node_tpu.native import reader

        monkeypatch.delenv("AT2_NO_NATIVE_READER", raising=False)
        monkeypatch.setenv("AT2_FORCE_NATIVE_READER", "1")
        self._pin_cores(monkeypatch, 1)
        # availability now depends only on the library actually loading
        assert reader.reader_available() == (reader._lib_with_reader() is not None)

    def test_multi_core_defaults_on(self, monkeypatch):
        from at2_node_tpu.native import reader

        monkeypatch.delenv("AT2_NO_NATIVE_READER", raising=False)
        monkeypatch.delenv("AT2_FORCE_NATIVE_READER", raising=False)
        self._pin_cores(monkeypatch, 8)
        assert reader.reader_default_on()
        assert reader.reader_available() == (reader._lib_with_reader() is not None)

    def test_affinity_narrower_than_host_wins(self, monkeypatch):
        # a 1-cpu container/cgroup on a multi-core host must read as 1
        from at2_node_tpu.native import reader

        monkeypatch.delenv("AT2_NO_NATIVE_READER", raising=False)
        monkeypatch.delenv("AT2_FORCE_NATIVE_READER", raising=False)
        monkeypatch.setattr(
            reader.os, "sched_getaffinity", lambda pid: {0}, raising=False
        )
        monkeypatch.setattr(reader.os, "cpu_count", lambda: 64)
        assert not reader.reader_default_on()

    def test_kill_switch_beats_force(self, monkeypatch):
        from at2_node_tpu.native import reader

        monkeypatch.setenv("AT2_NO_NATIVE_READER", "1")
        monkeypatch.setenv("AT2_FORCE_NATIVE_READER", "1")
        self._pin_cores(monkeypatch, 8)
        assert not reader.reader_available()
