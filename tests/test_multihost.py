"""Multi-host bring-up seam (parallel/multihost.py).

Real multi-host hardware is not available in CI; what IS testable:

* unconfigured environments are a strict no-op (no coordinator dial,
  no env mutation) — single-host deployments never pay for the seam;
* a 1-process distributed runtime (jax.distributed with
  num_processes=1, the degenerate but fully real code path) comes up in
  a subprocess, reports a coherent topology, and the sharded verifier
  pool works over the resulting global mesh;
* a REAL 2-process runtime (coordinator + worker over loopback, 4
  virtual CPU devices each): global devices = 2x local, pool meshes stay
  process-local (the multihost.py scaling model's load-bearing claim),
  and single-controller SPMD programs — a psum reduction in the fast
  tier, the full sharded ed25519 verify in the slow tier — span both
  processes' devices.
"""

import os
import subprocess
import sys

import pytest

import at2_node_tpu.parallel.multihost as mh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_unconfigured_is_noop(monkeypatch):
    monkeypatch.delenv("AT2_COORDINATOR", raising=False)
    assert mh.maybe_initialize() is False
    assert mh._initialized is False


@pytest.mark.slow  # subprocess pays a fresh XLA-CPU compile (~1.5 min)
def test_single_process_distributed_runtime_and_pool():
    """Subprocess isolation: jax.distributed.initialize is process-global
    and cannot be torn down for the other tests."""
    code = """
import os, sys
sys.path.insert(0, @REPO@)
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # older jax: backend is lazy, XLA_FLAGS still works
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
os.environ["AT2_COORDINATOR"] = "127.0.0.1:@PORT@"
os.environ["AT2_NUM_PROCESSES"] = "1"
os.environ["AT2_PROCESS_ID"] = "0"
from at2_node_tpu.parallel import multihost
assert multihost.maybe_initialize() is True
assert multihost.maybe_initialize() is True  # idempotent
info = multihost.process_info()
assert info["initialized"] and info["process_count"] == 1
assert info["global_devices"] == info["local_devices"] == 4

# the pool's default mesh now IS the global mesh; verify through it
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.parallel.pool import make_mesh, verify_batch_sharded
kp = SignKeyPair.from_hex("51" * 32)
msgs = [b"mh%d" % i for i in range(8)]
sigs = [kp.sign(m) for m in msgs]
bad = sigs[:3] + [b"\\x00" * 64] + sigs[4:]
ok = verify_batch_sharded([kp.public] * 8, msgs, bad, mesh=make_mesh())
assert list(ok) == [True, True, True, False, True, True, True, True], list(ok)
print("MULTIHOST_OK", info["process_count"], info["global_devices"])
""".replace("@REPO@", repr(REPO)).replace("@PORT@", str(_free_port()))
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "MULTIHOST_OK 1 4" in proc.stdout, proc.stdout


_TWO_PROC_PREAMBLE = """
import os, sys
sys.path.insert(0, @REPO@)
pid = int(sys.argv[1])
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # older jax: backend is lazy, XLA_FLAGS still works
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
os.environ["AT2_COORDINATOR"] = "127.0.0.1:@PORT@"
os.environ["AT2_NUM_PROCESSES"] = "2"
os.environ["AT2_PROCESS_ID"] = str(pid)
from at2_node_tpu.parallel import multihost
assert multihost.maybe_initialize() is True
info = multihost.process_info()
# the load-bearing topology claims: 2 real processes, global = 2x local
assert info["process_count"] == 2, info
assert info["local_devices"] == 4, info
assert info["global_devices"] == 8, info

# pool meshes stay HOST-LOCAL on a multi-process runtime (a per-node
# verifier can never enter a cross-process collective in lockstep)
from at2_node_tpu.parallel import pool
local_mesh = pool.make_mesh()
assert local_mesh.devices.size == 4, local_mesh
assert all(
    d.process_index == jax.process_index()
    for d in local_mesh.devices.flat
), "pool mesh leaked a remote device"
"""


def _run_two_procs(body: str, port: int, timeout: float):
    """Spawn both SPMD processes, wait for both, return them."""
    code = (_TWO_PROC_PREAMBLE + body).replace("@REPO@", repr(REPO)).replace(
        "@PORT@", str(port)
    )
    env = {**os.environ, "JAX_PLATFORMS": ""}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        if rc != 0 and "aren't implemented on the CPU backend" in err:
            # older jaxlib (<= 0.4.x): the CPU backend has no multiprocess
            # collectives at all — an environment capability gap, not a
            # regression in the code under test
            pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
        assert rc == 0, err[-2000:]
    return outs


def test_two_process_distributed_runtime():
    """A REAL 2-process distributed runtime (coordinator + worker over
    loopback, 4 virtual CPU devices each): topology, pool-mesh locality,
    and one single-controller SPMD program whose psum collective spans
    both processes' devices."""
    body = """
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

mesh = pool.make_mesh(jax.devices())  # explicit global mesh: all 8
assert mesh.devices.size == 8
shard = NamedSharding(mesh, PartitionSpec(pool.BATCH_AXIS))
replicated = NamedSharding(mesh, PartitionSpec())

full = np.arange(8, dtype=np.int32) + 1  # every process holds the input
garr = jax.make_array_from_callback(full.shape, shard, lambda idx: full[idx])
total = jax.jit(
    lambda x: jnp.sum(x), in_shardings=(shard,), out_shardings=replicated
)(garr)
# the sharded->replicated transition is an AllReduce over both processes;
# a wrong or hung collective cannot produce this in both of them
assert int(total) == 36, int(total)
print("MULTIHOST2_OK", info["process_count"], info["global_devices"])
"""
    outs = _run_two_procs(body, _free_port(), timeout=240)
    for _, out, _ in outs:
        assert "MULTIHOST2_OK 2 8" in out, out


@pytest.mark.slow  # both processes pay a fresh XLA-CPU kernel compile
def test_two_process_spmd_verify_spans_hosts():
    """The BASELINE config-5 shape at process granularity: ONE sharded
    ed25519 verify program spanning two processes' devices, with the
    validity count psum-reduced across them."""
    body = """
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.ops import ed25519 as kernel

mesh = pool.make_mesh(jax.devices())
assert mesh.devices.size == 8
shard = NamedSharding(mesh, PartitionSpec(pool.BATCH_AXIS))

kp = SignKeyPair.from_hex("52" * 32)
msgs = [b"2proc%d" % i for i in range(8)]
sigs = [kp.sign(m) for m in msgs]
sigs[3] = b"\\x00" * 64  # one invalid lane
prepared = kernel.prepare_batch([kp.public] * 8, msgs, sigs, 8)

garrs = [
    jax.make_array_from_callback(
        np.asarray(x).shape, shard, lambda idx, x=np.asarray(x): x[idx]
    )
    for x in prepared
]
ok, count = pool._count_fn(mesh)(*garrs)
# count is replicated: every process observes the global verdict of a
# program whose lanes ran on BOTH processes' devices
assert int(count) == 7, int(count)
for s in ok.addressable_shards:
    lane = int(np.asarray(s.index[0].start or 0))
    want = [i != 3 for i in range(lane, lane + s.data.shape[0])]
    assert list(np.asarray(s.data)) == want, (lane, s.data)
print("MULTIHOST2_VERIFY_OK")
"""
    outs = _run_two_procs(body, _free_port(), timeout=420)
    for _, out, _ in outs:
        assert "MULTIHOST2_VERIFY_OK" in out, out


def test_partial_configuration_raises_clearly(monkeypatch):
    monkeypatch.setattr(mh, "_initialized", False)
    monkeypatch.setenv("AT2_COORDINATOR", "127.0.0.1:1")
    monkeypatch.delenv("AT2_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("AT2_PROCESS_ID", raising=False)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="AT2_NUM_PROCESSES"):
        mh.maybe_initialize()
