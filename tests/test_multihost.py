"""Multi-host bring-up seam (parallel/multihost.py).

Real multi-host hardware is not available in CI; what IS testable:

* unconfigured environments are a strict no-op (no coordinator dial,
  no env mutation) — single-host deployments never pay for the seam;
* a 1-process distributed runtime (jax.distributed with
  num_processes=1, the degenerate but fully real code path) comes up in
  a subprocess, reports a coherent topology, and the sharded verifier
  pool works over the resulting global mesh.
"""

import os
import subprocess
import sys

import pytest

import at2_node_tpu.parallel.multihost as mh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_unconfigured_is_noop(monkeypatch):
    monkeypatch.delenv("AT2_COORDINATOR", raising=False)
    assert mh.maybe_initialize() is False
    assert mh._initialized is False


@pytest.mark.slow  # subprocess pays a fresh XLA-CPU compile (~1.5 min)
def test_single_process_distributed_runtime_and_pool():
    """Subprocess isolation: jax.distributed.initialize is process-global
    and cannot be torn down for the other tests."""
    code = """
import os, sys
sys.path.insert(0, @REPO@)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)
os.environ["AT2_COORDINATOR"] = "127.0.0.1:@PORT@"
os.environ["AT2_NUM_PROCESSES"] = "1"
os.environ["AT2_PROCESS_ID"] = "0"
from at2_node_tpu.parallel import multihost
assert multihost.maybe_initialize() is True
assert multihost.maybe_initialize() is True  # idempotent
info = multihost.process_info()
assert info["initialized"] and info["process_count"] == 1
assert info["global_devices"] == info["local_devices"] == 4

# the pool's default mesh now IS the global mesh; verify through it
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.parallel.pool import make_mesh, verify_batch_sharded
kp = SignKeyPair.from_hex("51" * 32)
msgs = [b"mh%d" % i for i in range(8)]
sigs = [kp.sign(m) for m in msgs]
bad = sigs[:3] + [b"\\x00" * 64] + sigs[4:]
ok = verify_batch_sharded([kp.public] * 8, msgs, bad, mesh=make_mesh())
assert list(ok) == [True, True, True, False, True, True, True, True], list(ok)
print("MULTIHOST_OK", info["process_count"], info["global_devices"])
""".replace("@REPO@", repr(REPO)).replace("@PORT@", str(_free_port()))
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "MULTIHOST_OK 1 4" in proc.stdout, proc.stdout


def test_partial_configuration_raises_clearly(monkeypatch):
    monkeypatch.setattr(mh, "_initialized", False)
    monkeypatch.setenv("AT2_COORDINATOR", "127.0.0.1:1")
    monkeypatch.delenv("AT2_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("AT2_PROCESS_ID", raising=False)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="AT2_NUM_PROCESSES"):
        mh.maybe_initialize()
