"""Unit tests for the catchup plane's defensive machinery and the inbox
byte budget — the bounds that keep an authenticated-but-byzantine peer
from using the new protocol surfaces as amplification levers. The happy
path (full rejoin re-convergence) lives in tests/test_faults.py and the
CLI drive; these pin the caps directly.
"""

import asyncio
import itertools

import pytest

from at2_node_tpu.broadcast import stack as stack_mod
from at2_node_tpu.broadcast.messages import (
    HistoryBatch,
    HistoryIndexRequest,
    HistoryRequest,
    Payload,
)
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.ledger import history as hist
from at2_node_tpu.node import service as service_mod
from at2_node_tpu.node.service import Service, _CatchupSession
from at2_node_tpu.types import ThinTransaction

from conftest import make_net_configs

_ports = itertools.count(24600)


def _payload(seed: int, seq: int = 1) -> Payload:
    kp = SignKeyPair.from_hex(f"{seed % 255 + 1:02x}" * 32)
    tx = ThinTransaction(bytes([seed % 256]) * 32, seed + 1)
    return Payload.create(kp, seq, tx)


class _FakeMesh:
    """Captures catchup-plane sends without a network."""

    def __init__(self, peers):
        self.peers = peers
        self.sent = []  # (peer, frame)

    def send(self, peer, frame):
        self.sent.append((peer, frame))

    def broadcast(self, frame, exclude=()):
        for p in self.peers:
            self.sent.append((p, frame))


def _service_with_fake_mesh(n_peers=2):
    cfgs = make_net_configs(n_peers + 1, _ports)
    svc = Service(cfgs[0])
    svc.mesh = _FakeMesh(cfgs[0].nodes)
    return svc, cfgs[0].nodes


@pytest.mark.asyncio
async def test_index_request_budget_throttles():
    svc, peers = _service_with_fake_mesh()
    for _ in range(service_mod.SERVE_IDX_PER_SEC + 3):
        svc._on_catchup(peers[0], HistoryIndexRequest(1))
    assert len(svc.mesh.sent) == service_mod.SERVE_IDX_PER_SEC
    assert svc.catchup_stats["catchup_throttled"] == 3
    # a different peer has its own budget
    svc._on_catchup(peers[1], HistoryIndexRequest(2))
    assert len(svc.mesh.sent) == service_mod.SERVE_IDX_PER_SEC + 1


@pytest.mark.asyncio
async def test_history_request_budget_charged_before_lookup():
    svc, peers = _service_with_fake_mesh()
    # fill some history so a lookup WOULD serve
    for i in range(10):
        svc.history.record(_payload(3, seq=i + 1))
    sender = _payload(3).sender
    # a huge claimed range charges its CLAMPED cost (MAX_RANGE) even
    # though only 10 payloads exist — the budget bounds the WORK, not
    # the result; 4 such requests exhaust SERVE_ROWS_PER_SEC exactly
    assert service_mod.SERVE_ROWS_PER_SEC == 4 * hist.MAX_RANGE
    for _ in range(4):
        svc._on_catchup(peers[0], HistoryRequest(1, sender, 1, 1 << 31))
    assert svc.catchup_stats["catchup_served"] == 40
    # budget now exhausted for this peer+window: next request does no work
    svc._on_catchup(peers[0], HistoryRequest(1, sender, 1, 10))
    assert svc.catchup_stats["catchup_throttled"] >= 1
    assert svc.catchup_stats["catchup_served"] == 40
    # inverted range costs nothing and serves nothing
    before = len(svc.mesh.sent)
    svc._on_catchup(peers[1], HistoryRequest(1, sender, 9, 3))
    assert len(svc.mesh.sent) == before


@pytest.mark.asyncio
async def test_session_per_peer_cap_never_blocks_vote_accrual(monkeypatch):
    monkeypatch.setattr(service_mod, "MAX_SESSION_PAYLOADS", 8)
    svc, peers = _service_with_fake_mesh(n_peers=2)
    session = _CatchupSession(nonce=7, n_peers=2)
    assert session.per_peer_cap == 4
    svc._catchup_session = session

    flood = tuple(_payload(i, seq=1) for i in range(10, 20))
    svc._on_catchup(peers[0], HistoryBatch(7, flood))
    # the flooding peer stored only its own share
    assert len(session.payloads) == 4
    assert session.stored_by_peer[peers[0].sign_public] == 4

    # the honest peer's copies of ALREADY-STORED slots accrue votes
    # despite the flood — quorum can still form
    stored_payloads = tuple(session.payloads.values())
    svc._on_catchup(peers[1], HistoryBatch(7, stored_payloads))
    for vote_key in session.payloads:
        assert len(session.votes[vote_key]) == 2
    # and the honest peer still has its own storage share
    fresh = tuple(_payload(i, seq=1) for i in range(30, 33))
    svc._on_catchup(peers[1], HistoryBatch(7, fresh))
    assert len(session.payloads) == 7


@pytest.mark.asyncio
async def test_index_rotation_covers_all_senders(monkeypatch):
    monkeypatch.setattr(hist, "MAX_IDX_ENTRIES", 3)
    svc, peers = _service_with_fake_mesh()
    for i in range(40, 47):  # 7 senders committed
        await svc.accounts.transfer(
            _payload(i).sender, 1, _payload(i + 100).sender, 1
        )
    seen = set()
    for nonce in range(4):
        svc._on_catchup(peers[0], HistoryIndexRequest(nonce))
        from at2_node_tpu.broadcast.messages import parse_frame

        _, frame = svc.mesh.sent[-1]
        (idx,) = parse_frame(frame)
        assert len(idx.entries) == 3
        seen.update(sender for sender, _ in idx.entries)
    # rotating windows cover every sender within ceil(7/3)+1 requests
    assert len(seen) == 7


@pytest.mark.asyncio
async def test_inbox_byte_budget(monkeypatch):
    monkeypatch.setattr(stack_mod, "INBOX_MAX_BYTES", 1000)
    bcast = stack_mod.Broadcast.__new__(stack_mod.Broadcast)
    bcast._inbox = asyncio.Queue(maxsize=65536)
    bcast._inbox_bytes = 0

    big = b"\x01" * 600
    await bcast.on_frame(None, big)
    assert bcast._inbox_bytes == 600
    await bcast.on_frame(None, big)  # would exceed the 1000-byte budget
    assert bcast._inbox_bytes == 600
    assert bcast._inbox.qsize() == 1

    # draining (what a worker does) frees the budget
    _, frame = bcast._inbox.get_nowait()
    bcast._inbox_bytes -= len(frame)
    await bcast.on_frame(None, big)
    assert bcast._inbox.qsize() == 1
