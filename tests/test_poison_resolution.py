"""Poison-entry resolution, ingress pre-verification, and stall-storm
damping (broadcast/stack.py + node/service.py, the robustness PR).

The amplification being closed: pre-fix, one never-deliverable entry
(bad client signature or equivocation-registry conflict) held its batch
slot "undelivered" for SLOT_MAX_AGE — burning retransmission budget and
firing a network-wide catchup kick every GC pass. These tests pin the
three independent defenses:

* slot RETIREMENT — a slot whose ready-quorate entries are delivered and
  whose remaining entries are locally resolved-rejected leaves the
  undelivered population and compacts like a delivered one (and a late
  Ready quorum on a rejected entry still delivers it while retained);
* ingress PRE-VERIFICATION ([admission]) — bad client signatures are
  rejected at the RPC boundary via one bulk verify_many, with a
  per-source token bucket charged only for FAILED entries;
* stall-kick HYSTERESIS — poison-blocked slots never classify as
  stalled, and genuine stalls fire the catchup kick through a min
  interval + exponential backoff instead of once per GC pass.
"""

import asyncio
import itertools
import time

import grpc
import pytest

from at2_node_tpu.broadcast.messages import (
    BATCH_ECHO,
    BATCH_READY,
    BatchAttestation,
    Payload,
    TxBatch,
)
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.crypto.verifier import make_verifier
from at2_node_tpu.node.config import AdmissionConfig
from at2_node_tpu.node.service import Service
from at2_node_tpu.proto import at2_pb2 as pb
from at2_node_tpu.types import ThinTransaction, transfer_signing_bytes

from conftest import make_net_configs, wait_until

_ports = itertools.count(27400)

FAUCET = 100_000


def make_payload(keypair, seq=1, amount=10, recipient=b"r" * 32):
    return Payload.create(keypair, seq, ThinTransaction(recipient, amount))


def bad_payload(public, seq=1, amount=10, recipient=b"r" * 32):
    """A transfer whose client signature can never verify."""
    return Payload(public, seq, ThinTransaction(recipient, amount), b"\x01" * 64)


def make_configs(n, **kwargs):
    return make_net_configs(n, _ports, **kwargs)


async def start_net(n, **kwargs):
    cfgs = make_configs(n, **kwargs)
    services = [await Service.start(c) for c in cfgs]
    return cfgs, services


async def close_all(services):
    for s in services:
        await s.close()


async def submit(service, payload):
    """Feed one payload straight into the ingress batcher — bypasses the
    RPC admission layer, i.e. models a byzantine/lenient ingress node."""
    await service.recent.put(payload.sender, payload.sequence, payload.transaction)
    service._batch_buf.append(payload)


def _count_stall_kicks(services):
    """Replace each node's stall handler with a counter (the real handler
    starts catchup sessions; counting is what these tests need)."""
    counts = {id(s): 0 for s in services}
    for s in services:

        def bump(_s=s):
            counts[id(_s)] += 1

        s.broadcast.stall_handler = bump
    return counts


class _StubMesh:
    """Minimal mesh for unit-level Broadcast tests: records frames."""

    def __init__(self, n_peers=0):
        self.peers = [object() for _ in range(n_peers)]
        self.by_sign = {}
        self.sent = []

    def broadcast(self, frame):
        self.sent.append((None, frame))

    def send(self, peer, frame):
        self.sent.append((peer, frame))


def make_batch(origin_kp, payloads, batch_seq=1):
    raw = b"".join(p.encode()[1:] for p in payloads)
    return TxBatch.create(origin_kp, batch_seq, raw)


def batch_att(kp, phase, slot, chash, bitmap):
    sig = kp.sign(
        BatchAttestation.signing_bytes(phase, slot[0], slot[1], chash, bitmap)
    )
    return BatchAttestation(phase, kp.public, slot[0], slot[1], chash, bitmap, sig)


class TestSlotRetirement:
    @pytest.mark.asyncio
    async def test_poison_slot_retires_and_compacts(self, monkeypatch):
        """One bad-sig entry no longer pins its slot for SLOT_MAX_AGE:
        the slot retires once the good siblings deliver, stops consuming
        retransmission budget, never classifies as stalled, and compacts
        after the normal retention."""
        import at2_node_tpu.broadcast.stack as stack_mod

        monkeypatch.setattr(stack_mod, "GC_INTERVAL", 0.2)
        monkeypatch.setattr(stack_mod, "DELIVERED_RETENTION", 0.4)
        monkeypatch.setattr(stack_mod, "RETRANSMIT_AFTER", 1.0)
        monkeypatch.setattr(stack_mod, "STALLED_CATCHUP_AFTER", 1.0)
        cfgs, services = await start_net(3)
        kicks = _count_stall_kicks(services)
        try:
            sender = SignKeyPair.random()
            poisoner = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            for seq in range(1, 6):
                await submit(
                    services[0], make_payload(sender, seq=seq, recipient=recipient)
                )
            await submit(services[0], bad_payload(poisoner.public, seq=1))
            await services[0]._flush_batch()

            async def good_committed():
                return all(s.committed >= 5 for s in services)

            await wait_until(good_committed, what="good siblings commit")

            async def all_retired_and_compacted():
                for s in services:
                    st = s.broadcast.stats
                    if st["slots_retired"] < 1 or st["poison_resolved"] < 1:
                        return False
                    if s.broadcast._batch_slots or s.broadcast._undelivered:
                        return False
                return True

            await wait_until(
                all_retired_and_compacted, what="poison slot retires + compacts"
            )
            for s in services:
                # retired slots are excluded from retransmission and from
                # the stall classification — kicks must never have fired
                assert s.broadcast.stats["retransmits"] == 0
                assert kicks[id(s)] == 0
                assert s.catchup_stats["catchup_sessions"] == 0
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_all_rejected_batch_retires_standalone(self, monkeypatch):
        """Degenerate single-node net: a batch that is 100% poison still
        resolves (no quorum will ever arrive to deliver anything)."""
        import at2_node_tpu.broadcast.stack as stack_mod

        monkeypatch.setattr(stack_mod, "GC_INTERVAL", 0.2)
        cfgs, services = await start_net(1)
        try:
            await submit(services[0], bad_payload(SignKeyPair.random().public))
            await services[0]._flush_batch()

            async def retired():
                st = services[0].broadcast.stats
                return st["slots_retired"] >= 1 and st["poison_resolved"] >= 1

            await wait_until(retired, what="all-poison slot retires")
            assert services[0].committed == 0
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_late_quorum_on_rejected_entry_still_delivers(self):
        """Retirement is a GC/stats state, not a delivery gate: our local
        rejection is not the network's verdict. If a Ready quorum for a
        rejected entry lands while the slot is retained, the entry
        delivers through the normal path."""
        kp = SignKeyPair.random()
        peers = [SignKeyPair.random() for _ in range(2)]
        mesh = _StubMesh(n_peers=2)
        bcast = __import__(
            "at2_node_tpu.broadcast.stack", fromlist=["Broadcast"]
        ).Broadcast(
            kp, mesh, make_verifier("cpu"), echo_threshold=2, ready_threshold=2
        )
        client = SignKeyPair.random()
        good = make_payload(client, seq=1)
        bad = bad_payload(SignKeyPair.random().public, seq=1)
        origin = SignKeyPair.random()
        batch = make_batch(origin, [good, bad], batch_seq=7)
        slot = (origin.public, 7)
        chash = batch.content_hash()
        # echo verdicts: entry 0 ok, entry 1 rejected
        bcast._post_batch(batch, [True, False])
        state = bcast._batch_slots[slot]
        assert state.rejected_bits[chash] == 0b10
        # both peers endorse only entry 0 through Echo AND Ready
        for peer in peers:
            bcast._post_batch_attestation(
                batch_att(peer, BATCH_ECHO, slot, chash, bytes([0b01]))
            )
        for peer in peers:
            bcast._post_batch_attestation(
                batch_att(peer, BATCH_READY, slot, chash, bytes([0b01]))
            )
        assert state.delivered_bits[chash] == 0b01
        bcast._maybe_retire_batch(slot, state)
        assert state.retired and not state.delivered_all
        retired_undelivered = bcast._undelivered
        # LATE full-width quorum (the network out-voted our rejection)
        for peer in peers:
            bcast._post_batch_attestation(
                batch_att(peer, BATCH_READY, slot, chash, bytes([0b11]))
            )
        assert state.delivered_bits[chash] == 0b11
        assert state.delivered_all
        # the undelivered population was decremented exactly once
        assert bcast._undelivered == retired_undelivered
        assert bcast.delivered.qsize() == 2

    @pytest.mark.asyncio
    async def test_no_retire_while_quorate_entry_undelivered(self):
        """A slot with a ready-quorate but undelivered entry (content
        still missing, say) is genuinely in progress — it must NOT
        retire, even if the echoed content is fully resolved."""
        kp = SignKeyPair.random()
        peers = [SignKeyPair.random() for _ in range(2)]
        mesh = _StubMesh(n_peers=2)
        bcast = __import__(
            "at2_node_tpu.broadcast.stack", fromlist=["Broadcast"]
        ).Broadcast(
            kp, mesh, make_verifier("cpu"), echo_threshold=2, ready_threshold=2
        )
        origin = SignKeyPair.random()
        slot = (origin.public, 3)
        other_hash = b"\x55" * 32  # an equivocating sibling content
        # a full Ready quorum for a content we never saw arrives FIRST
        for peer in peers:
            bcast._post_batch_attestation(
                batch_att(peer, BATCH_READY, slot, other_hash, bytes([0b01]))
            )
        # then our copy of the (all-rejected) echoed content lands
        bcast._post_batch(
            make_batch(origin, [bad_payload(SignKeyPair.random().public)], 3),
            [False],
        )
        state = bcast._batch_slots[slot]
        bcast._maybe_retire_batch(slot, state)
        assert not state.retired, "quorate undelivered entry must block retirement"
        assert bcast._poison_blocked_only(state) is False


class TestBitmapClamp:
    def _bcast(self, n=2):
        kp = SignKeyPair.random()
        mesh = _StubMesh(n_peers=n)
        return kp, __import__(
            "at2_node_tpu.broadcast.stack", fromlist=["Broadcast"]
        ).Broadcast(
            kp, mesh, make_verifier("cpu"), echo_threshold=n, ready_threshold=n
        )

    @pytest.mark.asyncio
    async def test_oversized_bitmap_clamped_to_entry_count(self):
        """An attestation claiming 1024 entries for a 2-entry batch must
        not inflate nbits past the real count (phantom positions used to
        spuriously quorate and drive endless content pulls)."""
        kp, bcast = self._bcast()
        origin = SignKeyPair.random()
        client = SignKeyPair.random()
        batch = make_batch(origin, [make_payload(client, seq=s) for s in (1, 2)])
        slot = (origin.public, 1)
        chash = batch.content_hash()
        bcast._post_batch(batch, [True, True])
        state = bcast._batch_slots[slot]
        assert state.nbits == 2
        wide = (1 << 1024) - 1  # every bit set, 128-byte bitmap
        att = batch_att(
            kp=SignKeyPair.random(),
            phase=BATCH_ECHO,
            slot=slot,
            chash=chash,
            bitmap=wide.to_bytes(128, "little"),
        )
        bcast._post_batch_attestation(att)
        assert state.nbits == 2, "phantom positions grew nbits"
        votes = state.echo_votes[chash]
        assert votes.by_origin[att.origin] == 0b11  # clamped to the count

    @pytest.mark.asyncio
    async def test_phantom_only_bitmap_ignored(self):
        """Bits exclusively at positions >= count carry no information
        after the clamp — the attestation is dropped entirely."""
        kp, bcast = self._bcast()
        origin = SignKeyPair.random()
        client = SignKeyPair.random()
        batch = make_batch(origin, [make_payload(client, seq=1)])
        slot = (origin.public, 1)
        chash = batch.content_hash()
        bcast._post_batch(batch, [True])
        state = bcast._batch_slots[slot]
        phantom = batch_att(
            SignKeyPair.random(),
            BATCH_ECHO,
            slot,
            chash,
            (0b10).to_bytes(1, "little"),  # only bit 1, count is 1
        )
        bcast._post_batch_attestation(phantom)
        votes = state.echo_votes.get(chash)
        assert votes is None or phantom.origin not in votes.by_origin

    @pytest.mark.asyncio
    async def test_content_arrival_clamps_preexisting_width(self):
        """Attestations can precede the batch gossip; once the content
        lands, nbits snaps to the real entry count."""
        kp, bcast = self._bcast()
        origin = SignKeyPair.random()
        client = SignKeyPair.random()
        batch = make_batch(origin, [make_payload(client, seq=1)])
        slot = (origin.public, 1)
        chash = batch.content_hash()
        wide = batch_att(
            SignKeyPair.random(),
            BATCH_ECHO,
            slot,
            chash,
            ((1 << 64) - 1).to_bytes(8, "little"),
        )
        bcast._post_batch_attestation(wide)
        assert bcast._batch_slots[slot].nbits == 64
        bcast._post_batch(batch, [True])
        assert bcast._batch_slots[slot].nbits == 1


class TestStallDamping:
    @pytest.mark.asyncio
    async def test_kick_hysteresis_and_rearm(self, monkeypatch):
        """A persistently stalled slot fires the catchup kick through
        exponential backoff — not once per GC pass — and a healthy pass
        re-arms the minimum interval."""
        import at2_node_tpu.broadcast.stack as stack_mod

        monkeypatch.setattr(stack_mod, "GC_INTERVAL", 0.05)
        monkeypatch.setattr(stack_mod, "STALLED_CATCHUP_AFTER", 0.0)
        monkeypatch.setattr(stack_mod, "RETRANSMIT_AFTER", 3600.0)
        kp = SignKeyPair.random()
        bcast = stack_mod.Broadcast(
            kp, _StubMesh(1), make_verifier("cpu"), 1, 1, workers=1
        )
        bcast._stall_backoff = 0.4
        monkeypatch.setattr(stack_mod, "STALL_KICK_MIN_INTERVAL", 0.4)
        monkeypatch.setattr(stack_mod, "STALL_KICK_MAX_INTERVAL", 0.8)
        kicks = []
        bcast.stall_handler = lambda: kicks.append(time.monotonic())
        # one genuinely stalled per-tx slot (no content, no quorum)
        state = bcast._new_or_existing_slot((b"s" * 32, 1))
        state.created -= 10.0
        await bcast.start()
        try:
            await asyncio.sleep(1.5)
            # ~30 GC passes happened; undamped this would be ~30 kicks.
            # Damped: first kick immediate, then 0.4s, then 0.8s ... => <= 4
            assert 1 <= len(kicks) <= 4, kicks
            assert bcast.stats["stall_kicks_suppressed"] > 0
            gaps = [b - a for a, b in zip(kicks, kicks[1:])]
            assert all(g >= 0.35 for g in gaps), gaps
            # heal the slot: backoff re-arms to the minimum
            del bcast._slots[(b"s" * 32, 1)]
            bcast._undelivered -= 1
            await asyncio.sleep(0.3)
            assert bcast._stall_backoff == 0.4
        finally:
            await bcast.close()


class TestRegistryRelease:
    @pytest.mark.asyncio
    async def test_commit_releases_entry_binding(self):
        """The ledger gate subsumes the equivocation registry once a
        sequence commits — the binding is dropped eagerly so the
        registry's working set tracks in-flight entries only."""
        cfgs, services = await start_net(1)
        try:
            sender = SignKeyPair.random()
            p = make_payload(sender, seq=1)
            await submit(services[0], p)
            await services[0]._flush_batch()

            async def committed():
                return services[0].committed >= 1

            await wait_until(committed, what="entry commits")
            reg = services[0].broadcast._entry_registry
            assert reg.get((sender.public, 1)) is None
        finally:
            await close_all(services)


class TestCommitTailShield:
    @pytest.mark.asyncio
    async def test_cancellation_cannot_split_commit_from_record(self):
        """Satellite: a cancellation landing mid-commit-pass must not
        leave the accounts mutated but history/ring unrecorded — the
        tail is shielded and runs to completion."""
        cfg = make_configs(1)[0]
        svc = Service(cfg)  # no start(): unit-level, no net
        sender = SignKeyPair.random()
        p = make_payload(sender, seq=1)
        await svc.recent.put(p.sender, p.sequence, p.transaction)
        svc._push_pending(p, time.monotonic())
        release = asyncio.Event()
        started = asyncio.Event()
        orig = svc.recent.apply_many

        async def gated(ops):
            started.set()
            await release.wait()
            await orig(ops)

        svc.recent.apply_many = gated
        task = asyncio.create_task(svc._drain_to_fixpoint())
        await asyncio.wait_for(started.wait(), 5)
        task.cancel()
        release.set()
        with pytest.raises(asyncio.CancelledError):
            await task
        # the shielded tail still completed: commit recorded everywhere
        await asyncio.sleep(0.1)
        assert svc.committed == 1
        assert len(svc.history) == 1
        from at2_node_tpu.types import TransactionState

        txs = await svc.recent.get_all()
        assert [t.state for t in txs] == [TransactionState.SUCCESS]


class TestAdmission:
    @pytest.mark.asyncio
    async def test_bad_signature_rejected_at_rpc_boundary(self):
        """With [admission] preverify on (the default), a forged client
        signature never reaches the gossip plane: the RPC fails with
        INVALID_ARGUMENT and the broadcast stack sees nothing."""
        cfgs, services = await start_net(1)
        try:
            async with grpc.aio.insecure_channel(cfgs[0].rpc_address) as ch:
                stub = _stub(ch)
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await stub.SendAsset(_bad_request(), timeout=10)
                assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                assert "signature" in exc.value.details()
            await asyncio.sleep(0.2)
            snap = services[0].snapshot_stats()
            assert snap["rejected_at_ingress"] == 1
            assert services[0].broadcast.stats["invalid_sig"] == 0
            assert services[0].committed == 0
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_batch_rejection_names_entries(self):
        """Per-entry status: the rejection detail names exactly the
        failing entry indices, so a client can drop them and retry."""
        cfgs, services = await start_net(1)
        try:
            sender = SignKeyPair.random()
            reqs = []
            for i, seq in enumerate((1, 2, 3)):
                sig = (
                    b"\x02" * 64
                    if i == 1
                    else sender.sign(
                        transfer_signing_bytes(
                            sender.public, seq, b"r" * 32, 10
                        )
                    )
                )
                reqs.append(
                    pb.SendAssetRequest(
                        sender=sender.public,
                        sequence=seq,
                        recipient=b"r" * 32,
                        amount=10,
                        signature=sig,
                    )
                )
            async with grpc.aio.insecure_channel(cfgs[0].rpc_address) as ch:
                stub = _stub(ch)
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await stub.SendAssetBatch(
                        pb.SendAssetBatchRequest(transactions=reqs), timeout=10
                    )
                assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                assert "[1]" in exc.value.details()
            await asyncio.sleep(0.2)
            assert services[0].committed == 0  # all-or-nothing admission
            assert services[0].snapshot_stats()["rejected_at_ingress"] == 1
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_fail_token_bucket_throttles_source(self):
        """The bucket is charged only for FAILED entries; once spent, the
        source is refused with RESOURCE_EXHAUSTED before any verifier
        work."""
        cfgs, services = await start_net(
            1, admission=AdmissionConfig(fail_limit=2, fail_window=3600.0)
        )
        try:
            async with grpc.aio.insecure_channel(cfgs[0].rpc_address) as ch:
                stub = _stub(ch)
                for _ in range(2):
                    with pytest.raises(grpc.aio.AioRpcError) as exc:
                        await stub.SendAsset(_bad_request(), timeout=10)
                    assert (
                        exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                    )
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await stub.SendAsset(_bad_request(), timeout=10)
                assert exc.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
                # valid traffic from the same connection was never charged
                # — but this source is now refused outright until refill
                snap = services[0].snapshot_stats()
                assert snap["admission_throttled"] == 1
                assert snap["rejected_at_ingress"] == 2
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_honest_client_pays_zero_tokens(self):
        """Valid entries cost nothing: an honest client can push far more
        than fail_limit entries through one source."""
        cfgs, services = await start_net(
            1, admission=AdmissionConfig(fail_limit=2, fail_window=3600.0)
        )
        try:
            sender = SignKeyPair.random()
            from at2_node_tpu.client import Client

            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset_many(
                    sender, [(s, b"r" * 32, 1) for s in range(1, 21)]
                )

            async def committed():
                return services[0].committed >= 20

            await wait_until(committed, what="honest batch commits")
            snap = services[0].snapshot_stats()
            assert snap["rejected_at_ingress"] == 0
            assert snap["admission_throttled"] == 0
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_preverify_false_restores_old_behavior(self):
        """[admission] preverify = false: everything is admitted and the
        broadcast workers' bulk verification is the (only) gate again."""
        cfgs, services = await start_net(
            1, admission=AdmissionConfig(preverify=False)
        )
        try:
            async with grpc.aio.insecure_channel(cfgs[0].rpc_address) as ch:
                stub = _stub(ch)
                await stub.SendAsset(_bad_request(), timeout=10)  # accepted
            await services[0]._flush_batch()

            async def plane_rejected():
                return services[0].broadcast.stats["invalid_sig"] >= 1

            await wait_until(plane_rejected, what="broadcast-plane rejection")
            assert services[0].snapshot_stats()["rejected_at_ingress"] == 0
            assert services[0].committed == 0
        finally:
            await close_all(services)


def _stub(channel):
    from at2_node_tpu.proto.rpc import At2Stub

    return At2Stub(channel)


def _bad_request():
    kp = SignKeyPair.random()
    return pb.SendAssetRequest(
        sender=kp.public,
        sequence=1,
        recipient=b"r" * 32,
        amount=10,
        signature=b"\x07" * 64,
    )
