"""Dispatch-pipeline tests on a fake device: overlap, backpressure,
adaptive bucket shaping, close/cancel capacity hygiene, bitmask contract.

Everything here runs with injected stage hooks (no XLA compile, no
device), so the tier stays fast enough for the CI pipeline smoke gate
(scripts/ci.sh) to call it by name.
"""

import asyncio
import time

import numpy as np
import pytest

from at2_node_tpu.crypto.verifier import TpuBatchVerifier


def _items(n, tag=b"m"):
    return [(b"p" * 32, tag + str(i).encode(), b"s" * 64) for i in range(n)]


class FakeDevice(TpuBatchVerifier):
    """Stage hooks that record an event log instead of touching a chip.

    prep/launch/finish each append (stage, edge, batch_seq, t); the
    handle threaded through the stages is (batch_seq, n, bucket) so the
    log can be correlated per batch.
    """

    def __init__(self, *a, prep_s=0.0, launch_s=0.0, finish_s=0.0, **kw):
        super().__init__(*a, **kw)
        self.events = []
        self.prep_log = []  # (seq, n, bucket, msgs) per dispatched batch
        self._seq = 0
        self._prep_s = prep_s
        self._launch_s = launch_s
        self._finish_s = finish_s

    def _prep(self, pks, msgs, sigs, bucket):
        seq = self._seq
        self._seq += 1
        self.events.append(("prep", "start", seq, time.monotonic()))
        if self._prep_s:
            time.sleep(self._prep_s)
        self.events.append(("prep", "end", seq, time.monotonic()))
        self.prep_log.append((seq, len(pks), bucket, list(msgs)))
        return (seq, len(pks), bucket)

    def _launch(self, prepared):
        seq = prepared[0]
        self.events.append(("launch", "start", seq, time.monotonic()))
        if self._launch_s:
            time.sleep(self._launch_s)
        self.events.append(("launch", "end", seq, time.monotonic()))
        return prepared

    def _finish(self, handle, n):
        seq = handle[0]
        self.events.append(("finish", "start", seq, time.monotonic()))
        if self._finish_s:
            time.sleep(self._finish_s)
        self.events.append(("finish", "end", seq, time.monotonic()))
        return np.ones(n, dtype=bool)

    def edge(self, stage, edge, seq):
        for s, e, q, t in self.events:
            if (s, e, q) == (stage, edge, seq):
                return t
        raise AssertionError(f"no event {(stage, edge, seq)}")


def test_overlap_next_prep_starts_before_prior_finish_ends():
    """The tentpole invariant: batch N+1's prep must START before batch
    N's finish has COMPLETED — the three stages genuinely overlap across
    consecutive batches rather than running as a serial relay."""

    async def run():
        ver = FakeDevice(
            batch_size=4, max_delay=0.001, prep_s=0.01, finish_s=0.05
        )
        out = await ver.verify_many(_items(24))  # 6 batches of 4
        assert out == [True] * 24
        assert ver.batches_dispatched == 6
        overlapped = sum(
            1
            for seq in range(1, 6)
            if ver.edge("prep", "start", seq) < ver.edge("finish", "end", seq - 1)
        )
        assert overlapped >= 3, f"only {overlapped}/5 successor preps overlapped"
        await ver.close()

    asyncio.run(run())


def test_backpressure_flood_is_bounded_and_fifo():
    """Flooding far past max_queue must (a) keep the accumulator bounded
    at max_queue — memory does not scale with offered load — and (b)
    preserve FIFO order within each caller's chunk stream."""

    async def run():
        ver = FakeDevice(
            batch_size=4, max_delay=0.001, max_queue=8, finish_s=0.005
        )
        callers = [
            asyncio.ensure_future(ver.verify_many(_items(16, tag=b"c%d-" % c)))
            for c in range(4)
        ]
        results = await asyncio.gather(*callers)
        for r in results:
            assert r == [True] * 16
        assert ver.queue_peak <= ver.max_queue, (
            f"queue grew to {ver.queue_peak} past the {ver.max_queue} bound"
        )
        # FIFO within each caller: its items were dispatched in the order
        # they were enqueued (single flusher pops the accumulator in order)
        order = {}
        for _seq, _n, _bucket, batch_msgs in ver.prep_log:
            for m in batch_msgs:
                caller, idx = m.split(b"-", 1)
                order.setdefault(caller, []).append(int(idx))
        assert len(order) == 4
        for caller, idx in order.items():
            assert idx == sorted(idx), f"caller {caller} reordered: {idx}"
        # no leaked capacity once everything drained
        assert ver._cap_free == ver.max_queue
        await ver.close()

    asyncio.run(run())


def test_adaptive_bucket_shrinks_timer_flush():
    """A 3-item timer flush on a (4, 8, 16) ladder must dispatch in the
    4-lane bucket, not pad 13 dead lanes into the 16 shape."""

    async def run():
        ver = FakeDevice(batch_size=16, max_delay=0.01, buckets=(4, 8, 16))
        out = await ver.verify_many(_items(3))
        assert out == [True] * 3
        buckets = [b for _, _, b, _ in ver.prep_log]
        assert buckets == [4], buckets
        await ver.close()

    asyncio.run(run())


def test_adaptive_bucket_coalesces_backlog():
    """A backlog deeper than batch_size must coalesce into the largest
    bucket it can fill: 16 queued items on a (4, 16) ladder go out as ONE
    16-lane dispatch, not four 4-lane ones."""

    async def run():
        ver = FakeDevice(batch_size=4, max_delay=10.0, buckets=(4, 16))
        out = await ver.verify_many(_items(16))
        assert out == [True] * 16
        assert ver.batches_dispatched == 1
        buckets = [b for _, _, b, _ in ver.prep_log]
        assert buckets == [16], buckets
        await ver.close()

    asyncio.run(run())


def test_close_releases_parked_acquirer_with_wedged_device():
    """A caller parked in _acquire when close() lands must get the
    'verifier closed' RuntimeError promptly — even while a wedged device
    holds an in-flight completion open (the old close drained completions
    BEFORE notifying, so a dead tunnel turned close into a global hang)."""

    async def run():
        ver = FakeDevice(
            batch_size=4, max_delay=0.001, max_queue=4, finish_s=0.4
        )
        # 6 batches: 4 wedge in the (serial) finish stage, the 5th blocks
        # the flusher on the depth gate, the 6th squats in the accumulator
        # holding ALL the capacity — so the next caller parks in _acquire
        first = asyncio.ensure_future(ver.verify_many(_items(24)))
        await asyncio.sleep(0.05)
        parked = asyncio.ensure_future(ver.verify_many(_items(4, tag=b"x-")))
        await asyncio.sleep(0.05)
        assert not parked.done()
        closer = asyncio.ensure_future(ver.close())
        # the parked caller must error out well before the 0.4s wedge ends
        with pytest.raises(RuntimeError, match="closed"):
            await asyncio.wait_for(asyncio.shield(parked), timeout=0.2)
        await closer
        await asyncio.gather(first, return_exceptions=True)

    asyncio.run(run())


def test_cancelled_caller_releases_reserved_capacity():
    """Cancelling a verify_many caller whose entries are still queued must
    evict them and return the reserved capacity (notify included), so the
    next caller is not starved by dead reservations."""

    async def run():
        # nothing ever flushes: batch_size is large and max_delay long
        ver = FakeDevice(batch_size=64, max_delay=30.0, max_queue=8)
        caller = asyncio.ensure_future(ver.verify_many(_items(6)))
        await asyncio.sleep(0.02)
        assert ver._cap_free == 2
        caller.cancel()
        await asyncio.gather(caller, return_exceptions=True)
        assert ver._cap_free == ver.max_queue, "cancelled capacity leaked"
        assert not ver._queue, "cancelled entries squat in the accumulator"
        # the freed capacity is usable immediately
        nxt = asyncio.ensure_future(ver.verify_many(_items(8, tag=b"y")))
        await asyncio.sleep(0.02)
        assert ver._cap_free == 0
        nxt.cancel()
        await asyncio.gather(nxt, return_exceptions=True)
        await ver.close()

    asyncio.run(run())


def test_pipeline_smoke_stats():
    """The CI smoke gate (scripts/ci.sh): 4 overlapped batches on the fake
    device; stats counters must report the batches, full occupancy, the
    per-stage timings, and ZERO leaked capacity."""

    async def run():
        ver = FakeDevice(batch_size=4, max_delay=0.001, finish_s=0.01)
        out = await ver.verify_many(_items(16))
        assert out == [True] * 16
        st = ver.stats()
        assert st["batches"] == 4
        assert st["signatures"] == 16
        assert st["batch_occupancy"] == 1.0
        assert st["padding_ratio"] == 0.0
        assert st["capacity_free"] == st["max_queue"], "leaked capacity"
        assert st["queue_depth"] == 0
        assert st["finish_ms_avg"] > 0.0
        assert st["avg_dispatch_ms"] > 0.0
        await ver.close()
        # close() must not disturb the drained counters
        assert ver.stats()["capacity_free"] == ver.max_queue

    asyncio.run(run())


def test_finish_packed_bitmask_roundtrip():
    """finish_packed's device-bitmask contract: a packed MSB-first bit
    vector unpacks to exactly the first n lanes' verdicts, for every
    alignment (n % 8 included)."""
    from at2_node_tpu.ops.ed25519 import _InFlight, finish_packed

    rng = np.random.default_rng(3)
    for n in (1, 5, 8, 12, 64, 129):
        verdicts = rng.integers(0, 2, size=n).astype(bool)
        bits = np.packbits(verdicts)  # MSB-first, same as jnp.packbits
        out = finish_packed(_InFlight(bits, None), n)
        assert out.dtype == bool and out.shape == (n,)
        assert (out == verdicts).all(), n
    # legacy handles (PoolVerifier's sharded output) still work: a plain
    # bool vector, possibly padded past n
    legacy = np.ones(16, dtype=bool)
    assert (finish_packed(legacy, 10) == np.ones(10, dtype=bool)).all()


def test_staging_pool_recycles_buffers():
    """The host staging pool must hand back released buffers instead of
    allocating fresh ones, and never grow past its cap."""
    from at2_node_tpu.ops import ed25519 as kernel

    with kernel._STAGING_LOCK:
        kernel._STAGING.pop(256, None)
    a = kernel._staging_acquire(256)
    b = kernel._staging_acquire(256)
    assert a is not b
    kernel._staging_release(a)
    assert kernel._staging_acquire(256) is a
    kernel._staging_release(a)
    kernel._staging_release(b)
    for _ in range(32):  # overfill: the pool must stay capped
        kernel._staging_release(np.empty((256, kernel.PACKED_WIDTH), np.uint8))
    with kernel._STAGING_LOCK:
        assert len(kernel._STAGING[256]) <= kernel._STAGING_CAP_PER_BUCKET
        kernel._STAGING.pop(256, None)
