"""Continuous-profiler tier tests (obs/profiler.py + /profilez + regress).

Four tiers, mirroring ISSUE 11's moving parts:

* PhaseAccounting — counter exactness under thread concurrency (the
  decomposition shares are only trustworthy if concurrent markers never
  lose a nanosecond), chained-segment disjointness, and the free ride
  through Registry.snapshot();
* StackSampler — start/stop/restart hygiene, duration self-stop, the
  bounded tree's ``(truncated)`` collapse, and byte-identical folded
  output regardless of insertion order (the CI determinism contract);
* EventLoopLagProbe — exact-zero lag under the sim virtual clock driven
  via probe_once (no standing timer, no SimScheduler deadlock), real
  measurements on a live loop;
* e2e — /profilez through the real PortMux (start/stop/folded/kill-
  switch), the /statusz build block, and regress.py verdicts over
  planted fixture artifacts (clean pass, planted regression, tunnel-
  state-incomparable rows skipped, schema violations).
"""

import asyncio
import itertools
import json
import threading

import pytest

from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.net.peers import Peer
from at2_node_tpu.node.config import Config, ObservabilityConfig
from at2_node_tpu.node.service import Service
from at2_node_tpu.obs import Registry
from at2_node_tpu.obs.profiler import (
    PHASES,
    PLANE_LEAF_PHASES,
    EventLoopLagProbe,
    PhaseAccounting,
    StackSampler,
    build_info,
)
from at2_node_tpu.sim.scheduler import SimClock, SimScheduler
from at2_node_tpu.tools import regress

_ports = itertools.count(26100)


def make_configs(n, **overrides):
    cfgs = [
        Config(
            node_address=f"127.0.0.1:{next(_ports)}",
            rpc_address=f"127.0.0.1:{next(_ports)}",
            sign_key=SignKeyPair.random(),
            network_key=ExchangeKeyPair.random(),
            **overrides,
        )
        for _ in range(n)
    ]
    for i, cfg in enumerate(cfgs):
        cfg.nodes = [
            Peer(o.node_address, o.network_key.public, o.sign_key.public)
            for j, o in enumerate(cfgs)
            if j != i
        ]
    return cfgs


# ------------------------------------------------------- phase accounting


class TestPhaseAccounting:
    def test_counters_exact_across_threads(self):
        reg = Registry()
        ph = PhaseAccounting(reg)
        threads, per = 8, 5000

        def work():
            for _ in range(per):
                ph.add_ns("rx_decode", 1)

        ts = [threading.Thread(target=work) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert ph.totals()["rx_decode"] == threads * per
        snap = reg.snapshot()
        assert snap["phase_rx_decode_ns"] == threads * per
        # the histogram splays into snapshot() too (per-segment latency)
        assert snap["phase_rx_decode_count"] == threads * per

    def test_add_chains_disjoint_segments(self):
        reg = Registry()
        ph = PhaseAccounting(reg)
        t0 = ph.t()
        t1 = ph.add("echo_apply", t0)
        t2 = ph.add("ready_deliver", t1)
        assert t0 <= t1 <= t2
        totals = ph.totals()
        # chained segments: each add() closes against a FRESH timestamp,
        # so the two accounts cover disjoint time
        assert totals["echo_apply"] + totals["ready_deliver"] <= t2 - t0
        assert totals["quorum_bitmap"] == 0

    def test_vocabulary_covers_the_planes(self):
        assert set(PLANE_LEAF_PHASES) < set(PHASES)
        assert "plane_total" in PHASES
        for off_plane in ("slot_gc", "commit_tail", "verifier_flush"):
            assert off_plane in PHASES
            assert off_plane not in PLANE_LEAF_PHASES

    def test_plane_total_reentrancy_accounts_once(self):
        """A drain cycle that re-enters the plane in-context (e.g. an
        rlc_ready_or_kick fallback driving another drain) must account
        its span ONCE: the nested begin_plane returns the -1 sentinel
        and its end_plane adds nothing."""
        reg = Registry()
        ph = PhaseAccounting(reg)
        t_outer = ph.begin_plane()
        assert t_outer >= 0
        t_inner = ph.begin_plane()  # re-entrant: must not double-count
        assert t_inner == -1
        ph.end_plane(t_inner)  # no-op account
        assert ph.totals()["plane_total"] == 0
        ph.end_plane(t_outer)
        outer_total = ph.totals()["plane_total"]
        assert outer_total > 0
        # fully unwound: the next cycle accounts again, from zero depth
        t2 = ph.begin_plane()
        assert t2 >= 0
        ph.end_plane(t2)
        assert ph.totals()["plane_total"] > outer_total

    def test_plane_total_depth_is_thread_local(self):
        """Shard executor threads each carry their own re-entrancy depth
        (contextvars): one thread's open plane span must not turn
        another thread's begin_plane into the nested sentinel."""
        reg = Registry()
        ph = PhaseAccounting(reg)
        t_outer = ph.begin_plane()
        assert t_outer >= 0
        seen = []

        def shard_cycle():
            t = ph.begin_plane()
            seen.append(t)
            ph.end_plane(t)

        th = threading.Thread(target=shard_cycle)
        th.start()
        th.join()
        assert seen and seen[0] >= 0  # NOT the nested sentinel
        ph.end_plane(t_outer)

    def test_shard_view_dual_writes(self):
        """ShardPhaseView: leaf marks land in BOTH the base aggregate
        (decomposition shares stay plane-wide) and the per-shard
        counter (phase_<p>_shard<i>_ns) on the plane registry."""
        base_reg = Registry()
        ph = PhaseAccounting(base_reg)
        plane_reg = Registry()
        v0 = ph.shard_view(0, plane_reg)
        v1 = ph.shard_view(1, plane_reg)
        v0.add_ns("echo_apply", 7)
        v1.add_ns("echo_apply", 5)
        v1.add_ns("ready_deliver", 3)
        assert ph.totals()["echo_apply"] == 12  # aggregate spans shards
        snap = plane_reg.snapshot()
        assert snap["phase_echo_apply_shard0_ns"] == 7
        assert snap["phase_echo_apply_shard1_ns"] == 5
        assert snap["phase_ready_deliver_shard1_ns"] == 3
        # begin/end_plane delegate to the base accounting (plane_total
        # stays an owner-loop aggregate, never per-shard)
        t = v0.begin_plane()
        assert v1.begin_plane() == -1  # same context: depth is shared
        v0.end_plane(t)
        assert ph.totals()["plane_total"] > 0


# ------------------------------------------------------------ stack sampler


def _stack(*labels, lineno=7):
    """Synthetic root-first stack from bare frame names."""
    return [(f"/x/{name}.py", name, lineno) for name in labels]


class TestStackSampler:
    def test_start_stop_restart_hygiene(self):
        s = StackSampler(hz=500.0)
        assert not s.running
        assert s.start() is True
        assert s.start() is False  # already running: no-op
        assert s.running
        s.stop()
        s.stop()  # idempotent
        assert not s.running
        assert s.start() is True  # restartable
        s.stop()

    def test_duration_self_stop(self):
        s = StackSampler(hz=500.0)
        assert s.start(duration=0.05) is True
        deadline = 5.0
        while s.running and deadline > 0:
            import time

            time.sleep(0.02)
            deadline -= 0.02
        assert not s.running
        assert s.stats()["samples"] > 0

    def test_sampling_captures_live_threads(self):
        s = StackSampler(hz=500.0)
        stop = threading.Event()

        def spin_target_fn():
            while not stop.is_set():
                sum(range(100))

        t = threading.Thread(target=spin_target_fn, daemon=True)
        t.start()
        try:
            s.start()
            import time

            time.sleep(0.3)
            s.stop()
        finally:
            stop.set()
            t.join()
        assert s.stats()["samples"] > 0
        assert "spin_target_fn" in s.folded()

    def test_bounded_tree_collapses_to_truncated(self):
        s = StackSampler(max_nodes=20)
        s.ingest([_stack(f"fn{i}") for i in range(100)])
        st = s.stats()
        # root + 19 distinct leaves + the (truncated) child
        assert st["nodes"] <= 21
        assert st["truncated_paths"] > 0
        assert "(truncated)" in s.folded()
        # reset() reclaims the budget
        s.reset()
        assert s.stats() == {
            "running": False, "samples": 0, "nodes": 1,
            "truncated_paths": 0, "hz": 97.0, "duration": None,
        }

    def test_folded_deterministic_across_insertion_order(self):
        stacks = [
            _stack("main", "worker", "decode"),
            _stack("main", "worker", "verify"),
            _stack("main", "gc"),
            _stack("main", "worker", "verify"),
        ]
        a, b = StackSampler(), StackSampler()
        for st in stacks:
            a.ingest([st])
        for st in reversed(stacks):
            b.ingest([st])
        assert a.folded() == b.folded()
        folded = a.folded()
        # leaf frames carry file:func:line, interior frames don't
        assert "main.py:main;worker.py:worker;verify.py:verify:7 2" in folded
        assert folded.splitlines()[0].endswith(" 2")  # count-descending
        # tree view is deterministic too and roots at the shared frame
        assert a.tree() == b.tree()
        assert a.tree()["children"][0]["name"] == "main.py:main"

    def test_folded_limit_and_validation(self):
        s = StackSampler()
        s.ingest([_stack("a"), _stack("b")])
        assert len(s.folded(limit=1).splitlines()) == 1
        with pytest.raises(ValueError):
            StackSampler(hz=0)
        with pytest.raises(ValueError):
            StackSampler(max_nodes=0)


# ------------------------------------------------------------- lag probe


class TestEventLoopLagProbe:
    def test_probe_once_exact_zero_under_sim_clock(self):
        loop = SimScheduler()
        try:
            clock = SimClock(loop)
            reg = Registry()
            probe = EventLoopLagProbe(reg, clock, interval=0.05)
            lag = loop.run_until_complete(probe.probe_once())
            # virtual sleeps are exact: zero overshoot, and the probe
            # never parks a standing timer that would blunt the
            # scheduler's deadlock detection
            assert lag == 0.0
            snap = reg.snapshot()
            assert snap["event_loop_lag_count"] == 1
            assert snap["event_loop_lag_p99_ms"] == 0.0
        finally:
            loop.close()

    async def test_standing_loop_measures_real_lag(self):
        from at2_node_tpu.clock import SYSTEM_CLOCK

        reg = Registry()
        probe = EventLoopLagProbe(reg, SYSTEM_CLOCK, interval=0.01)
        probe.start()
        await asyncio.sleep(0.08)
        await probe.stop()
        await probe.stop()  # idempotent
        count = reg.snapshot()["event_loop_lag_count"]
        assert count >= 1
        # stopped: no further observations accrue
        await asyncio.sleep(0.03)
        assert reg.snapshot()["event_loop_lag_count"] == count

    def test_interval_validation(self):
        with pytest.raises(ValueError):
            EventLoopLagProbe(Registry(), None, interval=0.0)


# ------------------------------------------------------------------- e2e


async def _get(addr, path):
    host, _, port = addr.rpartition(":")
    reader, writer = await asyncio.open_connection(host, int(port))
    try:
        writer.write(
            f"GET {path} HTTP/1.1\r\nHost: n\r\nConnection: close\r\n\r\n"
            .encode()
        )
        await writer.drain()
        raw = await reader.read()
    finally:
        writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split(b"\r\n", 1)[0].split(b" ")[1])
    ctype = ""
    for ln in head.split(b"\r\n")[1:]:
        if ln.lower().startswith(b"content-type:"):
            ctype = ln.split(b":", 1)[1].strip().decode()
    return status, ctype, body


class _Node:
    def __init__(self, **overrides):
        self.config = make_configs(1, **overrides)[0]

    async def __aenter__(self):
        self.service = await Service.start(self.config)
        return self

    async def __aexit__(self, *exc):
        await self.service.close()


class TestProfilezEndpoint:
    async def test_capture_cycle_through_real_mux(self):
        async with _Node() as node:
            addr = node.config.rpc_address

            # idle dump: JSON shape with build + phases + empty capture
            status, ctype, body = await _get(addr, "/profilez")
            assert status == 200 and ctype.startswith("application/json")
            doc = json.loads(body)
            assert set(doc) >= {
                "node", "build", "sampler", "phases", "folded", "tree",
            }
            assert set(doc["phases"]) == set(PHASES)
            assert doc["build"]["python"] == build_info()["python"]

            # start a long capture, confirm running, then stop it
            status, _, body = await _get(addr, "/profilez?start&duration=30")
            assert status == 200
            started = json.loads(body)
            assert started["started"] is True and started["running"]
            assert node.service.sampler.running
            # second start while running is a no-op
            _, _, body = await _get(addr, "/profilez?start")
            assert json.loads(body)["started"] is False
            await asyncio.sleep(0.05)
            status, _, body = await _get(addr, "/profilez?stop")
            assert status == 200
            stopped = json.loads(body)
            assert not stopped["running"] and stopped["samples"] > 0

            # folded text view of the finished capture
            status, ctype, body = await _get(addr, "/profilez?fmt=folded")
            assert status == 200 and ctype.startswith("text/plain")
            assert b" " in body  # "stack count" lines

    async def test_kill_switch_404s(self):
        async with _Node(
            observability=ObservabilityConfig(profilez=False)
        ) as node:
            status, _, body = await _get(
                node.config.rpc_address, "/profilez"
            )
            assert status == 404 and body == b"not found"

    async def test_statusz_build_block(self):
        async with _Node() as node:
            status, _, body = await _get(node.config.rpc_address, "/statusz")
            assert status == 200
            build = json.loads(body)["build"]
            assert build["python"] == build_info()["python"]
            assert len(build["config_hash"]) == 12
            assert build["uptime_s"] >= 0.0
            # the lag probe is live on a served node: its histogram
            # splays into stats once the first interval elapses
            await asyncio.sleep(0.15)
            _, _, body = await _get(node.config.rpc_address, "/statusz")
            stats = json.loads(body)["stats"]
            assert stats.get("event_loop_lag_count", 0) >= 1


# ------------------------------------------------------------ regress.py


def _bench_doc(value, tunnel=..., device="cpu"):
    parsed = {
        "metric": "committed_tx_per_sec",
        "unit": "tx/s",
        "value": value,
        "device": device,
    }
    if tunnel is not ...:
        parsed["tunnel_live_at_write"] = tunnel
    return {"cmd": "python bench.py", "rc": 0, "tail": "ok",
            "parsed": parsed}


def _write(tmp_path, name, doc):
    (tmp_path / name).write_text(json.dumps(doc))


class TestRegressSentry:
    def test_clean_pass_and_determinism(self, tmp_path, capsys):
        _write(tmp_path, "BENCH_r01.json", _bench_doc(100.0, tunnel=False))
        _write(tmp_path, "BENCH_r02.json", _bench_doc(103.0, tunnel=False))
        assert regress.main(["--dir", str(tmp_path)]) == 0
        out1 = capsys.readouterr().out
        assert "REGRESSIONS: none" in out1
        assert "ok (+3.0% vs r01)" in out1
        assert regress.main(["--dir", str(tmp_path)]) == 0
        assert capsys.readouterr().out == out1  # byte-identical

    def test_planted_regression_exits_nonzero(self, tmp_path, capsys):
        _write(tmp_path, "BENCH_r01.json", _bench_doc(100.0, tunnel=False))
        _write(tmp_path, "BENCH_r02.json", _bench_doc(70.0, tunnel=False))
        assert regress.main(["--dir", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION (-30.0% vs r01)" in out
        assert "REGRESSIONS: 1" in out

    def test_in_band_noise_passes(self, tmp_path, capsys):
        _write(tmp_path, "BENCH_r01.json", _bench_doc(100.0, tunnel=False))
        _write(tmp_path, "BENCH_r02.json", _bench_doc(90.0, tunnel=False))
        assert regress.main(["--dir", str(tmp_path)]) == 0
        assert "ok (-10.0% vs r01)" in capsys.readouterr().out
        # same drop with a tighter band IS a regression
        assert regress.main(["--dir", str(tmp_path), "--band", "0.05"]) == 1
        capsys.readouterr()

    def test_tunnel_mismatch_rows_are_skipped(self, tmp_path, capsys):
        # cpu-fallback capture (tunnel False) vs live-chip capture
        # (tunnel True): a 10x "drop" that must NOT be judged
        _write(tmp_path, "BENCH_r01.json", _bench_doc(1000.0, tunnel=True))
        _write(tmp_path, "BENCH_r02.json", _bench_doc(100.0, tunnel=False))
        assert regress.main(["--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "skipped (no comparable baseline" in out
        # legacy captures (no flag at all) only compare to legacy ones
        _write(tmp_path, "BENCH_r03.json", _bench_doc(100.0))
        _write(tmp_path, "BENCH_r04.json", _bench_doc(50.0))
        assert regress.main(["--dir", str(tmp_path)]) == 1
        assert "REGRESSION (-50.0% vs r03)" in capsys.readouterr().out

    def test_schema_violation_exits_2(self, tmp_path, capsys):
        doc = _bench_doc(100.0, tunnel=False)
        del doc["parsed"]["value"]
        _write(tmp_path, "BENCH_r01.json", doc)
        assert regress.main(["--dir", str(tmp_path)]) == 2
        assert "SCHEMA ERROR" in capsys.readouterr().err
        (tmp_path / "BENCH_r01.json").write_text("{not json")
        assert regress.main(["--dir", str(tmp_path)]) == 2
        capsys.readouterr()

    def test_empty_dir_exits_2(self, tmp_path, capsys):
        assert regress.main(["--dir", str(tmp_path)]) == 2
        capsys.readouterr()

    def test_scale_family_lower_better_direction(self, tmp_path, capsys):
        def scale(commit_seconds):
            return {
                "net": {
                    "nodes": 4, "clients": 8, "submitted": 400,
                    "committed": 400, "committed_tx_per_sec": 100.0,
                    "commit_seconds": commit_seconds,
                },
                "replay": {"status": "ok"},
            }

        _write(tmp_path, "SCALE_r01.json", scale(10.0))
        _write(tmp_path, "SCALE_r02.json", scale(20.0))  # latency DOUBLED
        assert regress.main(["--dir", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "scale/net.commit_seconds" in out
        assert "REGRESSION (-100.0% vs r01)" in out

    def test_real_repo_artifacts_load_clean(self, capsys):
        # the actual banked artifact set must always satisfy its schemas
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        assert regress.main(["--dir", repo]) == 0
        capsys.readouterr()
