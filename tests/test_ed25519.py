"""Batched ed25519 verifier tests: curve-op oracles, RFC 8032 vector,
differential fuzzing against the CPU implementation (OpenSSL via
`cryptography`), and negative/malformed cases.

Mirrors SURVEY.md §4's prescription: RFC-8032 vectors + CPU-vs-TPU
differential tests for the verifier.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from at2_node_tpu.crypto.keys import SignKeyPair, verify_one
from at2_node_tpu.ops import ed25519 as v
from at2_node_tpu.ops import edwards as ed
from at2_node_tpu.ops import field as fe

RNG = np.random.default_rng(0xED25519 % 2**32)

j_add = jax.jit(ed.add)
j_double = jax.jit(ed.double)
j_decompress = jax.jit(ed.decompress)


# -- host-side int oracle --


def scalar_mult_ints(k, point):
    acc = (0, 1)
    base = point
    while k:
        if k & 1:
            acc = ed.affine_add_ints(acc, base)
        base = ed.affine_add_ints(base, base)
        k >>= 1
    return acc


def test_base_point_on_curve():
    x, y = ed.BX_INT, ed.BY_INT
    lhs = (-x * x + y * y) % fe.P
    rhs = (1 + fe.D_INT * x * x % fe.P * y * y) % fe.P
    assert lhs == rhs


def test_add_double_match_int_oracle():
    pts_int = [scalar_mult_ints(k, (ed.BX_INT, ed.BY_INT)) for k in (1, 2, 5, 77)]
    pts = jnp.asarray(np.stack([ed.point_from_ints(x, y) for x, y in pts_int]))
    doubled = j_double(pts)
    for i, (x, y) in enumerate(pts_int):
        assert ed.point_to_ints(np.asarray(doubled)[i]) == ed.affine_add_ints(
            (x, y), (x, y)
        )
    summed = j_add(pts, jnp.asarray(ed.BASE))
    for i, (x, y) in enumerate(pts_int):
        assert ed.point_to_ints(np.asarray(summed)[i]) == ed.affine_add_ints(
            (x, y), (ed.BX_INT, ed.BY_INT)
        )
    # add identity is a no-op; add inverse gives identity
    ident = j_add(pts, jnp.asarray(ed.IDENTITY))
    for i, (x, y) in enumerate(pts_int):
        assert ed.point_to_ints(np.asarray(ident)[i]) == (x, y)


def test_base_table():
    for k in range(16):
        assert ed.point_to_ints(ed.BASE_TABLE[k]) == scalar_mult_ints(
            k, (ed.BX_INT, ed.BY_INT)
        )


def _compress_int_point(x, y):
    enc = y | ((x & 1) << 255)
    return np.frombuffer(enc.to_bytes(32, "little"), dtype=np.uint8)


def test_decompress_valid_points():
    ks = [1, 2, 3, 8, 127, 2**31, L_minus_one := v.L - 1]
    pts = [scalar_mult_ints(k, (ed.BX_INT, ed.BY_INT)) for k in ks]
    raw = jnp.asarray(np.stack([_compress_int_point(x, y) for x, y in pts]))
    point, ok = j_decompress(raw)
    assert np.asarray(ok).all()
    for i, (x, y) in enumerate(pts):
        assert ed.point_to_ints(np.asarray(point)[i]) == (x, y)


def test_decompress_rejects_bad_encodings():
    bad = np.zeros((3, 32), dtype=np.uint8)
    # y = p (non-canonical encoding of 0)
    bad[0] = np.frombuffer(fe.P.to_bytes(32, "little"), dtype=np.uint8)
    # y = 2^255 - 1 without sign bit is also >= p
    bad[1] = np.frombuffer(((1 << 255) - 1).to_bytes(32, "little"), dtype=np.uint8)
    bad[1, 31] &= 0x7F
    # y whose x^2 is non-square: y=2 -> u/v must be non-square (checked below)
    bad[2, 0] = 2
    _, ok = j_decompress(jnp.asarray(bad))
    ok = np.asarray(ok)
    assert not ok[0] and not ok[1]
    # confirm expectation for y=2 with the int oracle
    y = 2
    u = (y * y - 1) % fe.P
    vv = (fe.D_INT * y * y + 1) % fe.P
    x2 = u * pow(vv, fe.P - 2, fe.P) % fe.P
    if pow(x2, (fe.P - 1) // 2, fe.P) != 1:
        assert not ok[2]
    else:
        assert ok[2]


@pytest.mark.slow
def test_double_scalar_mul_vs_oracle():
    j_dsm = jax.jit(ed.double_scalar_mul_vs_base)
    ks_a = [3, 2**64 + 5]
    ks_b = [7, 2**200 + 11]
    a_pts = [scalar_mult_ints(9, (ed.BX_INT, ed.BY_INT))] * 2
    a = jnp.asarray(np.stack([ed.point_from_ints(x, y) for x, y in a_pts]))

    def win(k):
        return v._windows_msb_first(
            np.frombuffer(k.to_bytes(32, "little"), dtype=np.uint8)[None, :]
        )[0]

    aw = jnp.asarray(np.stack([win(k) for k in ks_a]))
    bw = jnp.asarray(np.stack([win(k) for k in ks_b]))
    out = j_dsm(a, aw, bw)
    for i in range(2):
        expect = ed.affine_add_ints(
            scalar_mult_ints(ks_a[i], a_pts[i]),
            scalar_mult_ints(ks_b[i], (ed.BX_INT, ed.BY_INT)),
        )
        assert ed.point_to_ints(np.asarray(out)[i]) == expect


# -- full verifier --


def _sign_many(n, msg_len=32):
    keys = [SignKeyPair.random() for _ in range(n)]
    msgs = [RNG.bytes(msg_len) for _ in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return [k.public for k in keys], msgs, sigs


def test_verify_valid_batch():
    pks, msgs, sigs = _sign_many(16)
    assert v.verify_batch(pks, msgs, sigs).all()


def test_verify_rfc8032_vector1():
    # RFC 8032 §7.1 TEST 1 (empty message); cross-checked against the CPU
    # implementation to guard against transcription errors.
    sk = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    keypair = SignKeyPair(sk)
    pk = keypair.public
    assert pk == bytes.fromhex(
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    sig = keypair.sign(b"")
    assert sig == bytes.fromhex(
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e065224901555fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    assert v.verify_batch([pk], [b""], [sig]).all()


def test_verify_rejects_corruptions():
    pks, msgs, sigs = _sign_many(4)
    # corrupt R, corrupt S, wrong message, wrong key
    bad_sig_r = bytes([sigs[0][0] ^ 1]) + sigs[0][1:]
    bad_sig_s = sigs[1][:32] + bytes([sigs[1][32] ^ 1]) + sigs[1][33:]
    cases_pk = [pks[0], pks[1], pks[2], pks[0]]
    cases_msg = [msgs[0], msgs[1], b"not the message", msgs[3]]
    cases_sig = [bad_sig_r, bad_sig_s, sigs[2], sigs[3]]
    out = v.verify_batch(cases_pk, cases_msg, cases_sig)
    assert not out.any()
    # CPU oracle agrees
    for pk, m, s in zip(cases_pk, cases_msg, cases_sig):
        assert not verify_one(pk, m, s)


def test_verify_rejects_high_s():
    pks, msgs, sigs = _sign_many(1)
    s = int.from_bytes(sigs[0][32:], "little")
    high = sigs[0][:32] + (s + v.L).to_bytes(32, "little")
    assert not v.verify_batch(pks, msgs, [high]).any()


def test_verify_malformed_lengths():
    pks, msgs, sigs = _sign_many(2)
    out = v.verify_batch(
        [pks[0], pks[1][:16]], msgs, [sigs[0][:20], sigs[1]]
    )
    assert not out.any()


def test_verify_mixed_batch_with_padding():
    pks, msgs, sigs = _sign_many(5)
    msgs[2] = b"tampered"
    out = v.verify_batch(pks, msgs, sigs)  # pads to the 64-bucket
    assert out.tolist() == [True, True, False, True, True]


def test_verify_differential_fuzz():
    n = 32
    pks, msgs, sigs = _sign_many(n, msg_len=7)
    # randomly corrupt ~half
    expect = []
    for i in range(n):
        if RNG.random() < 0.5:
            which = RNG.integers(0, 3)
            if which == 0:
                sigs[i] = bytes([sigs[i][0] ^ 0x40]) + sigs[i][1:]
            elif which == 1:
                msgs[i] = msgs[i] + b"x"
            else:
                pks[i] = SignKeyPair.random().public
        expect.append(verify_one(pks[i], msgs[i], sigs[i]))
    got = v.verify_batch(pks, msgs, sigs)
    assert got.tolist() == expect
