"""[wan] WAN-finality lever tests (ISSUE 14).

Three properties carry the feature:

* knobs OFF is the DEFAULT schedule — a `[wan]`-less config and an
  all-defaults WanConfig produce byte-identical wire traces, so every
  banked hash in the repo survives the feature landing;
* knobs ON is still deterministic — same seed, same cell, same hash on
  every run, at one plane shard and at four;
* the overlap lever actually overlaps — with ``overlap_ready`` on, the
  phase-overlap report shows Ready frames emitted BEFORE the local echo
  quorum formed (negative gap), which is the long-haul round the WAN
  p99 sheds.
"""

from __future__ import annotations

import pytest

from at2_node_tpu.node.config import Config, WanConfig
from at2_node_tpu.sim.scenarios import run_cell
from at2_node_tpu.tools.trace_collect import phase_overlap

# small wan3 cell: 3 nodes over the 3-region latency matrix, enough
# traffic for batched and per-tx paths both to fire, fast enough for
# the fast tier
_CELL = dict(
    nodes=3, n_clients=3, n_tx=8, duration=3.0, settle_horizon=60.0,
)


def _cell(*, wan: bool, shards: int = 1, seed: int = 21) -> dict:
    return run_cell(
        seed, "wan3", "steady", "none",
        wan=wan, plane_shards=shards, **_CELL,
    )


class TestWanConfig:
    def test_toml_roundtrip(self):
        import dataclasses

        from tests.test_batching import make_configs

        cfg = make_configs(2)[0]
        cfg.wan = WanConfig(
            overlap_ready=True, region_fanout=True, region="eu-west",
            verify_ahead=True, eager_broker=True,
        )
        cfg.nodes[0] = dataclasses.replace(cfg.nodes[0], region="us-east")
        text = cfg.dumps()
        assert "[wan]" in text
        loaded = Config.loads(text)
        assert loaded.wan == cfg.wan
        assert loaded.nodes[0].region == "us-east"

    def test_default_omitted_from_toml(self):
        from tests.test_batching import make_configs

        cfg = make_configs(1)[0]
        assert "[wan]" not in cfg.dumps()

    def test_region_validated(self):
        with pytest.raises(ValueError):
            WanConfig(region=3)  # type: ignore[arg-type]


class TestWanDeterminism:
    def test_off_is_the_default_schedule(self):
        # wan=False must not merely be self-consistent: it must be THE
        # default schedule, indistinguishable from a node that never
        # heard of the [wan] table
        base = _cell(wan=False)
        again = _cell(wan=False)
        assert base["trace_hash"] == again["trace_hash"]
        assert base["committed"] == base["offered"]
        assert not base["violations"]

    def test_on_deterministic_shards1(self):
        one = _cell(wan=True)
        two = _cell(wan=True)
        assert one["trace_hash"] == two["trace_hash"]
        assert one["committed"] == one["offered"]
        assert not one["violations"]
        assert one["slo"]["ok"]

    def test_on_deterministic_shards4(self):
        one = _cell(wan=True, shards=4)
        two = _cell(wan=True, shards=4)
        assert one["trace_hash"] == two["trace_hash"]
        assert one["committed"] == one["offered"]
        assert not one["violations"]

    def test_off_deterministic_shards4(self):
        one = _cell(wan=False, shards=4)
        two = _cell(wan=False, shards=4)
        assert one["trace_hash"] == two["trace_hash"]
        assert one["committed"] == one["offered"]

    def test_knobs_change_the_schedule(self):
        # region fanout reorders sends and overlap adds frames: the ON
        # trace must differ from OFF (this is exactly why the knobs
        # default off — hash compatibility is a property of the default
        # path, not of the feature)
        assert (
            _cell(wan=False)["trace_hash"] != _cell(wan=True)["trace_hash"]
        )


class TestPhaseOverlap:
    def test_overlap_piggybacks_ready(self):
        cell = run_cell(
            21, "wan3", "steady", "none",
            wan=True, capture_trace=True, **_CELL,
        )
        report = phase_overlap(cell["stitched"])
        assert report["piggybacked"] > 0
        assert report["gap_min_ms"] < 0.0

    def test_serial_path_never_negative(self):
        cell = run_cell(
            21, "wan3", "steady", "none",
            wan=False, capture_trace=True, **_CELL,
        )
        report = phase_overlap(cell["stitched"])
        assert report["spans"] > 0
        assert report["piggybacked"] == 0
        assert report["gap_min_ms"] >= 0.0

    def test_wan_cell_latency_beats_serial(self):
        # the levers must MEASURABLY move commit latency on the WAN
        # topology, not just reorder frames
        off = _cell(wan=False)
        on = _cell(wan=True)
        assert on["latency_p99_ms"] < off["latency_p99_ms"]
