"""Durable sharded store tests (at2_node_tpu/store, ISSUE 9).

Pins the crash-safety contract of the incremental checkpoint subsystem:

* the WAL line format round-trips every record kind and replay stops
  (silently) at a torn or corrupted tail — the only state a crash can
  leave is an intact prefix;
* commit -> flush -> reopen reproduces the exact ledger state, and the
  manifest carries the small state alongside it (client directory,
  recent ring, broadcast-safety watermarks, distilled-batch dedup
  window, membership epoch, parked payloads);
* flush cost is proportional to the DELTA: a quiet shard is carried
  forward by filename, never rewritten;
* a crash injected at EVERY durability step (mid-WAL-append, between
  segment writes, before/after the manifest rename) leaves a store
  that reopens to a consistent state — the committed prefix;
* the one-shot migration: a legacy monolithic checkpoint
  (ledger/checkpoint.py) seeds an uninitialized store exactly once,
  and a service configured with BOTH paths restores through the store
  (accounts, sequence gate, and the PR-7 client directory intact).
"""

import hashlib
import itertools
import json
import os

import pytest

from at2_node_tpu.broadcast.messages import Payload
from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.ledger import checkpoint
from at2_node_tpu.ledger.accounts import Accounts
from at2_node_tpu.ledger.recent import RecentTransactions
from at2_node_tpu.node.config import CheckpointConfig, Config, StoreConfig
from at2_node_tpu.node.service import Service
from at2_node_tpu.store import InjectedCrash, ShardedStore, WalRecord
from at2_node_tpu.store.manifest import read_manifest
from at2_node_tpu.store.wal import encode_line, replay, wal_name
from at2_node_tpu.types import ThinTransaction

_ports = itertools.count(21500)


def _kp(tag: str) -> SignKeyPair:
    return SignKeyPair(hashlib.sha256(f"store-test-{tag}".encode()).digest())


def _payload(kp: SignKeyPair, seq: int, amount: int = 10) -> Payload:
    return Payload.create(kp, seq, ThinTransaction(b"r" * 32, amount))


def _commit(store: ShardedStore, kp: SignKeyPair, seq: int,
            amount: int = 10) -> Payload:
    p = _payload(kp, seq, amount)
    store.note_commit(
        p,
        sender_seq=seq,
        sender_balance=100_000 - seq * amount,
        recipient_balance=100_000 + seq * amount,
    )
    return p


class TestWal:
    def test_record_kinds_roundtrip(self, tmp_path):
        path = str(tmp_path / "wal.log")
        records = [
            WalRecord("aa" * 70, 3, 970, 1030, True),
            WalRecord("bb" * 70, 4, 960, None, False),  # failed/self: no rb
            WalRecord.parked("cc" * 70),
            WalRecord.unparked("cc" * 70),
        ]
        with open(path, "wb") as fp:
            for r in records:
                fp.write(encode_line(r))
        assert list(replay(path)) == records

    def test_torn_tail_stops_replay(self, tmp_path):
        path = str(tmp_path / "wal.log")
        good = [WalRecord(f"{i:02x}" * 70, i, 100 - i, None, True)
                for i in range(1, 4)]
        raw = b"".join(encode_line(r) for r in good)
        # a torn last line: half the bytes a crashed append would leave
        tail = encode_line(WalRecord("ee" * 70, 9, 1, None, True))
        with open(path, "wb") as fp:
            fp.write(raw + tail[: len(tail) // 2])
        assert list(replay(path)) == good

    def test_corrupt_checksum_stops_replay(self, tmp_path):
        path = str(tmp_path / "wal.log")
        good = WalRecord("ab" * 70, 1, 99, None, True)
        bad = bytearray(encode_line(WalRecord("cd" * 70, 2, 98, None, True)))
        bad[20] ^= 0xFF  # flip a body byte; the crc header goes stale
        with open(path, "wb") as fp:
            fp.write(encode_line(good) + bytes(bad))
        assert list(replay(path)) == [good]

    def test_missing_file_replays_empty(self, tmp_path):
        assert list(replay(str(tmp_path / "absent.log"))) == []


class TestStoreLifecycle:
    def test_flush_reopen_roundtrip_with_meta(self, tmp_path):
        d = str(tmp_path / "store")
        store = ShardedStore.open(d, n_shards=4, sync="always")
        kp_a, kp_b = _kp("a"), _kp("b")
        for seq in range(1, 4):
            _commit(store, kp_a, seq)
        _commit(store, kp_b, 1)
        store.set_meta(
            directory_rows=[[7, kp_a.public.hex()]],
            recent_rows=[],
            watermarks={"tx": {kp_a.public.hex(): 3}, "batch": {}},
            distill_seen=[[12, 34]],
            epoch=2,
        )
        stats = store.flush()
        assert stats is not None and stats["gen"] == 1
        expected = store.accounts_state()
        store.close()

        loaded = ShardedStore.open(d, n_shards=4, sync="always")
        assert loaded.gen == 1
        assert loaded.accounts_state() == expected
        assert loaded.history_count() == 4
        assert loaded.directory_rows == [[7, kp_a.public.hex()]]
        assert loaded.watermarks["tx"] == {kp_a.public.hex(): 3}
        assert loaded.distill_seen == [[12, 34]]
        assert loaded.epoch == 2
        assert loaded.wal_replayed == 0  # flush rotated the log
        assert loaded.segments_loaded == stats["segments_written"]
        loaded.close()

    def test_wal_replay_recovers_unflushed_commits(self, tmp_path):
        d = str(tmp_path / "store")
        store = ShardedStore.open(d, n_shards=4, sync="always")
        kp = _kp("replay")
        store.flush(force=True)  # commit gen 1, then crash before flush 2
        for seq in range(1, 6):
            _commit(store, kp, seq)
        expected = store.accounts_state()
        store.close()  # no flush: state lives only in the WAL

        loaded = ShardedStore.open(d, n_shards=4, sync="always")
        assert loaded.wal_replayed == 5
        assert loaded.accounts_state() == expected
        assert loaded.history_count() == 5
        # replayed records are dirty again: the next flush folds them
        # into segments and a third open needs no replay at all
        assert loaded.flush() is not None
        loaded.close()
        third = ShardedStore.open(d, n_shards=4, sync="always")
        assert third.wal_replayed == 0
        assert third.accounts_state() == expected
        third.close()

    def test_incremental_flush_writes_only_dirty_shards(self, tmp_path):
        d = str(tmp_path / "store")
        store = ShardedStore.open(d, n_shards=8, sync="always")
        senders = [_kp(f"delta-{i}") for i in range(12)]
        for kp in senders:
            _commit(store, kp, 1)
        full = store.flush()
        assert full is not None and full["segments_written"] > 2

        _commit(store, senders[0], 2)
        delta = store.flush()
        assert delta is not None
        # one sender touches its own shard + the shared recipient's
        assert delta["segments_written"] <= 2
        assert delta["segment_bytes"] < full["segment_bytes"]
        # clean shards carry forward by filename in the manifest
        doc = read_manifest(d)
        assert len(doc["segments"]) == full["segments_written"]
        store.close()

    def test_parked_payloads_survive_crash_and_rotation(self, tmp_path):
        d = str(tmp_path / "store")
        store = ShardedStore.open(d, n_shards=4, sync="always")
        kp = _kp("parked")
        p2, p3 = _payload(kp, 2), _payload(kp, 3)
        store.note_parked(p2)
        store.note_parked(p3)
        store.note_parked(p2)  # idempotent
        assert store.parked_count() == 2
        store.close()  # crash before any flush: only the WAL has them

        loaded = ShardedStore.open(d, n_shards=4, sync="always")
        assert [p.sequence for p in loaded.iter_parked()] == [2, 3]
        # commit prunes its own parked record; flush rotates the WAL so
        # survival now depends on the manifest's parked list
        _commit(loaded, kp, 2)
        assert loaded.parked_count() == 1
        loaded.flush()
        loaded.close()

        again = ShardedStore.open(d, n_shards=4, sync="always")
        assert [p.sequence for p in again.iter_parked()] == [3]
        again.note_unparked(_payload(kp, 3))
        assert again.parked_count() == 0
        again.close()

    def test_parked_cap_evicts_oldest(self, tmp_path):
        from at2_node_tpu.store.sharded import PARKED_CAP

        store = ShardedStore.open(str(tmp_path / "store"), n_shards=2)
        for i in range(PARKED_CAP + 5):
            store._fold(WalRecord.parked(f"{i:08x}"), mark_dirty=False)
        assert store.parked_count() == PARKED_CAP
        assert next(iter(store._parked)) == f"{5:08x}"  # oldest 5 gone
        store.close()


class TestLegacyMigration:
    def _legacy_doc(self, kp: SignKeyPair) -> dict:
        return {
            "version": 1,
            "accounts": {kp.public.hex(): [3, 97_000], "ff" * 32: [0, 103_000]},
            "recent": [],
            "directory": [[5, kp.public.hex()]],
        }

    def test_one_shot_migration(self, tmp_path):
        d = str(tmp_path / "store")
        kp = _kp("legacy")
        store = ShardedStore.open(
            d, n_shards=4, legacy_checkpoint=self._legacy_doc(kp)
        )
        assert store.migrated is True
        assert store.gen == 1  # the migration flush committed
        assert store.accounts_state()[kp.public.hex()] == [3, 97_000]
        assert store.directory_rows == [[5, kp.public.hex()]]
        store.close()

        # once a manifest exists the legacy document is IGNORED — a
        # stale monolithic file must never roll the store backwards
        stale = self._legacy_doc(kp)
        stale["accounts"][kp.public.hex()] = [1, 1]
        again = ShardedStore.open(d, n_shards=4, legacy_checkpoint=stale)
        assert again.migrated is False
        assert again.accounts_state()[kp.public.hex()] == [3, 97_000]
        again.close()

    def test_bad_legacy_version_raises(self, tmp_path):
        with pytest.raises(ValueError):
            ShardedStore.open(
                str(tmp_path / "store"), legacy_checkpoint={"version": 99}
            )


class TestCrashAtomicity:
    """Satellite 4: inject a crash at every durability step and prove
    each intermediate on-disk state reopens consistent. The WAL append
    precedes every flush step, so from ``wal:post_append`` on, the
    committed prefix is FIXED — every flush-time crash must reopen to
    the identical full state."""

    def _labels(self, tmp_path) -> list:
        """Dry-run a commit+flush with a recording failpoint to learn
        the exact label sequence (shard count dependent)."""
        seen = []
        store = ShardedStore.open(
            str(tmp_path / "probe"), n_shards=4, sync="always"
        )
        store.failpoint = seen.append
        _commit(store, _kp("probe"), 1)
        store.flush()
        store.close()
        return seen

    def test_failpoint_walk_every_step(self, tmp_path):
        labels = self._labels(tmp_path)
        assert "wal:pre_append" in labels
        assert "flush:pre_manifest" in labels
        assert "flush:post_manifest" in labels

        for n, crash_label in enumerate(labels):
            d = str(tmp_path / f"walk-{n}")
            store = ShardedStore.open(d, n_shards=4, sync="always")
            kp = _kp("walk")
            _commit(store, kp, 1)
            store.flush()  # a committed generation to fall back on
            baseline = store.accounts_state()

            hits = iter(range(len(labels)))

            def fp(label, _crash=crash_label, _hits=hits):
                if label == _crash and next(_hits) is not None:
                    raise InjectedCrash(label)

            store.failpoint = fp
            crashed = False
            try:
                _commit(store, kp, 2)
                store.flush()
            except InjectedCrash:
                crashed = True
            store.failpoint = None
            store.close()
            assert crashed, f"failpoint {crash_label!r} never fired"

            loaded = ShardedStore.open(d, n_shards=4, sync="always")
            state = loaded.accounts_state()
            if crash_label == "wal:pre_append":
                # the only step where the slot is legitimately lost:
                # nothing durable happened yet
                assert state == baseline
            else:
                # WAL append landed -> the slot survives no matter where
                # inside the flush the crash hit
                assert state[kp.public.hex()][0] == 2, (crash_label, state)
            # the reopened store must be fully writable: a post-crash
            # commit + flush advances a (single, consistent) generation
            _commit(loaded, kp, state[kp.public.hex()][0] + 1)
            assert loaded.flush() is not None
            loaded.close()

    def test_crashed_flush_does_not_leak_wal_fd(self, tmp_path):
        d = str(tmp_path / "store")
        store = ShardedStore.open(d, n_shards=4, sync="always")
        _commit(store, _kp("fd"), 1)

        def fp(label):
            if label == "flush:pre_manifest":
                raise InjectedCrash(label)

        store.failpoint = fp
        with pytest.raises(InjectedCrash):
            store.flush()
        store.failpoint = None
        # the aborted flush's replacement WAL was closed; the original
        # keeps appending and a retried flush commits normally
        _commit(store, _kp("fd"), 2)
        assert store.flush()["gen"] >= 1
        store.close()

    def test_orphans_swept_after_crash_recovery(self, tmp_path):
        d = str(tmp_path / "store")
        store = ShardedStore.open(d, n_shards=4, sync="always")
        _commit(store, _kp("orphan"), 1)

        def fp(label):
            if label == "flush:pre_manifest":
                raise InjectedCrash(label)

        store.failpoint = fp
        with pytest.raises(InjectedCrash):
            store.flush()  # wrote gen-1 segments the manifest never saw
        store.failpoint = None
        store.close()

        loaded = ShardedStore.open(d, n_shards=4, sync="always")
        loaded.close()
        doc = read_manifest(d)
        referenced = set(doc["segments"].values()) | {doc["wal"]}
        on_disk = {
            f for f in os.listdir(d)
            if f.startswith(("segment-", "wal-"))
        }
        assert on_disk == referenced  # the uncommitted generation is gone


class TestServiceMigration:
    """Satellite 1 at service level: a node configured with BOTH the
    legacy [checkpoint] path and the new [store] dir restores the old
    monolithic snapshot through the store — balances, the sequence
    gate, and the PR-7 client directory all intact."""

    @pytest.mark.asyncio
    async def test_service_migrates_monolithic_checkpoint(self, tmp_path):
        ckpt_path = str(tmp_path / "legacy.ckpt")
        sender = SignKeyPair.random()

        # a legacy-format snapshot written by the old checkpoint path
        accounts, recent = Accounts(), RecentTransactions()
        await accounts.transfer(sender.public, 1, b"\x02" * 32, 250)
        doc = await checkpoint.snapshot(accounts, recent)
        doc["directory"] = [["9", sender.public.hex()]]
        checkpoint.write_atomic(ckpt_path, doc)

        def make_config():
            return Config(
                node_address=f"127.0.0.1:{next(_ports)}",
                rpc_address=f"127.0.0.1:{next(_ports)}",
                sign_key=SignKeyPair.random(),
                network_key=ExchangeKeyPair.random(),
                checkpoint=CheckpointConfig(path=ckpt_path, interval=60.0),
                store=StoreConfig(
                    dir=str(tmp_path / "store"), sync="always", shards=4
                ),
            )

        service = await Service.start(make_config())
        try:
            assert service.recovery.migrated is True
            assert service.store.migrated is True
            async with Client(f"http://{service.config.rpc_address}") as c:
                assert await c.get_balance(sender.public) == 99_750
                assert await c.get_last_sequence(sender.public) == 1
            # the PR-7 directory round-trip keeps working through the
            # manifest instead of the monolithic document
            assert service.directory.export() == [["9", sender.public.hex()]]
            await service._store_flush()
        finally:
            await service.close()

        # second restart: manifest exists now, migration must NOT rerun
        service2 = await Service.start(make_config())
        try:
            assert service2.recovery.migrated is False
            async with Client(f"http://{service2.config.rpc_address}") as c:
                assert await c.get_balance(sender.public) == 99_750
            assert service2.directory.export() == [["9", sender.public.hex()]]
            sz = service2.statusz()
            assert sz["recovery"]["state"] == "live"
            assert json.dumps(sz, default=float)  # surface stays JSON-able
        finally:
            await service2.close()
