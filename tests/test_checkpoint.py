"""Checkpoint/resume tests: ledger survives a node restart.

The reference has NO persistence — "store state on disk to restart after
crash" is an open roadmap item (`/root/reference/README.md:52`); these
tests pin this build's implementation of it."""

import asyncio
import itertools
import json

import pytest

from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.ledger import checkpoint
from at2_node_tpu.ledger.accounts import Accounts
from at2_node_tpu.ledger.recent import RecentTransactions
from at2_node_tpu.node.config import CheckpointConfig, Config
from at2_node_tpu.node.service import Service
from at2_node_tpu.types import ThinTransaction, TransactionState

_ports = itertools.count(20500)


class TestSnapshotRoundtrip:
    @pytest.mark.asyncio
    async def test_accounts_and_ring_roundtrip(self, tmp_path):
        accounts, recent = Accounts(), RecentTransactions()
        alice, bob = b"\x01" * 32, b"\x02" * 32
        await accounts.transfer(alice, 1, bob, 500)
        await recent.put(alice, 1, ThinTransaction(bob, 500))
        await recent.update(alice, 1, TransactionState.SUCCESS)

        path = str(tmp_path / "ledger.json")
        await checkpoint.save(path, accounts, recent)

        restored_a, restored_r = Accounts(), RecentTransactions()
        assert await checkpoint.load(path, restored_a, restored_r) is True
        assert await restored_a.get_balance(alice) == 99_500
        assert await restored_a.get_balance(bob) == 100_500
        assert await restored_a.get_last_sequence(alice) == 1
        txs = await restored_r.get_all()
        assert len(txs) == 1 and txs[0].state is TransactionState.SUCCESS
        assert txs[0].amount == 500 and txs[0].sender == alice

    @pytest.mark.asyncio
    async def test_load_missing_is_fresh_start(self, tmp_path):
        ok = await checkpoint.load(
            str(tmp_path / "absent.json"), Accounts(), RecentTransactions()
        )
        assert ok is False

    @pytest.mark.asyncio
    async def test_corrupt_checkpoint_raises(self, tmp_path):
        path = tmp_path / "bad.json"
        path.write_text(json.dumps({"version": 99}))
        with pytest.raises(ValueError):
            await checkpoint.load(str(path), Accounts(), RecentTransactions())


class TestNodeRestart:
    @pytest.mark.asyncio
    async def test_single_node_resumes_ledger_after_restart(self, tmp_path):
        ckpt_path = str(tmp_path / "node.ckpt")

        def make_config():
            return Config(
                node_address=f"127.0.0.1:{next(_ports)}",
                rpc_address=f"127.0.0.1:{next(_ports)}",
                sign_key=SignKeyPair.random(),
                network_key=ExchangeKeyPair.random(),
                checkpoint=CheckpointConfig(path=ckpt_path, interval=60.0),
            )

        sender, recipient = SignKeyPair.random(), SignKeyPair.random()

        service = await Service.start(make_config())
        try:
            async with Client(f"http://{service.config.rpc_address}") as client:
                await client.send_asset(sender, 1, recipient.public, 777)
                deadline = asyncio.get_event_loop().time() + 10
                while asyncio.get_event_loop().time() < deadline:
                    if await client.get_last_sequence(sender.public) == 1:
                        break
                    await asyncio.sleep(0.1)
                assert await client.get_balance(sender.public) == 99_223
        finally:
            await service.close()  # writes the final snapshot

        # a NEW process-equivalent: fresh Service, same checkpoint path
        service2 = await Service.start(make_config())
        try:
            async with Client(f"http://{service2.config.rpc_address}") as client:
                assert await client.get_balance(sender.public) == 99_223
                assert await client.get_balance(recipient.public) == 100_777
                assert await client.get_last_sequence(sender.public) == 1
                # the sequence gate carries over: replaying seq 1 must not
                # double-apply, and seq 2 continues normally
                await client.send_asset(sender, 2, recipient.public, 1)
                deadline = asyncio.get_event_loop().time() + 10
                while asyncio.get_event_loop().time() < deadline:
                    if await client.get_last_sequence(sender.public) == 2:
                        break
                    await asyncio.sleep(0.1)
                assert await client.get_balance(sender.public) == 99_222
        finally:
            await service2.close()
