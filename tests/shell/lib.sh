# Shared harness for the shell e2e tier: spawn a real localhost network of
# `server` processes and drive it with the `client` CLI, all through PATH —
# the reference's tests/lib.sh workflow rebuilt for this framework's
# binaries (same operator pipeline: TOML over stdin/stdout, fragments
# appended with `config get-node`).

set -eu

N_NODES=3
WORK="$(mktemp -d)"
PIDS=""

# CPU backend for e2e: these scripts test protocol plumbing, not kernels
export JAX_PLATFORMS=cpu

cleanup() {
    status=$?
    for pid in $PIDS; do
        kill -9 "$pid" 2>/dev/null || true
    done
    rm -rf "$WORK"
    exit $status
}
trap cleanup EXIT INT TERM

wait_for_port_connect() { # port [timeout_s]
    port=$1
    timeout=${2:-60}
    i=0
    while ! (exec 3<>"/dev/tcp/127.0.0.1/$port") 2>/dev/null; do
        i=$((i + 1))
        if [ "$i" -ge $((timeout * 5)) ]; then
            echo "port $port never came up" >&2
            return 1
        fi
        sleep 0.2
    done
    exec 3>&- 2>/dev/null || true
}

# start_network [base_port]: boots N_NODES servers; sets RPC_PORT_0..2
start_network() {
    base=${1:-$((RANDOM % 10000 + 10000))}  # 10000-19999: below both the ephemeral range (32768+) and the Python suites' fixed bases (20500+)
    n=0
    while [ "$n" -lt "$N_NODES" ]; do
        server config new "127.0.0.1:$((base + n * 2))" "127.0.0.1:$((base + n * 2 + 1))" \
            > "$WORK/node$n.toml"
        n=$((n + 1))
    done
    i=0
    while [ "$i" -lt "$N_NODES" ]; do
        j=0
        while [ "$j" -lt "$N_NODES" ]; do
            if [ "$i" != "$j" ]; then
                server config get-node < "$WORK/node$j.toml" >> "$WORK/node$i.toml"
            fi
            j=$((j + 1))
        done
        i=$((i + 1))
    done
    n=0
    while [ "$n" -lt "$N_NODES" ]; do
        server run < "$WORK/node$n.toml" &
        PIDS="$PIDS $!"
        eval "RPC_PORT_$n=$((base + n * 2 + 1))"
        n=$((n + 1))
    done
    n=0
    while [ "$n" -lt "$N_NODES" ]; do
        wait_for_port_connect $((base + n * 2 + 1))
        n=$((n + 1))
    done
}

new_client() { # rpc_port -> writes $WORK/client_$port.toml, echoes path
    port=$1
    cfg="$WORK/client_$port.toml"
    client config new "http://127.0.0.1:$port" > "$cfg"
    echo "$cfg"
}

wait_for_sequence() { # client_cfg expected_seq [timeout_s]
    cfg=$1
    want=$2
    timeout=${3:-30}
    i=0
    while true; do
        seq=$(client get-last-sequence < "$cfg" 2>/dev/null || echo "")
        [ "$seq" = "$want" ] && return 0
        i=$((i + 1))
        if [ "$i" -ge $((timeout * 10)) ]; then
            echo "sequence never reached $want (last: '$seq')" >&2
            return 1
        fi
        sleep 0.1
    done
}
