"""Fault-injection tier: the recovery paths under real failures.

The reference leaves recovery as roadmap items ("TODO readd connections
if dropped", `/root/reference/src/bin/server/rpc.rs:87`; TTL drop at
`rpc.rs:35`; "catchup mechanism", `/root/reference/README.md:53`); this
build implements them, and these tests pin the implementations under
REAL faults on real localhost nets:

* kill one node of a 3-node net under traffic, restart it, assert the
  peers' redial/backoff loop re-converges the net (net/peers.py
  `_outbound_loop`);
* the deliberately-kept TTL quirk: a payload that outlives
  TRANSACTION_TTL is recorded Failure yet still processes and can flip
  to Success (node/service.py `_drain_to_fixpoint`, mirroring the
  reference's missing `continue`, rpc.rs:183-205);
* a partition that loses a payload's gossip entirely: the node still
  reaches Ready quorum via attestations and pulls the content from the
  quorum (broadcast/stack.py `_request_content` — exercised here over
  real sockets, not state-machine calls);
* SIGKILL a CLI server mid-commit-stream with [checkpoint] enabled,
  restart it, assert no double-apply (per-account sequence gate) and
  re-convergence for new traffic.
"""

import asyncio
import itertools
import os
import signal
import subprocess
import sys
import time

import pytest

from at2_node_tpu.broadcast.messages import Payload, TxBatch, parse_frame
from at2_node_tpu.types import TransactionState
from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.node.config import CatchupConfig, CheckpointConfig
from at2_node_tpu.node.service import Service

from conftest import make_net_configs, wait_until

_ports = itertools.count(21600)

FAUCET = 100_000


def make_configs(n, **kwargs):
    return make_net_configs(n, _ports, **kwargs)


class TestKillRestartRedial:
    @pytest.mark.asyncio
    async def test_node_killed_and_restarted_reconverges(self):
        # f=1-tolerant thresholds: with one node down the other two can
        # still commit (default reference thresholds are n_peers, which
        # has zero fault tolerance — the knobs exist for exactly this).
        # Catchup quorum 2 = BOTH survivors must agree on each historical
        # slot's content before the restarted node applies it.
        cfgs = make_configs(
            3,
            echo_threshold=1,
            ready_threshold=1,
            catchup=CatchupConfig(quorum=2, after=0.5, window=0.3),
        )
        services = [await Service.start(c) for c in cfgs]
        sender = SignKeyPair.random()
        recipient = SignKeyPair.random().public
        try:
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset(sender, 1, recipient, 10)
                await wait_until(
                    lambda: _committed_on(services, 1, sender.public),
                    what="tx1 on all nodes",
                )

                # kill node 2 (connections drop; peers enter redial)
                await services[2].close()

                # the surviving majority keeps committing under traffic
                await client.send_asset(sender, 2, recipient, 10)
                await wait_until(
                    lambda: _committed_on(services[:2], 2, sender.public),
                    what="tx2 on survivors",
                )

                # restart node 2 on the same addresses; peers redial it.
                # It missed seq 1-2 entirely (no checkpoint — full state
                # loss): tx3 parks on its sequence gate until the ledger
                # catchup pulls the missed history from the survivors and
                # replays it — then ALL THREE nodes commit everything.
                services[2] = await Service.start(cfgs[2])
                await client.send_asset(sender, 3, recipient, 10)
                await wait_until(
                    lambda: _committed_on(services, 3, sender.public),
                    what="full commit parity after restart",
                )
            # the restarted node's broadcast saw tx3 via redialed links,
            # recovered the missed history via the catchup protocol, and
            # its ledger fully re-converged. catchup_applied counts only
            # NEWLY-enqueued payloads (ADVICE r4): part of the gap can
            # arrive via the survivors' queued send backlog on redial
            # (tx2's frames were parked in their bounded send queues
            # while the node was down), so the catchup's own share is
            # >= 1, not necessarily the whole gap.
            assert services[2].broadcast.stats["delivered"] >= 1
            assert services[2].catchup_stats["catchup_applied"] >= 1
            for s in services:
                assert await s.accounts.get_balance(sender.public) == FAUCET - 30
                assert await s.accounts.get_balance(recipient) == FAUCET + 30
            # and the survivors actually served it from their history
            served = sum(
                s.catchup_stats["catchup_served"] for s in services[:2]
            )
            assert served >= 2
        finally:
            for s in services:
                await s.close()


async def _committed_on(services, seq, sender_pub):
    for s in services:
        if await s.accounts.get_last_sequence(sender_pub) < seq:
            return False
    return True


class TestTtlQuirk:
    @pytest.mark.asyncio
    async def test_expired_payload_marked_failure_then_flips_success(
        self, monkeypatch
    ):
        from at2_node_tpu.node import service as service_mod

        monkeypatch.setattr(service_mod, "TRANSACTION_TTL", 0.3)
        cfg = make_configs(1)[0]
        svc = await Service.start(cfg)
        sender = SignKeyPair.random()
        recipient = SignKeyPair.random().public
        try:
            async with Client(f"http://{cfg.rpc_address}") as client:
                # seq 2 first: gap-blocked, parks in the retry heap
                await client.send_asset(sender, 2, recipient, 10)
                await asyncio.sleep(0.5)  # outlive the 0.3s TTL

                # a second gapped payload triggers a drain pass that must
                # record the expired seq-2 as FAILURE (and still retry it)
                await client.send_asset(sender, 3, recipient, 10)

                async def seq2_failed():
                    txs = await client.get_latest_transactions()
                    return any(
                        t.sender_sequence == 2 and t.state.name == "FAILURE"
                        for t in txs
                    )

                await wait_until(seq2_failed, what="seq2 FAILURE record")

                # gap-filling seq 1 lets the EXPIRED payloads commit: the
                # reference quirk — no `continue` after the TTL branch —
                # means expiry does not drop them
                await client.send_asset(sender, 1, recipient, 10)

                async def all_success():
                    if await client.get_last_sequence(sender.public) != 3:
                        return False
                    txs = await client.get_latest_transactions()
                    states = {
                        t.sender_sequence: t.state.name
                        for t in txs
                        if t.sender == sender.public
                    }
                    return states == {1: "SUCCESS", 2: "SUCCESS", 3: "SUCCESS"}

                await wait_until(all_success, what="expired payloads flip to SUCCESS")
                assert await client.get_balance(sender.public) == FAUCET - 30
        finally:
            await svc.close()


class TestPartitionHealContentPull:
    @pytest.mark.asyncio
    async def test_lost_gossip_recovered_via_content_request(self):
        # Thresholds such that Echo/Ready quorums can form WITHOUT the
        # starved node's echo (it has no content, so it cannot echo):
        # with the reference's defaults (= all peers) a single lost
        # gossip stalls the slot net-wide — the exact fragility the
        # pull-based catch-up exists to break out of. The victim still
        # needs a full Ready quorum (2) before it pulls.
        # Batching OFF: this test faults the PER-TX gossip/pull plane
        # (the batched plane's pull twin is tests/test_batching.py).
        from at2_node_tpu.node.config import BatchingConfig

        cfgs = make_configs(
            3,
            echo_threshold=1,
            ready_threshold=2,
            batching=BatchingConfig(enabled=False),
        )
        services = [await Service.start(c) for c in cfgs]
        victim = services[2]

        # fault injection at the wire boundary: strip the first two
        # Payload copies addressed to node 2 (the gossip relays from each
        # peer), let everything else — echoes, readies, and the later
        # content-pull response — through untouched
        dropped = 0
        original = victim.mesh.on_frame

        async def lossy(peer, frame):
            nonlocal dropped
            msgs = parse_frame(frame)
            kept = []
            for m in msgs:
                if isinstance(m, Payload) and dropped < 2:
                    dropped += 1
                    continue
                kept.append(m)
            if kept:
                await original(peer, b"".join(m.encode() for m in kept))

        victim.mesh.on_frame = lossy

        sender = SignKeyPair.random()
        recipient = SignKeyPair.random().public
        try:
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset(sender, 1, recipient, 25)

                async def all_committed():
                    for s in services:
                        if await s.accounts.get_last_sequence(sender.public) < 1:
                            return False
                    return True

                await wait_until(
                    all_committed, what="commit on the gossip-starved node"
                )
            assert dropped == 2, "the fault never actually fired"
            # the victim pulled the content after observing the quorum
            assert victim.broadcast.stats["content_req_tx"] >= 1
            served = sum(
                s.broadcast.stats["content_served"] for s in services[:2]
            )
            assert served >= 1
            assert await victim.accounts.get_balance(recipient) == FAUCET + 25
        finally:
            for s in services:
                await s.close()


class TestStalledSlotRetransmission:
    """Liveness under message loss (round-5): the planes are best-effort
    (bounded queues drop under overload) and with thresholds = n_peers a
    single lost attestation gap-blocks its slot network-wide — burst
    measurements caught exactly that (BENCH_E2E.json batched_plane
    burst_robustness). A slot still undelivered after RETRANSMIT_AFTER
    re-broadcasts the node's content + own attestations (dedup absorbs
    them wherever they already landed)."""

    @staticmethod
    def _speed_up(monkeypatch):
        import at2_node_tpu.broadcast.stack as stack_mod

        monkeypatch.setattr(stack_mod, "GC_INTERVAL", 0.3)
        monkeypatch.setattr(stack_mod, "RETRANSMIT_AFTER", 0.5)
        monkeypatch.setattr(stack_mod, "RETRANSMIT_EVERY", 0.5)

    @staticmethod
    def _drop_first(victim, pred):
        """Drop the first message matching pred arriving at victim."""
        state = {"dropped": 0}
        original = victim.mesh.on_frame

        async def lossy(peer, frame):
            kept = []
            for m in parse_frame(frame):
                if state["dropped"] < 1 and pred(m):
                    state["dropped"] += 1
                    continue
                kept.append(m)
            if kept:
                await original(peer, b"".join(m.encode() for m in kept))

        victim.mesh.on_frame = lossy
        return state

    # The stalling shape (a single lost ECHO heals for free via Ready
    # amplification): the FIRST Ready arriving at nodes 0 and 1 is
    # dropped. Each then holds 1 of 2 required readies — permanently
    # stalled pre-fix — while node 2 reaches its quorum and DELIVERS, so
    # node 2 never retransmits. Recovery: the stalled nodes' periodic
    # retransmission of their own attestations reaches node 2 as
    # duplicates for a delivered slot (a straggler beacon), and node 2
    # answers with its content + attestations (_help_straggler).

    @pytest.mark.asyncio
    async def test_lost_ready_recovered_per_tx(self, monkeypatch):
        from at2_node_tpu.broadcast.messages import READY, Attestation
        from at2_node_tpu.node.config import BatchingConfig

        self._speed_up(monkeypatch)
        cfgs = make_configs(3, batching=BatchingConfig(enabled=False))
        services = [await Service.start(c) for c in cfgs]

        def is_ready(m):
            return isinstance(m, Attestation) and m.phase == READY

        drops = [
            self._drop_first(services[0], is_ready),
            self._drop_first(services[1], is_ready),
        ]
        sender = SignKeyPair.random()
        recipient = SignKeyPair.random().public
        try:
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset(sender, 1, recipient, 10)

                async def all_committed():
                    for s in services:
                        if await s.accounts.get_last_sequence(sender.public) < 1:
                            return False
                    return True

                await wait_until(
                    all_committed, what="slot heals via retransmission"
                )
            assert all(d["dropped"] == 1 for d in drops), "fault never fired"
            assert (
                sum(s.broadcast.stats["retransmits"] for s in services) >= 1
            )
        finally:
            for s in services:
                await s.close()

    @pytest.mark.asyncio
    async def test_lost_batch_ready_recovered(self, monkeypatch):
        from at2_node_tpu.broadcast.messages import (
            BATCH_READY,
            BatchAttestation,
        )

        self._speed_up(monkeypatch)
        cfgs = make_configs(3)  # batching default-on
        services = [await Service.start(c) for c in cfgs]

        def is_bready(m):
            return isinstance(m, BatchAttestation) and m.phase == BATCH_READY

        drops = [
            self._drop_first(services[0], is_bready),
            self._drop_first(services[1], is_bready),
        ]
        sender = SignKeyPair.random()
        recipient = SignKeyPair.random().public
        try:
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset(sender, 1, recipient, 10)

                async def all_committed():
                    for s in services:
                        if await s.accounts.get_last_sequence(sender.public) < 1:
                            return False
                    return True

                await wait_until(
                    all_committed, what="batch slot heals via retransmission"
                )
            assert all(d["dropped"] == 1 for d in drops), "fault never fired"
            assert (
                sum(s.broadcast.stats["retransmits"] for s in services) >= 1
            )
        finally:
            for s in services:
                await s.close()


class TestBeyondHorizonRejoin:
    """VERDICT r4 #3/#4: the rejoin story when the gap EXCEEDS peers'
    bounded history horizon (ledger/history.py retention). Two halves:

    * the documented operator path WORKS: a node restoring from its own
      stale local checkpoint only needs the tail within the horizon —
      tested end-to-end with a tiny history_cap;
    * without a checkpoint the gap is genuinely unrecoverable via
      catchup (the docstring's honest limit) — and the node must
      DEGRADE SOUNDLY: no livelock (catchup progress counted honestly,
      sessions back off — ADVICE r4 medium), and no recent-ring FAILURE
      record for slots the network committed (ADVICE r4 low).
    """

    @pytest.mark.asyncio
    async def test_stale_checkpoint_plus_catchup_tail_converges(
        self, tmp_path
    ):
        cfgs = make_configs(
            3,
            echo_threshold=1,
            ready_threshold=1,
            catchup=CatchupConfig(
                quorum=2, after=0.3, window=0.3, history_cap=4
            ),
        )
        # node2 snapshots on graceful shutdown (interval<=0: final only)
        cfgs[2].checkpoint = CheckpointConfig(
            path=str(tmp_path / "node2.ckpt"), interval=0
        )
        services = [await Service.start(c) for c in cfgs]
        sender = SignKeyPair.random()
        recipient = SignKeyPair.random().public
        try:
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                for seq in range(1, 7):
                    await client.send_asset(sender, seq, recipient, 10)
                await wait_until(
                    lambda: _committed_on(services, 6, sender.public),
                    what="seqs 1-6 everywhere",
                )
                # node2 leaves gracefully -> checkpoint at frontier 6
                await services[2].close()
                # the network moves on; peers' history_cap=4 retains
                # only seqs 7-10 — seqs 1-6 fall past the horizon
                for seq in range(7, 11):
                    await client.send_asset(sender, seq, recipient, 10)
                await wait_until(
                    lambda: _committed_on(services[:2], 10, sender.public),
                    what="seqs 7-10 on survivors",
                )
                # rejoin: checkpoint restores frontier 6; catchup pulls
                # exactly the in-horizon tail 7-10 and re-converges
                services[2] = await Service.start(cfgs[2])
                # Simulate a LONG absence at the wire boundary: on a
                # short outage the survivors' outbound loops replay
                # their in-flight batch on redial (bounded queues +
                # retained `pending`), which would hand node2 the tail
                # for free; over a multi-day gap that replay holds only
                # unrelated recent traffic. Drop replayed gossip at the
                # victim's ingress so convergence must come from the
                # CATCHUP protocol (HistoryBatch passes untouched).
                original = services[2].mesh.on_frame

                async def no_gossip_replay(peer, frame, _orig=original):
                    kept = [
                        m
                        for m in parse_frame(frame)
                        if not isinstance(m, (Payload, TxBatch))
                    ]
                    if kept:
                        await _orig(
                            peer, b"".join(m.encode() for m in kept)
                        )

                services[2].mesh.on_frame = no_gossip_replay
                await wait_until(
                    lambda: _committed_on(services, 10, sender.public),
                    what="full re-convergence from stale checkpoint + tail",
                )
            for s in services:
                assert await s.accounts.get_balance(recipient) == FAUCET + 100
                assert await s.accounts.get_balance(sender.public) == FAUCET - 100
            assert services[2].catchup_stats["catchup_applied"] >= 1
        finally:
            for s in services:
                await s.close()

    @pytest.mark.asyncio
    async def test_no_checkpoint_degrades_soundly(self, monkeypatch):
        import at2_node_tpu.node.service as service_mod

        # short TTL so the gap-blocked entries expire several times
        # within the test window (the FAILURE-suppression path)
        monkeypatch.setattr(service_mod, "TRANSACTION_TTL", 0.3)
        cfgs = make_configs(
            3,
            echo_threshold=1,
            ready_threshold=1,
            catchup=CatchupConfig(
                quorum=2, after=0.3, window=0.3, history_cap=4
            ),
        )
        services = [await Service.start(c) for c in cfgs]
        sender = SignKeyPair.random()
        recipient = SignKeyPair.random().public
        try:
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                for seq in range(1, 11):
                    await client.send_asset(sender, seq, recipient, 10)
                await wait_until(
                    lambda: _committed_on(services, 10, sender.public),
                    what="seqs 1-10 everywhere",
                )
                # node2 dies with TOTAL state loss (no checkpoint) and
                # rejoins: it needs 1-10 but peers retain only 7-10
                await services[2].close()
                services[2] = await Service.start(cfgs[2])
                victim = services[2]

                async def sessions_ran():
                    return victim.catchup_stats["catchup_sessions"] >= 2

                await wait_until(sessions_ran, what="catchup sessions ran")
                # the network-committed tail is re-submitted through the
                # VICTIM's ingress (deterministic ed25519 -> identical
                # content): it lands in its recent ring as Pending and
                # gap-blocks — the exact shape where the old code wrote
                # FAILURE for a transfer every peer calls SUCCESS
                async with Client(f"http://{cfgs[2].rpc_address}") as c2:
                    await c2.send_asset(sender, 8, recipient, 10)
                await asyncio.sleep(1.2)  # > several TTLs and sessions
                applied_then = victim.catchup_stats["catchup_applied"]
                # honest progress counting: the in-horizon tail entered
                # the heap ONCE; later sessions are dedup hits, not
                # "progress" (the ADVICE livelock: applied never 0)
                assert 1 <= applied_then <= 4
                await asyncio.sleep(1.0)
                assert victim.catchup_stats["catchup_applied"] == applied_then
                # the gap is genuinely unrecoverable: frontier stays 0
                assert (
                    await victim.accounts.get_last_sequence(sender.public)
                ) == 0
                # ...and the ring NEVER contradicts the network: seq 8 is
                # committed everywhere else; locally it must still read
                # PENDING (gap-blocked), not FAILURE
                ring = await victim.recent.get_all()
                states = {
                    t.sender_sequence: t.state
                    for t in ring
                    if t.sender == sender.public
                }
                assert states.get(8) == TransactionState.PENDING, states
        finally:
            for s in services:
                await s.close()


class TestCrashConsistency:
    def _spawn_server(self, toml: str, log):
        proc = subprocess.Popen(
            [sys.executable, "-m", "at2_node_tpu.cli.server", "run"],
            stdin=subprocess.PIPE,
            stdout=log,
            stderr=log,
            text=True,
        )
        proc.stdin.write(toml)
        proc.stdin.close()
        return proc

    @pytest.mark.asyncio
    async def test_sigkill_midstream_restart_no_double_apply(self, tmp_path):
        """kill -9 (not a graceful stop): restart must not double-apply
        what the snapshot already holds, and the node must serve and
        commit new traffic afterwards."""
        cfg = make_configs(1)[0]
        cfg.checkpoint = CheckpointConfig(
            path=str(tmp_path / "ledger.ckpt"), interval=0.2
        )
        toml = cfg.dumps()
        log = open(tmp_path / "server.log", "w")
        proc = self._spawn_server(toml, log)
        sender = SignKeyPair.random()
        recipient = SignKeyPair.random().public
        try:
            async with Client(f"http://{cfg.rpc_address}") as client:
                await wait_until(
                    lambda: _rpc_up(client, sender.public), what="server up"
                )
                # commit a stream, give the periodic snapshot a beat
                for seq in range(1, 6):
                    await client.send_asset(sender, seq, recipient, 10)
                await wait_until(
                    lambda: _seq_is(client, sender.public, 5),
                    what="pre-kill commits",
                )
                await asyncio.sleep(0.5)  # >= 2 checkpoint intervals

            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=5)

            proc = self._spawn_server(toml, log)
            async with Client(f"http://{cfg.rpc_address}") as client:
                await wait_until(
                    lambda: _rpc_up(client, sender.public), what="restarted"
                )
                # no double-apply: balances/sequence match the committed
                # stream exactly (the snapshot held them; replays would
                # break the sequence gate or inflate balances)
                assert await client.get_last_sequence(sender.public) == 5
                assert await client.get_balance(sender.public) == FAUCET - 50
                assert await client.get_balance(recipient) == FAUCET + 50

                # and the node still commits new traffic
                await client.send_asset(sender, 6, recipient, 10)
                await wait_until(
                    lambda: _seq_is(client, sender.public, 6),
                    what="post-restart commit",
                )
                assert await client.get_balance(recipient) == FAUCET + 60
        finally:
            log.close()
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()


async def _rpc_up(client, user) -> bool:
    try:
        await asyncio.wait_for(client.get_balance(user), timeout=1.0)
        return True
    except Exception:
        return False


async def _seq_is(client, user, seq) -> bool:
    return await client.get_last_sequence(user) == seq


class TestPoisonChaos:
    """The poison-storm episode the resolution machinery exists for
    (robustness PR acceptance): a byzantine client salts EVERY ingress
    batch with one bad-signature entry on a 4-node net. Pre-fix, each
    poisoned slot stayed "undelivered" for SLOT_MAX_AGE — burning the
    retransmission budget and kicking a network-wide catchup session per
    GC pass. Post-fix the episode must be boring: throughput within 10%
    of a clean run, zero catchup sessions, zero stall kicks, and no
    retransmissions at all (slots retire before the retransmit horizon)."""

    ROUNDS = 6
    GOOD_PER_ROUND = 25

    @staticmethod
    async def _submit(service, payload):
        await service.recent.put(
            payload.sender, payload.sequence, payload.transaction
        )
        service._batch_buf.append(payload)

    async def _episode(self, poison: bool):
        from at2_node_tpu.types import ThinTransaction

        # catchup.after far past the episode length: the drain loop's
        # ordinary transient-gap kick (single-flight, delayed) can never
        # mature into a session here, so any session observed could only
        # come from the stall-storm path under test
        cfgs = make_configs(4, catchup=CatchupConfig(after=30.0))
        services = [await Service.start(c) for c in cfgs]
        kicks = [0]
        for s in services:
            orig = s.broadcast.stall_handler

            def wrapped(_orig=orig):
                kicks[0] += 1
                if _orig is not None:
                    _orig()

            s.broadcast.stall_handler = wrapped
        try:
            sender = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            total = self.ROUNDS * self.GOOD_PER_ROUND
            seq = 0
            t0 = time.monotonic()
            for _ in range(self.ROUNDS):
                for _ in range(self.GOOD_PER_ROUND):
                    seq += 1
                    thin = ThinTransaction(recipient, 1)
                    await self._submit(
                        services[0],
                        Payload.create(sender, seq, thin),
                    )
                if poison:
                    # fresh forged sender each round: a bad-sig entry in
                    # every single batch slot, never gap-blocking the
                    # honest sender
                    await self._submit(
                        services[0],
                        Payload(
                            SignKeyPair.random().public,
                            1,
                            ThinTransaction(recipient, 1),
                            b"\x0b" * 64,
                        ),
                    )
                await services[0]._flush_batch()

            async def all_committed():
                return all(s.committed >= total for s in services)

            await wait_until(all_committed, what="episode commits")
            elapsed = time.monotonic() - t0
            # settle: several GC passes classify/retire what is left;
            # long enough that the first rounds' slots age past the
            # stall horizon — a pre-fix stuck poison slot WOULD kick here
            await asyncio.sleep(2.0)
            stats = [s.snapshot_stats() for s in services]
            return elapsed, stats, kicks[0]
        finally:
            for s in services:
                await s.close()

    @pytest.mark.asyncio
    async def test_poison_storm_is_boring(self, monkeypatch):
        import at2_node_tpu.broadcast.stack as stack_mod

        monkeypatch.setattr(stack_mod, "GC_INTERVAL", 0.2)
        monkeypatch.setattr(stack_mod, "STALLED_CATCHUP_AFTER", 4.0)
        monkeypatch.setattr(stack_mod, "RETRANSMIT_AFTER", 1.5)
        clean_t, clean_stats, clean_kicks = await self._episode(poison=False)
        dirty_t, dirty_stats, dirty_kicks = await self._episode(poison=True)
        # throughput within 10% of the clean episode (+0.75s absorbs
        # scheduler noise on runs this short)
        assert dirty_t <= clean_t * 1.10 + 0.75, (clean_t, dirty_t)
        for snap in dirty_stats:
            assert snap["catchup_sessions"] == 0
        # FLAT retransmits: pre-fix every poisoned slot re-broadcast its
        # content once past the horizon (+ROUNDS per node); post-fix the
        # retired slots are excluded, so the poison adds nothing beyond
        # the clean episode's ordinary backlog stragglers
        clean_rtx = sum(s["retransmits"] for s in clean_stats)
        dirty_rtx = sum(s["retransmits"] for s in dirty_stats)
        assert dirty_rtx <= clean_rtx + 2, (clean_rtx, dirty_rtx)
        assert dirty_kicks == 0 and clean_kicks == 0
        # every poisoned slot resolved by local rejection on every node
        assert all(
            snap["poison_resolved"] >= self.ROUNDS for snap in dirty_stats
        ), [s["poison_resolved"] for s in dirty_stats]
        assert all(snap["slots_retired"] >= self.ROUNDS for snap in dirty_stats)
