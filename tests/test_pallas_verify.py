"""Pallas verify kernel: differential tests against the XLA graph and the
CPU (OpenSSL) oracle, run through the Pallas interpreter on the CPU mesh.

Pins the production TPU path (ops.pallas_verify) to the reference
implementation bit-for-bit across valid, corrupted, malformed, and
non-canonical inputs (SURVEY.md §4: CPU-vs-TPU differential tests).
"""

import numpy as np
import pytest

from at2_node_tpu.crypto.keys import SignKeyPair, verify_one
from at2_node_tpu.ops import ed25519 as v
from at2_node_tpu.ops import field as fe
from at2_node_tpu.ops.pallas_verify import verify_batch_pallas

# Interpreter-mode Pallas is minutes-slow on CPU; the whole module is part
# of the kernel tier (`-m slow`), not the fast dev loop.
pytestmark = pytest.mark.slow

RNG = np.random.default_rng(0xA11A5)


def _sign_many(n, msg_len=24):
    keys = [SignKeyPair.random() for _ in range(n)]
    msgs = [RNG.bytes(msg_len) for _ in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return [k.public for k in keys], msgs, sigs


def test_pallas_valid_and_corrupted():
    pks, msgs, sigs = _sign_many(12)
    sigs[2] = bytes([sigs[2][0] ^ 1]) + sigs[2][1:]       # corrupt R
    sigs[5] = sigs[5][:32] + bytes([sigs[5][32] ^ 1]) + sigs[5][33:]  # corrupt S
    msgs[8] = b"swapped"                                   # wrong message
    got = verify_batch_pallas(pks, msgs, sigs, interpret=True)
    expect = [verify_one(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    assert got.tolist() == expect
    assert expect == [True, True, False, True, True, False, True, True, False, True, True, True]


def test_pallas_matches_xla_graph():
    pks, msgs, sigs = _sign_many(16, msg_len=5)
    # randomly corrupt ~half, any field
    for i in range(16):
        r = RNG.random()
        if r < 0.25:
            sigs[i] = bytes([sigs[i][0] ^ 0x40]) + sigs[i][1:]
        elif r < 0.5:
            pks[i] = SignKeyPair.random().public
    xla = v.verify_batch(pks, msgs, sigs)  # CPU backend -> XLA graph
    pal = verify_batch_pallas(pks, msgs, sigs, interpret=True)
    assert pal.tolist() == xla.tolist()


def test_pallas_rejects_high_s_and_malformed():
    pks, msgs, sigs = _sign_many(3)
    s = int.from_bytes(sigs[0][32:], "little")
    bad = [
        sigs[0][:32] + (s + v.L).to_bytes(32, "little"),  # S >= L
        sigs[1][:20],                                      # short signature
        sigs[2],
    ]
    pks[2] = pks[2][:16]                                   # short key
    got = verify_batch_pallas(pks, msgs, bad, interpret=True)
    assert not got.any()


def test_pallas_rejects_noncanonical_y():
    # y >= p is a non-canonical encoding: R = p (i.e. 0 encoded badly)
    pks, msgs, sigs = _sign_many(1)
    bad_r = fe.P.to_bytes(32, "little") + sigs[0][32:]
    got = verify_batch_pallas(pks, msgs, [bad_r], interpret=True)
    assert not got.any()
