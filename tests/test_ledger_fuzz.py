"""Ledger differential fuzz: random op sequences against an independent
model of the reference's account rules.

The targeted tests (test_ledger.py) pin the reference's documented
quirks; this tier replays seeded random transfer streams — gap/replayed
sequences, overdrafts, self-transfers, u64-edge amounts, overflow-bound
credits — through `Accounts` and an independently written model, and
checks after every op that balances, sequences, and error outcomes agree
exactly, plus the global conservation invariant. A checkpoint round-trip
mid-stream must be state-identical too.

Model rules (re-derived from the reference, account.rs:12-54 +
accounts/mod.rs:155-201, NOT from the implementation under test):
fresh accounts hold 100,000; debit requires sequence == last+1 and
consumes the sequence even when the balance check then fails;
self-transfer is debit(seq, 0); receiver credit checks u64 overflow and
the sender's debit persists even if the credit fails.
"""

import random

import pytest

from at2_node_tpu.ledger import checkpoint
from at2_node_tpu.ledger.account import INITIAL_BALANCE, _U64_MAX
from at2_node_tpu.ledger.accounts import AccountModificationError, Accounts
from at2_node_tpu.ledger.recent import RecentTransactions


class Model:
    """Independent reimplementation of the reference's observable rules."""

    def __init__(self):
        self.bal = {}
        self.seq = {}

    def _get(self, user):
        return self.bal.get(user, INITIAL_BALANCE), self.seq.get(user, 0)

    def transfer(self, sender, sequence, receiver, amount) -> bool:
        """True = commits, False = rejected (AccountModification)."""
        s_bal, s_seq = self._get(sender)
        if sequence != s_seq + 1:
            return False
        if sender == receiver:
            # self-transfer = debit(seq, 0): consumes sequence, keeps funds
            self.seq[sender] = sequence
            self.bal[sender] = s_bal
            return True
        # sequence consumed BEFORE the balance check (reference quirk)
        self.seq[sender] = sequence
        self.bal[sender] = s_bal
        if amount > s_bal:
            return False
        r_bal, _ = self._get(receiver)
        if r_bal + amount > _U64_MAX:
            # receiver overflow: sender's debit has already persisted
            self.bal[sender] = s_bal - amount
            return False
        self.bal[sender] = s_bal - amount
        self.bal[receiver] = r_bal + amount
        return True


async def _assert_agree(accounts: Accounts, model: Model, users) -> None:
    for u in users:
        want_bal, want_seq = model._get(u)
        assert await accounts.get_balance(u) == want_bal
        assert await accounts.get_last_sequence(u) == want_seq


@pytest.mark.parametrize("seed", [2, 11, 29, 73, 97])
async def test_random_streams_match_model(seed):
        rng = random.Random(seed)
        users = [bytes([i]) * 32 for i in range(1, 6)]
        accounts = Accounts()
        model = Model()
        next_seq = {u: 1 for u in users}

        for step in range(300):
            sender = rng.choice(users)
            receiver = rng.choice(users)  # may equal sender
            roll = rng.random()
            if roll < 0.60:
                seq = next_seq[sender]  # the valid next sequence
            elif roll < 0.80:
                seq = max(1, next_seq[sender] - rng.randrange(1, 3))  # replay
            else:
                seq = next_seq[sender] + rng.randrange(1, 4)  # gap
            amount_roll = rng.random()
            if amount_roll < 0.5:
                amount = rng.randrange(0, 2000)
            elif amount_roll < 0.8:
                amount = rng.randrange(90_000, 250_000)  # overdraft range
            else:
                amount = rng.choice((0, 1, INITIAL_BALANCE, 10**15))

            want = model.transfer(sender, seq, receiver, amount)
            try:
                await accounts.transfer(sender, seq, receiver, amount)
                got = True
            except AccountModificationError:
                got = False
            assert got is want, (
                f"step {step}: divergence on "
                f"({sender[:1].hex()},{seq},{receiver[:1].hex()},{amount}): "
                f"impl={got} model={want}"
            )
            if seq == next_seq[sender]:
                # a correctly-sequenced debit consumes the sequence even
                # when it fails (the reference quirk) — success and
                # failure advance identically
                next_seq[sender] = seq + 1

            if step % 97 == 0:
                await _assert_agree(accounts, model, users)

        await _assert_agree(accounts, model, users)
        # conservation: only transfers happened, so total = faucet * users
        total = 0
        for u in users:
            total += await accounts.get_balance(u)
        assert total == INITIAL_BALANCE * len(users)


@pytest.mark.parametrize("seed", [5, 41])
async def test_checkpoint_roundtrip_mid_stream_is_state_identical(seed, tmp_path):
        rng = random.Random(seed)
        users = [bytes([i]) * 32 for i in range(1, 5)]
        accounts = Accounts()
        recent = RecentTransactions()
        model = Model()
        next_seq = {u: 1 for u in users}

        async def one_op():
            sender, receiver = rng.choice(users), rng.choice(users)
            seq = next_seq[sender]
            amount = rng.randrange(0, 120_000)
            want = model.transfer(sender, seq, receiver, amount)
            try:
                await accounts.transfer(sender, seq, receiver, amount)
                assert want
            except AccountModificationError:
                assert not want
            next_seq[sender] = seq + 1

        for _ in range(60):
            await one_op()
        path = str(tmp_path / "ledger.ckpt")
        await checkpoint.save(path, accounts, recent)
        restored_a, restored_r = Accounts(), RecentTransactions()
        assert await checkpoint.load(path, restored_a, restored_r)
        await _assert_agree(restored_a, model, users)
        # the restored ledger continues the stream identically
        accounts2 = restored_a
        for _ in range(60):
            sender, receiver = rng.choice(users), rng.choice(users)
            seq = next_seq[sender]
            amount = rng.randrange(0, 120_000)
            want = model.transfer(sender, seq, receiver, amount)
            try:
                await accounts2.transfer(sender, seq, receiver, amount)
                got = True
            except AccountModificationError:
                got = False
            assert got is want
            next_seq[sender] = seq + 1
        await _assert_agree(accounts2, model, users)


@pytest.mark.parametrize("seed", [17, 59])
async def test_overflow_rich_accounts_match_model(seed):
    """Receiver-overflow coverage needs balances transfers alone cannot
    reach (the faucet total is ~500k): seed near-u64 accounts through the
    checkpoint import path, then fuzz transfers INTO them so the credit
    overflow — and the sender's-debit-persists-anyway quirk — actually
    fire."""
    rng = random.Random(seed)
    users = [bytes([i]) * 32 for i in range(1, 4)]
    whale = b"\xee" * 32
    accounts = Accounts()
    model = Model()
    whale_balance = _U64_MAX - 5_000
    await accounts.import_state({whale.hex(): (0, whale_balance)})
    model.bal[whale] = whale_balance
    next_seq = {u: 1 for u in users + [whale]}

    overflowed = 0
    for _ in range(200):
        sender = rng.choice(users)
        receiver = whale if rng.random() < 0.7 else rng.choice(users)
        seq = next_seq[sender]
        amount = rng.randrange(0, 20_000)
        want = model.transfer(sender, seq, receiver, amount)
        try:
            await accounts.transfer(sender, seq, receiver, amount)
            got = True
        except AccountModificationError:
            got = False
        assert got is want, (sender[:1].hex(), seq, amount, got, want)
        if not want and receiver is whale and amount > 0:
            overflowed += 1
        if seq == next_seq[sender]:
            next_seq[sender] = seq + 1
    await _assert_agree(accounts, model, users + [whale])
    assert overflowed > 0, "the overflow path never fired; weaken the seed"


class RingModel:
    """Independent model of the recent-transactions ring (reference
    recent_transactions.rs:7,149-200): capacity 10 FIFO, put dedups by
    (sender, sequence), update rewrites the LATEST matching entry's
    state and is a NOP when absent."""

    CAP = 10

    def __init__(self):
        self.entries = []  # (sender, seq, state)

    def put(self, sender, seq):
        if any(e[0] == sender and e[1] == seq for e in self.entries):
            return
        self.entries.append((sender, seq, "PENDING"))
        if len(self.entries) > self.CAP:
            self.entries.pop(0)

    def update(self, sender, seq, state):
        for i in range(len(self.entries) - 1, -1, -1):
            if self.entries[i][0] == sender and self.entries[i][1] == seq:
                self.entries[i] = (sender, seq, state)
                return


@pytest.mark.parametrize("seed", [6, 47, 88])
async def test_recent_ring_matches_model(seed):
    from at2_node_tpu.types import ThinTransaction, TransactionState

    rng = random.Random(seed)
    recent = RecentTransactions()
    model = RingModel()
    users = [bytes([i]) * 32 for i in range(1, 4)]
    for _ in range(250):
        sender = rng.choice(users)
        seq = rng.randrange(1, 15)
        roll = rng.random()
        if roll < 0.55:
            await recent.put(sender, seq, ThinTransaction(b"r" * 32, 1))
            model.put(sender, seq)
        else:
            state = rng.choice(
                (TransactionState.SUCCESS, TransactionState.FAILURE)
            )
            await recent.update(sender, seq, state)
            model.update(sender, seq, state.name)
        got = [
            (t.sender, t.sender_sequence, t.state.name)
            for t in await recent.get_all()
        ]
        # get_all's order is part of the contract: oldest first
        # (recent.py export docstring; GetLatestTransactions relies on it)
        assert got == model.entries, (got, model.entries)
