"""Node integration tests: real Services over real localhost sockets.

The reference's integration tier (`/root/reference/tests/cli.rs`) spawns
real server processes and polls for commits with a 100 ms tick / 10 s
budget (`cli.rs:24-25,282-294`); here the same networks run in-process
(the subprocess/CLI tier lives in test_cli.py) with the same polling
pattern and the same assertions: faucet balance, sequence bumps, and the
conservation property sender+AMOUNT == receiver−AMOUNT (`cli.rs:316-334`).
"""

import asyncio
import itertools

import pytest

from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.net.peers import Peer
from at2_node_tpu.net import transport
from at2_node_tpu.node.config import Config
from at2_node_tpu.node.service import Service

# reference's polling budget: cli.rs:24-25
TICK = 0.1
TIMEOUT = 10.0

_ports = itertools.count(23000)


def make_configs(n):
    cfgs = [
        Config(
            node_address=f"127.0.0.1:{next(_ports)}",
            rpc_address=f"127.0.0.1:{next(_ports)}",
            sign_key=SignKeyPair.random(),
            network_key=ExchangeKeyPair.random(),
        )
        for _ in range(n)
    ]
    for i, cfg in enumerate(cfgs):
        cfg.nodes = [
            Peer(o.node_address, o.network_key.public, o.sign_key.public)
            for j, o in enumerate(cfgs)
            if j != i
        ]
    return cfgs


class Network:
    def __init__(self, n):
        self.n = n
        self.configs = make_configs(n)
        self.services = []

    async def __aenter__(self):
        self.services = [await Service.start(c) for c in self.configs]
        return self

    async def __aexit__(self, *exc):
        for s in self.services:
            await s.close()

    def rpc_url(self, i=0):
        return f"http://{self.configs[i].rpc_address}"


async def wait_for_sequence(client, user, seq):
    deadline = asyncio.get_event_loop().time() + TIMEOUT
    while asyncio.get_event_loop().time() < deadline:
        if await client.get_last_sequence(user) == seq:
            return
        await asyncio.sleep(TICK)
    raise TimeoutError(f"sequence {seq} not committed within {TIMEOUT}s")


class TestTransport:
    async def test_encrypted_roundtrip(self):
        server_kp, client_kp = ExchangeKeyPair.random(), ExchangeKeyPair.random()
        accepted = asyncio.get_event_loop().create_future()

        async def on_conn(reader, writer):
            ch = await transport.accept(reader, writer, server_kp)
            accepted.set_result(await ch.recv())
            ch.close()

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        ch = await transport.connect("127.0.0.1", port, client_kp)
        assert ch.peer_public == server_kp.public
        await ch.send(b"hello over the wire")
        assert await asyncio.wait_for(accepted, 2) == b"hello over the wire"
        ch.close()
        server.close()

    async def test_tampered_frame_rejected(self):
        server_kp, client_kp = ExchangeKeyPair.random(), ExchangeKeyPair.random()
        got = asyncio.get_event_loop().create_future()

        async def on_conn(reader, writer):
            ch = await transport.accept(reader, writer, server_kp)
            try:
                await ch.recv()
                got.set_result("accepted")
            except Exception as exc:
                got.set_result(type(exc).__name__)

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(client_kp.public + b"\x07" * 32)  # hello: key + nonce
        await reader.readexactly(64)
        # a frame that was never AEAD-encrypted must not decrypt
        bogus = b"\x10\x00\x00\x00" + b"Z" * 16
        writer.write(bogus)
        await writer.drain()
        # integrity failure surfaces as the channel-fatal ChannelClosed,
        # not a raw InvalidTag traceback (on-path garbage must not be
        # able to spam ERROR logs through the mesh handler)
        assert await asyncio.wait_for(got, 2) == "ChannelClosed"
        writer.close()
        server.close()


class TestTransportFreshness:
    async def test_low_order_peer_key_rejected(self):
        server_kp = ExchangeKeyPair.random()
        outcome = asyncio.get_event_loop().create_future()

        async def on_conn(reader, writer):
            try:
                await transport.accept(reader, writer, server_kp)
                outcome.set_result("accepted")
            except transport.HandshakeError:
                outcome.set_result("rejected")
            except Exception as exc:
                outcome.set_result(type(exc).__name__)

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"\x00" * 32 + b"\x01" * 32)  # low-order point hello
        await writer.drain()
        assert await asyncio.wait_for(outcome, 2) == "rejected"
        writer.close()
        server.close()

    async def test_replayed_frame_from_old_connection_rejected(self):
        # session keys must be fresh per connection: a ciphertext recorded
        # on connection 1 cannot authenticate on connection 2
        server_kp, client_kp = ExchangeKeyPair.random(), ExchangeKeyPair.random()
        results = asyncio.Queue()

        async def on_conn(reader, writer):
            ch = await transport.accept(reader, writer, server_kp)
            try:
                await results.put(("ok", await ch.recv()))
            except Exception as exc:
                await results.put(("err", type(exc).__name__))

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]

        recorded = []
        orig_write = asyncio.StreamWriter.write

        ch1 = await transport.connect("127.0.0.1", port, client_kp)
        # capture the exact wire bytes of one encrypted frame
        frame_bytes = bytearray()
        ch1.writer.write, orig = (
            lambda data: (frame_bytes.extend(data), orig_write(ch1.writer, data)),
            ch1.writer.write,
        )
        await ch1.send(b"secret message")
        ch1.writer.write = orig
        assert (await asyncio.wait_for(results.get(), 2))[0] == "ok"
        ch1.close()

        # new connection, same static keys: replay the recorded ciphertext
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(client_kp.public + b"\x05" * 32)
        await reader.readexactly(64)
        writer.write(bytes(frame_bytes))
        await writer.drain()
        kind, detail = await asyncio.wait_for(results.get(), 2)
        assert kind == "err" and detail == "ChannelClosed"
        writer.close()
        server.close()


class TestSingleNode:
    async def test_new_client_has_faucet_balance(self):
        # cli.rs:239-248
        async with Network(1) as net:
            async with Client(net.rpc_url()) as client:
                user = SignKeyPair.random()
                assert await client.get_balance(user.public) == 100_000

    async def test_transfer_commits_and_conserves(self):
        async with Network(1) as net:
            async with Client(net.rpc_url()) as client:
                sender, recipient = SignKeyPair.random(), SignKeyPair.random()
                await client.send_asset(sender, 1, recipient.public, 100)
                await wait_for_sequence(client, sender.public, 1)
                assert await client.get_balance(sender.public) == 99_900
                assert await client.get_balance(recipient.public) == 100_100

    async def test_latest_transactions_shows_success(self):
        # shell e2e `sent-tx-shows-in-latest-txs` parity
        async with Network(1) as net:
            async with Client(net.rpc_url()) as client:
                sender, recipient = SignKeyPair.random(), SignKeyPair.random()
                await client.send_asset(sender, 1, recipient.public, 42)
                await wait_for_sequence(client, sender.public, 1)
                txs = await client.get_latest_transactions()
                assert len(txs) == 1
                assert txs[0].amount == 42
                assert txs[0].state.name == "SUCCESS"
                assert txs[0].sender == sender.public

    async def test_self_transfer_keeps_balance(self):
        # shell e2e `send-asset-to-itself-keep-balance` parity
        async with Network(1) as net:
            async with Client(net.rpc_url()) as client:
                user = SignKeyPair.random()
                await client.send_asset(user, 1, user.public, 1000)
                await wait_for_sequence(client, user.public, 1)
                assert await client.get_balance(user.public) == 100_000

    async def test_bad_arguments_rejected(self):
        import grpc

        from at2_node_tpu.proto import at2_pb2 as pb
        from at2_node_tpu.proto.rpc import At2Stub

        async with Network(1) as net:
            channel = grpc.aio.insecure_channel(net.configs[0].rpc_address)
            stub = At2Stub(channel)
            with pytest.raises(grpc.aio.AioRpcError) as err:
                await stub.SendAsset(
                    pb.SendAssetRequest(
                        sender=b"short",
                        sequence=1,
                        recipient=b"r" * 32,
                        amount=1,
                        signature=b"s" * 64,
                    )
                )
            assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            await channel.close()


class TestObservability:
    async def test_stats_snapshot_and_periodic_log(self, caplog):
        import logging

        from at2_node_tpu.node.config import ObservabilityConfig
        from at2_node_tpu.node.service import stats_logger

        net = Network(1)
        net.configs[0].observability = ObservabilityConfig(stats_interval=0.2)
        propagate_before = stats_logger.propagate
        try:
            async with net:
                stats_logger.propagate = True  # let caplog see the records
                async with Client(net.rpc_url()) as client:
                    with caplog.at_level(logging.INFO, logger="at2_node_tpu.stats"):
                        sender, recipient = SignKeyPair.random(), SignKeyPair.random()
                        await client.send_asset(sender, 1, recipient.public, 7)
                        await wait_for_sequence(client, sender.public, 1)
                        await asyncio.sleep(0.5)  # at least one stats tick
                snap = net.services[0].snapshot_stats()
                assert snap["committed"] == 1
                assert snap["delivered"] == 1
                assert snap["verifier_signatures"] >= 1
                # transport-plane counters (mesh + rpc mux) ride along
                assert snap["mesh_redials"] == 0
                assert snap["mesh_send_overflows"] == 0
                assert "mesh_channels" in snap and "mesh_send_queue_depth" in snap
                # this test's client is native gRPC (spliced), so the
                # HTTP/1 counter must be exactly zero — catching both a
                # phantom increment and a missing key
                assert snap["rpc_http1_accepted"] == 0
                assert snap["mesh_dial_failures"] == 0
                assert "rpc_splices" in snap
                # each stats line is one JSON object, keys sorted
                import json

                stats_objs = []
                for r in caplog.records:
                    try:
                        obj = json.loads(r.message)
                    except ValueError:
                        continue
                    if isinstance(obj, dict) and "committed" in obj:
                        stats_objs.append(obj)
                assert stats_objs, "no periodic JSON stats line was logged"
                assert stats_objs[-1]["committed"] == 1
        finally:
            stats_logger.propagate = propagate_before


class TestMultiNode:
    async def test_three_node_boot(self):
        # cli.rs:210-213 can_run_network
        async with Network(3):
            pass

    async def test_transfer_visible_on_all_nodes(self):
        # conservation across the net: cli.rs:316-334
        async with Network(4) as net:
            sender, recipient = SignKeyPair.random(), SignKeyPair.random()
            async with Client(net.rpc_url(0)) as c0:
                await c0.send_asset(sender, 1, recipient.public, 250)
            for i in range(4):
                async with Client(net.rpc_url(i)) as c:
                    await wait_for_sequence(c, sender.public, 1)
                    assert await c.get_balance(sender.public) == 99_750
                    assert await c.get_balance(recipient.public) == 100_250

    async def test_sequence_gap_fills(self):
        # out-of-order delivery: seq 2 waits for seq 1 (rpc.rs:195-205)
        async with Network(3) as net:
            sender, recipient = SignKeyPair.random(), SignKeyPair.random()
            async with Client(net.rpc_url(0)) as c0, Client(net.rpc_url(1)) as c1:
                for seq in (1, 2, 3):
                    await c0.send_asset(sender, seq, recipient.public, 10)
                await wait_for_sequence(c1, sender.public, 3)
                assert await c1.get_balance(sender.public) == 99_970

    async def test_same_content_twice_commits_twice(self):
        # shell e2e `send-two-tx-with-same-content-works` parity: same
        # (recipient, amount) under two sequences both commit
        async with Network(3) as net:
            sender, recipient = SignKeyPair.random(), SignKeyPair.random()
            async with Client(net.rpc_url(0)) as client:
                await client.send_asset(sender, 1, recipient.public, 5)
                await wait_for_sequence(client, sender.public, 1)
                await client.send_asset(sender, 2, recipient.public, 5)
                await wait_for_sequence(client, sender.public, 2)
                assert await client.get_balance(recipient.public) == 100_010

    async def test_overdraft_consumes_sequence_but_not_balance(self):
        async with Network(3) as net:
            sender, recipient = SignKeyPair.random(), SignKeyPair.random()
            async with Client(net.rpc_url(0)) as client:
                await client.send_asset(sender, 1, recipient.public, 999_999_999)
                await wait_for_sequence(client, sender.public, 1)
                assert await client.get_balance(sender.public) == 100_000
                assert await client.get_balance(recipient.public) == 100_000
