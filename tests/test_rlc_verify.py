"""Amortized (RLC) verification property tests — ISSUE 10.

Three layers, matching the engine's soundness argument:

* verdict agreement: `RlcEngine.verify_batch` must return EXACTLY the
  per-signature cofactorless verdicts on every input class, including
  the adversarial ones (small-order / mixed-torsion R, tainted-A keys
  whose cofactorless verdict differs from any batched equation);
* bisection cost: with injected check/leaf functions (no curve work),
  a planted culprit must be isolated in ~2*log2(B/leaf) extra checks,
  and the pathological shapes (all-bad, parent-fails-halves-pass)
  resolve exactly without over-trusting any single check;
* routing: the VerifyRouter's policy gates and its convergence against
  a salting source, plus the TpuBatchVerifier capacity invariant when
  a verify_many caller is cancelled while an RLC flush is resolving.

The TPU-twin graph (`ops.aggregate.rlc_verify_batch`) is exercised in
the slow tier only: like the aggregate-certificate graph it wraps, its
triple-table Straus kernel is a minutes-scale XLA compile on CPU
(tests/test_aggregate.py documents the same split).
"""

import asyncio
import hashlib
import threading

import numpy as np
import pytest

from at2_node_tpu.crypto.keys import SignKeyPair, verify_one
from at2_node_tpu.crypto.verifier import (
    CpuVerifier,
    RlcEngine,
    TpuBatchVerifier,
    VerifyRouter,
)
from at2_node_tpu.native.rlc import rlc_available
from at2_node_tpu.ops import ed25519 as base
from at2_node_tpu.ops import edwards as ed

requires_rlc = pytest.mark.skipif(
    not rlc_available(), reason="native rlc library unavailable"
)


def _signed(n, tag=b"rlc"):
    keys = [SignKeyPair.random() for _ in range(n)]
    msgs = [tag + b" %d" % i for i in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return [(k.public, m, s) for k, m, s in zip(keys, msgs, sigs)]


# -- adversarial constructions (same recipe as tests/test_aggregate.py:
# a signer who KNOWS its scalar plants torsion components) ---------------


def _affine_scalar_mult(k, p):
    acc = (0, 1)
    while k:
        if k & 1:
            acc = ed.affine_add_ints(acc, p)
        p = ed.affine_add_ints(p, p)
        k >>= 1
    return acc


def _compress(pt):
    x, y = pt
    enc = bytearray(y.to_bytes(32, "little"))
    if x & 1:
        enc[31] |= 0x80
    return bytes(enc)


def _torsion_point():
    for y in range(2, 60):
        try:
            x = ed._recover_x(y, 0)
        except ValueError:
            continue
        t = _affine_scalar_mult(base.L, (x, y))
        if t != (0, 1):
            return t
    raise AssertionError("no torsion point found")


_BASE_PT = (ed.BX_INT, ed.BY_INT)


def _torsioned_r_item(a_scalar, i=0):
    """Signature whose R carries a small-order component: cofactorless
    per-sig verification REJECTS ([S]B - R - [h]A == -T != identity)."""
    torsion = _torsion_point()
    a_pub = _compress(_affine_scalar_mult(a_scalar, _BASE_PT))
    msg = b"torsioned R %d" % i
    r_nonce = 31337 + i
    r_pt = ed.affine_add_ints(_affine_scalar_mult(r_nonce, _BASE_PT), torsion)
    r_bytes = _compress(r_pt)
    h = (
        int.from_bytes(hashlib.sha512(r_bytes + a_pub + msg).digest(), "little")
        % base.L
    )
    s = (r_nonce + h * a_scalar) % base.L
    return (a_pub, msg, r_bytes + s.to_bytes(32, "little"))


def _tainted_a_item(a_scalar, want_accept):
    """Signature under a pubkey A' = A + T (torsion in the KEY). The
    cofactorless residual is -[h]T, so the per-sig verdict depends on
    h mod ord(T): grinding the message picks acceptance or rejection.
    Either way the lane must NEVER enter the RLC equation — the engine
    reroutes it exactly (certification cache)."""
    torsion = _torsion_point()
    a_pt = _affine_scalar_mult(a_scalar, _BASE_PT)
    a_pub = _compress(ed.affine_add_ints(a_pt, torsion))
    r_nonce = 424242
    r_bytes = _compress(_affine_scalar_mult(r_nonce, _BASE_PT))
    for trial in range(256):
        msg = b"tainted A trial %d" % trial
        h = (
            int.from_bytes(
                hashlib.sha512(r_bytes + a_pub + msg).digest(), "little"
            )
            % base.L
        )
        s = (r_nonce + h * a_scalar) % base.L
        item = (a_pub, msg, r_bytes + s.to_bytes(32, "little"))
        if verify_one(*item) == want_accept:
            return item
    raise AssertionError("torsion order exhausted without a matching h")


# -- verdict agreement ---------------------------------------------------


@requires_rlc
def test_rlc_verdicts_agree_on_adversarial_matrix():
    """One batch striping every input class; engine verdicts must equal
    verify_one lane-for-lane (the ISSUE's core acceptance criterion)."""
    # 28 lanes: after the invalid + rerouted lanes leave, the RLC-eligible
    # set stays above the engine's exact-leaf floor (leaf_size=16) so the
    # amortized check + bisection path actually runs
    items = _signed(28, b"matrix")
    pk0, m0, s0 = items[0]
    items[1] = (items[1][0], items[1][1], items[1][2][:32]
                + bytes([items[1][2][32] ^ 1]) + items[1][2][33:])  # bad s
    items[2] = (items[2][0], b"substituted message", items[2][2])
    # non-canonical s (s + L): host prep must flag it invalid
    s_int = int.from_bytes(items[3][2][32:], "little")
    items[3] = (items[3][0], items[3][1],
                items[3][2][:32] + ((s_int + base.L) % (1 << 256)).to_bytes(32, "little"))
    items[4] = (items[4][0], items[4][1], b"\xff" * 32 + items[4][2][32:])  # bad R enc
    items[5] = _torsioned_r_item(987654321987654321987654321 % base.L)
    items[6] = _tainted_a_item(1122334455667788990 % base.L, want_accept=True)
    items[7] = _tainted_a_item(998877665544332211 % base.L, want_accept=False)

    expected = [verify_one(pk, m, s) for pk, m, s in items]
    engine = RlcEngine()
    got = engine.verify_batch(items)
    assert got == expected
    st = engine.stats()
    assert st["rlc_batches"] == 1
    # the batch carried culprits, so the single check failed and bisected
    assert st["rlc_fallbacks"] == 1 and st["rlc_checks"] >= 1
    # both tainted-A lanes were rerouted — including the ACCEPTING one
    # (reroute, never reject)
    assert st["exact_reroutes"] >= 2
    assert expected[6] is True and got[6] is True


@requires_rlc
def test_rlc_clean_batch_one_check_no_leaves():
    items = _signed(24, b"clean")
    engine = RlcEngine()
    assert engine.verify_batch(items) == [True] * 24
    st = engine.stats()
    assert st["rlc_checks"] == 1
    assert st["rlc_fallbacks"] == 0
    assert st["leaf_sigs"] == 0
    assert st["certified_keys"] == 24


@requires_rlc
def test_rlc_small_order_cancellation_pair_rejected():
    """The test_aggregate cancellation pair (residuals -T, -T built to
    cancel under chosen coefficients): the engine's RANDOM z and torsion
    rounds must still reject both lanes, exactly as per-sig does."""
    torsion = _torsion_point()
    a_scalar = 987654321987654321987654321 % base.L
    a_pub = _compress(_affine_scalar_mult(a_scalar, _BASE_PT))
    attack = []
    for i, r_nonce in enumerate((11111, 22222)):
        msg = b"small-order attack %d" % i
        r_pt = ed.affine_add_ints(
            _affine_scalar_mult(r_nonce, _BASE_PT), torsion
        )
        r_bytes = _compress(r_pt)
        h = (
            int.from_bytes(
                hashlib.sha512(r_bytes + a_pub + msg).digest(), "little"
            )
            % base.L
        )
        s = (r_nonce + h * a_scalar) % base.L
        attack.append((a_pub, msg, r_bytes + s.to_bytes(32, "little")))
    items = attack + _signed(22, b"filler")
    expected = [verify_one(pk, m, s) for pk, m, s in items]
    assert expected[:2] == [False, False]
    assert RlcEngine().verify_batch(items) == expected


@requires_rlc
def test_rlc_cert_cache_hits_across_batches():
    kp = SignKeyPair.random()
    engine = RlcEngine()
    for round_ in range(3):
        items = [
            (kp.public, b"round %d msg %d" % (round_, i), None)
            for i in range(20)
        ]
        items = [(pk, m, kp.sign(m)) for pk, m, _ in items]
        assert engine.verify_batch(items) == [True] * 20
    st = engine.stats()
    assert st["certified_keys"] == 1
    assert st["cert_misses"] == 1  # one exact [L]A, 60 lanes amortized


# -- bisection cost (injected checks: counts, not curve work) ------------


def _planted_engine(bad, leaf_size=16):
    def check(prep, idxs):
        ok = not any(int(i) in bad for i in idxs)
        return ok, np.ones(len(idxs), dtype=bool)

    def leaf(items, idxs):
        return [int(i) not in bad for i in idxs]

    return RlcEngine(leaf_size=leaf_size, check_fn=check, leaf_fn=leaf)


def test_bisection_isolates_single_culprit_in_log_checks():
    n = 256
    items = _signed(n, b"bisect1")
    engine = _planted_engine({5})
    got = engine.verify_batch(items)
    assert got == [i != 5 for i in range(n)]
    st = engine.stats()
    # 1 failing batch check + 2 checks per halving level (256 -> 16)
    levels = 4  # log2(256/16)
    assert st["rlc_checks"] == 1 + 2 * levels
    assert st["bisection_depth"] == levels + 1
    assert st["leaf_sigs"] == 16  # one exact leaf around the culprit
    assert st["rlc_fallbacks"] == 1


def test_bisection_isolates_k_culprits_within_bound():
    n, bad = 256, {10, 80, 150, 240}
    items = _signed(n, b"bisectk")
    engine = _planted_engine(bad)
    assert engine.verify_batch(items) == [i not in bad for i in range(n)]
    st = engine.stats()
    # spread culprits share upper levels; the hard bound is 2k per level
    assert 1 + 2 * 4 < st["rlc_checks"] <= 1 + 2 * len(bad) * 4
    assert st["leaf_sigs"] == 16 * len(bad)


def test_bisection_all_bad_degrades_to_exact():
    n = 64
    items = _signed(n, b"allbad")
    engine = _planted_engine(set(range(n)))
    assert engine.verify_batch(items) == [False] * n
    st = engine.stats()
    assert st["leaf_sigs"] == n  # every lane resolved exactly
    assert st["rlc_checks"] == 7  # 1 + 2 (at 64) + 4 (both 32-halves)


def test_bisection_parent_fails_halves_pass_anomaly():
    """A torsion round firing on the parent and missing on both halves
    must resolve the whole range exactly, not trust either half."""
    n = 64
    items = _signed(n, b"anomaly")

    def check(prep, idxs):
        return len(idxs) < n, np.ones(len(idxs), dtype=bool)

    def leaf(items_, idxs):
        return [True] * len(idxs)

    engine = RlcEngine(leaf_size=16, check_fn=check, leaf_fn=leaf)
    assert engine.verify_batch(items) == [True] * n
    st = engine.stats()
    assert st["rlc_anomalies"] == 1
    assert st["leaf_sigs"] == n


def test_small_batch_skips_rlc_entirely():
    items = _signed(12, b"small")
    engine = _planted_engine({3}, leaf_size=16)
    assert engine.verify_batch(items) == [i != 3 for i in range(12)]
    st = engine.stats()
    assert st["rlc_checks"] == 0  # under the amortization floor
    assert st["leaf_sigs"] == 12


# -- router policy -------------------------------------------------------


def test_router_gates_and_forced_modes():
    srcs = [b"k%d" % i for i in range(16)]
    r = VerifyRouter("auto", min_batch=8)
    assert r.choose(srcs) == "rlc"
    assert r.choose(srcs[:4]) == "per_sig"  # below min_batch
    assert r.choose(srcs, rlc_ready=False) == "per_sig"  # engine not built
    assert VerifyRouter("per_sig", min_batch=1).choose(srcs) == "per_sig"
    assert VerifyRouter("rlc", min_batch=1 << 30).choose(srcs[:2]) == "rlc"
    with pytest.raises(ValueError):
        VerifyRouter("both")


def test_router_converges_against_salter_and_recovers():
    r = VerifyRouter("auto", min_batch=8, expected_bad_budget=0.5)
    salter, honest = b"salter", [b"h%d" % i for i in range(15)]
    batch = [salter] + honest
    assert r.choose(batch) == "rlc"
    # a few salted flushes drive the salter's EWMA over budget
    for _ in range(5):
        r.observe([(salter, False)] + [(h, True) for h in honest])
    assert r.expected_bad(batch) > r.expected_bad_budget
    assert r.choose(batch) == "per_sig"
    assert r.hot_sources() == 1
    # honest-only flushes from other sources still route amortized
    assert r.choose(honest * 2) == "rlc"
    # the salter behaving again decays its EWMA back under budget
    for _ in range(30):
        r.observe([(salter, True)])
    assert r.choose(batch) == "rlc"
    assert r.hot_sources() == 0


def test_router_source_table_is_bounded():
    r = VerifyRouter("auto", max_sources=64)
    r.observe([(b"s%04d" % i, False) for i in range(500)])
    assert r.stats()["router_sources"] == 64


def test_router_stats_shape():
    r = VerifyRouter("auto", min_batch=4)
    r.choose([b"a"] * 8)
    r.choose([b"a"])
    st = r.stats()
    assert st["route_rlc"] == 1 and st["route_per_sig"] == 1
    assert st["route_last"] == "per_sig" and st["route_last_batch"] == 1
    assert st["route_rlc_lanes_count"] == 1


# -- CpuVerifier integration ---------------------------------------------


@requires_rlc
def test_cpu_verifier_rlc_mode_exact_verdicts():
    async def run():
        v = CpuVerifier(mode="rlc", rlc_min_batch=8)
        await v.warmup()
        items = _signed(24, b"cpu-rlc")
        items[7] = (items[7][0], b"tampered", items[7][2])
        try:
            got = await v.verify_many(items)
        finally:
            await v.close()
        assert got == [i != 7 for i in range(24)]
        st = v.stats()
        assert st["route_rlc"] >= 1 and st["rlc_batches"] >= 1
        assert st["rlc_fallbacks"] >= 1

    asyncio.run(run())


@requires_rlc
def test_cpu_verifier_auto_flips_to_per_sig_under_salting():
    async def run():
        v = CpuVerifier(mode="auto", rlc_min_batch=8)
        await v.warmup()
        salter = SignKeyPair.random()
        try:
            clean = _signed(16, b"pre-salt")
            assert await v.verify_many(clean) == [True] * 16
            assert v.router.last_route == "rlc"
            # salted flushes: the salter's lane always fails
            for round_ in range(4):
                items = _signed(12, b"salt %d" % round_)
                m = b"salted %d" % round_
                items.append((salter.public, m, b"\0" * 64))
                got = await v.verify_many(items)
                assert got == [True] * 12 + [False]
            # its EWMA now prices any batch it rides over budget
            batch_srcs = [it[0] for it in _signed(12, b"x")] + [salter.public]
            assert (
                v.router.expected_bad(batch_srcs)
                > v.router.expected_bad_budget
            )
            items = _signed(15, b"post-salt") + [
                (salter.public, b"again", b"\0" * 64)
            ]
            await v.verify_many(items)
            assert v.router.last_route == "per_sig"
        finally:
            await v.close()

    asyncio.run(run())


# -- TpuBatchVerifier: capacity safety while an RLC flush resolves -------


class _GatedRlcVerifier(TpuBatchVerifier):
    """Stage-stubbed twin (tests/test_verifier.py idiom): the RLC finish
    stage blocks on a gate so the test can cancel callers while a flush
    is mid-resolution."""

    def __init__(self, gate, **kw):
        super().__init__(**kw)
        self._gate = gate

    def _prep(self, pks, msgs, sigs, bucket):
        return len(pks)

    def _launch(self, packed):
        return packed

    def _finish(self, handle, n):
        return np.ones(n, dtype=bool)

    def _prep_rlc(self, pks, msgs, sigs, bucket):
        return len(pks)

    def _launch_rlc(self, packed):
        return packed

    def _finish_rlc(self, handle, n):
        self._gate.wait(10.0)
        return True, np.ones(n, dtype=np.int64)


def test_cancelled_verify_many_mid_rlc_releases_capacity():
    async def run():
        gate = threading.Event()
        v = _GatedRlcVerifier(
            gate,
            batch_size=8,
            max_delay=30.0,
            max_queue=16,
            mode="rlc",
            rlc_min_batch=1,
        )
        try:
            # full batch -> immediate flush -> blocks in _finish_rlc
            inflight = asyncio.create_task(v.verify_many(_signed(8, b"in")))
            await asyncio.sleep(0.05)
            assert v.router.last_route == "rlc"
            # second caller's chunk is UNDER batch_size: it parks in the
            # accumulator holding reserved capacity
            parked = asyncio.create_task(v.verify_many(_signed(4, b"park")))
            await asyncio.sleep(0.05)
            assert v._cap_free == v.max_queue - 4
            parked.cancel()
            with pytest.raises(asyncio.CancelledError):
                await parked
            # the cancelled caller's reservation is back, with the RLC
            # flush still mid-resolution
            assert v._cap_free == v.max_queue
            assert not gate.is_set()
            gate.set()
            assert await inflight == [True] * 8
            assert v.rlc_batches == 1 and v.rlc_fallbacks == 0
        finally:
            gate.set()
            await v.close()
        # every pipeline slot drained back
        assert v._inflight._value == v.PIPELINE_DEPTH
        assert v._cap_free == v.max_queue

    asyncio.run(run())


def test_tpu_auto_default_never_routes_rlc():
    """On-chip auto keeps the per-sig kernel unless the operator opts in
    (AGGREGATE_r02: one-MSM certificate shape measured SLOWER than the
    Pallas per-sig kernel at every banked bucket)."""

    async def run():
        gate = threading.Event()
        gate.set()
        v = _GatedRlcVerifier(gate, batch_size=8, max_delay=0.001, mode="auto")
        try:
            assert await v.verify_many(_signed(8, b"auto")) == [True] * 8
            assert v.rlc_batches == 0
            assert v.router.route_rlc == 0
        finally:
            await v.close()

    asyncio.run(run())


# -- TPU-twin graph (slow tier: the triple-table Straus graph is a
# minutes-scale XLA compile on CPU, same pathology and same tiering as
# tests/test_aggregate.py) ----------------------------------------------


@pytest.mark.slow
def test_rlc_graph_matches_per_sig_kernel():
    from at2_node_tpu.ops.aggregate import rlc_verify_batch

    n = 8
    items = _signed(n, b"twin")
    items[3] = (items[3][0], b"tampered", items[3][2])
    items[5] = _torsioned_r_item(555444333222111 % base.L, i=5)
    pks = [it[0] for it in items]
    msgs = [it[1] for it in items]
    sigs = [it[2] for it in items]
    expected = [verify_one(pk, m, s) for pk, m, s in items]
    got = rlc_verify_batch(pks, msgs, sigs, n)
    assert list(np.asarray(got, dtype=bool)) == expected
