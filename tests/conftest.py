"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is not available in CI; shardings are validated on a
virtual CPU mesh, mirroring how the driver dry-runs the multi-chip path.

Note: this environment preloads jax via a .pth hook with JAX_PLATFORMS=axon
baked in, so env-var edits here are too late — `jax.config.update` is the
reliable way to retarget the (not-yet-initialized) backend.
"""

import asyncio
import inspect
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.4.38) has no jax_num_cpu_devices option. XLA_FLAGS
    # still works here because the backend initializes lazily — no device
    # has been touched yet at conftest import time.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Persistent XLA compilation cache: the crypto graphs take tens of seconds
# to compile; caching them across test processes/runs cuts the kernel test
# tier from ~19 minutes to seconds on re-runs (round-1 weak item #7).
_CACHE_DIR = os.path.join(_REPO, ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run the coroutine test on a fresh event loop")
    config.addinivalue_line("markers", "slow: long-running (interpreter-mode Pallas, big compiles); deselect with -m 'not slow'")


async def _run_with_watchdog(coro, timeout=900):
    """Turn async-test hangs into failures with task stacks (a real hang
    once cost a whole CI run; XLA compiles inside async tests can
    legitimately take minutes on one core, hence the generous bound)."""
    task = asyncio.ensure_future(coro)
    done, pending = await asyncio.wait({task}, timeout=timeout)
    if pending:
        import sys

        print("\n=== WATCHDOG: test hung; task stacks ===", file=sys.stderr)
        for t in asyncio.all_tasks():
            print("--- task:", t.get_name(), file=sys.stderr)
            t.print_stack(file=sys.stderr)
        task.cancel()
        raise TimeoutError("async test hung (watchdog)")
    return task.result()


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async-test support (pytest-asyncio is not installed): any
    coroutine test function runs on a fresh event loop."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(_run_with_watchdog(fn(**kwargs)))
        return True
    return None


# ---- shared multi-node test helpers (one copy; each test module passes
# its own port counter so ranges stay disjoint across files) ----

NET_TICK = 0.1
NET_TIMEOUT = 15.0


def make_net_configs(n, ports, **config_overrides):
    """N full-mesh node Configs with fresh keys — delegates to the tools'
    canonical builder so tests and benches construct nets one way."""
    from at2_node_tpu.tools._common import make_net_configs as _make

    return _make(n, ports, **config_overrides)


async def wait_until(pred, timeout=NET_TIMEOUT, what="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if await pred():
            return
        await asyncio.sleep(NET_TICK)
    raise TimeoutError(f"{what} not reached within {timeout}s")
