"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is not available in CI; shardings are validated on a
virtual CPU mesh, mirroring how the driver dry-runs the multi-chip path.

Note: this environment preloads jax via a .pth hook with JAX_PLATFORMS=axon
baked in, so env-var edits here are too late — `jax.config.update` is the
reliable way to retarget the (not-yet-initialized) backend.
"""

import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
