"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is not available in CI; shardings are validated on a
virtual CPU mesh, mirroring how the driver dry-runs the multi-chip path.

Note: this environment preloads jax via a .pth hook with JAX_PLATFORMS=axon
baked in, so env-var edits here are too late — `jax.config.update` is the
reliable way to retarget the (not-yet-initialized) backend.
"""

import asyncio
import inspect
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run the coroutine test on a fresh event loop")


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async-test support (pytest-asyncio is not installed): any
    coroutine test function runs on a fresh event loop."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
