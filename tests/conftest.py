"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is not available in CI; shardings are validated on a
virtual CPU mesh (`--xla_force_host_platform_device_count`), mirroring how
the driver dry-runs the multi-chip path. Must run before `import jax`.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
