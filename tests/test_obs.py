"""Observability subsystem tests (obs/ package + GET endpoints).

Three tiers, mirroring how the subsystem is layered:

* registry unit tests — instrument semantics (monotonicity, histogram
  bucket math, percentile interpolation), thread + asyncio concurrency
  exactness (the verifier bumps histograms from worker threads while the
  broadcast plane bumps counters on the event loop), CounterGroup
  dict-compat;
* TxTrace behavior — sampling lottery, cardinality cap eviction,
  idempotent / order-tolerant stamps;
* endpoint e2e — raw HTTP/1.1 GETs against the same public PortMux port
  that serves native gRPC and grpc-web, validating the Prometheus
  exposition format, the JSON bodies, 404 routing, the config
  kill-switch, and keep-alive reuse (the endpoints ride the grpc-web
  HTTP/1 loop, so they inherit its connection accounting).
"""

import asyncio
import itertools
import json
import math
import threading

import pytest

from at2_node_tpu.client import Client
from at2_node_tpu.crypto.keys import ExchangeKeyPair, SignKeyPair
from at2_node_tpu.net.peers import Peer
from at2_node_tpu.node.config import Config, ObservabilityConfig
from at2_node_tpu.node.service import Service
from at2_node_tpu.obs import (
    REJECTED,
    STAGES,
    Counter,
    CounterGroup,
    FlightRecorder,
    Gauge,
    Histogram,
    Registry,
    TxTrace,
)

_ports = itertools.count(25600)

TICK = 0.1
TIMEOUT = 10.0


def make_configs(n, **overrides):
    cfgs = [
        Config(
            node_address=f"127.0.0.1:{next(_ports)}",
            rpc_address=f"127.0.0.1:{next(_ports)}",
            sign_key=SignKeyPair.random(),
            network_key=ExchangeKeyPair.random(),
            **overrides,
        )
        for _ in range(n)
    ]
    for i, cfg in enumerate(cfgs):
        cfg.nodes = [
            Peer(o.node_address, o.network_key.public, o.sign_key.public)
            for j, o in enumerate(cfgs)
            if j != i
        ]
    return cfgs


# ---------------------------------------------------------------- registry


class TestInstruments:
    def test_counter_monotonic(self):
        c = Counter("events")
        c.inc()
        c.inc(41)
        assert c.value == 42
        with pytest.raises(ValueError):
            c.inc(-1)
        with pytest.raises(ValueError):
            c.set(41)  # set() exists for CounterGroup but stays monotonic
        c.set(50)
        assert c.value == 50

    def test_gauge_set_and_fn(self):
        g = Gauge("depth")
        g.set(7)
        assert g.value == 7
        backing = [3]
        g2 = Gauge("lazy", fn=lambda: backing[0])
        assert g2.value == 3 and isinstance(g2.value, int)
        backing[0] = 9
        assert g2.value == 9
        with pytest.raises(RuntimeError):
            g2.set(1)  # callback-backed gauges are read-only

    def test_gauge_fn_exception_reads_zero(self):
        def boom():
            raise RuntimeError("dead component")

        g = Gauge("broken", fn=boom)
        assert g.value == 0.0  # a dead provider must not take stats down

    def test_histogram_exact_count_sum_max(self):
        h = Histogram("lat")
        for v in (0.001, 0.002, 0.004, 0.100):
            h.observe(v)
        h.observe(-1.0)  # negative (clock skew): dropped
        h.observe(float("nan"))  # dropped
        snap = h.snapshot()
        assert snap["count"] == 4
        assert snap["sum_ms"] == pytest.approx(107.0, abs=0.01)
        assert snap["max_ms"] == pytest.approx(100.0, abs=0.01)

    def test_histogram_bucket_math(self):
        # bounds 1,2,4: values land in the right bucket, cumulative
        # counts are monotone, +Inf equals the total count
        h = Histogram("b", bounds=(1.0, 2.0, 4.0))
        for v in (0.5, 1.0, 1.5, 3.0, 100.0):
            h.observe(v)
        pairs, total, count = h.buckets()
        assert [(le, c) for le, c in pairs] == [
            (1.0, 2),  # 0.5, 1.0 (le is inclusive)
            (2.0, 3),  # + 1.5
            (4.0, 4),  # + 3.0
            (math.inf, 5),  # + 100.0 overflow
        ]
        assert count == 5 and total == pytest.approx(106.0)

    def test_histogram_percentiles_interpolated_and_capped(self):
        h = Histogram("p", bounds=(1.0, 2.0, 4.0, 8.0))
        for _ in range(100):
            h.observe(3.0)
        snap = h.snapshot()
        # all mass in one bucket: percentiles interpolate inside (2,3]
        # (capped at the observed max), so p50 < p99 <= max
        assert 2000.0 < snap["p50_ms"] <= 3000.0
        assert snap["p50_ms"] < snap["p99_ms"] <= snap["max_ms"]

    def test_histogram_rejects_bad_bounds(self):
        with pytest.raises(ValueError):
            Histogram("bad", bounds=(2.0, 1.0))

    def test_counter_group_dict_compat(self):
        r = Registry()
        g = r.counter_group(("rx", "tx"))
        g["rx"] += 1  # the migrated call-site surface
        g["rx"] += 2
        assert g["rx"] == 3 and g["tx"] == 0
        assert "rx" in g and "nope" not in g
        assert sorted(g.keys()) == ["rx", "tx"]
        assert dict(g.items()) == {"rx": 3, "tx": 0}
        assert g.as_dict() == {"rx": 3, "tx": 0}
        assert g.get("nope", 7) == 7
        assert len(g) == 2 and set(g) == {"rx", "tx"}
        with pytest.raises(KeyError):
            g["typo"] += 1  # fixed key set, like the old literal dicts

    def test_registry_get_or_create_and_kind_check(self):
        r = Registry()
        assert r.counter("x") is r.counter("x")
        with pytest.raises(TypeError):
            r.gauge("x")  # same name, different kind

    def test_registry_snapshot_merges_providers(self):
        r = Registry()
        r.counter("a").inc(2)
        r.histogram("h").observe(0.001)
        r.register_provider("vrf_", lambda: {"batches": 5})
        r.register_provider("dead_", lambda: 1 / 0)  # swallowed
        snap = r.snapshot()
        assert snap["a"] == 2
        assert snap["vrf_batches"] == 5
        assert snap["h_count"] == 1 and "h_p99_ms" in snap
        assert not any(k.startswith("dead_") for k in snap)

    def test_prometheus_exposition_format(self):
        r = Registry()
        r.counter("commits", "total commits").inc(3)
        r.gauge("depth").set(2)
        r.histogram("lat", bounds=(0.001, 0.01)).observe(0.005)
        r.register_provider("vrf_", lambda: {"occ": 0.5, "skip": "str"})
        text = r.render_prometheus()
        assert text.endswith("\n")
        lines = text.splitlines()
        assert "# TYPE at2_commits_total counter" in lines
        assert "at2_commits_total 3" in lines
        assert "at2_depth 2" in lines
        assert '''at2_lat_seconds_bucket{le="0.001"} 0''' in lines
        assert '''at2_lat_seconds_bucket{le="+Inf"} 1''' in lines
        assert "at2_lat_seconds_count 1" in lines
        assert "at2_vrf_occ 0.5" in lines
        assert not any("skip" in ln for ln in lines)  # non-numeric dropped
        # every sample line is `name{labels}? value` with a float value
        for ln in lines:
            if ln.startswith("#"):
                continue
            name, _, value = ln.rpartition(" ")
            assert name and float(value) is not None


class TestConcurrency:
    def test_threaded_counter_and_histogram_exact(self):
        # the verifier contract: worker threads bump instruments while
        # the event loop reads them — totals must come out exact
        r = Registry()
        c = r.counter("hits")
        h = r.histogram("lat")
        n_threads, per_thread = 8, 5000

        def worker():
            for _ in range(per_thread):
                c.inc()
                h.observe(0.001)

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n_threads * per_thread
        assert h.snapshot()["count"] == n_threads * per_thread

    async def test_asyncio_tasks_counter_exact(self):
        r = Registry()
        c = r.counter("ticks")

        async def bump():
            for _ in range(1000):
                c.inc()
                if _ % 100 == 0:
                    await asyncio.sleep(0)  # interleave tasks

        await asyncio.gather(*(bump() for _ in range(10)))
        assert c.value == 10_000


# ----------------------------------------------------------------- TxTrace


class TestTxTrace:
    def test_full_lifecycle_feeds_histograms(self):
        r = Registry()
        tr = TxTrace(r, sample_every=1)
        key = (b"s" * 32, 1)
        tr.begin(key, now=100.0)
        for i, stage in enumerate(STAGES[1:], start=1):
            tr.stamp(key, stage, now=100.0 + i * 0.01)
        assert tr.live == 0  # committed removes the live record
        snap = tr.snapshot()
        for stage in STAGES[1:]:
            assert snap[f"ingress_to_{stage}"]["count"] == 1
        assert snap[f"ingress_to_{STAGES[-1]}"]["max_ms"] == pytest.approx(
            50.0, abs=0.5
        )
        stats = r.snapshot()
        assert stats["tx_traced"] == 1
        assert stats["tx_trace_completed"] == 1

    def test_sampling_every_nth(self):
        r = Registry()
        tr = TxTrace(r, sample_every=3)
        for seq in range(1, 10):  # 9 ingresses -> 3 traced
            tr.begin((b"s" * 32, seq))
        assert r.counter("tx_traced").value == 3

    def test_disabled_traces_nothing(self):
        r = Registry()
        tr = TxTrace(r, sample_every=0)
        assert not tr.enabled
        tr.begin((b"s" * 32, 1))
        assert tr.live == 0 and r.counter("tx_traced").value == 0

    def test_cap_evicts_oldest(self):
        r = Registry()
        tr = TxTrace(r, sample_every=1, cap=4)
        for seq in range(1, 7):
            tr.begin((b"s" * 32, seq))
        assert tr.live == 4
        assert r.counter("tx_trace_evicted").value == 2
        # the evicted (oldest) trace no longer stamps
        tr.stamp((b"s" * 32, 1), "committed")
        assert r.counter("tx_trace_completed").value == 0

    def test_stamps_idempotent_and_order_tolerant(self):
        r = Registry()
        tr = TxTrace(r, sample_every=1)
        key = (b"s" * 32, 1)
        tr.begin(key, now=0.0)
        tr.stamp(key, "delivered", now=1.0)
        tr.stamp(key, "echoed", now=2.0)  # backwards: ignored
        tr.stamp(key, "delivered", now=3.0)  # duplicate: ignored
        snap = tr.snapshot()
        assert snap["ingress_to_delivered"]["count"] == 1
        assert snap["ingress_to_echoed"]["count"] == 0
        tr.stamp((b"x" * 32, 9), "committed")  # untraced key: no-op
        assert r.counter("tx_trace_completed").value == 0

    def test_bad_params_rejected(self):
        r = Registry()
        with pytest.raises(ValueError):
            TxTrace(r, sample_every=-1)
        with pytest.raises(ValueError):
            TxTrace(r, cap=0)
        with pytest.raises(ValueError):
            TxTrace(r, done_cap=0)

    def test_stamps_carry_mono_and_wall_timestamps(self):
        # every stage retains BOTH clocks: monotonic for local deltas,
        # wall for the cross-node join (tools/trace_collect.py)
        r = Registry()
        tr = TxTrace(r, sample_every=1)
        key = (b"s" * 32, 1)
        tr.begin(key)
        tr.stamp(key, "admitted")
        rec = tr.tracez()["live"][0]
        assert rec["sender"] == (b"s" * 32).hex() and rec["seq"] == 1
        assert rec["origin"] is True and rec["terminal"] is None
        assert [s[0] for s in rec["stages"]] == ["ingress", "admitted"]
        for _stage, mono, wall in rec["stages"]:
            assert isinstance(mono, float) and isinstance(wall, float)

    def test_committed_retires_into_completed_ring(self):
        r = Registry()
        tr = TxTrace(r, sample_every=1)
        key = (b"s" * 32, 1)
        tr.begin(key, now=0.0)
        for i, stage in enumerate(STAGES[1:], start=1):
            tr.stamp(key, stage, now=float(i))
        z = tr.tracez()
        assert z["live"] == []
        (rec,) = z["completed"]
        assert rec["terminal"] == "committed"
        assert [s[0] for s in rec["stages"]] == list(STAGES)

    def test_rejected_is_terminal_and_feeds_histogram(self):
        r = Registry()
        tr = TxTrace(r, sample_every=1)
        key = (b"s" * 32, 1)
        tr.begin(key, now=10.0)
        tr.stamp(key, REJECTED, now=10.5)
        assert tr.live == 0
        (rec,) = tr.tracez()["completed"]
        assert rec["terminal"] == REJECTED
        snap = tr.snapshot()
        assert snap["ingress_to_rejected"]["count"] == 1
        assert snap["ingress_to_rejected"]["max_ms"] == pytest.approx(
            500.0, abs=1.0
        )
        assert r.counter("tx_trace_rejected").value == 1
        # rejection never resurrects: later stamps on the key are no-ops
        tr.stamp(key, "committed", now=11.0)
        assert r.counter("tx_trace_completed").value == 0

    def test_completed_ring_bounded_by_done_cap(self):
        r = Registry()
        tr = TxTrace(r, sample_every=1, done_cap=3)
        for seq in range(1, 6):
            key = (b"s" * 32, seq)
            tr.begin(key)
            tr.stamp(key, "committed")
        done = tr.tracez()["completed"]
        assert [rec["seq"] for rec in done] == [3, 4, 5]
        # limit keeps the NEWEST n; 0 keeps none
        assert [r_["seq"] for r_ in tr.tracez(limit=2)["completed"]] == [4, 5]
        assert tr.tracez(limit=0)["completed"] == []

    def test_relay_records_join_without_feeding_histograms(self):
        # a stamp for a key never seen at ingress opens a RELAY span:
        # counted separately, kept out of the latency histograms (no
        # ingress t0 to measure from), exported for the stitcher
        r = Registry()
        tr = TxTrace(r, sample_every=1)
        key = (b"r" * 32, 7)
        tr.stamp(key, "echoed")
        assert tr.live == 1
        assert r.counter("tx_traced").value == 0
        assert r.counter("tx_trace_relayed").value == 1
        tr.stamp(key, "committed")
        (rec,) = tr.tracez()["completed"]
        assert rec["origin"] is False
        assert [s[0] for s in rec["stages"]] == ["echoed", "committed"]
        snap = tr.snapshot()
        assert snap["ingress_to_committed"]["count"] == 0

    def test_relay_lottery_is_key_based(self):
        # sample_every=2: relay records open for the same HALF of the
        # key space on every node (key-hash, not arrival order), so
        # sampled spans join across the fleet
        r = Registry()
        tr = TxTrace(r, sample_every=2)
        for seq in range(1, 9):
            tr.stamp((bytes([0]) * 32, seq), "echoed")
        assert tr.live == 4  # even (0 + seq) % 2 == 0 keys only
        assert r.counter("tx_trace_relayed").value == 4


# ------------------------------------------------------- flight recorder


class TestFlightRecorder:
    def test_ring_bounded_with_drop_accounting(self):
        rec = FlightRecorder(cap=4)
        for i in range(10):
            rec.record("rx", (i,))
        d = rec.dump()
        assert d["cap"] == 4 and d["recorded"] == 10 and d["dropped"] == 6
        assert len(d["events"]) == 4
        # ring keeps the NEWEST cap events, oldest first
        assert [e[2][0] for e in d["events"]] == [6, 7, 8, 9]
        assert [e[1] for e in d["events"]] == ["rx"] * 4
        # paired clock readings for wall alignment at the consumer
        assert d["now_monotonic"] > 0 and d["now_wall"] > 0

    def test_cap_zero_disables(self):
        rec = FlightRecorder(cap=0)
        assert not rec.enabled
        rec.record("rx", (1,))
        rec.snapshot("anomaly")
        d = rec.dump()
        assert d["recorded"] == 0 and d["events"] == []
        assert d["snapshots"] == []

    def test_snapshots_survive_rollover_and_stay_bounded(self):
        rec = FlightRecorder(cap=2, max_snapshots=2)
        rec.record("a", (1,))
        rec.snapshot("first")
        for i in range(5):
            rec.record("b", (i,))
        # the frozen copy still shows the pre-rollover ring
        d = rec.dump()
        assert len(d["snapshots"]) == 1
        assert [e[1] for e in d["snapshots"][0]["events"]] == ["a"]
        # a flapping anomaly cannot grow the snapshot list unboundedly
        for n in range(5):
            rec.snapshot(f"flap{n}")
        d = rec.dump()
        assert len(d["snapshots"]) == 2
        assert rec.snapshots_taken == 6
        assert [s["reason"] for s in d["snapshots"]] == ["flap3", "flap4"]

    def test_thread_safety_exact_total(self):
        rec = FlightRecorder(cap=256)
        n_threads, per = 8, 500

        def hammer(t):
            for i in range(per):
                rec.record("t", (t, i))

        threads = [
            threading.Thread(target=hammer, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        d = rec.dump()
        assert d["recorded"] == n_threads * per
        assert len(d["events"]) == 256

    def test_bad_params_rejected(self):
        with pytest.raises(ValueError):
            FlightRecorder(cap=-1)
        with pytest.raises(ValueError):
            FlightRecorder(max_snapshots=0)


# ------------------------------------------------- stitch + tail (pure)


class TestTraceTools:
    def _dump(self, node, records):
        return {"node": node, "live": [], "completed": records}

    def _rec(self, seq, origin, stages, terminal="committed"):
        return {
            "sender": "aa" * 32,
            "seq": seq,
            "origin": origin,
            "terminal": terminal,
            "stages": stages,
        }

    def test_stitch_joins_and_attributes_stragglers(self):
        from at2_node_tpu.tools.trace_collect import stitch

        origin = self._dump("n0", [self._rec(
            1, True,
            [["ingress", 0.0, 100.0], ["echoed", 0.01, 100.01],
             ["committed", 0.05, 100.05]],
        )])
        relay = self._dump("n1", [self._rec(
            1, False,
            [["echoed", 7.02, 100.02], ["committed", 7.09, 100.09]],
        )])
        st = stitch([relay, origin])  # polling order must not matter
        assert st["coverage"] == {
            "txs": 1, "committed": 1,
            "stitched_committed": 1, "with_origin": 1,
            "with_broker": 0,
        }
        (tx,) = st["txs"]
        assert tx["origin_node"] == "n0" and tx["nodes"] == 2
        # times normalize to the ORIGIN ingress wall stamp (t=0)
        n1 = [s for s in tx["spans"] if s["node"] == "n1"][0]
        assert n1["stages"] == [["echoed", 0.02], ["committed", 0.09]]
        # n1 was last into both stages: it is the straggler
        assert tx["stragglers"]["committed"] == ["n1", 0.09]
        assert st["straggler_counts"]["echoed"] == {"n1": 1}
        # pure: same dumps in, byte-identical JSON out
        assert json.dumps(st, sort_keys=True) == json.dumps(
            stitch([relay, origin]), sort_keys=True
        )

    def test_stitch_broker_hop_decomposition(self):
        from at2_node_tpu.tools.trace_collect import stitch

        # the broker saw the tx first (rx at t=-0.04 relative to node
        # ingress), flushed at -0.01; the node committed at +0.05 — the
        # hop decomposes into queue 30ms, handoff 10ms, plane 50ms
        broker = self._dump("broker:127.0.0.1:9", [self._rec(
            1, False,
            [["broker_rx", 0.0, 99.96], ["broker_flush", 0.03, 99.99]],
            terminal="broker_flush",
        )])
        node = self._dump("n0", [self._rec(
            1, True,
            [["ingress", 0.0, 100.0], ["committed", 0.05, 100.05]],
        )])
        st = stitch([node, broker])
        assert st["coverage"]["with_broker"] == 1
        (tx,) = st["txs"]
        hop = tx["broker_hop"]
        # rels normalize to the ORIGIN ingress stamp: the broker stages
        # land at negative offsets (custody precedes node ingress)
        assert hop["rx"] == -0.04 and hop["flush"] == -0.01
        assert hop["queue_ms"] == 30.0
        assert hop["handoff_ms"] == 10.0
        assert hop["plane_ms"] == 50.0
        assert hop["total_ms"] == 90.0
        assert hop["bottleneck"] == "plane_ms"
        seg = st["broker_hop"]["segments"]
        assert seg["total_ms"]["count"] == 1
        assert seg["total_ms"]["p99_ms"] == 90.0
        assert st["broker_hop"]["bottleneck_counts"] == {"plane_ms": 1}
        # pure: same dumps in, byte-identical JSON out
        assert json.dumps(st, sort_keys=True) == json.dumps(
            stitch([node, broker]), sort_keys=True
        )

    def test_chrome_trace_shape(self):
        from at2_node_tpu.tools.trace_collect import chrome_trace, stitch

        st = stitch([self._dump("n0", [self._rec(
            1, True,
            [["ingress", 0.0, 100.0], ["committed", 0.05, 100.05]],
        )])])
        ev = chrome_trace(st)["traceEvents"]
        (x,) = [e for e in ev if e["ph"] == "X"]
        assert x["name"] == "ingress→committed"
        assert x["ts"] == 0 and x["dur"] == 50_000  # µs
        assert any(e["ph"] == "M" for e in ev)  # process/thread names
        assert any(e["ph"] == "i" for e in ev)  # terminal instant

    def test_top_tracez_tail_dedups(self):
        from at2_node_tpu.tools.top import render_trace_lines

        dump = self._dump("n0", [self._rec(
            3, True,
            [["ingress", 0.0, 100.0], ["committed", 0.05, 100.05]],
        )])
        seen: set = set()
        first = render_trace_lines("127.0.0.1:7001", dump, seen)
        assert len(first) == 1
        assert "committed" in first[0] and "50.00" in first[0]
        # second poll with the same ring: nothing new to print
        assert render_trace_lines("127.0.0.1:7001", dump, seen) == []


# ----------------------------------------------------- endpoints over mux


async def _http_get(reader, writer, path, keep=False):
    """One GET on an open connection; returns (status, headers, body).
    Reads exactly Content-Length so the connection survives keep-alive."""
    conn = "keep-alive" if keep else "close"
    writer.write(
        f"GET {path} HTTP/1.1\r\nHost: n\r\nConnection: {conn}\r\n\r\n".encode()
    )
    await writer.drain()
    head = await reader.readuntil(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ")[1])
    headers = {}
    for ln in lines[1:]:
        if ":" in ln:
            k, v = ln.split(":", 1)
            headers[k.strip().lower()] = v.strip()
    body = await reader.readexactly(int(headers["content-length"]))
    return status, headers, body


async def _get(addr, path):
    host, _, port = addr.rpartition(":")
    reader, writer = await asyncio.open_connection(host, int(port))
    try:
        return await _http_get(reader, writer, path)
    finally:
        writer.close()


class _Node:
    def __init__(self, **overrides):
        self.config = make_configs(1, **overrides)[0]

    async def __aenter__(self):
        self.service = await Service.start(self.config)
        return self

    async def __aexit__(self, *exc):
        await self.service.close()


class TestEndpoints:
    async def test_metrics_healthz_statusz_after_commit(self):
        async with _Node() as node:
            addr = node.config.rpc_address
            async with Client(f"http://{addr}") as client:
                sender, recipient = SignKeyPair.random(), SignKeyPair.random()
                await client.send_asset(sender, 1, recipient.public, 5)
                deadline = asyncio.get_event_loop().time() + TIMEOUT
                while await client.get_last_sequence(sender.public) != 1:
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(TICK)

            # /metrics: Prometheus text exposition on the public RPC port
            status, headers, body = await _get(addr, "/metrics")
            assert status == 200
            assert headers["content-type"].startswith("text/plain")
            text = body.decode()
            lines = text.splitlines()
            assert "at2_committed 1" in lines
            assert any(
                ln.startswith("# TYPE at2_tx_ingress_to_committed_seconds")
                for ln in lines
            )
            # bucket series are cumulative and close with +Inf == count
            buckets = [
                ln for ln in lines
                if ln.startswith("at2_tx_ingress_to_committed_seconds_bucket")
            ]
            counts = [int(ln.rpartition(" ")[2]) for ln in buckets]
            assert counts == sorted(counts) and counts[-1] == 1
            assert 'le="+Inf"' in buckets[-1]
            for ln in lines:  # every sample parses as `name value`
                if not ln.startswith("#"):
                    float(ln.rpartition(" ")[2])

            # /healthz: liveness + quorum verdict (single node: trivially ok)
            status, headers, body = await _get(addr, "/healthz")
            assert status == 200
            health = json.loads(body)
            assert health["status"] == "ok"
            assert health["committed"] == 1

            # /statusz: the operator JSON the top.py dashboard polls
            status, headers, body = await _get(addr, "/statusz")
            assert status == 200
            assert headers["content-type"].startswith("application/json")
            sz = json.loads(body)
            assert set(sz) >= {
                "node", "rpc_address", "health", "stats",
                "tx_lifecycle", "verifier_stages",
            }
            life = sz["tx_lifecycle"]["ingress_to_committed"]
            assert life["count"] == 1 and life["p99_ms"] > 0.0
            assert sz["stats"]["committed"] == 1

            # unknown GET path routes to 404, connection still usable
            status, _, body = await _get(addr, "/nope")
            assert status == 404 and body == b"not found"

    async def test_keep_alive_reuses_one_connection(self):
        async with _Node() as node:
            addr = node.config.rpc_address
            host, _, port = addr.rpartition(":")
            reader, writer = await asyncio.open_connection(host, int(port))
            try:
                for path in ("/healthz", "/metrics", "/statusz"):
                    status, headers, _ = await _http_get(
                        reader, writer, path, keep=True
                    )
                    assert status == 200
                    assert headers["connection"] == "keep-alive"
            finally:
                writer.close()

    async def test_endpoints_disabled_by_config(self):
        async with _Node(
            observability=ObservabilityConfig(endpoints=False)
        ) as node:
            for path in (
                "/metrics", "/healthz", "/statusz", "/tracez", "/debugz",
                "/sloz",
            ):
                status, _, _ = await _get(node.config.rpc_address, path)
                assert status == 404

    async def test_tracez_and_debugz_after_commit(self):
        async with _Node() as node:
            addr = node.config.rpc_address
            async with Client(f"http://{addr}") as client:
                sender = SignKeyPair.random()
                await client.send_asset(
                    sender, 1, SignKeyPair.random().public, 5
                )
                deadline = asyncio.get_event_loop().time() + TIMEOUT
                while await client.get_last_sequence(sender.public) != 1:
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(TICK)

            # /tracez: the committed tx sits in the completed ring with
            # the full stage ladder and a paired clock reading
            status, headers, body = await _get(addr, "/tracez")
            assert status == 200
            assert headers["content-type"].startswith("application/json")
            z = json.loads(body)
            assert set(z) >= {"node", "clock", "live", "completed"}
            (rec,) = [
                r_ for r_ in z["completed"]
                if r_["sender"] == sender.public.hex()
            ]
            assert rec["origin"] is True
            assert rec["terminal"] == "committed"
            stages = [s[0] for s in rec["stages"]]
            assert stages[0] == "ingress" and stages[-1] == "committed"

            # ?limit= bounds the completed list (0 = none)
            status, _, body = await _get(addr, "/tracez?limit=0")
            assert status == 200
            assert json.loads(body)["completed"] == []

            # /debugz: the flight-recorder ring saw the protocol run
            status, headers, body = await _get(addr, "/debugz")
            assert status == 200
            assert headers["content-type"].startswith("application/json")
            d = json.loads(body)
            rec = d["recorder"]
            assert rec["cap"] == 2048 and rec["recorded"] > 0
            codes = {e[1] for e in rec["events"]}
            # single node, default (batched) plane: the slot crossed
            # its echo decision and ready-quorum delivery edge, and the
            # attestation send path fired
            assert {"batch_echo", "batch_deliver", "tx"} <= codes

    async def test_sloz_serves_burn_rate_verdicts(self):
        async with _Node() as node:
            addr = node.config.rpc_address
            async with Client(f"http://{addr}") as client:
                sender = SignKeyPair.random()
                await client.send_asset(
                    sender, 1, SignKeyPair.random().public, 5
                )
                deadline = asyncio.get_event_loop().time() + TIMEOUT
                while await client.get_last_sequence(sender.public) != 1:
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(TICK)
            # two direct probes bracket the commit so the engine holds a
            # window regardless of the probe loop's own cadence
            node.service.slo_probe()
            await asyncio.sleep(0.01)
            node.service.slo_probe()

            status, headers, body = await _get(addr, "/sloz")
            assert status == 200
            assert headers["content-type"].startswith("application/json")
            z = json.loads(body)
            assert set(z) >= {
                "node", "windows_s", "samples", "objectives", "breaching",
            }
            assert z["samples"] >= 2
            # the default throughput floor is 0.0 = disabled (an idle
            # node has no committed rate to hold)
            kinds = {o["kind"] for o in z["objectives"]}
            assert kinds == {
                "latency_p99", "rejection_ratio", "stall_budget",
            }
            for o in z["objectives"]:
                assert {"name", "kind", "target", "status", "windows"} <= set(o)
                assert len(o["windows"]) == 2
            # one committed tx in milliseconds on localhost: a healthy
            # idle-ish node must NOT breach the default objectives
            assert z["breaching"] == []

            # the degradation verdict folds the SLO state in
            status, _, body = await _get(addr, "/healthz")
            assert status == 200
            health = json.loads(body)
            assert health["status"] == "ok"
            assert health["slo_breach"] == []

            # /statusz carries the same evaluation for the dashboard
            status, _, body = await _get(addr, "/statusz")
            assert json.loads(body)["slo"]["breaching"] == []

    async def test_recorder_disabled_by_cap_zero(self):
        async with _Node(
            observability=ObservabilityConfig(recorder_cap=0)
        ) as node:
            addr = node.config.rpc_address
            assert not node.service.recorder.enabled
            status, _, body = await _get(addr, "/debugz")
            assert status == 200
            assert json.loads(body)["recorder"]["recorded"] == 0

    async def test_snapshot_stats_key_set_stable(self):
        # the registry view must not grow/shrink keys between scrapes
        # (dashboards and the bench JSON diff on the key set)
        async with _Node() as node:
            first = set(node.service.snapshot_stats())
            async with Client(f"http://{node.config.rpc_address}") as client:
                sender = SignKeyPair.random()
                await client.send_asset(
                    sender, 1, SignKeyPair.random().public, 5
                )
                deadline = asyncio.get_event_loop().time() + TIMEOUT
                while await client.get_last_sequence(sender.public) != 1:
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(TICK)
            after = set(node.service.snapshot_stats())
            assert first == after
            # the legacy ad-hoc dict keys all survived the migration
            assert after >= {
                "committed", "pending", "history_retained",
                "catchup_served", "rejected_at_ingress",
            }
            assert "tx_ingress_to_committed_p50_ms" in after


# ------------------------------------------------------------- fleet audit


class TestFleetAudit:
    """Unit tier for obs/audit.py: contribution rules, order
    independence, the zero-false-positive compare, and attribution."""

    def test_initial_balance_pinned_to_ledger(self):
        # obs/ is a leaf package, so audit.py duplicates the ledger's
        # INITIAL_BALANCE instead of importing it; this pin is the
        # compile-time guard that the copies never drift (a drift would
        # silently break the virgin-row rule below)
        from at2_node_tpu.ledger.account import INITIAL_BALANCE as ledger_ib
        from at2_node_tpu.obs.audit import INITIAL_BALANCE as audit_ib

        assert audit_ib == ledger_ib

    def test_virgin_row_contributes_zero(self):
        from at2_node_tpu.obs.audit import (
            INITIAL_BALANCE,
            account_contrib,
            watermark_contrib,
        )

        key = bytes(range(32))
        # row creation timing differs across nodes (failed applies make
        # rows as a side effect), so an untouched row must be invisible
        assert account_contrib(key, 0, INITIAL_BALANCE) == 0
        assert watermark_contrib(key, 0) == 0
        # any observable state change shows
        assert account_contrib(key, 1, INITIAL_BALANCE) != 0
        assert account_contrib(key, 0, INITIAL_BALANCE - 1) != 0
        assert watermark_contrib(key, 1) != 0

    def test_digest_is_order_independent(self):
        from at2_node_tpu.obs.audit import LedgerDigest

        moves = [
            (bytes([i]) * 32, s, 100_000 + d, s + 1, 100_000 + d - 7)
            for i in (3, 200, 77)
            for s, d in ((0, 0), (1, -7), (2, -14))
        ]
        a, b = LedgerDigest(), LedgerDigest()
        for m in moves:
            a.touch(*m)
        for m in reversed(moves):
            b.touch(*m)
        assert a.ranges == b.ranges
        assert a.wm == b.wm
        # reseed from the final rows reproduces the incremental digest
        c = LedgerDigest()
        c.reseed((bytes([i]) * 32, 3, 100_000 - 21) for i in (3, 200, 77))
        assert c.ranges == a.ranges and c.wm == a.wm

    @staticmethod
    def _beacon_fields(point):
        return {
            "epoch": point["epoch"],
            "commits": point["commits"],
            "wm": point["wm"],
            "ranges": point["ranges"],
            "dir": point["dir"],
            "chain": point["chain"],
        }

    def test_matching_peers_never_diverge(self):
        from at2_node_tpu.obs.audit import FleetAuditor, LedgerDigest

        da, db = LedgerDigest(), LedgerDigest()
        key = bytes([16]) * 32
        for d in (da, db):
            d.touch(key, 0, 100_000, 1, 99_000)
        a, b = FleetAuditor(da), FleetAuditor(db)
        a.note_commit()
        b.note_commit()
        pb = b.snapshot(0, 0)
        assert a.observe("bb", self._beacon_fields(pb)) is None  # parked
        a.snapshot(0, 0)  # local point lands -> parked beacon settles
        assert a.counters["compared"] == 1
        assert a.counters["matched"] == 1
        assert a.divergence is None
        # chain heads are order-dependent local evidence, never compared
        assert a.chain != b.chain or a.chain == b.chain  # both legal

    def test_divergence_detected_and_attributed(self):
        from at2_node_tpu.obs.audit import FleetAuditor, LedgerDigest

        da, db = LedgerDigest(), LedgerDigest()
        key = bytes([0x42]) * 32  # lane 4
        for d in (da, db):
            d.touch(key, 0, 100_000, 1, 99_000)
        # same watermark, corrupted balance on b: the only digest
        # coordinate where a mismatch is a REAL divergence
        db.touch(key, 1, 99_000, 1, 99_007)
        a, b = FleetAuditor(da), FleetAuditor(db)
        a.note_commit()
        b.note_commit()
        pa = a.snapshot(3, 0)
        rec = b.observe("aa", self._beacon_fields(pa))
        assert rec is None  # parked until b folds the same watermark
        b.snapshot(3, 0)
        assert b.divergence is not None
        assert b.divergence["peer"] == "aa"
        assert b.divergence["ranges"] == [4]
        assert b.divergence["epoch"] == 3
        assert b.counters["diverged"] == 1
        # latched: a later matching beacon does not clear the record
        first = dict(b.divergence)
        assert b.divergence == first

    def test_epoch_and_dir_skew_are_informational(self):
        from at2_node_tpu.obs.audit import FleetAuditor, LedgerDigest

        d = LedgerDigest()
        d.touch(bytes([1]) * 32, 0, 100_000, 1, 99_000)
        a = FleetAuditor(d)
        p = a.snapshot(1, 7)
        # same wm, different epoch: incomparable, never divergence
        other = dict(self._beacon_fields(p), epoch=2)
        assert a.observe("bb", other) is None
        assert a.counters["epoch_skew"] == 1
        assert a.counters["compared"] == 0
        # same wm + ranges, different dir: eventual-consistency skew
        skew = dict(self._beacon_fields(p), dir=b"\x09" * 8)
        assert a.observe("cc", skew) is None
        assert a.counters["dir_skew"] == 1
        assert a.divergence is None

    def test_restore_folds_restart_marker(self):
        from at2_node_tpu.obs.audit import FleetAuditor, LedgerDigest

        a = FleetAuditor(LedgerDigest())
        a.note_commit(5)
        a.snapshot(0, 0)
        doc = a.export()
        b = FleetAuditor(LedgerDigest())
        b.restore(doc)
        assert b.commits == 5
        # a restarted chain is tamper-evidently distinct from the
        # continuous one it resumed
        assert b.chain != bytes.fromhex(doc["chain"])
        c = FleetAuditor(LedgerDigest())
        c.restore({})  # no persisted chain: fresh start stays fresh
        assert c.chain == bytes(32)


# --------------------------------------------------------- incident bundles


class TestIncidentBundle:
    _DUMPS = {
        "nodes": {
            "127.0.0.1:9101": {
                "statusz": {"health": {"status": "ok"}, "stats": {"c": 1}},
                "healthz": {"status": "ok"},
                "tracez": {"traces": [{"seq": 1}]},
                "debugz": {"snapshots": []},
            },
            "127.0.0.1:9102": {
                "statusz": {"health": {"status": "degraded"}},
                "healthz": {"status": "degraded"},
                "capturez": {"cap": 8, "captured": 2, "records": []},
            },
        }
    }

    def test_bundle_is_byte_identical(self):
        import copy

        from at2_node_tpu.tools.incident import build_bundle

        b1 = build_bundle(copy.deepcopy(self._DUMPS), reason="slo:breach")
        b2 = build_bundle(copy.deepcopy(self._DUMPS), reason="slo:breach")
        assert b1["files"] == b2["files"]
        assert b1["manifest"] == b2["manifest"]
        # every dump surface landed as a file, hashed in the manifest
        assert set(b1["manifest"]["files"]) == set(b1["files"])
        assert len(b1["files"]) == 7

    def test_bundle_hash_tracks_content_and_reason_is_unhashed(self):
        import copy

        from at2_node_tpu.tools.incident import build_bundle

        base = build_bundle(copy.deepcopy(self._DUMPS), reason="a")
        mutated = copy.deepcopy(self._DUMPS)
        mutated["nodes"]["127.0.0.1:9101"]["statusz"]["stats"]["c"] = 2
        changed = build_bundle(mutated, reason="a")
        assert (
            changed["manifest"]["bundle_sha256"]
            != base["manifest"]["bundle_sha256"]
        )
        # two collectors racing the same incident may name the trigger
        # differently; the bundle hash covers the EVIDENCE, not the label
        relabeled = build_bundle(copy.deepcopy(self._DUMPS), reason="b")
        assert (
            relabeled["manifest"]["bundle_sha256"]
            == base["manifest"]["bundle_sha256"]
        )

    def test_write_bundle_matches_manifest(self, tmp_path):
        import copy
        import hashlib
        import json as _json

        from at2_node_tpu.tools.incident import build_bundle, write_bundle

        bundle = build_bundle(copy.deepcopy(self._DUMPS))
        manifest_path = write_bundle(str(tmp_path / "b"), bundle)
        with open(manifest_path) as fp:
            manifest = _json.load(fp)
        assert manifest == bundle["manifest"]
        for rel, digest in manifest["files"].items():
            data = (tmp_path / "b" / rel).read_bytes()
            assert hashlib.sha256(data).hexdigest() == digest

    def test_edge_triggering(self):
        from at2_node_tpu.tools.incident import _edges

        ok = {
            "nodes": {
                "a:1": {
                    "statusz": {
                        "health": {"status": "ok"},
                        "stats": {"recorder_snapshots": 2},
                    }
                }
            }
        }
        bad = {
            "nodes": {
                "a:1": {
                    "statusz": {
                        "health": {
                            "status": "diverged",
                            "slo_breach": ["latency_p99"],
                            "divergence": {"peer": "ff"},
                        },
                        "stats": {"recorder_snapshots": 3},
                    }
                }
            }
        }
        assert _edges(None, bad) == []  # first poll is baseline only
        assert _edges(ok, ok) == []
        reasons = _edges(ok, bad)
        assert any("health:diverged" in r for r in reasons)
        assert any("slo:" in r for r in reasons)
        assert any("divergence" in r for r in reasons)
        assert any("anomaly_snapshot" in r for r in reasons)
        # level-hold: staying degraded is NOT a fresh incident
        assert _edges(bad, bad) == []


# ------------------------------------------------------- wire-capture ring


class TestWireCapture:
    def _mesh(self, cap):
        from at2_node_tpu.net.peers import Mesh

        kp = ExchangeKeyPair.random()
        return Mesh(
            "127.0.0.1:0",
            kp,
            [],
            on_frame=None,
            capture_cap=cap,
        )

    def test_ring_bounded_and_cumulative(self):
        mesh = self._mesh(4)
        peer = Peer(
            "127.0.0.1:1",
            ExchangeKeyPair.random().public,
            SignKeyPair.random().public,
        )
        for i in range(6):
            mesh._capture_frame(peer, bytes([15, i]))
        dump = mesh.capture_dump()
        assert dump["cap"] == 4
        assert dump["captured"] == 6  # cumulative, past the ring
        assert len(dump["records"]) == 4  # ring keeps the newest
        mono, peer_hex, kind, frame = dump["records"][-1]
        assert peer_hex == peer.sign_public.hex()
        assert kind == 15
        assert frame == bytes([15, 5]).hex()
        assert mesh.stats()["captured"] == 6

    def test_kill_switch_cap_zero(self):
        mesh = self._mesh(0)
        assert mesh._capture is None  # hot path: one attribute check
        assert mesh.stats()["captured"] == 0

    def test_capture_to_events_normalizes_time(self):
        from at2_node_tpu.tools.capture_replay import capture_to_events

        doc = {
            "records": [
                [2_000_000_000, "aa", 1, "02"],
                [1_000_000_000, "aa", 1, "01"],  # out of order on wire
                [1_500_000_000, "aa", 1, "03"],
            ]
        }
        events = capture_to_events(doc, target=2, speed=2.0, start=0.5)
        # sorted by capture time, re-anchored to virtual start, spacing
        # compressed by speed
        assert [e[2]["frame"] for e in events] == ["01", "03", "02"]
        assert [round(e[0], 3) for e in events] == [0.5, 0.75, 1.0]
        assert all(e[1] == "inject" for e in events)
        assert all(e[2]["target"] == 2 for e in events)
        assert capture_to_events({"records": []}) == []
