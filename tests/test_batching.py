"""Batched-broadcast-plane tests (broadcast/stack.py module docstring).

One broadcast slot carries many client transactions; these tests pin the
properties the design argues for:

* per-entry quorum counting — an entry delivers exactly when enough
  distinct nodes endorsed IT (bitmaps, not whole batches);
* the cross-plane entry registry — a byzantine client racing conflicting
  same-(sender, sequence) transfers into two different honest nodes'
  batches (or one batch + the per-tx plane) can never get both contents
  echo-endorsed by one honest node, so with intersecting quorums at most
  one content commits network-wide;
* one conflicting/invalid entry never poisons its batch siblings;
* batch content pull (totality when the batch gossip is lost);
* the ingress batcher's size/window flush and the single-tx parity path
  (`batching.enabled = false` restores the reference surface,
  `/root/reference/src/bin/server/rpc.rs:275-284`).
"""

import asyncio
import itertools

import pytest

from at2_node_tpu.broadcast.messages import (
    BATCH_ECHO,
    BATCH_READY,
    BatchAttestation,
    BatchContentRequest,
    MAX_BATCH_ENTRIES,
    Payload,
    TxBatch,
    WireError,
    parse_frame,
)
from at2_node_tpu.crypto.keys import SignKeyPair
from at2_node_tpu.node.config import BatchingConfig
from at2_node_tpu.node.service import Service
from at2_node_tpu.types import ThinTransaction

from conftest import make_net_configs, wait_until

_ports = itertools.count(23400)

FAUCET = 100_000


def make_payload(keypair, seq=1, amount=10, recipient=b"r" * 32):
    return Payload.create(keypair, seq, ThinTransaction(recipient, amount))


def make_batch(origin_kp, payloads, batch_seq=1):
    raw = b"".join(p.encode()[1:] for p in payloads)
    return TxBatch.create(origin_kp, batch_seq, raw)


class TestWire:
    def test_batch_roundtrip(self):
        node = SignKeyPair.random()
        client = SignKeyPair.random()
        batch = make_batch(
            node, [make_payload(client, seq=s) for s in (1, 2, 3)], batch_seq=42
        )
        [decoded] = parse_frame(batch.encode())
        assert decoded == batch
        assert decoded.count == 3
        assert decoded.content_hash() == batch.content_hash()
        assert decoded.entries()[2].sequence == 3

    def test_attestation_roundtrip_and_domain_separation(self):
        kp = SignKeyPair.random()
        bm = bytes([0b101])
        args = (kp.public, 7, b"h" * 32, bm)
        sig = kp.sign(BatchAttestation.signing_bytes(BATCH_ECHO, *args))
        att = BatchAttestation(BATCH_ECHO, kp.public, *args[:3], bm, sig)
        [decoded] = parse_frame(att.encode())
        assert decoded == att
        # an echo signature can never be replayed as a ready (and the
        # bitmap is inside the signed bytes, so bits can't be forged on)
        assert BatchAttestation.signing_bytes(
            BATCH_ECHO, *args
        ) != BatchAttestation.signing_bytes(BATCH_READY, *args)
        assert BatchAttestation.signing_bytes(
            BATCH_ECHO, kp.public, 7, b"h" * 32, bytes([0b111])
        ) != BatchAttestation.signing_bytes(BATCH_ECHO, *args)

    def test_content_request_roundtrip(self):
        req = BatchContentRequest(b"o" * 32, 9, b"h" * 32)
        assert parse_frame(req.encode()) == [req]

    def test_oversized_batch_rejected(self):
        node = SignKeyPair.random()
        client = SignKeyPair.random()
        batch = make_batch(node, [make_payload(client)])
        # forge the count field beyond the cap
        enc = bytearray(batch.encode())
        enc[41:45] = (MAX_BATCH_ENTRIES + 1).to_bytes(4, "little")
        with pytest.raises(WireError):
            parse_frame(bytes(enc))

    def test_native_parser_parity(self):
        from at2_node_tpu.native import ingest_available
        from at2_node_tpu.native.ingest import parse_frames_native

        if not ingest_available():
            pytest.skip("native ingest unavailable")
        node = SignKeyPair.random()
        client = SignKeyPair.random()
        batch = make_batch(
            node, [make_payload(client, seq=s) for s in (1, 2)], batch_seq=5
        )
        bm = bytes([0b11])
        sig = node.sign(
            BatchAttestation.signing_bytes(
                BATCH_READY, node.public, 5, batch.content_hash(), bm
            )
        )
        att = BatchAttestation(
            BATCH_READY, node.public, node.public, 5, batch.content_hash(), bm, sig
        )
        req = BatchContentRequest(node.public, 5, batch.content_hash())
        frame = batch.encode() + att.encode() + req.encode()
        msgs, frame_ok = parse_frames_native([frame])
        assert list(frame_ok) == [True]
        assert [m for _, m in msgs] == parse_frame(frame) == [batch, att, req]
        # malformed batch (count overflows the cap) drops the whole frame
        bad = bytearray(batch.encode())
        bad[41:45] = (MAX_BATCH_ENTRIES + 1).to_bytes(4, "little")
        msgs2, frame_ok2 = parse_frames_native([bytes(bad), att.encode()])
        assert list(frame_ok2) == [False, True]
        assert [m for _, m in msgs2] == [att]


def make_configs(n, **kwargs):
    return make_net_configs(n, _ports, **kwargs)


async def start_net(n, **kwargs):
    cfgs = make_configs(n, **kwargs)
    services = []
    for c in cfgs:
        services.append(await Service.start(c))
    return cfgs, services


async def close_all(services):
    for s in services:
        await s.close()


async def submit(service, payload):
    """Feed one client payload through the node's ingress batcher."""
    await service.recent.put(payload.sender, payload.sequence, payload.transaction)
    service._batch_buf.append(payload)


class TestBatchDelivery:
    @pytest.mark.asyncio
    async def test_one_slot_commits_many_txs_on_all_nodes(self):
        cfgs, services = await start_net(4)
        try:
            sender = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            for seq in range(1, 51):
                await submit(
                    services[0], make_payload(sender, seq=seq, recipient=recipient)
                )
            await services[0]._flush_batch()

            async def all_committed():
                return all(s.committed >= 50 for s in services)

            await wait_until(all_committed, what="batch entries commit")
            for s in services:
                assert await s.accounts.get_balance(recipient) == FAUCET + 500
                assert await s.accounts.get_last_sequence(sender.public) == 50
            # ONE slot: a handful of protocol messages, not 50 x 9
            st = services[0].broadcast.stats
            assert st["batch_rx"] >= 1
            assert st["batch_entries_delivered"] == 50
            assert st["gossip_rx"] == 0  # nothing rode the per-tx plane
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_invalid_entry_does_not_poison_siblings(self):
        cfgs, services = await start_net(3)
        try:
            sender = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            good1 = make_payload(sender, seq=1, recipient=recipient)
            bad = Payload(  # garbage client signature
                sender.public,
                2,
                ThinTransaction(recipient, 10),
                b"\x01" * 64,
            )
            good2 = make_payload(sender, seq=3, recipient=recipient)
            for p in (good1, bad, good2):
                await submit(services[0], p)
            await services[0]._flush_batch()

            # seq 1 commits everywhere; seq 3 stays gap-blocked in the
            # heap (seq 2 never delivers) — the commit FRONTIER is 1
            async def seq1_committed():
                seqs = [
                    await s.accounts.get_last_sequence(sender.public)
                    for s in services
                ]
                return all(q >= 1 for q in seqs)

            await wait_until(seq1_committed, what="good sibling commits")
            await asyncio.sleep(0.2)
            for s in services:
                assert await s.accounts.get_last_sequence(sender.public) == 1
                # the invalid entry was never endorsed anywhere
                assert s.broadcast.stats["invalid_sig"] >= 1
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_standalone_invalid_entry_never_commits(self):
        """Degenerate thresholds (0) must NOT bypass client-signature
        verification: with no peer quorum to carry the argument, the
        delivery gate is the node's OWN endorsement bits — a forged
        entry in a standalone node's batch stays out of the ledger
        (code-review r5 finding)."""
        cfgs, services = await start_net(1)
        try:
            sender = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            forged = Payload(
                sender.public, 1, ThinTransaction(recipient, 1000), b"\x03" * 64
            )
            good = make_payload(sender, seq=2, recipient=recipient, amount=7)
            for p in (forged, good):
                await submit(services[0], p)
            await services[0]._flush_batch()
            await asyncio.sleep(0.5)
            # the forged transfer never committed; seq 2 is gap-blocked
            # behind it (exactly like the per-tx plane would behave)
            assert await services[0].accounts.get_last_sequence(sender.public) == 0
            assert await services[0].accounts.get_balance(recipient) == FAUCET
            assert services[0].broadcast.stats["invalid_sig"] >= 1
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_single_node_standalone_batch(self):
        # degenerate net (no peers, thresholds 0) — mirrors the
        # reference's standalone-node shape
        # (/root/reference/tests/server-config-resolve-addrs)
        cfgs, services = await start_net(1)
        try:
            sender = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            for seq in (1, 2):
                await submit(
                    services[0], make_payload(sender, seq=seq, recipient=recipient)
                )
            await services[0]._flush_batch()

            async def committed():
                return services[0].committed >= 2

            await wait_until(committed, what="standalone batch commit")
            assert await services[0].accounts.get_balance(recipient) == FAUCET + 20
        finally:
            await close_all(services)


class TestByzantineClientConflicts:
    @pytest.mark.asyncio
    async def test_conflicting_entries_in_two_nodes_batches(self):
        """The attack the per-entry registry exists for: one byzantine
        client races two conflicting seq-1 transfers into two different
        honest ingress nodes. With echo_threshold = 3 (> n/2 of the 3
        peers each node counts), the two contents' Echo quorums must
        intersect in an honest node, which endorses only its first-bound
        content — so at most ONE of the transfers commits, identically
        on every node."""
        cfgs, services = await start_net(4)
        try:
            byz = SignKeyPair.random()
            alice = SignKeyPair.random().public
            bob = SignKeyPair.random().public
            pay_a = make_payload(byz, seq=1, amount=100, recipient=alice)
            pay_b = make_payload(byz, seq=1, amount=100, recipient=bob)
            await submit(services[0], pay_a)
            await submit(services[1], pay_b)
            await asyncio.gather(
                services[0]._flush_batch(), services[1]._flush_batch()
            )

            async def resolved():
                # every node must converge on the same outcome for seq 1
                seqs = [
                    await s.accounts.get_last_sequence(byz.public)
                    for s in services
                ]
                return all(q == 1 for q in seqs) or all(q == 0 for q in seqs)

            # give the net a moment; then assert NO divergence
            await asyncio.sleep(1.0)
            assert await resolved(), "nodes diverged on the conflicting slot"
            bal_a = [await s.accounts.get_balance(alice) for s in services]
            bal_b = [await s.accounts.get_balance(bob) for s in services]
            assert len(set(bal_a)) == 1, f"alice balances diverged: {bal_a}"
            assert len(set(bal_b)) == 1, f"bob balances diverged: {bal_b}"
            # at most one of the conflicting transfers landed
            assert not (
                bal_a[0] == FAUCET + 100 and bal_b[0] == FAUCET + 100
            ), "both conflicting transfers committed"
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_cross_plane_conflict_batch_vs_single_tx(self):
        """Same attack across PLANES: content X rides a batch from node
        0, conflicting content Y rides the per-tx plane via node 1. The
        shared entry registry must keep honest nodes from endorsing
        both."""
        cfgs, services = await start_net(4)
        try:
            byz = SignKeyPair.random()
            alice = SignKeyPair.random().public
            bob = SignKeyPair.random().public
            pay_x = make_payload(byz, seq=1, amount=50, recipient=alice)
            pay_y = make_payload(byz, seq=1, amount=50, recipient=bob)
            await submit(services[0], pay_x)
            await asyncio.gather(
                services[0]._flush_batch(),
                services[1].broadcast.broadcast(pay_y),  # per-tx plane
            )
            await asyncio.sleep(1.0)
            bal_a = [await s.accounts.get_balance(alice) for s in services]
            bal_b = [await s.accounts.get_balance(bob) for s in services]
            assert len(set(bal_a)) == 1, f"alice balances diverged: {bal_a}"
            assert len(set(bal_b)) == 1, f"bob balances diverged: {bal_b}"
            assert not (
                bal_a[0] == FAUCET + 50 and bal_b[0] == FAUCET + 50
            ), "both conflicting transfers committed"
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_byzantine_origin_batch_equivocation(self):
        """A byzantine NODE gossips two different batches under one
        (origin, batch_seq) slot. Node-level sieve (first content echoed
        per slot) keeps honest nodes split across at most the two
        contents; entries of at most one batch can quorate, and no node
        diverges."""
        cfgs, services = await start_net(4)
        try:
            byz_node_key = cfgs[0].sign_key  # node 0 plays byzantine
            client = SignKeyPair.random()
            alice = SignKeyPair.random().public
            bob = SignKeyPair.random().public
            batch_a = make_batch(
                byz_node_key,
                [make_payload(client, seq=1, recipient=alice)],
                batch_seq=777,
            )
            batch_b = make_batch(
                byz_node_key,
                [make_payload(client, seq=1, recipient=bob)],
                batch_seq=777,
            )
            # ship conflicting batches to different peers directly
            services[0].mesh.send(services[0].mesh.peers[0], batch_a.encode())
            services[0].mesh.send(services[0].mesh.peers[1], batch_b.encode())
            services[0].mesh.send(services[0].mesh.peers[2], batch_a.encode())
            await asyncio.sleep(1.0)
            bal_a = [await s.accounts.get_balance(alice) for s in services[1:]]
            bal_b = [await s.accounts.get_balance(bob) for s in services[1:]]
            assert len(set(bal_a)) == 1, f"alice balances diverged: {bal_a}"
            assert len(set(bal_b)) == 1, f"bob balances diverged: {bal_b}"
            assert not (
                bal_a[0] == FAUCET + 10 and bal_b[0] == FAUCET + 10
            ), "both equivocated batches committed"
        finally:
            await close_all(services)


class TestBatchContentPull:
    @pytest.mark.asyncio
    async def test_lost_batch_gossip_recovered_via_pull(self):
        # same shape as the per-tx pull fault test, batched plane:
        # thresholds let quorums form without the starved node
        cfgs, services = await start_net(3, echo_threshold=1, ready_threshold=2)
        victim = services[2]
        dropped = 0
        original = victim.mesh.on_frame

        async def lossy(peer, frame):
            nonlocal dropped
            msgs = parse_frame(frame)
            kept = []
            for m in msgs:
                if isinstance(m, TxBatch) and dropped < 2:
                    dropped += 1
                    continue
                kept.append(m)
            if kept:
                await original(peer, b"".join(m.encode() for m in kept))

        victim.mesh.on_frame = lossy
        try:
            sender = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            await submit(services[0], make_payload(sender, recipient=recipient, amount=25))
            await services[0]._flush_batch()

            async def all_committed():
                for s in services:
                    if await s.accounts.get_last_sequence(sender.public) < 1:
                        return False
                return True

            await wait_until(all_committed, what="starved node pulls the batch")
            assert dropped == 2, "the fault never actually fired"
            assert victim.broadcast.stats["content_req_tx"] >= 1
            assert await victim.accounts.get_balance(recipient) == FAUCET + 25
        finally:
            await close_all(services)


class TestIngressBatcher:
    @pytest.mark.asyncio
    async def test_window_flush_and_size_flush(self):
        cfgs, services = await start_net(
            1, batching=BatchingConfig(enabled=True, max_entries=4, window=0.02)
        )
        svc = services[0]
        try:
            from at2_node_tpu.client import Client

            sender = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                # below max_entries: the WINDOW must flush it
                await client.send_asset(sender, 1, recipient, 5)

                async def committed_one():
                    return svc.committed >= 1

                await wait_until(committed_one, what="window flush commits")
                # exactly max_entries: the SIZE trigger flushes immediately
                for seq in range(2, 6):
                    await client.send_asset(sender, seq, recipient, 5)

                async def committed_all():
                    return svc.committed >= 5

                await wait_until(committed_all, what="size flush commits")
            assert await svc.accounts.get_balance(recipient) == FAUCET + 25
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_mixed_net_interop(self):
        """A net where only SOME nodes batch at their ingress: batching
        is a per-node ingress choice, not a protocol version — every
        node understands relayed batches and per-tx payloads alike, and
        traffic entering through either kind of ingress commits
        everywhere."""
        cfgs = make_configs(3)
        cfgs[1].batching = BatchingConfig(enabled=False)
        services = []
        try:
            for c in cfgs:
                services.append(await Service.start(c))
            from at2_node_tpu.client import Client

            a = SignKeyPair.random()
            b = SignKeyPair.random()
            rcpt = SignKeyPair.random().public
            # a's txs enter through the BATCHING node 0; b's through the
            # per-tx node 1
            async with Client(f"http://{cfgs[0].rpc_address}") as c0:
                await c0.send_asset(a, 1, rcpt, 5)
            async with Client(f"http://{cfgs[1].rpc_address}") as c1:
                await c1.send_asset(b, 1, rcpt, 7)

            async def all_committed():
                for s in services:
                    if await s.accounts.get_last_sequence(a.public) < 1:
                        return False
                    if await s.accounts.get_last_sequence(b.public) < 1:
                        return False
                return True

            await wait_until(all_committed, what="mixed-plane commits")
            for s in services:
                assert await s.accounts.get_balance(rcpt) == FAUCET + 12
            # each plane actually carried its tx
            st = services[2].broadcast.stats
            assert st["batch_entries_delivered"] >= 1
            assert st["gossip_rx"] >= 1
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_batching_disabled_uses_per_tx_plane(self):
        cfgs, services = await start_net(
            3, batching=BatchingConfig(enabled=False)
        )
        try:
            from at2_node_tpu.client import Client

            sender = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset(sender, 1, recipient, 30)

                async def all_committed():
                    return all(s.committed >= 1 for s in services)

                await wait_until(all_committed, what="per-tx plane commit")
            st = services[1].broadcast.stats
            assert st["gossip_rx"] >= 1  # rode the reference-parity plane
            assert st["batch_rx"] == 0
        finally:
            await close_all(services)


class TestSendAssetBatchRpc:
    """The beyond-parity bulk-ingress RPC (at2.proto SendAssetBatch):
    semantically one SendAsset per entry, one round-trip."""

    @pytest.mark.asyncio
    async def test_bulk_submit_commits_everywhere(self):
        cfgs, services = await start_net(3)
        try:
            from at2_node_tpu.client import Client

            sender = SignKeyPair.random()
            rcpt = SignKeyPair.random().public
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset_many(
                    sender, [(s, rcpt, 2) for s in range(1, 101)]
                )

                async def all_committed():
                    seqs = [
                        await s.accounts.get_last_sequence(sender.public)
                        for s in services
                    ]
                    return all(q == 100 for q in seqs)

                await wait_until(all_committed, what="bulk RPC commits")
            for s in services:
                assert await s.accounts.get_balance(rcpt) == FAUCET + 200
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_validation_all_or_nothing(self):
        import grpc

        from at2_node_tpu.proto import at2_pb2 as pb
        from at2_node_tpu.proto.rpc import At2Stub

        cfgs, services = await start_net(1)
        try:
            sender = SignKeyPair.random()
            rcpt = SignKeyPair.random().public
            good = pb.SendAssetRequest(
                sender=sender.public, sequence=1, recipient=rcpt,
                amount=5, signature=b"s" * 64,
            )
            bad = pb.SendAssetRequest(  # 31-byte recipient
                sender=sender.public, sequence=2, recipient=b"x" * 31,
                amount=5, signature=b"s" * 64,
            )
            channel = grpc.aio.insecure_channel(cfgs[0].rpc_address)
            stub = At2Stub(channel)
            with pytest.raises(grpc.aio.AioRpcError) as exc:
                await stub.SendAssetBatch(
                    pb.SendAssetBatchRequest(transactions=[good, bad])
                )
            assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "entry 1" in exc.value.details()
            with pytest.raises(grpc.aio.AioRpcError):
                await stub.SendAssetBatch(pb.SendAssetBatchRequest())
            # nothing was admitted from the failed batch
            await asyncio.sleep(0.1)
            assert services[0].committed == 0
            assert not services[0]._batch_buf
            await channel.close()
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_oversized_rpc_batch_rejected(self):
        import grpc

        from at2_node_tpu.proto import at2_pb2 as pb
        from at2_node_tpu.proto.rpc import At2Stub

        cfgs, services = await start_net(1)
        try:
            sender = SignKeyPair.random()
            rcpt = SignKeyPair.random().public
            reqs = [
                pb.SendAssetRequest(
                    sender=sender.public, sequence=s, recipient=rcpt,
                    amount=1, signature=b"s" * 64,
                )
                for s in range(1, MAX_BATCH_ENTRIES + 2)
            ]
            channel = grpc.aio.insecure_channel(cfgs[0].rpc_address)
            stub = At2Stub(channel)
            with pytest.raises(grpc.aio.AioRpcError) as exc:
                await stub.SendAssetBatch(
                    pb.SendAssetBatchRequest(transactions=reqs)
                )
            assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            await channel.close()
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_client_chunks_past_server_cap(self, monkeypatch):
        """send_asset_many transparently splits lists beyond the server's
        per-request cap into multiple RPCs, in order."""
        import at2_node_tpu.client as client_mod

        monkeypatch.setattr(client_mod, "_RPC_BATCH_CAP", 10)
        cfgs, services = await start_net(1)
        try:
            sender = SignKeyPair.random()
            rcpt = SignKeyPair.random().public
            async with client_mod.Client(f"http://{cfgs[0].rpc_address}") as c:
                await c.send_asset_many(
                    sender, [(s, rcpt, 1) for s in range(1, 26)]
                )

                async def committed():
                    return services[0].committed >= 25

                await wait_until(committed, what="chunked client commits")
            assert (
                await services[0].accounts.get_last_sequence(sender.public)
                == 25
            )
        finally:
            await close_all(services)

    @pytest.mark.asyncio
    async def test_flush_chunks_respect_wire_cap(self):
        """An ingress burst larger than max_entries flushes as MULTIPLE
        slots, none exceeding the wire cap."""
        cfgs, services = await start_net(
            1, batching=BatchingConfig(enabled=True, max_entries=16)
        )
        svc = services[0]
        try:
            from at2_node_tpu.client import Client

            sender = SignKeyPair.random()
            rcpt = SignKeyPair.random().public
            async with Client(f"http://{cfgs[0].rpc_address}") as client:
                await client.send_asset_many(
                    sender, [(s, rcpt, 1) for s in range(1, 41)]
                )

                async def committed():
                    return svc.committed >= 40

                await wait_until(committed, what="chunked flush commits")
            # 40 entries / cap 16 => at least 3 slots
            assert svc.broadcast.stats["batch_rx"] >= 3
        finally:
            await close_all(services)


class TestSlotLifecycle:
    @pytest.mark.asyncio
    async def test_batch_slots_compact_and_counters_balance(self, monkeypatch):
        """GC lifecycle of the batched plane: delivered batch slots
        compact into the bounded delivered-set after retention, the
        undelivered counter returns to zero (its imbalance would
        eventually wedge the MAX_LIVE_SLOTS admission cap), and the
        commit heap fully drains."""
        import at2_node_tpu.broadcast.stack as stack_mod

        monkeypatch.setattr(stack_mod, "GC_INTERVAL", 0.2)
        monkeypatch.setattr(stack_mod, "DELIVERED_RETENTION", 0.3)
        cfgs, services = await start_net(3)
        try:
            sender = SignKeyPair.random()
            recipient = SignKeyPair.random().public
            # several flushes => several batch slots per node
            seq = 0
            for _ in range(5):
                for _ in range(20):
                    seq += 1
                    await submit(
                        services[0],
                        make_payload(sender, seq=seq, recipient=recipient),
                    )
                await services[0]._flush_batch()

            async def all_committed():
                return all(s.committed >= seq for s in services)

            await wait_until(all_committed, what="soak commits")

            async def compacted():
                for s in services:
                    b = s.broadcast
                    if b._batch_slots or b._undelivered != 0:
                        return False
                    if len(b._delivered_batch_slots) < 5:
                        return False
                return True

            await wait_until(compacted, what="batch slots compact")
            for s in services:
                assert not s._heap and not s._heap_keys
                assert await s.accounts.get_balance(recipient) == FAUCET + 10 * seq
        finally:
            await close_all(services)


class TestConfig:
    def test_toml_roundtrip(self):
        cfg = make_configs(1)[0]
        cfg.batching = BatchingConfig(enabled=True, max_entries=64, window=0.01)
        text = cfg.dumps()
        assert "[batching]" in text
        from at2_node_tpu.node.config import Config

        loaded = Config.loads(text)
        assert loaded.batching == cfg.batching

    def test_default_omitted_from_toml(self):
        cfg = make_configs(1)[0]
        assert "[batching]" not in cfg.dumps()

    def test_max_entries_validated(self):
        with pytest.raises(ValueError):
            BatchingConfig(max_entries=MAX_BATCH_ENTRIES + 1)
        with pytest.raises(ValueError):
            BatchingConfig(max_entries=0)
