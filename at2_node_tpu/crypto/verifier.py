"""The Verifier boundary: pluggable CPU / TPU-batch signature verification.

This is the plugin seam BASELINE.json's north star names: the reference
checks each broadcast message's ed25519 signature synchronously on CPU
inside its dependency crates; here every check goes through an async
``Verifier`` so the node can transparently swap:

* :class:`CpuVerifier` — per-signature verification (OpenSSL via
  `cryptography`) on a thread pool; the parity baseline.
* :class:`TpuBatchVerifier` — accumulates requests, pads to a fixed batch
  bucket, and dispatches ONE XLA call for the whole batch. Adaptive flush:
  a batch goes out when it reaches ``batch_size`` OR when the oldest
  request has waited ``max_delay`` (whichever first), bounding the latency
  a consensus round pays for batching (SURVEY.md §7 hard part #2).

Selected by node config: ``verifier = "cpu" | "tpu"`` (SURVEY.md §5
config addition).
"""

from __future__ import annotations

import asyncio
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import List, Protocol, Sequence, Tuple

import numpy as np

from ..obs.registry import Histogram
from .keys import verify_one


class Verifier(Protocol):
    """Anything that can check ed25519 signatures asynchronously."""

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        ...

    async def verify_many(
        self, items: Sequence[Tuple[bytes, bytes, bytes]]
    ) -> List[bool]:
        ...

    async def warmup(self) -> None:
        ...

    async def close(self) -> None:
        ...

    def stats(self) -> dict:
        ...


class CpuVerifier:
    """Per-signature CPU verification on a thread pool (the reference's
    execution model: `num_cpus` broadcast workers each verifying inline,
    `/root/reference/src/bin/server/rpc.rs:125`)."""

    def __init__(self, max_workers: int | None = None) -> None:
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._max_workers = self._pool._max_workers
        self.signatures_verified = 0

    def stats(self) -> dict:
        return {"signatures": self.signatures_verified}

    async def warmup(self) -> None:
        """Build/load the native ingest library off the event loop (its
        bulk-verify path uses it; Broadcast.start covers the parse path
        for every verifier configuration)."""
        from ..native import ingest_available

        await asyncio.get_running_loop().run_in_executor(
            self._pool, ingest_available
        )

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        loop = asyncio.get_running_loop()
        self.signatures_verified += 1
        return await loop.run_in_executor(
            self._pool, verify_one, public_key, message, signature
        )

    async def verify_many(
        self, items: Sequence[Tuple[bytes, bytes, bytes]]
    ) -> List[bool]:
        """Bulk path: ONE executor round-trip and (when the native ingest
        library built) ONE C call for the whole chunk — OpenSSL grinds on
        native threads with the GIL released, fanned out across real
        cores C++-side instead of GIL-juggled Python slices. Falls back
        to per-slice Python verification (round-2 shape) otherwise."""
        loop = asyncio.get_running_loop()
        self.signatures_verified += len(items)
        n = len(items)
        if n == 0:
            return []

        from ..native import ingest_ready_or_kick, verify_bulk_native

        # The one-C-call path has fixed staging cost (ragged ndarray
        # packing, ctypes crossing) that only amortizes on real batches;
        # trickle-sized chunks stay on the slice path (measured on the
        # 4-node e2e config: the native call is a wash below ~32 items
        # and LOSES below ~16). ingest_ready_or_kick never builds — a
        # verifier used without warmup must not run g++ on the event loop.
        if n >= 32 and ingest_ready_or_kick():
            # thread fan-out capped at the REAL core count: executor
            # max_workers is an IO-sizing default (cpu+4) and oversubscribing
            # OpenSSL threads on small hosts costs more than it buys
            import os

            n_threads = max(1, min(self._max_workers, os.cpu_count() or 1))
            result = await loop.run_in_executor(
                self._pool, verify_bulk_native, items, n_threads
            )
            return result.tolist()

        slices = min(n, self._max_workers)
        step = (n + slices - 1) // slices

        def run(chunk):
            return [verify_one(pk, msg, sig) for pk, msg, sig in chunk]

        futs = [
            loop.run_in_executor(self._pool, run, items[i : i + step])
            for i in range(0, n, step)
        ]
        out: List[bool] = []
        for results in await asyncio.gather(*futs):
            out.extend(results)
        return out

    async def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)


class _ChunkSink:
    """Result collector shared by every signature of one enqueued chunk:
    ONE asyncio future per chunk (the broadcast worker's verify_many slice),
    not one per signature — the per-message future/gather overhead was the
    TPU path's residual event-loop cost (round-2 advisor finding)."""

    __slots__ = ("future", "results", "remaining")

    def __init__(self, loop: asyncio.AbstractEventLoop, n: int) -> None:
        self.future: asyncio.Future = loop.create_future()
        self.results: List[bool] = [False] * n
        self.remaining = n

    def set(self, idx: int, ok: bool) -> None:
        self.results[idx] = ok
        self.remaining -= 1
        if self.remaining == 0 and not self.future.done():
            self.future.set_result(self.results)

    def fail(self, exc: BaseException) -> None:
        if not self.future.done():
            self.future.set_exception(exc)


@dataclass
class _Pending:
    public_key: bytes
    message: bytes
    signature: bytes
    sink: _ChunkSink
    idx: int  # this signature's slot in sink.results
    enqueued_at: float


class TpuBatchVerifier:
    """Accumulate -> pad to bucket -> one XLA dispatch -> resolve futures.

    Dispatch is a three-stage pipeline, each stage on its own executor
    thread so consecutive batches OVERLAP (the round-1 bench measured the
    async-chained shape at ~4x the serial-per-batch rate on the tunnel):

    * ``_prep``   — host-side batch preparation + packing (CPU-bound;
      the native C++ path when available);
    * ``_launch`` — device transfer + kernel dispatch + async copy-back
      start (returns the in-flight device handle without blocking);
    * ``_finish`` — materialize the results (the one blocking sync).

    Up to ``PIPELINE_DEPTH`` batches are in flight past launch; the
    flusher keeps prepping/launching while older batches drain. The event
    loop (gRPC handlers, broadcast state machines) never blocks on any
    stage; results come back as resolved futures per chunk sink.
    """

    PIPELINE_DEPTH = 4  # matches the bench's measured sweet spot

    def __init__(
        self,
        batch_size: int = 256,
        max_delay: float = 0.002,
        buckets: Sequence[int] | None = None,
        max_queue: int | None = None,
        clock=None,
    ) -> None:
        from ..clock import SYSTEM_CLOCK

        self.batch_size = batch_size
        self.max_delay = max_delay
        self._clock = SYSTEM_CLOCK if clock is None else clock
        if buckets is None:
            # One bucket == one compiled program: a flush never exceeds
            # batch_size, so padding to it keeps every dispatch the same
            # shape and warmup() covers all compilation up front. Pass an
            # explicit bucket ladder (e.g. ops.ed25519.BUCKETS) to enable
            # ADAPTIVE shaping: timer flushes land in the smallest bucket
            # that fits instead of padding to batch_size, and a deep
            # backlog coalesces into the largest bucket the queue can
            # fill instead of paying per-batch_size dispatch overhead.
            buckets = ()
        self.buckets = tuple(sorted(set(buckets) | {batch_size}))
        self._queue: List[_Pending] = []
        # Backpressure bound: callers await queue room instead of growing
        # the accumulator without limit (the broadcast worker pool already
        # self-limits; this protects against unbounded verify_many floods).
        # Capacity is a counted reservation (condition variable, bulk
        # acquire/release) so verify_many reserves a whole chunk in one
        # await instead of one semaphore acquire per signature.
        self.max_queue = (
            max_queue if max_queue is not None else max(8 * batch_size, 4096)
        )
        self._cap_free = self.max_queue
        self._cap_cond = asyncio.Condition()
        self._wakeup = asyncio.Event()
        # one thread per pipeline stage: prep of batch N+1 overlaps the
        # device execution of batch N, whose completion drains in parallel
        self._prep_pool = ThreadPoolExecutor(max_workers=1)
        self._device_pool = ThreadPoolExecutor(max_workers=1)
        self._finish_pool = ThreadPoolExecutor(max_workers=1)
        self._inflight = asyncio.Semaphore(self.PIPELINE_DEPTH)
        self._completions: set = set()
        self._closed = False
        self._flusher = asyncio.get_running_loop().create_task(self._flush_loop())
        # Stats for observability (SURVEY.md §5: per-stage counters)
        self.batches_dispatched = 0
        self.signatures_verified = 0
        self.total_padding = 0
        self.total_dispatch_s = 0.0
        self.last_dispatch_s = 0.0
        self.total_prep_s = 0.0
        self.total_launch_s = 0.0
        self.total_finish_s = 0.0
        self.queue_peak = 0
        # Per-batch latency DISTRIBUTIONS (obs/registry.py): the stage
        # means above tell you where the average batch goes; these tell
        # you what the tail does (p99 queue-wait is the number that
        # bounds client-visible admission latency under load). Standalone
        # histograms — the owning Service surfaces them through
        # stats()/stage_histograms(), so they need no registry.
        self.h_queue_wait = Histogram(
            "queue_wait", "enqueue -> dispatch wait of a batch's oldest item"
        )
        self.h_prep = Histogram("prep", "host-side prep stage per batch")
        self.h_launch = Histogram("launch", "device launch stage per batch")
        self.h_finish = Histogram("finish", "device sync + readback per batch")
        self.h_dispatch = Histogram(
            "dispatch", "prep -> results pipeline latency per batch"
        )
        # optional protocol flight recorder (obs/recorder.py), attached
        # by the owning Service after start: flush decisions (take /
        # depth / bucket) are exactly the events a post-mortem needs to
        # explain a latency spike. Duck-typed so the verifier keeps its
        # no-registry, no-obs-import design.
        self.recorder = None

    def stats(self) -> dict:
        """Operator-facing counters: batch occupancy, padding ratio, and
        device dispatch latency (SURVEY.md §5 tracing/metrics row)."""
        n_b = self.batches_dispatched
        n_s = self.signatures_verified
        return {
            "batches": n_b,
            "signatures": n_s,
            "queue_depth": len(self._queue),
            "queue_peak": self.queue_peak,
            "max_queue": self.max_queue,
            "capacity_free": self._cap_free,
            "batch_occupancy": (n_s / (n_s + self.total_padding))
            if n_s + self.total_padding
            else 0.0,
            "padding_ratio": (self.total_padding / (n_s + self.total_padding))
            if n_s + self.total_padding
            else 0.0,
            # per-batch prep->results pipeline latency (stages overlap
            # across batches, so this is NOT additive with throughput)
            "avg_dispatch_ms": (1e3 * self.total_dispatch_s / n_b) if n_b else 0.0,
            "last_dispatch_ms": 1e3 * self.last_dispatch_s,
            # per-stage means: where a batch's wall time actually goes
            # (prep/launch include their executor-queue wait, so a
            # saturated stage shows up here as inflation)
            "prep_ms_avg": (1e3 * self.total_prep_s / n_b) if n_b else 0.0,
            "launch_ms_avg": (1e3 * self.total_launch_s / n_b) if n_b else 0.0,
            "finish_ms_avg": (1e3 * self.total_finish_s / n_b) if n_b else 0.0,
            # queue-wait DISTRIBUTION: the tail the means can't show
            # (benches bank p50/p99 from here — ISSUE 3 satellite)
            **self.h_queue_wait.flat("queue_wait"),
        }

    def stage_histograms(self) -> dict:
        """Per-stage latency distributions (count/sum/max/p50/p90/p99 in
        ms) for /statusz — the pipeline's shape under live load."""
        return {
            "queue_wait": self.h_queue_wait.snapshot(),
            "prep": self.h_prep.snapshot(),
            "launch": self.h_launch.snapshot(),
            "finish": self.h_finish.snapshot(),
            "dispatch": self.h_dispatch.snapshot(),
        }

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _take_for_flush(self) -> int:
        """Adaptive dispatch sizing from LIVE queue depth: normally one
        batch_size slice, but a backlog deeper than batch_size coalesces
        into the largest configured bucket it can FILL — one 4096-lane
        dispatch instead of sixteen 256s amortizes the fixed per-dispatch
        tunnel sync ~16x (bench.py's transfer analysis). Single-bucket
        verifiers degrade to the old fixed-slice behavior exactly."""
        depth = len(self._queue)
        take = self.batch_size
        for b in self.buckets:
            if b <= depth:
                take = max(take, b)
        return take

    async def _acquire(self, n: int) -> None:
        """Reserve queue room for ``n`` signatures in one await."""
        async with self._cap_cond:
            while self._cap_free < n and not self._closed:
                try:
                    await self._cap_cond.wait()
                except asyncio.CancelledError:
                    # a cancelled waiter may have CONSUMED a notify meant
                    # for a sibling; pass it on before unwinding or that
                    # sibling parks forever on free capacity (classic
                    # Condition lost-wakeup)
                    self._cap_cond.notify_all()
                    raise
            if self._closed:
                raise RuntimeError("verifier closed")
            self._cap_free -= n

    async def _release(self, n: int) -> None:
        async with self._cap_cond:
            self._cap_free += n
            self._cap_cond.notify_all()

    def _enqueue_chunk(self, items, sink: _ChunkSink) -> None:
        was_empty = not self._queue
        now = self._clock.monotonic()
        append = self._queue.append
        for idx, (pk, msg, sig) in enumerate(items):
            append(_Pending(pk, msg, sig, sink, idx, now))
        if len(self._queue) > self.queue_peak:
            self.queue_peak = len(self._queue)
        # Wake the flusher on the empty->non-empty transition too, so a lone
        # request waits max_delay, not the flusher's 100ms idle-poll tick.
        if was_empty or len(self._queue) >= self.batch_size:
            self._wakeup.set()

    async def _evict_sinks(self, sinks: set) -> None:
        """Pull a cancelled caller's not-yet-dispatched entries back out of
        the accumulator and return their reserved capacity. Entries already
        popped by the flusher are past the point of no return (the device
        is working on them); they resolve or fail through _complete."""
        kept: List[_Pending] = []
        evicted = 0
        for p in self._queue:
            if p.sink in sinks:
                evicted += 1
            else:
                kept.append(p)
        self._queue = kept
        for sink in sinks:
            sink.fail(RuntimeError("verify cancelled"))
        if evicted:
            # shielded: this runs inside cancellation unwinding and MUST
            # complete, or the cancelled caller's capacity leaks forever
            await asyncio.shield(self._release(evicted))

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        if self._closed:
            raise RuntimeError("verifier closed")
        await self._acquire(1)
        sink = _ChunkSink(asyncio.get_running_loop(), 1)
        self._enqueue_chunk(((public_key, message, signature),), sink)
        return (await sink.future)[0]

    async def verify_many(
        self, items: Sequence[Tuple[bytes, bytes, bytes]]
    ) -> List[bool]:
        """Bulk path: the whole chunk enters the accumulator under ONE
        capacity reservation and resolves through ONE future per
        batch_size slice (slices larger than a batch could never flush as
        one dispatch anyway, so slicing there costs nothing)."""
        if self._closed:
            raise RuntimeError("verifier closed")
        n = len(items)
        if n == 0:
            return []
        loop = asyncio.get_running_loop()
        sinks: List[_ChunkSink] = []
        items = list(items) if not isinstance(items, (list, tuple)) else items
        try:
            for i in range(0, n, self.batch_size):
                chunk = items[i : i + self.batch_size]
                await self._acquire(len(chunk))
                sink = _ChunkSink(loop, len(chunk))
                self._enqueue_chunk(chunk, sink)
                sinks.append(sink)
        except BaseException:
            # close() landed between chunks: the already-enqueued sinks
            # WILL be resolved (close fails queued entries; in-flight
            # batches resolve via _complete) — consume those futures so
            # their exceptions are retrieved and any completed chunk's
            # results aren't silently dropped as un-awaited warnings
            if sinks:
                await asyncio.gather(
                    *(s.future for s in sinks), return_exceptions=True
                )
            raise
        # gather (not sequential awaits): when an early chunk's dispatch
        # fails, every sink's exception is still retrieved — no
        # "exception was never retrieved" spam for the later chunks
        try:
            chunk_results = await asyncio.gather(*(s.future for s in sinks))
        except asyncio.CancelledError:
            # the CALLER was cancelled mid-wait: its undispatched entries
            # must not squat in the accumulator holding reserved capacity
            # (a flood of cancelled clients would otherwise wedge the
            # verifier at max_queue with work nobody wants)
            await self._evict_sinks(set(sinks))
            raise
        out: List[bool] = []
        for results in chunk_results:
            out.extend(results)
        return out

    async def _flush_loop(self) -> None:
        while not self._closed:
            if not self._queue:
                self._wakeup.clear()
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=0.1)
                except asyncio.TimeoutError:
                    continue
            # wait for a full batch or until the oldest request expires
            while (
                len(self._queue) < self.batch_size
                and self._queue
                and (self._clock.monotonic() - self._queue[0].enqueued_at)
                < self.max_delay
            ):
                self._wakeup.clear()
                remaining = self.max_delay - (
                    self._clock.monotonic() - self._queue[0].enqueued_at
                )
                try:
                    await asyncio.wait_for(
                        self._wakeup.wait(), timeout=max(remaining, 0.0001)
                    )
                except asyncio.TimeoutError:
                    break
            if not self._queue:
                continue
            take = self._take_for_flush()
            if self.recorder is not None:
                self.recorder.record(
                    "vflush",
                    (take, len(self._queue), self._bucket_for(take)),
                )
            batch, self._queue = (
                self._queue[:take],
                self._queue[take:],
            )
            try:
                await self._release(len(batch))
                await self._dispatch(batch)
            except BaseException as exc:
                # once popped from _queue, close()'s sweep can no longer
                # see this batch — a cancellation landing in the _release
                # await (or anywhere before dispatch resolves the sinks)
                # must fail them here or their callers hang forever
                for p in batch:
                    p.sink.fail(
                        RuntimeError("verifier closed")
                        if isinstance(exc, asyncio.CancelledError)
                        else exc
                    )
                if isinstance(exc, asyncio.CancelledError):
                    raise  # close() is tearing the flusher down
                # anything else: this batch already failed its callers;
                # the flusher itself stays up for subsequent batches

    # -- pipeline stages (subclasses — parallel.pool.PoolVerifier —
    # override all three to shard over a mesh) ---------------------------

    def _prep(self, pks, msgs, sigs, bucket):
        """Host stage: bucket policy + batch prep + packing (the shape
        rules — incl. Pallas TILE rounding — live in ops.ed25519), then
        the host->device upload — HERE rather than in _launch so batch
        N+1's tunnel transfer overlaps batch N's dispatch/kernel (the
        round-4 trace attributes the 250k-vs-475k pipelined gap to
        transfers serializing on the launch thread; ops/ed25519.py
        upload_packed)."""
        from ..ops import ed25519 as kernel

        return kernel.upload_packed(kernel.prep_packed(pks, msgs, sigs, bucket))

    def _launch(self, packed):
        """Device stage: transfer + dispatch + start the async copy-back;
        returns the in-flight handle without blocking."""
        from ..ops import ed25519 as kernel

        return kernel.launch_packed(packed)

    def _finish(self, handle, n: int) -> np.ndarray:
        """Completion stage: block until the device results land."""
        from ..ops import ed25519 as kernel

        return kernel.finish_packed(handle, n)

    def _run_batch(self, pks, msgs, sigs, bucket) -> np.ndarray:
        """Synchronous compose of the three stages (warmup path; also the
        historical override seam: a subclass that replaces only THIS
        method still works — _dispatch detects that case and routes the
        whole batch through it on the device thread)."""
        return self._finish(
            self._launch(self._prep(pks, msgs, sigs, bucket)), len(pks)
        )

    def _staged_overrides_consistent(self) -> bool:
        """True when the staged pipeline reflects this instance's actual
        verify logic: either nothing is overridden, or the stages are.
        A subclass overriding only _run_batch must not be bypassed."""
        cls = type(self)
        run_overridden = cls._run_batch is not TpuBatchVerifier._run_batch
        stages_overridden = (
            cls._prep is not TpuBatchVerifier._prep
            or cls._launch is not TpuBatchVerifier._launch
        )
        return stages_overridden or not run_overridden

    async def warmup(self) -> None:
        """Compile EVERY bucket's program before serving traffic.

        XLA/Mosaic compilation takes tens of seconds cold; a node must not
        report ready (bind its RPC port) while the first real signature
        would stall behind the compiler. Dispatches one padded throwaway
        batch per configured bucket shape, then one request through the
        full accumulate/flush path."""
        from .keys import SignKeyPair

        kp = SignKeyPair.from_hex("01" * 32)
        msg = b"verifier warmup"
        sig = kp.sign(msg)
        loop = asyncio.get_running_loop()
        for bucket in self.buckets:
            out = await loop.run_in_executor(
                self._device_pool, self._run_batch, [kp.public], [msg], [sig], bucket
            )
            if not bool(out[0]):
                raise RuntimeError(
                    f"verifier warm-up failed for bucket {bucket}"
                )
        ok = await self.verify(kp.public, msg, sig)
        if not ok:
            raise RuntimeError("verifier warm-up batch failed to verify")

    @staticmethod
    def _fail_batch(batch: List[_Pending], exc: BaseException) -> None:
        """Resolve every sink of an abandoned batch (callers must never
        hang; close() cannot see batches already popped from _queue)."""
        err = (
            RuntimeError("verifier closed")
            if isinstance(exc, asyncio.CancelledError)
            else exc
        )
        for p in batch:
            p.sink.fail(err)

    async def _dispatch(self, batch: List[_Pending]) -> None:
        """Prep and launch this batch, then hand completion to a
        background task so the flusher can pipeline the NEXT batch while
        the device works; at most PIPELINE_DEPTH batches run past launch."""
        bucket = self._bucket_for(len(batch))
        loop = asyncio.get_running_loop()
        pks = [p.public_key for p in batch]
        msgs = [p.message for p in batch]
        sigs = [p.signature for p in batch]

        # queue wait of the OLDEST item (FIFO queue: batch[0]), observed
        # BEFORE the depth gate — waiting for an in-flight slot is queue
        # time from the caller's perspective, exactly what the admission
        # path's latency budget pays
        self.h_queue_wait.observe(self._clock.monotonic() - batch[0].enqueued_at)
        await self._inflight.acquire()
        # clock starts AFTER the depth gate: avg/last_dispatch_ms measure
        # one batch's prep->results pipeline latency, not queue wait
        t0 = self._clock.monotonic()
        try:
            if self._staged_overrides_consistent():
                prepared = await loop.run_in_executor(
                    self._prep_pool, self._prep, pks, msgs, sigs, bucket
                )
                t1 = self._clock.monotonic()
                self.total_prep_s += t1 - t0
                self.h_prep.observe(t1 - t0)
                handle = await loop.run_in_executor(
                    self._device_pool, self._launch, prepared
                )
                t2 = self._clock.monotonic()
                self.total_launch_s += t2 - t1
                self.h_launch.observe(t2 - t1)
                finish = loop.run_in_executor(
                    self._finish_pool, self._finish, handle, len(batch)
                )
            else:
                # legacy seam: subclass replaced _run_batch only — run it
                # whole on the device thread (no stage overlap, but the
                # depth bound still lets batches queue behind each other)
                finish = loop.run_in_executor(
                    self._device_pool, self._run_batch, pks, msgs, sigs, bucket
                )
        except BaseException as exc:
            self._inflight.release()
            self._fail_batch(batch, exc)
            if isinstance(exc, asyncio.CancelledError):
                raise
            return
        task = loop.create_task(self._complete(batch, bucket, finish, t0))
        self._completions.add(task)
        task.add_done_callback(self._completions.discard)

    async def _complete(self, batch, bucket, finish, t0) -> None:
        t_fin = self._clock.monotonic()
        try:
            results = await finish
        except BaseException as exc:
            self._fail_batch(batch, exc)
            if isinstance(exc, asyncio.CancelledError):
                raise
            return
        finally:
            self._inflight.release()
        t_done = self._clock.monotonic()
        self.total_finish_s += t_done - t_fin
        self.h_finish.observe(t_done - t_fin)
        self.last_dispatch_s = t_done - t0
        self.total_dispatch_s += self.last_dispatch_s
        self.h_dispatch.observe(self.last_dispatch_s)
        self.batches_dispatched += 1
        self.signatures_verified += len(batch)
        self.total_padding += bucket - len(batch)
        for p, ok in zip(batch, results):
            p.sink.set(p.idx, bool(ok))

    async def close(self) -> None:
        self._closed = True
        # Wake parked _acquire callers FIRST, before draining in-flight
        # completions: a wedged device (tunnel dead mid-batch) can hold
        # the completion gather below forever, and a caller parked in
        # _cap_cond.wait() must get its "verifier closed" RuntimeError
        # now, not after a hang that never ends. They re-check _closed
        # under the condition and raise.
        async with self._cap_cond:
            self._cap_cond.notify_all()
        self._wakeup.set()
        self._flusher.cancel()
        try:
            await self._flusher
        except (asyncio.CancelledError, Exception):
            pass
        # drain in-flight completions: their batches already left _queue,
        # so only these tasks can resolve (or fail) those sinks
        if self._completions:
            await asyncio.gather(
                *list(self._completions), return_exceptions=True
            )
        for p in self._queue:
            p.sink.fail(RuntimeError("verifier closed"))
        released = len(self._queue)
        self._queue.clear()
        # return the dead queue's capacity and wake every caller parked in
        # _acquire (they re-check _closed under the condition and raise —
        # the notify matters even when released == 0)
        await self._release(released)
        for pool in (self._prep_pool, self._device_pool, self._finish_pool):
            pool.shutdown(wait=False, cancel_futures=True)


def make_verifier(kind: str, **kwargs) -> Verifier:
    """Config-driven verifier selection
    (``verifier = "cpu" | "tpu" | "pool"``)."""
    if kind == "cpu":
        return CpuVerifier(**kwargs)
    if kind == "tpu":
        return TpuBatchVerifier(**kwargs)
    if kind == "pool":
        from ..parallel.pool import PoolVerifier

        return PoolVerifier(**kwargs)
    raise ValueError(f"unknown verifier kind: {kind!r}")
