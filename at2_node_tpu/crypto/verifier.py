"""The Verifier boundary: pluggable CPU / TPU-batch signature verification.

This is the plugin seam BASELINE.json's north star names: the reference
checks each broadcast message's ed25519 signature synchronously on CPU
inside its dependency crates; here every check goes through an async
``Verifier`` so the node can transparently swap:

* :class:`CpuVerifier` — per-signature verification (OpenSSL via
  `cryptography`) on a thread pool; the parity baseline.
* :class:`TpuBatchVerifier` — accumulates requests, pads to a fixed batch
  bucket, and dispatches ONE XLA call for the whole batch. Adaptive flush:
  a batch goes out when it reaches ``batch_size`` OR when the oldest
  request has waited ``max_delay`` (whichever first), bounding the latency
  a consensus round pays for batching (SURVEY.md §7 hard part #2).

Selected by node config: ``verifier = "cpu" | "tpu"`` (SURVEY.md §5
config addition).

Amortized verification (ISSUE 10): both verifiers take a
``mode = "auto" | "per_sig" | "rlc"``. In RLC mode a flush bucket is
verified with ONE random-linear-combination check (native engine on CPU,
the promoted ops/aggregate graph on TPU) instead of per-signature
passes; a failing batch falls back to **bisection** (:class:`RlcEngine`)
that recursively splits until culprits are isolated, and an adaptive
:class:`VerifyRouter` chooses per-sig vs RLC per flush from live batch
size and a decaying per-source failure rate — a byzantine client salting
every batch degrades its own traffic to per-sig cost instead of forcing
O(B log B) bisections on everyone. Verdicts are ALWAYS identical to the
per-signature path: tainted-A keys are rerouted (never rejected) by the
certification cache, tainted-R lanes are caught by the engine's
randomized torsion rounds, and bisection leaves resolve exactly.
"""

from __future__ import annotations

import asyncio
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, List, Optional, Protocol, Sequence, Tuple

import numpy as np

from ..obs.registry import Histogram
from .keys import verify_one

_MODE_CODES = {"per_sig": 0, "rlc": 1, "auto": 2}


class Verifier(Protocol):
    """Anything that can check ed25519 signatures asynchronously."""

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        ...

    async def verify_many(
        self, items: Sequence[Tuple[bytes, bytes, bytes]]
    ) -> List[bool]:
        ...

    async def warmup(self) -> None:
        ...

    async def close(self) -> None:
        ...

    def stats(self) -> dict:
        ...


class CpuVerifier:
    """Per-signature CPU verification on a thread pool (the reference's
    execution model: `num_cpus` broadcast workers each verifying inline,
    `/root/reference/src/bin/server/rpc.rs:125`)."""

    def __init__(
        self,
        max_workers: int | None = None,
        mode: str = "auto",
        rlc_min_batch: int = 128,
    ) -> None:
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._max_workers = self._pool._max_workers
        self.signatures_verified = 0
        self.router = VerifyRouter(mode, min_batch=rlc_min_batch)
        self.engine = RlcEngine()

    @property
    def mode(self) -> str:
        return self.router.mode

    def stats(self) -> dict:
        return {
            "signatures": self.signatures_verified,
            "mode": _MODE_CODES[self.router.mode],
            "mode_name": self.router.mode,
            **self.router.stats(),
            **self.engine.stats(),
        }

    async def warmup(self) -> None:
        """Build/load the native ingest AND rlc libraries off the event
        loop (the bulk-verify and RLC paths use them; Broadcast.start
        covers the parse path for every verifier configuration)."""
        from ..native import ingest_available
        from ..native.rlc import rlc_available

        loop = asyncio.get_running_loop()
        await loop.run_in_executor(self._pool, ingest_available)
        if self.router.mode != "per_sig":
            await loop.run_in_executor(self._pool, rlc_available)

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        loop = asyncio.get_running_loop()
        self.signatures_verified += 1
        return await loop.run_in_executor(
            self._pool, verify_one, public_key, message, signature
        )

    async def verify_many(
        self, items: Sequence[Tuple[bytes, bytes, bytes]]
    ) -> List[bool]:
        """Bulk path: ONE executor round-trip and (when the native ingest
        library built) ONE C call for the whole chunk — OpenSSL grinds on
        native threads with the GIL released, fanned out across real
        cores C++-side instead of GIL-juggled Python slices. Falls back
        to per-slice Python verification (round-2 shape) otherwise."""
        loop = asyncio.get_running_loop()
        self.signatures_verified += len(items)
        n = len(items)
        if n == 0:
            return []

        from ..native import ingest_ready_or_kick, verify_bulk_native

        # Amortized route (ISSUE 10): ONE RLC check for the whole chunk
        # when the router says the batch is big and clean enough. The
        # engine resolves exact per-entry verdicts (bisection on batch
        # failure), so callers can't tell the routes apart except by
        # speed; verdict outcomes feed the router's per-source EWMA on
        # both routes.
        if self.router.mode != "per_sig":
            sources = [it[0] for it in items]
            route = self.router.choose(sources, rlc_ready=self.engine.ready())
            if route == "rlc":
                results = await loop.run_in_executor(
                    self._pool, self.engine.verify_batch, items
                )
                self.router.observe(list(zip(sources, results)))
                return results

        # The one-C-call path has fixed staging cost (ragged ndarray
        # packing, ctypes crossing) that only amortizes on real batches;
        # trickle-sized chunks stay on the slice path (measured on the
        # 4-node e2e config: the native call is a wash below ~32 items
        # and LOSES below ~16). ingest_ready_or_kick never builds — a
        # verifier used without warmup must not run g++ on the event loop.
        if n >= 32 and ingest_ready_or_kick():
            # thread fan-out capped at the REAL core count: executor
            # max_workers is an IO-sizing default (cpu+4) and oversubscribing
            # OpenSSL threads on small hosts costs more than it buys
            import os

            n_threads = max(1, min(self._max_workers, os.cpu_count() or 1))
            result = await loop.run_in_executor(
                self._pool, verify_bulk_native, items, n_threads
            )
            out = result.tolist()
            self._observe(items, out)
            return out

        slices = min(n, self._max_workers)
        step = (n + slices - 1) // slices

        def run(chunk):
            return [verify_one(pk, msg, sig) for pk, msg, sig in chunk]

        futs = [
            loop.run_in_executor(self._pool, run, items[i : i + step])
            for i in range(0, n, step)
        ]
        out = []
        for results in await asyncio.gather(*futs):
            out.extend(results)
        self._observe(items, out)
        return out

    def _observe(self, items, results: Sequence[bool]) -> None:
        """Per-sig verdicts still train the router's failure EWMA, so a
        salting source stays routed per-sig while misbehaving and decays
        back to RLC eligibility once it stops."""
        if self.router.mode == "auto":
            self.router.observe(
                [(it[0], bool(ok)) for it, ok in zip(items, results)]
            )

    async def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)


class _ChunkSink:
    """Result collector shared by every signature of one enqueued chunk:
    ONE asyncio future per chunk (the broadcast worker's verify_many slice),
    not one per signature — the per-message future/gather overhead was the
    TPU path's residual event-loop cost (round-2 advisor finding)."""

    __slots__ = ("future", "results", "remaining")

    def __init__(self, loop: asyncio.AbstractEventLoop, n: int) -> None:
        self.future: asyncio.Future = loop.create_future()
        self.results: List[bool] = [False] * n
        self.remaining = n

    def set(self, idx: int, ok: bool) -> None:
        self.results[idx] = ok
        self.remaining -= 1
        if self.remaining == 0 and not self.future.done():
            self.future.set_result(self.results)

    def fail(self, exc: BaseException) -> None:
        if not self.future.done():
            self.future.set_exception(exc)


@dataclass
class _Pending:
    public_key: bytes
    message: bytes
    signature: bytes
    sink: _ChunkSink
    idx: int  # this signature's slot in sink.results
    enqueued_at: float


class VerifyRouter:
    """Per-flush routing between per-signature and RLC verification.

    Policy (ISSUE 10): route a flush to RLC only when (a) the engine is
    ready, (b) the batch is big enough that one RLC check beats B
    per-sig checks (the fixed torsion-round cost dominates small
    batches — BENCH_AGGREGATE.json banks the measured crossover), and
    (c) the batch's *expected bad count* — the sum of a decaying
    per-source failure EWMA over its entries — stays under budget. A
    salting source drives its own EWMA toward 1 within one bad flush, so
    batches carrying its traffic fall back to per-sig cost immediately
    and recover (EWMA decays on clean observations) when it stops.
    """

    def __init__(
        self,
        mode: str = "auto",
        *,
        min_batch: int = 128,
        decay: float = 0.2,
        expected_bad_budget: float = 0.5,
        max_sources: int = 8192,
    ) -> None:
        if mode not in _MODE_CODES:
            raise ValueError(f"unknown verifier mode: {mode!r}")
        self.mode = mode
        self.min_batch = min_batch
        self.decay = decay
        self.expected_bad_budget = expected_bad_budget
        self.max_sources = max_sources
        self._fail_ewma: dict[bytes, float] = {}
        self._lock = threading.Lock()
        self.route_rlc = 0
        self.route_per_sig = 0
        self.last_route = "per_sig"
        self.last_batch = 0
        self.last_expected_bad = 0.0
        # routing DISTRIBUTIONS: lanes per flush by chosen route — the
        # crossover evidence /metrics needs (a healthy auto node shows
        # rlc lanes clustered at full buckets, per-sig at trickles)
        self.h_rlc_lanes = Histogram("route_rlc_lanes", "lanes per RLC-routed flush")
        self.h_per_sig_lanes = Histogram(
            "route_per_sig_lanes", "lanes per per-sig-routed flush"
        )

    def expected_bad(self, sources: Sequence[bytes]) -> float:
        ewma = self._fail_ewma
        return sum(ewma.get(s, 0.0) for s in sources)

    def choose(
        self, sources: Sequence[bytes], *, rlc_ready: bool = True
    ) -> str:
        """Route one flush: ``"rlc"`` or ``"per_sig"``."""
        n = len(sources)
        if self.mode == "per_sig" or not rlc_ready:
            route = "per_sig"
            exp_bad = 0.0
        elif self.mode == "rlc":
            route = "rlc"
            exp_bad = 0.0
        else:
            exp_bad = self.expected_bad(sources)
            route = (
                "rlc"
                if n >= self.min_batch and exp_bad <= self.expected_bad_budget
                else "per_sig"
            )
        with self._lock:
            if route == "rlc":
                self.route_rlc += 1
                self.h_rlc_lanes.observe(float(n))
            else:
                self.route_per_sig += 1
                self.h_per_sig_lanes.observe(float(n))
            self.last_route = route
            self.last_batch = n
            self.last_expected_bad = exp_bad
        return route

    def observe(self, outcomes: Sequence[Tuple[bytes, bool]]) -> None:
        """Feed per-entry verdicts back into the per-source failure EWMA
        (both routes observe, so a salter stays hot even while its
        traffic runs per-sig, and decays back once it behaves)."""
        d = self.decay
        with self._lock:
            ewma = self._fail_ewma
            for src, ok in outcomes:
                p = ewma.get(src, 0.0)
                p += d * ((0.0 if ok else 1.0) - p)
                if p < 1e-4:
                    ewma.pop(src, None)
                else:
                    ewma[src] = p
            while len(ewma) > self.max_sources:
                # bounded state: drop the coldest source
                coldest = min(ewma, key=ewma.get)
                del ewma[coldest]

    def hot_sources(self, threshold: float = 0.1) -> int:
        with self._lock:
            return sum(1 for p in self._fail_ewma.values() if p > threshold)

    def stats(self) -> dict:
        with self._lock:
            return {
                "route_rlc": self.route_rlc,
                "route_per_sig": self.route_per_sig,
                "route_last": self.last_route,
                "route_last_batch": self.last_batch,
                "route_last_expected_bad": round(self.last_expected_bad, 4),
                "router_sources": len(self._fail_ewma),
                **self.h_rlc_lanes.flat("route_rlc_lanes"),
                **self.h_per_sig_lanes.flat("route_per_sig_lanes"),
            }


class RlcEngine:
    """CPU RLC batch verification with bisection fallback (sync; callers
    run it on executor threads — the native calls release the GIL).

    One :meth:`verify_batch` call resolves exact per-entry verdicts:

    1. prepare (shared host prep: s-range checks, h = SHA-512 mod L);
    2. certify public keys through the per-key cache — exact [L]A once
       per distinct key; lanes whose A is tainted/undecodable reroute to
       the exact per-sig path (their cofactorless verdict can differ
       from any batched check, so they never enter the RLC equation);
    3. ONE native RLC check (equation + randomized R-torsion rounds)
       over the remaining lanes;
    4. on failure, bisect: recursively split and re-check halves with
       fresh randomness until sub-batches pass whole or shrink to
       ``leaf_size``, then resolve leaves exactly per-signature — a
       poison entry costs ~2·log2(B/leaf) extra checks, everyone else
       still verifies amortized.

    ``check_fn``/``leaf_fn`` are injectable for tests (check counting
    without curve work).
    """

    def __init__(
        self,
        *,
        leaf_size: int = 16,
        k_rounds: int | None = None,
        cert_cache_max: int = 65536,
        check_fn: Optional[Callable] = None,
        leaf_fn: Optional[Callable] = None,
    ) -> None:
        from ..native import rlc as rlc_native

        self._rlc = rlc_native
        self.leaf_size = leaf_size
        self.k_rounds = (
            k_rounds if k_rounds is not None else rlc_native.TORSION_ROUNDS
        )
        self.cert_cache_max = cert_cache_max
        self._check_fn = check_fn
        self._leaf_fn = leaf_fn
        self._cert: dict[bytes, int] = {}
        self._lock = threading.Lock()
        # counters (locked: CpuVerifier's pool may run two batches at once)
        self.rlc_batches = 0
        self.rlc_fallbacks = 0
        self.rlc_checks = 0
        self.rlc_sigs = 0
        self.rlc_anomalies = 0
        self.bisection_depth = 0
        self.leaf_sigs = 0
        self.cert_misses = 0
        self.exact_reroutes = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "rlc_batches": self.rlc_batches,
                "rlc_fallbacks": self.rlc_fallbacks,
                "rlc_checks": self.rlc_checks,
                "rlc_sigs": self.rlc_sigs,
                "rlc_anomalies": self.rlc_anomalies,
                "bisection_depth": self.bisection_depth,
                "leaf_sigs": self.leaf_sigs,
                "certified_keys": len(self._cert),
                "cert_misses": self.cert_misses,
                "exact_reroutes": self.exact_reroutes,
            }

    def ready(self) -> bool:
        return self._rlc.rlc_ready_or_kick()

    # -- certification cache ---------------------------------------------

    def _certify(self, pks: Sequence[bytes]) -> np.ndarray:
        """Per-lane verdicts from the cache: True when the key's A is
        certified torsion-free (safe for the RLC equation)."""
        cache = self._cert
        misses: list[bytes] = []
        seen: set = set()
        for pk in pks:
            if pk not in cache and pk not in seen:
                seen.add(pk)
                misses.append(pk)
        if misses:
            verdicts = self._rlc.certify_keys(misses)
            with self._lock:
                self.cert_misses += len(misses)
                for pk, v in zip(misses, verdicts):
                    cache[pk] = int(v)
                while len(cache) > self.cert_cache_max:
                    cache.pop(next(iter(cache)))
        return np.fromiter(
            (cache.get(pk, 0) == 2 for pk in pks), dtype=bool, count=len(pks)
        )

    # -- checking --------------------------------------------------------

    def _check(self, prep, idxs: np.ndarray):
        """One RLC check over the lanes in ``idxs``. Returns
        (batch_ok, decomp_ok-over-idxs)."""
        a, r, s_le, h_le, _valid = prep
        with self._lock:
            self.rlc_checks += 1
        if self._check_fn is not None:
            return self._check_fn(prep, idxs)
        sub_valid = np.ones(len(idxs), dtype=bool)
        return self._rlc.rlc_check(
            r[idxs], a[idxs], s_le[idxs], h_le[idxs], sub_valid,
            k_rounds=self.k_rounds,
        )

    def _leaf(self, items, idxs: np.ndarray, verdicts: np.ndarray) -> None:
        """Exact per-signature resolution of a bisection leaf."""
        with self._lock:
            self.leaf_sigs += len(idxs)
        if self._leaf_fn is not None:
            res = self._leaf_fn(items, idxs)
        else:
            from ..native import ingest_available, verify_bulk_native

            chunk = [items[int(i)] for i in idxs]
            if ingest_available():
                res = verify_bulk_native(chunk, 1)
            else:
                res = [verify_one(pk, m, s) for pk, m, s in chunk]
        for i, ok in zip(idxs, res):
            verdicts[int(i)] = bool(ok)

    def _bisect(
        self, prep, items, idxs: np.ndarray, verdicts: np.ndarray, depth: int
    ) -> None:
        """Resolve ``idxs`` (known to have failed a check) exactly."""
        with self._lock:
            if depth > self.bisection_depth:
                self.bisection_depth = depth
        if len(idxs) <= self.leaf_size:
            self._leaf(items, idxs, verdicts)
            return
        mid = len(idxs) // 2
        halves = (idxs[:mid], idxs[mid:])
        results = []
        for half in halves:
            ok, decomp = self._check(prep, half)
            results.append((half, ok, decomp))
        if all(ok for _, ok, _ in results):
            # the parent failed but both halves pass: a torsion round
            # fired on the parent and missed on both halves (probability
            # 2^-k each) — resolve everything exactly rather than trust
            # either verdict
            with self._lock:
                self.rlc_anomalies += 1
            self._leaf(items, idxs, verdicts)
            return
        for half, ok, decomp in results:
            if ok:
                verdicts[half[decomp]] = True  # non-decomp lanes stay False
            else:
                sub = half[decomp]
                if len(sub):
                    self._bisect(prep, items, sub, verdicts, depth + 1)

    def verify_batch(
        self, items: Sequence[Tuple[bytes, bytes, bytes]]
    ) -> List[bool]:
        """Exact per-entry verdicts for one flush, RLC-amortized."""
        from ..ops import ed25519 as ed_ops

        n = len(items)
        pks = [it[0] for it in items]
        msgs = [it[1] for it in items]
        sigs = [it[2] for it in items]
        prep = ed_ops.prepare_batch(pks, msgs, sigs)
        a, r, s_le, h_le, valid = prep
        verdicts = np.zeros(n, dtype=bool)

        if self._check_fn is None:
            cert_ok = self._certify(pks)
        else:
            cert_ok = np.ones(n, dtype=bool)
        rlc_lanes = np.flatnonzero(valid[:n] & cert_ok)
        exact_lanes = np.flatnonzero(valid[:n] & ~cert_ok)
        with self._lock:
            self.rlc_batches += 1
            self.rlc_sigs += len(rlc_lanes)
            self.exact_reroutes += len(exact_lanes)

        if len(rlc_lanes) <= self.leaf_size:
            # not enough amortizable lanes to beat per-sig: resolve exact
            if len(rlc_lanes):
                self._leaf(items, rlc_lanes, verdicts)
        else:
            ok, decomp = self._check(prep, rlc_lanes)
            if ok:
                verdicts[rlc_lanes[decomp]] = True
            else:
                with self._lock:
                    self.rlc_fallbacks += 1
                sub = rlc_lanes[decomp]
                if len(sub):
                    self._bisect(prep, items, sub, verdicts, 1)
        if len(exact_lanes):
            self._leaf(items, exact_lanes, verdicts)
        return verdicts.tolist()


class TpuBatchVerifier:
    """Accumulate -> pad to bucket -> one XLA dispatch -> resolve futures.

    Dispatch is a three-stage pipeline, each stage on its own executor
    thread so consecutive batches OVERLAP (the round-1 bench measured the
    async-chained shape at ~4x the serial-per-batch rate on the tunnel):

    * ``_prep``   — host-side batch preparation + packing (CPU-bound;
      the native C++ path when available);
    * ``_launch`` — device transfer + kernel dispatch + async copy-back
      start (returns the in-flight device handle without blocking);
    * ``_finish`` — materialize the results (the one blocking sync).

    Up to ``PIPELINE_DEPTH`` batches are in flight past launch; the
    flusher keeps prepping/launching while older batches drain. The event
    loop (gRPC handlers, broadcast state machines) never blocks on any
    stage; results come back as resolved futures per chunk sink.
    """

    PIPELINE_DEPTH = 4  # matches the bench's measured sweet spot

    def __init__(
        self,
        batch_size: int = 256,
        max_delay: float = 0.002,
        buckets: Sequence[int] | None = None,
        max_queue: int | None = None,
        clock=None,
        mode: str = "auto",
        rlc_min_batch: int | None = None,
    ) -> None:
        from ..clock import SYSTEM_CLOCK

        self.batch_size = batch_size
        self.max_delay = max_delay
        self._clock = SYSTEM_CLOCK if clock is None else clock
        # Routing (ISSUE 10): on-chip the Pallas per-sig kernel already
        # wins at every banked bucket (AGGREGATE_r02 measured the one-MSM
        # certificate shape SLOWER than per-sig on TPU), so ``auto``
        # never routes a TPU flush to RLC unless the operator opts in
        # with an explicit ``rlc_min_batch``; ``mode="rlc"`` forces it
        # (the CPU twin is where auto-RLC pays — see CpuVerifier).
        self.router = VerifyRouter(
            mode,
            min_batch=rlc_min_batch if rlc_min_batch is not None else 1 << 30,
        )
        self.rlc_batches = 0
        self.rlc_fallbacks = 0
        self.rlc_reroutes = 0
        if buckets is None:
            # One bucket == one compiled program: a flush never exceeds
            # batch_size, so padding to it keeps every dispatch the same
            # shape and warmup() covers all compilation up front. Pass an
            # explicit bucket ladder (e.g. ops.ed25519.BUCKETS) to enable
            # ADAPTIVE shaping: timer flushes land in the smallest bucket
            # that fits instead of padding to batch_size, and a deep
            # backlog coalesces into the largest bucket the queue can
            # fill instead of paying per-batch_size dispatch overhead.
            buckets = ()
        self.buckets = tuple(sorted(set(buckets) | {batch_size}))
        self._queue: List[_Pending] = []
        # Backpressure bound: callers await queue room instead of growing
        # the accumulator without limit (the broadcast worker pool already
        # self-limits; this protects against unbounded verify_many floods).
        # Capacity is a counted reservation (condition variable, bulk
        # acquire/release) so verify_many reserves a whole chunk in one
        # await instead of one semaphore acquire per signature.
        self.max_queue = (
            max_queue if max_queue is not None else max(8 * batch_size, 4096)
        )
        self._cap_free = self.max_queue
        self._cap_cond = asyncio.Condition()
        self._wakeup = asyncio.Event()
        # one thread per pipeline stage: prep of batch N+1 overlaps the
        # device execution of batch N, whose completion drains in parallel
        self._prep_pool = ThreadPoolExecutor(max_workers=1)
        self._device_pool = ThreadPoolExecutor(max_workers=1)
        self._finish_pool = ThreadPoolExecutor(max_workers=1)
        self._inflight = asyncio.Semaphore(self.PIPELINE_DEPTH)
        self._completions: set = set()
        self._closed = False
        self._flusher = asyncio.get_running_loop().create_task(self._flush_loop())
        # Stats for observability (SURVEY.md §5: per-stage counters)
        self.batches_dispatched = 0
        self.signatures_verified = 0
        self.total_padding = 0
        self.total_dispatch_s = 0.0
        self.last_dispatch_s = 0.0
        self.total_prep_s = 0.0
        self.total_launch_s = 0.0
        self.total_finish_s = 0.0
        self.queue_peak = 0
        # Per-batch latency DISTRIBUTIONS (obs/registry.py): the stage
        # means above tell you where the average batch goes; these tell
        # you what the tail does (p99 queue-wait is the number that
        # bounds client-visible admission latency under load). Standalone
        # histograms — the owning Service surfaces them through
        # stats()/stage_histograms(), so they need no registry.
        self.h_queue_wait = Histogram(
            "queue_wait", "enqueue -> dispatch wait of a batch's oldest item"
        )
        self.h_prep = Histogram("prep", "host-side prep stage per batch")
        self.h_launch = Histogram("launch", "device launch stage per batch")
        self.h_finish = Histogram("finish", "device sync + readback per batch")
        self.h_dispatch = Histogram(
            "dispatch", "prep -> results pipeline latency per batch"
        )
        # optional protocol flight recorder (obs/recorder.py), attached
        # by the owning Service after start: flush decisions (take /
        # depth / bucket) are exactly the events a post-mortem needs to
        # explain a latency spike. Duck-typed so the verifier keeps its
        # no-registry, no-obs-import design.
        self.recorder = None
        # optional plane time-accounting seam (obs/profiler.py), attached
        # the same duck-typed way: the flush decision is one of the named
        # serial terms in the per-node plane decomposition.
        self.phases = None

    def stats(self) -> dict:
        """Operator-facing counters: batch occupancy, padding ratio, and
        device dispatch latency (SURVEY.md §5 tracing/metrics row)."""
        n_b = self.batches_dispatched
        n_s = self.signatures_verified
        return {
            "batches": n_b,
            "signatures": n_s,
            "queue_depth": len(self._queue),
            "queue_peak": self.queue_peak,
            "max_queue": self.max_queue,
            "capacity_free": self._cap_free,
            "batch_occupancy": (n_s / (n_s + self.total_padding))
            if n_s + self.total_padding
            else 0.0,
            "padding_ratio": (self.total_padding / (n_s + self.total_padding))
            if n_s + self.total_padding
            else 0.0,
            # per-batch prep->results pipeline latency (stages overlap
            # across batches, so this is NOT additive with throughput)
            "avg_dispatch_ms": (1e3 * self.total_dispatch_s / n_b) if n_b else 0.0,
            "last_dispatch_ms": 1e3 * self.last_dispatch_s,
            # per-stage means: where a batch's wall time actually goes
            # (prep/launch include their executor-queue wait, so a
            # saturated stage shows up here as inflation)
            "prep_ms_avg": (1e3 * self.total_prep_s / n_b) if n_b else 0.0,
            "launch_ms_avg": (1e3 * self.total_launch_s / n_b) if n_b else 0.0,
            "finish_ms_avg": (1e3 * self.total_finish_s / n_b) if n_b else 0.0,
            # queue-wait DISTRIBUTION: the tail the means can't show
            # (benches bank p50/p99 from here — ISSUE 3 satellite)
            **self.h_queue_wait.flat("queue_wait"),
            "mode": _MODE_CODES[self.router.mode],
            "mode_name": self.router.mode,
            "rlc_batches": self.rlc_batches,
            "rlc_fallbacks": self.rlc_fallbacks,
            "rlc_reroutes": self.rlc_reroutes,
            **self.router.stats(),
        }

    def stage_histograms(self) -> dict:
        """Per-stage latency distributions (count/sum/max/p50/p90/p99 in
        ms) for /statusz — the pipeline's shape under live load."""
        return {
            "queue_wait": self.h_queue_wait.snapshot(),
            "prep": self.h_prep.snapshot(),
            "launch": self.h_launch.snapshot(),
            "finish": self.h_finish.snapshot(),
            "dispatch": self.h_dispatch.snapshot(),
        }

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _take_for_flush(self) -> int:
        """Adaptive dispatch sizing from LIVE queue depth: normally one
        batch_size slice, but a backlog deeper than batch_size coalesces
        into the largest configured bucket it can FILL — one 4096-lane
        dispatch instead of sixteen 256s amortizes the fixed per-dispatch
        tunnel sync ~16x (bench.py's transfer analysis). Single-bucket
        verifiers degrade to the old fixed-slice behavior exactly."""
        depth = len(self._queue)
        take = self.batch_size
        for b in self.buckets:
            if b <= depth:
                take = max(take, b)
        return take

    async def _acquire(self, n: int) -> None:
        """Reserve queue room for ``n`` signatures in one await."""
        async with self._cap_cond:
            while self._cap_free < n and not self._closed:
                try:
                    await self._cap_cond.wait()
                except asyncio.CancelledError:
                    # a cancelled waiter may have CONSUMED a notify meant
                    # for a sibling; pass it on before unwinding or that
                    # sibling parks forever on free capacity (classic
                    # Condition lost-wakeup)
                    self._cap_cond.notify_all()
                    raise
            if self._closed:
                raise RuntimeError("verifier closed")
            self._cap_free -= n

    async def _release(self, n: int) -> None:
        async with self._cap_cond:
            self._cap_free += n
            self._cap_cond.notify_all()

    def _enqueue_chunk(self, items, sink: _ChunkSink) -> None:
        was_empty = not self._queue
        now = self._clock.monotonic()
        append = self._queue.append
        for idx, (pk, msg, sig) in enumerate(items):
            append(_Pending(pk, msg, sig, sink, idx, now))
        if len(self._queue) > self.queue_peak:
            self.queue_peak = len(self._queue)
        # Wake the flusher on the empty->non-empty transition too, so a lone
        # request waits max_delay, not the flusher's 100ms idle-poll tick.
        if was_empty or len(self._queue) >= self.batch_size:
            self._wakeup.set()

    async def _evict_sinks(self, sinks: set) -> None:
        """Pull a cancelled caller's not-yet-dispatched entries back out of
        the accumulator and return their reserved capacity. Entries already
        popped by the flusher are past the point of no return (the device
        is working on them); they resolve or fail through _complete."""
        kept: List[_Pending] = []
        evicted = 0
        for p in self._queue:
            if p.sink in sinks:
                evicted += 1
            else:
                kept.append(p)
        self._queue = kept
        for sink in sinks:
            sink.fail(RuntimeError("verify cancelled"))
        if evicted:
            # shielded: this runs inside cancellation unwinding and MUST
            # complete, or the cancelled caller's capacity leaks forever
            await asyncio.shield(self._release(evicted))

    async def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        if self._closed:
            raise RuntimeError("verifier closed")
        await self._acquire(1)
        sink = _ChunkSink(asyncio.get_running_loop(), 1)
        self._enqueue_chunk(((public_key, message, signature),), sink)
        return (await sink.future)[0]

    async def verify_many(
        self, items: Sequence[Tuple[bytes, bytes, bytes]]
    ) -> List[bool]:
        """Bulk path: the whole chunk enters the accumulator under ONE
        capacity reservation and resolves through ONE future per
        batch_size slice (slices larger than a batch could never flush as
        one dispatch anyway, so slicing there costs nothing)."""
        if self._closed:
            raise RuntimeError("verifier closed")
        n = len(items)
        if n == 0:
            return []
        loop = asyncio.get_running_loop()
        sinks: List[_ChunkSink] = []
        items = list(items) if not isinstance(items, (list, tuple)) else items
        try:
            for i in range(0, n, self.batch_size):
                chunk = items[i : i + self.batch_size]
                await self._acquire(len(chunk))
                sink = _ChunkSink(loop, len(chunk))
                self._enqueue_chunk(chunk, sink)
                sinks.append(sink)
        except BaseException:
            # close() landed between chunks: the already-enqueued sinks
            # WILL be resolved (close fails queued entries; in-flight
            # batches resolve via _complete) — consume those futures so
            # their exceptions are retrieved and any completed chunk's
            # results aren't silently dropped as un-awaited warnings
            if sinks:
                await asyncio.gather(
                    *(s.future for s in sinks), return_exceptions=True
                )
            raise
        # gather (not sequential awaits): when an early chunk's dispatch
        # fails, every sink's exception is still retrieved — no
        # "exception was never retrieved" spam for the later chunks
        try:
            chunk_results = await asyncio.gather(*(s.future for s in sinks))
        except asyncio.CancelledError:
            # the CALLER was cancelled mid-wait: its undispatched entries
            # must not squat in the accumulator holding reserved capacity
            # (a flood of cancelled clients would otherwise wedge the
            # verifier at max_queue with work nobody wants)
            await self._evict_sinks(set(sinks))
            raise
        out: List[bool] = []
        for results in chunk_results:
            out.extend(results)
        return out

    async def _flush_loop(self) -> None:
        while not self._closed:
            if not self._queue:
                self._wakeup.clear()
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=0.1)
                except asyncio.TimeoutError:
                    continue
            # wait for a full batch or until the oldest request expires
            while (
                len(self._queue) < self.batch_size
                and self._queue
                and (self._clock.monotonic() - self._queue[0].enqueued_at)
                < self.max_delay
            ):
                self._wakeup.clear()
                remaining = self.max_delay - (
                    self._clock.monotonic() - self._queue[0].enqueued_at
                )
                try:
                    await asyncio.wait_for(
                        self._wakeup.wait(), timeout=max(remaining, 0.0001)
                    )
                except asyncio.TimeoutError:
                    break
            if not self._queue:
                continue
            ph = self.phases
            t0 = ph.t() if ph is not None else 0
            take = self._take_for_flush()
            if self.recorder is not None:
                self.recorder.record(
                    "vflush",
                    (take, len(self._queue), self._bucket_for(take)),
                )
            batch, self._queue = (
                self._queue[:take],
                self._queue[take:],
            )
            try:
                await self._release(len(batch))
                # flush decision only: pipeline latency past this point
                # is already measured by h_dispatch
                if ph is not None:
                    ph.add("verifier_flush", t0)
                await self._dispatch(batch)
            except BaseException as exc:
                # once popped from _queue, close()'s sweep can no longer
                # see this batch — a cancellation landing in the _release
                # await (or anywhere before dispatch resolves the sinks)
                # must fail them here or their callers hang forever
                for p in batch:
                    p.sink.fail(
                        RuntimeError("verifier closed")
                        if isinstance(exc, asyncio.CancelledError)
                        else exc
                    )
                if isinstance(exc, asyncio.CancelledError):
                    raise  # close() is tearing the flusher down
                # anything else: this batch already failed its callers;
                # the flusher itself stays up for subsequent batches

    # -- pipeline stages (subclasses — parallel.pool.PoolVerifier —
    # override all three to shard over a mesh) ---------------------------

    def _prep(self, pks, msgs, sigs, bucket):
        """Host stage: bucket policy + batch prep + packing (the shape
        rules — incl. Pallas TILE rounding — live in ops.ed25519), then
        the host->device upload — HERE rather than in _launch so batch
        N+1's tunnel transfer overlaps batch N's dispatch/kernel (the
        round-4 trace attributes the 250k-vs-475k pipelined gap to
        transfers serializing on the launch thread; ops/ed25519.py
        upload_packed)."""
        from ..ops import ed25519 as kernel

        return kernel.upload_packed(kernel.prep_packed(pks, msgs, sigs, bucket))

    def _launch(self, packed):
        """Device stage: transfer + dispatch + start the async copy-back;
        returns the in-flight handle without blocking."""
        from ..ops import ed25519 as kernel

        return kernel.launch_packed(packed)

    def _finish(self, handle, n: int) -> np.ndarray:
        """Completion stage: block until the device results land."""
        from ..ops import ed25519 as kernel

        return kernel.finish_packed(handle, n)

    def _run_batch(self, pks, msgs, sigs, bucket) -> np.ndarray:
        """Synchronous compose of the three stages (warmup path; also the
        historical override seam: a subclass that replaces only THIS
        method still works — _dispatch detects that case and routes the
        whole batch through it on the device thread)."""
        return self._finish(
            self._launch(self._prep(pks, msgs, sigs, bucket)), len(pks)
        )

    # -- RLC stages (ISSUE 10): same three-thread pipeline shape, but the
    # device dispatch is ONE classified RLC check (ops.aggregate) instead
    # of the per-sig kernel; _complete interprets the (eq_ok, codes)
    # verdict and falls back to one exact per-sig kernel pass when the
    # equation fails or any lane needs rerouting ---------------------------

    def _prep_rlc(self, pks, msgs, sigs, bucket):
        from ..ops import aggregate as agg

        return agg.rlc_prep(pks, msgs, sigs, bucket)

    def _launch_rlc(self, packed):
        from ..ops import aggregate as agg

        return agg.rlc_launch(packed)

    def _finish_rlc(self, handle, n: int):
        from ..ops import aggregate as agg

        return agg.rlc_finish(handle, n)

    def _run_batch_rlc(self, pks, msgs, sigs, bucket):
        return self._finish_rlc(
            self._launch_rlc(self._prep_rlc(pks, msgs, sigs, bucket)), len(pks)
        )

    def _staged_overrides_consistent(self) -> bool:
        """True when the staged pipeline reflects this instance's actual
        verify logic: either nothing is overridden, or the stages are.
        A subclass overriding only _run_batch must not be bypassed."""
        cls = type(self)
        run_overridden = cls._run_batch is not TpuBatchVerifier._run_batch
        stages_overridden = (
            cls._prep is not TpuBatchVerifier._prep
            or cls._launch is not TpuBatchVerifier._launch
        )
        return stages_overridden or not run_overridden

    async def warmup(self) -> None:
        """Compile EVERY bucket's program before serving traffic.

        XLA/Mosaic compilation takes tens of seconds cold; a node must not
        report ready (bind its RPC port) while the first real signature
        would stall behind the compiler. Dispatches one padded throwaway
        batch per configured bucket shape, then one request through the
        full accumulate/flush path."""
        from .keys import SignKeyPair

        kp = SignKeyPair.from_hex("01" * 32)
        msg = b"verifier warmup"
        sig = kp.sign(msg)
        loop = asyncio.get_running_loop()
        warm_rlc = (
            self.router.mode == "rlc"
            or (self.router.mode == "auto" and self.router.min_batch < (1 << 30))
        ) and self._staged_overrides_consistent()
        for bucket in self.buckets:
            out = await loop.run_in_executor(
                self._device_pool, self._run_batch, [kp.public], [msg], [sig], bucket
            )
            if not bool(out[0]):
                raise RuntimeError(
                    f"verifier warm-up failed for bucket {bucket}"
                )
            if warm_rlc:
                eq_ok, codes = await loop.run_in_executor(
                    self._device_pool,
                    self._run_batch_rlc, [kp.public], [msg], [sig], bucket,
                )
                if not (bool(eq_ok) and int(codes[0]) == 1):
                    raise RuntimeError(
                        f"rlc warm-up failed for bucket {bucket}"
                    )
        ok = await self.verify(kp.public, msg, sig)
        if not ok:
            raise RuntimeError("verifier warm-up batch failed to verify")

    @staticmethod
    def _fail_batch(batch: List[_Pending], exc: BaseException) -> None:
        """Resolve every sink of an abandoned batch (callers must never
        hang; close() cannot see batches already popped from _queue)."""
        err = (
            RuntimeError("verifier closed")
            if isinstance(exc, asyncio.CancelledError)
            else exc
        )
        for p in batch:
            p.sink.fail(err)

    async def _dispatch(self, batch: List[_Pending]) -> None:
        """Prep and launch this batch, then hand completion to a
        background task so the flusher can pipeline the NEXT batch while
        the device works; at most PIPELINE_DEPTH batches run past launch."""
        bucket = self._bucket_for(len(batch))
        loop = asyncio.get_running_loop()
        pks = [p.public_key for p in batch]
        msgs = [p.message for p in batch]
        sigs = [p.signature for p in batch]

        # queue wait of the OLDEST item (FIFO queue: batch[0]), observed
        # BEFORE the depth gate — waiting for an in-flight slot is queue
        # time from the caller's perspective, exactly what the admission
        # path's latency budget pays
        self.h_queue_wait.observe(self._clock.monotonic() - batch[0].enqueued_at)
        await self._inflight.acquire()
        # route THIS flush (ISSUE 10): the decision is per-dispatch, from
        # live batch size + the per-source failure EWMA; per_sig mode and
        # subclasses with a legacy _run_batch override always take the
        # per-sig kernel
        rlc = (
            self.router.mode != "per_sig"
            and self._staged_overrides_consistent()
            and self.router.choose(pks) == "rlc"
        )
        # clock starts AFTER the depth gate: avg/last_dispatch_ms measure
        # one batch's prep->results pipeline latency, not queue wait
        t0 = self._clock.monotonic()
        try:
            if self._staged_overrides_consistent():
                prepared = await loop.run_in_executor(
                    self._prep_pool,
                    self._prep_rlc if rlc else self._prep,
                    pks, msgs, sigs, bucket,
                )
                t1 = self._clock.monotonic()
                self.total_prep_s += t1 - t0
                self.h_prep.observe(t1 - t0)
                handle = await loop.run_in_executor(
                    self._device_pool,
                    self._launch_rlc if rlc else self._launch,
                    prepared,
                )
                t2 = self._clock.monotonic()
                self.total_launch_s += t2 - t1
                self.h_launch.observe(t2 - t1)
                finish = loop.run_in_executor(
                    self._finish_pool,
                    self._finish_rlc if rlc else self._finish,
                    handle, len(batch),
                )
            else:
                # legacy seam: subclass replaced _run_batch only — run it
                # whole on the device thread (no stage overlap, but the
                # depth bound still lets batches queue behind each other)
                finish = loop.run_in_executor(
                    self._device_pool, self._run_batch, pks, msgs, sigs, bucket
                )
        except BaseException as exc:
            self._inflight.release()
            self._fail_batch(batch, exc)
            if isinstance(exc, asyncio.CancelledError):
                raise
            return
        task = loop.create_task(self._complete(batch, bucket, finish, t0, rlc))
        self._completions.add(task)
        task.add_done_callback(self._completions.discard)

    async def _resolve_rlc(self, batch, bucket, out) -> np.ndarray:
        """Turn an RLC stage result into exact per-lane verdicts.

        Clean case (equation holds, no reroutes): the codes ARE the
        verdicts. Otherwise fall back to ONE exact per-signature kernel
        pass over the same flush — on-chip that single dispatch resolves
        every lane at once, so it IS the degenerate bisection leaf (the
        recursive split only pays on the CPU engine, where leaf cost is
        per-signature). Runs while _inflight is still held: the fallback
        occupies this batch's pipeline slot, not a new one."""
        eq_ok, codes = out
        self.rlc_batches += 1
        reroutes = int((codes == 2).sum())
        self.rlc_reroutes += reroutes
        if eq_ok and not reroutes:
            results = codes == 1
        else:
            self.rlc_fallbacks += 1
            loop = asyncio.get_running_loop()
            pks = [p.public_key for p in batch]
            msgs = [p.message for p in batch]
            sigs = [p.signature for p in batch]
            results = await loop.run_in_executor(
                self._device_pool, self._run_batch, pks, msgs, sigs, bucket
            )
        if self.router.mode == "auto":
            self.router.observe(
                [
                    (p.public_key, bool(ok))
                    for p, ok in zip(batch, results)
                ]
            )
        return results

    async def _complete(self, batch, bucket, finish, t0, rlc=False) -> None:
        t_fin = self._clock.monotonic()
        try:
            results = await finish
            if rlc:
                results = await self._resolve_rlc(batch, bucket, results)
        except BaseException as exc:
            self._fail_batch(batch, exc)
            if isinstance(exc, asyncio.CancelledError):
                raise
            return
        finally:
            self._inflight.release()
        t_done = self._clock.monotonic()
        self.total_finish_s += t_done - t_fin
        self.h_finish.observe(t_done - t_fin)
        self.last_dispatch_s = t_done - t0
        self.total_dispatch_s += self.last_dispatch_s
        self.h_dispatch.observe(self.last_dispatch_s)
        self.batches_dispatched += 1
        self.signatures_verified += len(batch)
        self.total_padding += bucket - len(batch)
        for p, ok in zip(batch, results):
            p.sink.set(p.idx, bool(ok))

    async def close(self) -> None:
        self._closed = True
        # Wake parked _acquire callers FIRST, before draining in-flight
        # completions: a wedged device (tunnel dead mid-batch) can hold
        # the completion gather below forever, and a caller parked in
        # _cap_cond.wait() must get its "verifier closed" RuntimeError
        # now, not after a hang that never ends. They re-check _closed
        # under the condition and raise.
        async with self._cap_cond:
            self._cap_cond.notify_all()
        self._wakeup.set()
        self._flusher.cancel()
        try:
            await self._flusher
        except (asyncio.CancelledError, Exception):
            pass
        # drain in-flight completions: their batches already left _queue,
        # so only these tasks can resolve (or fail) those sinks
        if self._completions:
            await asyncio.gather(
                *list(self._completions), return_exceptions=True
            )
        for p in self._queue:
            p.sink.fail(RuntimeError("verifier closed"))
        released = len(self._queue)
        self._queue.clear()
        # return the dead queue's capacity and wake every caller parked in
        # _acquire (they re-check _closed under the condition and raise —
        # the notify matters even when released == 0)
        await self._release(released)
        for pool in (self._prep_pool, self._device_pool, self._finish_pool):
            pool.shutdown(wait=False, cancel_futures=True)


def make_verifier(kind: str, **kwargs) -> Verifier:
    """Config-driven verifier selection
    (``verifier = "cpu" | "tpu" | "pool"``)."""
    if kind == "cpu":
        return CpuVerifier(**kwargs)
    if kind == "tpu":
        return TpuBatchVerifier(**kwargs)
    if kind == "pool":
        from ..parallel.pool import PoolVerifier

        return PoolVerifier(**kwargs)
    raise ValueError(f"unknown verifier kind: {kind!r}")
