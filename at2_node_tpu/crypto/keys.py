"""ed25519 signing keys and X25519 network (channel) keys.

Equivalent of the reference's `drop::crypto::sign::{KeyPair, PublicKey,
PrivateKey}` (used at `/root/reference/src/lib.rs:5`,
`/root/reference/src/client.rs:77-78`) and
`drop::crypto::key::exchange::KeyPair` (used at
`/root/reference/src/bin/server/rpc.rs:14-17,80`).

Host-side single signatures use the `cryptography` library (OpenSSL)
when the wheel is present, else the pure-Python RFC implementations in
`crypto/_fallback.py` (same algorithms, wire-compatible); the batched
hot path lives on TPU (`at2_node_tpu.ops.ed25519`). Keys are hex-encoded
in config files, matching the reference's `#[serde(with = "hex")]`
(`/root/reference/src/bin/server/config.rs:14-17`).
"""

from __future__ import annotations

from dataclasses import dataclass

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519, x25519

    _HAVE_OPENSSL = True
    _RAW = serialization.Encoding.Raw
    _RAW_PUB = serialization.PublicFormat.Raw
    _RAW_PRIV = serialization.PrivateFormat.Raw
    _NOENC = serialization.NoEncryption()
except ImportError:  # image without the OpenSSL wheels: RFC fallback
    from ._fallback import InvalidSignature  # noqa: F401 (re-exported)

    _HAVE_OPENSSL = False

from . import _fallback as _fb


@dataclass(frozen=True)
class SignKeyPair:
    """ed25519 keypair; signs the canonical byte form of messages.

    The OpenSSL key object and the derived public bytes are cached on
    first use: ``from_private_bytes`` re-derives the public point every
    call (~40us on the deployment cores — measured round 3), and the
    broadcast plane signs one Echo and one Ready per slot, so rebuilding
    per sign() would double the hot path's signing cost."""

    private_bytes: bytes  # 32-byte seed

    @staticmethod
    def random() -> "SignKeyPair":
        if not _HAVE_OPENSSL:
            return SignKeyPair(_fb.ed25519_generate_seed())
        key = ed25519.Ed25519PrivateKey.generate()
        return SignKeyPair(key.private_bytes(_RAW, _RAW_PRIV, _NOENC))

    @staticmethod
    def from_hex(s: str) -> "SignKeyPair":
        return SignKeyPair(bytes.fromhex(s))

    def to_hex(self) -> str:
        return self.private_bytes.hex()

    def _key(self) -> "ed25519.Ed25519PrivateKey":
        cached = self.__dict__.get("_key_obj")
        if cached is None:
            cached = ed25519.Ed25519PrivateKey.from_private_bytes(
                self.private_bytes
            )
            object.__setattr__(self, "_key_obj", cached)
        return cached

    @property
    def public(self) -> bytes:
        cached = self.__dict__.get("_pub")
        if cached is None:
            if _HAVE_OPENSSL:
                cached = self._key().public_key().public_bytes(_RAW, _RAW_PUB)
            else:
                cached = _fb.ed25519_public(self.private_bytes)
            object.__setattr__(self, "_pub", cached)
        return cached

    def sign(self, message: bytes) -> bytes:
        if not _HAVE_OPENSSL:
            return _fb.ed25519_sign(self.private_bytes, message)
        return self._key().sign(message)


def verify_one(public_key: bytes, message: bytes, signature: bytes) -> bool:
    """Single CPU ed25519 verification (the reference's per-message path;
    the TPU batch path is `ops.ed25519.verify_batch`)."""
    try:
        if _HAVE_OPENSSL:
            ed25519.Ed25519PublicKey.from_public_bytes(public_key).verify(
                signature, message
            )
        else:
            _fb.ed25519_verify(public_key, message, signature)
        return True
    except (InvalidSignature, ValueError):
        return False


@dataclass(frozen=True)
class ExchangeKeyPair:
    """X25519 keypair authenticating node<->node channels (drop's
    `key::exchange::KeyPair`, `/root/reference/src/bin/server/config.rs:16`)."""

    private_bytes: bytes

    @staticmethod
    def random() -> "ExchangeKeyPair":
        if not _HAVE_OPENSSL:
            return ExchangeKeyPair(_fb.x25519_generate_seed())
        key = x25519.X25519PrivateKey.generate()
        return ExchangeKeyPair(key.private_bytes(_RAW, _RAW_PRIV, _NOENC))

    @staticmethod
    def from_hex(s: str) -> "ExchangeKeyPair":
        return ExchangeKeyPair(bytes.fromhex(s))

    def to_hex(self) -> str:
        return self.private_bytes.hex()

    @property
    def public(self) -> bytes:
        if not _HAVE_OPENSSL:
            return _fb.x25519_public(self.private_bytes)
        key = x25519.X25519PrivateKey.from_private_bytes(self.private_bytes)
        return key.public_key().public_bytes(_RAW, _RAW_PUB)

    def exchange(self, peer_public: bytes) -> bytes:
        if not _HAVE_OPENSSL:
            return _fb.x25519(self.private_bytes, peer_public)
        key = x25519.X25519PrivateKey.from_private_bytes(self.private_bytes)
        return key.exchange(x25519.X25519PublicKey.from_public_bytes(peer_public))
