"""Host-side cryptography: keys, signing, channel auth, and the Verifier
boundary that routes signature checks to CPU or the TPU batch kernel."""
