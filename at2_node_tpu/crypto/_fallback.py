"""Dependency-free fallback crypto: ed25519, X25519, ChaCha20-Poly1305, HKDF.

`crypto/keys.py` and `net/transport.py` prefer the `cryptography` wheel
(OpenSSL) and fall back HERE when it is absent from the interpreter —
some deployment images bake only the jax toolchain. Everything in this
module is a straight transcription of the RFCs:

* ed25519 — RFC 8032 §5.1 (sign/verify over edwards25519, SHA-512);
* X25519 — RFC 7748 §5 (montgomery ladder, clamped scalars);
* ChaCha20-Poly1305 — RFC 8439 (the cipher core is vectorized across
  blocks with numpy so large frames stay off the per-byte Python path);
* HKDF-SHA256 — RFC 5869 via stdlib hmac.

Interop: these are the same algorithms OpenSSL implements, so a
fallback-built node talks to an OpenSSL-built node byte-for-byte — the
self-tests in tests/test_ed25519.py and tests/test_node.py exercise the
shared RFC vectors. Performance is adequate for control-plane use (a few
thousand ops/s); the BULK verification path stays on the jax kernels
(`ops/ed25519.py`), which never depended on the wheel.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import struct

import numpy as np


class InvalidSignature(Exception):
    """Mirror of cryptography.exceptions.InvalidSignature."""


class InvalidTag(Exception):
    """Mirror of cryptography.exceptions.InvalidTag."""


# -- edwards25519 field / group (RFC 8032 §5.1) ---------------------------

_P = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493
_D = (-121665 * pow(121666, _P - 2, _P)) % _P
_I = pow(2, (_P - 1) // 4, _P)  # sqrt(-1)

_BY = 4 * pow(5, _P - 2, _P) % _P


def _recover_x(y: int, sign: int) -> int:
    if y >= _P:
        raise InvalidSignature("y out of range")
    x2 = (y * y - 1) * pow(_D * y * y + 1, _P - 2, _P) % _P
    if x2 == 0:
        if sign:
            raise InvalidSignature("bad point")
        return 0
    x = pow(x2, (_P + 3) // 8, _P)
    if (x * x - x2) % _P != 0:
        x = x * _I % _P
    if (x * x - x2) % _P != 0:
        raise InvalidSignature("not a square")
    if x & 1 != sign:
        x = _P - x
    return x


_BX = _recover_x(_BY, 0)
# extended homogeneous coordinates (X, Y, Z, T), RFC 8032 §5.1.4
_BASE = (_BX, _BY, 1, _BX * _BY % _P)
_IDENT = (0, 1, 1, 0)


def _pt_add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % _P
    B = (Y1 + X1) * (Y2 + X2) % _P
    C = 2 * T1 * T2 * _D % _P
    Dv = 2 * Z1 * Z2 % _P
    E, F, G, H = B - A, Dv - C, Dv + C, B + A
    return (E * F % _P, G * H % _P, F * G % _P, E * H % _P)


def _pt_mul(s: int, p):
    q = _IDENT
    while s > 0:
        if s & 1:
            q = _pt_add(q, p)
        p = _pt_add(p, p)
        s >>= 1
    return q


def _pt_equal(p, q) -> bool:
    # cross-multiply out the projective Z factors
    return (
        (p[0] * q[2] - q[0] * p[2]) % _P == 0
        and (p[1] * q[2] - q[1] * p[2]) % _P == 0
    )


def _pt_compress(p) -> bytes:
    zinv = pow(p[2], _P - 2, _P)
    x, y = p[0] * zinv % _P, p[1] * zinv % _P
    return ((y | ((x & 1) << 255))).to_bytes(32, "little")


def _pt_decompress(b: bytes):
    if len(b) != 32:
        raise InvalidSignature("bad point length")
    n = int.from_bytes(b, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    x = _recover_x(y, sign)
    return (x, y, 1, x * y % _P)


def _sha512_int(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little")


def _clamp(h32: bytes) -> int:
    a = int.from_bytes(h32, "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def ed25519_public(seed: bytes) -> bytes:
    a = _clamp(hashlib.sha512(seed).digest()[:32])
    return _pt_compress(_pt_mul(a, _BASE))


def ed25519_sign(seed: bytes, message: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h[:32])
    prefix = h[32:]
    A = _pt_compress(_pt_mul(a, _BASE))
    r = _sha512_int(prefix, message) % _L
    R = _pt_compress(_pt_mul(r, _BASE))
    k = _sha512_int(R, A, message) % _L
    s = (r + k * a) % _L
    return R + s.to_bytes(32, "little")


def ed25519_verify(public: bytes, message: bytes, signature: bytes) -> None:
    """Raises InvalidSignature on failure (cryptography-style contract)."""
    if len(signature) != 64:
        raise InvalidSignature("bad signature length")
    A = _pt_decompress(public)
    R = _pt_decompress(signature[:32])
    s = int.from_bytes(signature[32:], "little")
    if s >= _L:
        raise InvalidSignature("non-canonical s")
    k = _sha512_int(signature[:32], public, message) % _L
    if not _pt_equal(_pt_mul(s, _BASE), _pt_add(R, _pt_mul(k, A))):
        raise InvalidSignature("signature mismatch")


def ed25519_generate_seed() -> bytes:
    return os.urandom(32)


# -- X25519 (RFC 7748 §5) -------------------------------------------------

_A24 = 121665


def _x25519_ladder(k: int, u: int) -> int:
    x1, x2, z2, x3, z3 = u, 1, 0, u, 1
    swap = 0
    for t in reversed(range(255)):
        bit = (k >> t) & 1
        swap ^= bit
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = bit
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = u * z3 * z3 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return x2 * pow(z2, _P - 2, _P) % _P


def x25519(private: bytes, peer_public: bytes) -> bytes:
    k = int.from_bytes(private, "little")
    k &= (1 << 254) - 8
    k |= 1 << 254
    u = int.from_bytes(peer_public, "little") & ((1 << 255) - 1)
    out = _x25519_ladder(k, u)
    if out == 0:
        # RFC 7748 §6.1: an all-zero shared secret means the peer sent a
        # low-order point; OpenSSL's X25519 raises here, so must we
        # (transport.py turns this into HandshakeError)
        raise ValueError("x25519: low-order peer public key")
    return out.to_bytes(32, "little")


_X25519_BASE = (9).to_bytes(32, "little")


def x25519_public(private: bytes) -> bytes:
    return x25519(private, _X25519_BASE)


def x25519_generate_seed() -> bytes:
    return os.urandom(32)


# -- ChaCha20-Poly1305 AEAD (RFC 8439) ------------------------------------

_CHACHA_CONST = np.frombuffer(b"expa" b"nd 3" b"2-by" b"te k", dtype="<u4")


def _rotl(x, n):
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _chacha_rounds(state: np.ndarray) -> np.ndarray:
    """20 ChaCha rounds over shape (16, nblocks) uint32 working state —
    all blocks of a message advance in lockstep (numpy vectorization is
    what keeps megabyte frames off the per-byte Python path)."""
    x = state.copy()

    def qr(a, b, c, d):
        x[a] += x[b]
        x[d] = _rotl(x[d] ^ x[a], 16)
        x[c] += x[d]
        x[b] = _rotl(x[b] ^ x[c], 12)
        x[a] += x[b]
        x[d] = _rotl(x[d] ^ x[a], 8)
        x[c] += x[d]
        x[b] = _rotl(x[b] ^ x[c], 7)

    for _ in range(10):
        qr(0, 4, 8, 12)
        qr(1, 5, 9, 13)
        qr(2, 6, 10, 14)
        qr(3, 7, 11, 15)
        qr(0, 5, 10, 15)
        qr(1, 6, 11, 12)
        qr(2, 7, 8, 13)
        qr(3, 4, 9, 14)
    x += state
    return x


def _chacha20_stream(key: bytes, nonce: bytes, counter: int, n: int) -> bytes:
    """n bytes of keystream starting at the given block counter."""
    nblocks = (n + 63) // 64
    state = np.empty((16, nblocks), dtype=np.uint32)
    state[0:4] = _CHACHA_CONST[:, None]
    state[4:12] = np.frombuffer(key, dtype="<u4")[:, None]
    state[12] = np.arange(counter, counter + nblocks, dtype=np.uint64).astype(
        np.uint32
    )
    state[13:16] = np.frombuffer(nonce, dtype="<u4")[:, None]
    with np.errstate(over="ignore"):
        out = _chacha_rounds(state)
    # column-major: each block is one column of 16 words
    return out.T.astype("<u4").tobytes()[:n]


_POLY_P = (1 << 130) - 5


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        chunk = msg[i : i + 16]
        n = int.from_bytes(chunk, "little") + (1 << (8 * len(chunk)))
        acc = (acc + n) * r % _POLY_P
    return ((acc + s) % (1 << 128)).to_bytes(16, "little")


def _pad16(b: bytes) -> bytes:
    rem = len(b) % 16
    return b"\x00" * (16 - rem) if rem else b""


class ChaCha20Poly1305:
    """Drop-in for cryptography.hazmat...aead.ChaCha20Poly1305."""

    def __init__(self, key: bytes) -> None:
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = key

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        otk = _chacha20_stream(self._key, nonce, 0, 32)
        mac_data = (
            aad
            + _pad16(aad)
            + ct
            + _pad16(ct)
            + struct.pack("<QQ", len(aad), len(ct))
        )
        return _poly1305(otk, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        aad = aad or b""
        stream = _chacha20_stream(self._key, nonce, 1, len(data))
        ct = bytes(a ^ b for a, b in zip(data, stream)) if len(
            data
        ) < 64 else np.bitwise_xor(
            np.frombuffer(data, dtype=np.uint8),
            np.frombuffer(stream, dtype=np.uint8),
        ).tobytes()
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTag("ciphertext too short")
        aad = aad or b""
        ct, tag = data[:-16], data[-16:]
        if not _hmac.compare_digest(self._tag(nonce, ct, aad), tag):
            raise InvalidTag("poly1305 tag mismatch")
        stream = _chacha20_stream(self._key, nonce, 1, len(ct))
        if len(ct) < 64:
            return bytes(a ^ b for a, b in zip(ct, stream))
        return np.bitwise_xor(
            np.frombuffer(ct, dtype=np.uint8),
            np.frombuffer(stream, dtype=np.uint8),
        ).tobytes()


# -- HKDF-SHA256 (RFC 5869) -----------------------------------------------


def hkdf_sha256(ikm: bytes, salt: bytes, info: bytes, length: int) -> bytes:
    prk = _hmac.new(salt or b"\x00" * 32, ikm, hashlib.sha256).digest()
    out = b""
    t = b""
    counter = 1
    while len(out) < length:
        t = _hmac.new(prk, t + info + bytes([counter]), hashlib.sha256).digest()
        out += t
        counter += 1
    return out[:length]
