"""Fleet consistency auditor: incremental ledger digests + beacon compare.

AT2's correctness claim is that independent nodes converge on the same
ledger without consensus (arXiv:1812.10844) — this module is the runtime
evidence for that claim. Each node maintains a cheap incremental digest
of its committed state (folded at the mutation sites, never recomputed),
periodically gossips it as a signed ``StateBeacon`` (broadcast/messages
kind 15), and compares peers' beacons against its own history; a
confirmed conflict flips /healthz to ``diverged`` with attribution.

Digest rules (TECHNICAL.md "Fleet audit & incident capture"):

* Correct AT2 nodes commit the same *set* of transfers in different
  *orders*, so every cross-node-comparable digest is **additive**: an
  unordered sum of per-item contributions mod 2^64 / 2^128. Updating on
  a mutation is O(1): subtract the old contribution, add the new one.
* A virgin account (sequence 0, balance ``INITIAL_BALANCE``) contributes
  zero, so a ledger row created as a side effect of a *failed* apply
  (e.g. a sequence-gap retry) is digest-neutral until its observable
  state actually changes — row-creation timing can differ across nodes
  without perturbing the digest.
* Beacons are compared only between snapshots taken at the **same
  watermark digest** and the same membership epoch. The watermark digest
  sums H(sender, last_sequence) over the per-sender commit frontier;
  under AT2's gap-free per-sender sequencing, equal watermark vectors
  mean equal applied sets, so two correct nodes at the same coordinate
  MUST agree on every account-range lane. A mismatch there is a real
  divergence (corrupted apply, torn restart, registry eviction), never
  a reordering artifact — the comparison is zero-false-positive by
  construction.
* Directory skew is informational only: directory gossip is eventually
  consistent and a stale mapping is a liveness issue, not a safety one.
* The sha256 ``chain`` head is order-dependent and therefore *local
  only* — folded per beacon point, persisted in the store manifest, and
  used as restart tamper evidence; it is never compared across peers.
"""

from __future__ import annotations

import hashlib
import struct
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

# ledger/account.INITIAL_BALANCE, duplicated so obs/ stays a leaf
# package (ledger/accounts.py imports the digest, not the other way
# around); pinned against the ledger constant by tests/test_obs.py.
INITIAL_BALANCE = 100_000

AUDIT_RANGES = 16  # account-range lanes; range index = key[0] >> 4

_M64 = (1 << 64) - 1
_M128 = (1 << 128) - 1
_ACCT_TAG = b"at2-audit/acct/v1"
_WM_TAG = b"at2-audit/wm/v1"
_DIR_TAG = b"at2-audit/dir/v1"
_CHAIN_TAG = b"at2-audit/chain/v1"
_RESTART_TAG = b"at2-audit/restart/v1"
_QQ = struct.Struct("<QQ")
_Q = struct.Struct("<Q")


def account_contrib(key: bytes, sequence: int, balance: int) -> int:
    """u64 contribution of one ledger row to its account-range lane.

    Virgin rows contribute 0 (see module docstring) so row presence
    alone — which is not deterministic across nodes — never shows."""
    if sequence == 0 and balance == INITIAL_BALANCE:
        return 0
    h = hashlib.sha256(_ACCT_TAG + key + _QQ.pack(sequence, balance)).digest()
    return int.from_bytes(h[:8], "little")


def watermark_contrib(key: bytes, sequence: int) -> int:
    """128-bit contribution of one sender's frontier entry."""
    if sequence == 0:
        return 0
    h = hashlib.sha256(_WM_TAG + key + _Q.pack(sequence)).digest()
    return int.from_bytes(h[:16], "little")


def directory_contrib(client_id: int, pubkey: bytes) -> int:
    """u64 contribution of one installed client-directory binding."""
    h = hashlib.sha256(_DIR_TAG + _Q.pack(client_id) + pubkey).digest()
    return int.from_bytes(h[:8], "little")


class LedgerDigest:
    """Additive digest lanes over the account ledger, maintained at the
    mutation sites (ledger/accounts.py ``_touch``) so they are always an
    O(1)-updated pure function of the current ledger state."""

    __slots__ = ("ranges", "wm")

    def __init__(self) -> None:
        self.ranges: List[int] = [0] * AUDIT_RANGES
        self.wm: int = 0

    def touch(
        self,
        key: bytes,
        old_sequence: int,
        old_balance: int,
        new_sequence: int,
        new_balance: int,
    ) -> None:
        lane = key[0] >> 4
        self.ranges[lane] = (
            self.ranges[lane]
            - account_contrib(key, old_sequence, old_balance)
            + account_contrib(key, new_sequence, new_balance)
        ) & _M64
        if old_sequence != new_sequence:
            self.wm = (
                self.wm
                - watermark_contrib(key, old_sequence)
                + watermark_contrib(key, new_sequence)
            ) & _M128

    def reseed(self, rows: Iterable[Tuple[bytes, int, int]]) -> None:
        """Recompute from scratch over (key, sequence, balance) rows —
        the restart path, after a checkpoint/store import replaces the
        ledger wholesale."""
        self.ranges = [0] * AUDIT_RANGES
        self.wm = 0
        for key, sequence, balance in rows:
            lane = key[0] >> 4
            self.ranges[lane] = (
                self.ranges[lane] + account_contrib(key, sequence, balance)
            ) & _M64
            self.wm = (self.wm + watermark_contrib(key, sequence)) & _M128

    def ranges_bytes(self) -> bytes:
        return b"".join(_Q.pack(r) for r in self.ranges)

    def wm_bytes(self) -> bytes:
        return self.wm.to_bytes(16, "little")


class FleetAuditor:
    """Local beacon history + peer comparison + divergence attribution.

    Single-threaded: every call happens on the node's event loop (commit
    tail, beacon handler, statusz renderer). Peers whose beacons arrive
    *before* the local chain reaches the same watermark are parked in a
    bounded foreign buffer and compared when the local point lands, so
    detection is symmetric regardless of who beacons first."""

    def __init__(
        self, digest: LedgerDigest, history_cap: int = 512, clock=None
    ) -> None:
        self.digest = digest
        self.history_cap = max(8, history_cap)
        # monotonic-clock source (service injects its own, virtual under
        # sim); only used to stamp the last matched-watermark comparison
        # so /statusz can report beacon AGE — a silently-stalled audit
        # loop shows as a growing age where counters alone look healthy
        self.clock = clock
        self.last_matched_mono: Optional[float] = None
        self.chain = bytes(32)
        self.commits = 0  # transfers folded since process start/restore
        self._points: "OrderedDict[bytes, dict]" = OrderedDict()
        self._foreign: "OrderedDict[bytes, list]" = OrderedDict()
        self.peers: Dict[str, dict] = {}  # origin hex -> latest summary
        self.divergence: Optional[dict] = None  # first confirmed, latched
        self.counters: Dict[str, int] = {
            "beacons_tx": 0,
            "beacons_rx": 0,
            "beacon_invalid": 0,
            "compared": 0,
            "matched": 0,
            "diverged": 0,
            "dir_skew": 0,
            "epoch_skew": 0,
        }

    # ---- local chain ---------------------------------------------------

    def note_commit(self, n: int = 1) -> None:
        self.commits += n

    def snapshot(self, epoch: int, dir_digest: int) -> dict:
        """Fold a new audit point at the current state and return it;
        beacons are built from exactly this dict (service._emit_beacon).
        Also settles any parked foreign beacons at the same watermark."""
        wm = self.digest.wm_bytes()
        ranges = self.digest.ranges_bytes()
        dird = _Q.pack(dir_digest & _M64)
        self.chain = hashlib.sha256(
            _CHAIN_TAG
            + self.chain
            + _QQ.pack(epoch, self.commits)
            + wm
            + ranges
            + dird
        ).digest()
        point = {
            "epoch": epoch,
            "commits": self.commits,
            "wm": wm,
            "ranges": ranges,
            "dir": dird,
            "chain": self.chain,
        }
        # first observation of a watermark wins: its `commits` is the
        # earliest local coordinate, which is what attribution reports
        if wm not in self._points:
            self._points[wm] = point
            while len(self._points) > self.history_cap:
                self._points.popitem(last=False)
        for origin, remote in self._foreign.pop(wm, ()):
            self._compare(origin, remote, self._points.get(wm, point))
        return point

    # ---- peer beacons --------------------------------------------------

    def observe(self, origin_hex: str, remote: dict) -> Optional[dict]:
        """Feed one verified peer beacon (as a field dict); returns the
        divergence record when this observation confirms a conflict."""
        self.counters["beacons_rx"] += 1
        self.peers[origin_hex] = {
            "epoch": remote["epoch"],
            "commits": remote["commits"],
            "wm": remote["wm"].hex(),
            "chain": remote["chain"].hex(),
        }
        point = self._points.get(remote["wm"])
        if point is None:
            parked = self._foreign.setdefault(remote["wm"], [])
            parked.append((origin_hex, remote))
            while len(self._foreign) > self.history_cap:
                self._foreign.popitem(last=False)
            return None
        return self._compare(origin_hex, remote, point)

    def _compare(
        self, origin_hex: str, remote: dict, local: dict
    ) -> Optional[dict]:
        if remote["epoch"] != local["epoch"]:
            # mid-reconfiguration snapshots are incomparable, not wrong
            self.counters["epoch_skew"] += 1
            return None
        self.counters["compared"] += 1
        if remote["ranges"] == local["ranges"]:
            self.counters["matched"] += 1
            if self.clock is not None:
                self.last_matched_mono = self.clock.monotonic()
            if remote["dir"] != local["dir"]:
                self.counters["dir_skew"] += 1
            return None
        self.counters["diverged"] += 1
        lanes = [
            i
            for i in range(AUDIT_RANGES)
            if remote["ranges"][i * 8 : i * 8 + 8]
            != local["ranges"][i * 8 : i * 8 + 8]
        ]
        record = {
            "peer": origin_hex,
            "epoch": local["epoch"],
            "ranges": lanes,  # which account ranges conflict
            "wm": remote["wm"].hex(),  # first divergent watermark
            "commits": local["commits"],  # earliest local coordinate
            "peer_commits": remote["commits"],
            "detected_commits": self.commits,
        }
        if self.divergence is None:
            self.divergence = record
        return record

    # ---- views & persistence -------------------------------------------

    def stats(self) -> Dict[str, int]:
        return dict(self.counters)

    def beacon_age(self) -> Optional[float]:
        """Mono seconds since the last matched-watermark comparison;
        None until the first match (or without a clock)."""
        if self.clock is None or self.last_matched_mono is None:
            return None
        return max(0.0, self.clock.monotonic() - self.last_matched_mono)

    def status(self, dir_digest: int) -> dict:
        return {
            "beacon_age_s": self.beacon_age(),
            "chain": self.chain.hex(),
            "commits": self.commits,
            "wm": self.digest.wm_bytes().hex(),
            "ranges": self.digest.ranges_bytes().hex(),
            "dir": dir_digest & _M64,
            "points": len(self._points),
            "foreign_parked": len(self._foreign),
            "peers": dict(self.peers),
            "divergence": self.divergence,
            "counters": self.stats(),
        }

    def export(self) -> dict:
        """Manifest-persisted view: the chain head survives restarts as
        tamper evidence (store/sharded.py ``audit``)."""
        return {"chain": self.chain.hex(), "commits": self.commits}

    def restore(self, doc: dict) -> None:
        """Resume a persisted chain, folding an explicit restart marker
        so a restarted history is distinguishable from a continuous one
        (the additive lanes are reseeded separately from the restored
        ledger by the caller)."""
        chain = doc.get("chain")
        if not chain:
            return
        self.commits = int(doc.get("commits", 0))
        self.chain = hashlib.sha256(
            _RESTART_TAG + bytes.fromhex(chain)
        ).digest()
