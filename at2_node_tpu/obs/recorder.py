"""Protocol flight recorder: a fixed-size ring of compact protocol
events, always on, dumped only when something goes wrong.

The pattern is FoundationDB's: you cannot reproduce a distributed
anomaly after the fact, so every node continuously records the last N
protocol-level events — frame rx/tx by kind byte, slot state
transitions, quorum edges, admission rejects, stall kicks, verifier
flush decisions — into a bounded ring (`deque(maxlen=cap)`) that costs
one lock + one append per event and can never grow. ``GET /debugz``
dumps it on demand; the owning Service *snapshots* it automatically the
moment an anomaly fires (``/healthz`` flipping to degraded, a stall
kick), so the lead-up to the anomaly survives even though the ring
itself keeps rolling.

Events are ``(t_monotonic, code, detail)`` with ``detail`` a small
tuple of scalars — no string formatting on the hot path. Wall-clock
alignment happens once at dump time (the dump carries a paired
``now_monotonic``/``now_wall`` reading from the same clock), which is
exact under the simulator's virtual clock and good to scheduler jitter
on a real host.

Thread/asyncio safety: ``record`` can be called from the event loop,
the verifier's flush task, or (in principle) executor threads — a plain
``threading.Lock`` around the deque keeps the ring coherent everywhere;
the lock is uncontended in steady state so the cost is a couple hundred
nanoseconds per event.

``cap = 0`` disables recording entirely (the config kill-switch);
``record`` then returns before taking the lock.
"""

from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["FlightRecorder"]


class _FallbackClock:
    monotonic = staticmethod(time.monotonic)
    wall = staticmethod(time.time)


class FlightRecorder:
    def __init__(
        self,
        cap: int = 2048,
        clock=None,
        max_snapshots: int = 4,
    ) -> None:
        if cap < 0:
            raise ValueError("recorder cap must be >= 0 (0 disables)")
        if max_snapshots < 1:
            raise ValueError("max_snapshots must be >= 1")
        self._cap = cap
        self._clock = clock if clock is not None else _FallbackClock()
        self._ring: deque = deque(maxlen=cap or 1)
        self._lock = threading.Lock()
        self._total = 0
        # frozen ring copies captured at anomaly time; bounded so a
        # flapping health check cannot turn the recorder into a leak
        self._snapshots: deque = deque(maxlen=max_snapshots)
        self._snapshots_taken = 0

    @property
    def enabled(self) -> bool:
        return self._cap > 0

    @property
    def recorded(self) -> int:
        """Total events ever recorded (ring holds the newest ``cap``)."""
        return self._total

    @property
    def snapshots_taken(self) -> int:
        return self._snapshots_taken

    def record(self, code: str, detail: tuple = ()) -> None:
        """Append one event. ``detail`` must be a tuple of scalars
        (ints / short strings) — it is exported as-is."""
        if not self._cap:
            return
        t = self._clock.monotonic()
        with self._lock:
            self._ring.append((t, code, detail))
            self._total += 1

    def events_since(self, n: int) -> tuple[list, int]:
        """Events whose total-counter position is ``> n``, formatted, plus
        the new total. The delta-export primitive for shipping recorder
        events out of a worker process: the caller remembers the returned
        total and passes it back next time. Events that rolled out of the
        ring between calls are simply gone (same loss contract as the
        ring itself)."""
        with self._lock:
            total = self._total
            missing = total - n
            if missing <= 0:
                return [], total
            take = min(missing, len(self._ring))
            events = [self._fmt(e) for e in list(self._ring)[-take:]]
        return events, total

    def snapshot(self, reason: str, extra: dict | None = None) -> None:
        """Freeze the current ring under ``reason`` (anomaly capture).
        The frozen copy survives ring rollover; at most ``max_snapshots``
        newest snapshots are kept. ``extra`` attaches an arbitrary
        forensic payload (e.g. a dead worker's post-mortem drain) to the
        frozen copy."""
        if not self._cap:
            return
        now_m = self._clock.monotonic()
        now_w = self._clock.wall()
        with self._lock:
            snap = {
                "reason": reason,
                "now_monotonic": round(now_m, 9),
                "now_wall": round(now_w, 9),
                "events": [self._fmt(e) for e in self._ring],
            }
            if extra is not None:
                snap["extra"] = extra
            self._snapshots.append(snap)
            self._snapshots_taken += 1

    @staticmethod
    def _fmt(event: tuple) -> list:
        t, code, detail = event
        return [round(t, 9), code, list(detail)]

    def dump(self) -> dict:
        """The /debugz body: current ring + anomaly snapshots + paired
        clock readings for wall alignment."""
        now_m = self._clock.monotonic()
        now_w = self._clock.wall()
        with self._lock:
            events = [self._fmt(e) for e in self._ring]
            snapshots = list(self._snapshots)
        return {
            "cap": self._cap,
            "recorded": self._total,
            "dropped": max(0, self._total - len(events)),
            "now_monotonic": round(now_m, 9),
            "now_wall": round(now_w, 9),
            "events": events,
            "snapshots": snapshots,
        }
