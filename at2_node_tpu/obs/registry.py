"""Typed metrics registry — the node's single source of runtime numbers.

Dependency-free (stdlib only) by design: the node must stay deployable
on a bare TPU VM image, so there is no prometheus_client / opentelemetry
here. Three instrument kinds cover everything the stack needs:

* ``Counter``   — monotonic event count (``gossip_rx``, ``committed``…)
* ``Gauge``     — point-in-time value, either set explicitly or read
                  through a callable at snapshot time (``pending``,
                  ``slots_undelivered``)
* ``Histogram`` — log-bucketed latency distribution with exact
                  count/sum/max and estimated p50/p90/p99

All three are safe to bump from asyncio callbacks AND plain worker
threads (the TpuBatchVerifier's prep/launch/finish pools): every mutation
takes the instrument's own ``threading.Lock``, which a non-contended
CPython acquire makes nearly free relative to the work being measured.

The ``Registry`` is per-``Service`` instance, NOT process-global: tests
and bench tools run many Services in one process, and a global registry
would silently sum their counters together. Components that other code
constructs standalone (``Broadcast`` in unit tests) create a private
registry when none is passed.

``CounterGroup`` is the migration shim for the pre-existing ad-hoc stats
dicts (``broadcast.stats``, ``catchup_stats``, ``admission_stats``): it
keeps the ``stats["key"] += 1`` call-site surface — and the dozens of
test assertions written against it — while the actual storage moves onto
registry Counters, so ``snapshot_stats()`` becomes a pure registry view
with nothing counted twice.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Iterable, Iterator, Sequence

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "CounterGroup",
    "Registry",
    "DEFAULT_BOUNDS",
]

# Default histogram ladder: geometric, 100µs .. ~210s in ×2 steps.
# Covers everything this node times — sub-ms verifier stages up to
# multi-second catchup stalls — in 22 buckets (+1 overflow), cheap
# enough to keep one histogram per lifecycle stage always on.
DEFAULT_BOUNDS: tuple[float, ...] = tuple(1e-4 * 2.0**i for i in range(22))


class Counter:
    """Monotonic counter. ``set()`` exists only for the CounterGroup
    dict-compat path (``stats["k"] += 1`` desugars to a read+set); it
    still refuses to move backwards so the instrument stays monotonic."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    def set(self, value: int) -> None:
        with self._lock:
            if value < self._value:
                raise ValueError(
                    f"counter {self.name}: {value} < current {self._value}"
                )
            self._value = value

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value. Either ``set()`` it, or construct with
    ``fn=`` and the registry reads it lazily at snapshot time (the idiom
    for values another object already owns, e.g. ``len(self._heap)``)."""

    __slots__ = ("name", "help", "_lock", "_value", "_fn")

    def __init__(
        self,
        name: str,
        help: str = "",
        fn: Callable[[], float] | None = None,
    ) -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = fn

    def set(self, value: float) -> None:
        if self._fn is not None:
            raise RuntimeError(f"gauge {self.name} is callback-backed")
        with self._lock:
            self._value = value

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                v = self._fn()
            except Exception:
                return 0.0
            # preserve int-ness: queue depths / commit counts read better
            # as integers in JSON snapshots than as 1.0
            return v if isinstance(v, (int, float)) else float(v)
        with self._lock:
            return self._value


class Histogram:
    """Log-bucketed latency histogram (values in SECONDS).

    count/sum/max are exact; percentiles are estimated as the upper
    bound of the bucket holding the target rank (clamped to the observed
    max), which for a ×2 ladder bounds the error at 2× — plenty to tell
    "100µs stage" from "10ms stage", which is what the operator view
    needs. Usable standalone (the verifier owns its stage histograms
    directly) or through ``Registry.histogram``.
    """

    __slots__ = ("name", "help", "bounds", "_lock", "_counts", "_sum",
                 "_count", "_max")

    def __init__(
        self,
        name: str,
        help: str = "",
        bounds: Sequence[float] | None = None,
    ) -> None:
        self.name = name
        self.help = help
        b = tuple(bounds) if bounds is not None else DEFAULT_BOUNDS
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"histogram {name}: bounds must be increasing")
        self.bounds = b
        self._lock = threading.Lock()
        self._counts = [0] * (len(b) + 1)  # last = overflow (+Inf)
        self._sum = 0.0
        self._count = 0
        self._max = 0.0

    def observe(self, value: float) -> None:
        if value < 0 or math.isnan(value):
            return  # clock skew / bad input: never poison the histogram
        # bisect without importing: bounds are tiny (22), linear is fine
        # and avoids holding the lock during a function call
        idx = 0
        for bound in self.bounds:
            if value <= bound:
                break
            idx += 1
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if value > self._max:
                self._max = value

    def _percentile_locked(self, q: float) -> float:
        """Caller holds the lock. Linear interpolation inside the bucket
        holding the target rank (Prometheus histogram_quantile's model),
        capped at the exact observed max — so p50 and p99 stay distinct
        even when they land in the same ×2 bucket."""
        if self._count == 0:
            return 0.0
        rank = q * self._count
        cum = 0
        for i, c in enumerate(self._counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self._max if i >= len(self.bounds) else min(
                    self.bounds[i], self._max
                )
                if hi <= lo:
                    return hi
                return lo + (hi - lo) * ((rank - prev_cum) / c)
        return self._max

    def snapshot(self) -> dict:
        """Exact count/sum/max + estimated percentiles, in milliseconds
        (the unit every stats() dict in this repo already reports)."""
        with self._lock:
            return {
                "count": self._count,
                "sum_ms": round(self._sum * 1e3, 3),
                "max_ms": round(self._max * 1e3, 3),
                "p50_ms": round(self._percentile_locked(0.50) * 1e3, 3),
                "p90_ms": round(self._percentile_locked(0.90) * 1e3, 3),
                "p99_ms": round(self._percentile_locked(0.99) * 1e3, 3),
            }

    def flat(self, prefix: str) -> dict:
        """snapshot() splayed into ``{prefix}_{key}`` form for merging
        into flat stats dicts (snapshot_stats, verifier.stats)."""
        return {f"{prefix}_{k}": v for k, v in self.snapshot().items()}

    def raw(self) -> tuple[list[int], float, int, float]:
        """Non-cumulative bucket counts + exact sum/count/max, copied
        under the lock. The worker-side delta-export primitive: a
        process-mode shard diffs two raw() readings to ship bucket-count
        deltas to the owner."""
        with self._lock:
            return list(self._counts), self._sum, self._count, self._max

    def merge_deltas(
        self,
        bucket_deltas: Sequence[int],
        sum_delta: float,
        count_delta: int,
        max_value: float,
    ) -> None:
        """Fold another histogram's increments into this one: per-bucket
        count deltas (same bounds ladder assumed), exact sum/count
        deltas, and an ABSOLUTE max merged via max(). The owner-side
        counterpart of ``raw()`` for cross-process folding."""
        if count_delta <= 0 and not any(bucket_deltas):
            if max_value > self._max:
                with self._lock:
                    if max_value > self._max:
                        self._max = max_value
            return
        n = len(self._counts)
        with self._lock:
            for i, d in enumerate(bucket_deltas):
                if i >= n:
                    break
                if d:
                    self._counts[i] += d
            self._sum += sum_delta
            self._count += count_delta
            if max_value > self._max:
                self._max = max_value

    def buckets(self) -> tuple[list[tuple[float, int]], float, int]:
        """(cumulative (le, count) pairs incl +Inf, sum, count) — the
        exact shape Prometheus text exposition wants."""
        with self._lock:
            cum = 0
            out: list[tuple[float, int]] = []
            for bound, c in zip(self.bounds, self._counts):
                cum += c
                out.append((bound, cum))
            out.append((math.inf, self._count))
            return out, self._sum, self._count


class CounterGroup:
    """Dict-shaped facade over a fixed set of registry Counters.

    Exists so ``self.stats = {...}`` call sites (and every test that
    reads ``stats["delivered"]``) survive the registry migration
    unchanged. The key set is fixed at construction — same as the old
    literal dicts, where a typo'd key raised KeyError."""

    __slots__ = ("_counters",)

    def __init__(self, counters: dict[str, Counter]) -> None:
        self._counters = counters

    def __getitem__(self, key: str) -> int:
        return self._counters[key].value

    def __setitem__(self, key: str, value: int) -> None:
        self._counters[key].set(value)

    def __contains__(self, key: str) -> bool:
        return key in self._counters

    def __iter__(self) -> Iterator[str]:
        return iter(self._counters)

    def __len__(self) -> int:
        return len(self._counters)

    def keys(self):
        return self._counters.keys()

    def get(self, key: str, default=None):
        c = self._counters.get(key)
        return c.value if c is not None else default

    def items(self) -> Iterable[tuple[str, int]]:
        return [(k, c.value) for k, c in self._counters.items()]

    def as_dict(self) -> dict[str, int]:
        return dict(self.items())


def _sanitize(name: str) -> str:
    return "".join(
        ch if (ch.isalnum() or ch in "_:") else "_" for ch in name
    )


class Registry:
    """Ordered collection of instruments + lazy stat providers.

    Providers cover the components that already expose a ``stats()``
    dict and own their numbers (Mesh, PortMux, the active Verifier):
    rather than double-count them into counters, the registry calls the
    provider at snapshot time and merges the result under a prefix —
    exactly what the old hand-rolled ``snapshot_stats()`` did, now in
    one place.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: dict[str, Counter | Gauge | Histogram] = {}
        self._providers: list[tuple[str, Callable[[], dict]]] = []
        self._hist_providers: list[tuple[str, Callable[[], dict]]] = []

    # -- instrument construction (get-or-create, kind-checked) ----------

    def _get_or_create(self, name: str, kind, factory):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = factory()
                self._instruments[name] = inst
            elif not isinstance(inst, kind):
                raise TypeError(
                    f"instrument {name!r} already registered as "
                    f"{type(inst).__name__}, not {kind.__name__}"
                )
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, lambda: Counter(name, help))

    def gauge(
        self, name: str, help: str = "",
        fn: Callable[[], float] | None = None,
    ) -> Gauge:
        return self._get_or_create(name, Gauge, lambda: Gauge(name, help, fn))

    def histogram(
        self, name: str, help: str = "",
        bounds: Sequence[float] | None = None,
    ) -> Histogram:
        return self._get_or_create(
            name, Histogram, lambda: Histogram(name, help, bounds)
        )

    def counter_group(
        self, names: Sequence[str], help: str = ""
    ) -> CounterGroup:
        return CounterGroup({n: self.counter(n, help) for n in names})

    def register_provider(
        self, prefix: str, fn: Callable[[], dict]
    ) -> None:
        with self._lock:
            self._providers.append((prefix, fn))

    def register_histogram_provider(
        self, prefix: str, fn: Callable[[], dict]
    ) -> None:
        """Expose EXTERNALLY-owned ``Histogram`` objects (``fn`` returns
        ``{suffix: Histogram}``) with the full Prometheus histogram
        convention — cumulative ``_bucket{le=...}`` series, ``_sum``,
        ``_count`` — instead of the spot-percentile gauges a plain stats
        provider would yield. The verifier's per-stage histograms are
        the motivating case: they are constructed by the verifier (which
        deliberately has no registry), yet external scrapers need real
        buckets to aggregate latency across nodes."""
        with self._lock:
            self._hist_providers.append((prefix, fn))

    # -- views -----------------------------------------------------------

    def snapshot(self) -> dict:
        """One flat dict: counters as ints, gauges as numbers,
        histograms splayed via flat(), providers merged under their
        prefix. This IS ``Service.snapshot_stats()`` now."""
        with self._lock:
            instruments = list(self._instruments.values())
            providers = list(self._providers)
            hist_providers = list(self._hist_providers)
        out: dict = {}
        for inst in instruments:
            if isinstance(inst, Histogram):
                out.update(inst.flat(inst.name))
            else:
                out[inst.name] = inst.value
        for prefix, fn in providers:
            try:
                extra = fn()
            except Exception:
                continue  # a dead provider must not take /statusz down
            if extra:
                out.update({f"{prefix}{k}": v for k, v in extra.items()})
        for prefix, fn in hist_providers:
            try:
                hists = fn()
            except Exception:
                continue
            for suffix, h in sorted(hists.items()):
                out.update(h.flat(f"{prefix}{suffix}"))
        return out

    def render_prometheus(self, namespace: str = "at2") -> str:
        """Prometheus text exposition (version 0.0.4). Counters get the
        ``_total`` suffix, histograms the ``_seconds`` unit +
        bucket/sum/count triplet, provider values are exported as
        untyped gauges (they are point-in-time dict reads)."""
        with self._lock:
            instruments = list(self._instruments.values())
            providers = list(self._providers)
            hist_providers = list(self._hist_providers)
        lines: list[str] = []

        def emit_histogram(base: str, h: Histogram, help_text: str) -> None:
            fam = f"{base}_seconds"
            if help_text:
                lines.append(f"# HELP {fam} {help_text}")
            lines.append(f"# TYPE {fam} histogram")
            buckets, total, count = h.buckets()
            for bound, cum in buckets:
                le = "+Inf" if math.isinf(bound) else _fmt(bound)
                lines.append(f'{fam}_bucket{{le="{le}"}} {cum}')
            lines.append(f"{fam}_sum {_fmt(total)}")
            lines.append(f"{fam}_count {count}")

        for inst in instruments:
            base = f"{namespace}_{_sanitize(inst.name)}"
            if isinstance(inst, Counter):
                fam = f"{base}_total"
                if inst.help:
                    lines.append(f"# HELP {fam} {inst.help}")
                lines.append(f"# TYPE {fam} counter")
                lines.append(f"{fam} {inst.value}")
            elif isinstance(inst, Gauge):
                if inst.help:
                    lines.append(f"# HELP {base} {inst.help}")
                lines.append(f"# TYPE {base} gauge")
                lines.append(f"{base} {_fmt(inst.value)}")
            else:
                emit_histogram(base, inst, inst.help)
        for prefix, fn in hist_providers:
            try:
                hists = fn()
            except Exception:
                continue
            for suffix, h in sorted(hists.items()):
                emit_histogram(
                    f"{namespace}_{_sanitize(prefix + suffix)}", h, h.help
                )
        for prefix, fn in providers:
            try:
                extra = fn()
            except Exception:
                continue
            for k, v in sorted(extra.items()):
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    continue
                name = f"{namespace}_{_sanitize(prefix + k)}"
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(v)}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    if isinstance(v, int):
        return str(v)
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))
