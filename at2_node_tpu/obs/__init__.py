"""Observability subsystem: metrics registry, lifecycle tracing, wire
exposition. See registry.py / trace.py module docstrings and the
TECHNICAL.md "Observability" section for the contracts."""

from .registry import (
    Counter,
    CounterGroup,
    Gauge,
    Histogram,
    Registry,
)
from .trace import STAGES, TxTrace

__all__ = [
    "Counter",
    "CounterGroup",
    "Gauge",
    "Histogram",
    "Registry",
    "STAGES",
    "TxTrace",
]
