"""Observability subsystem: metrics registry, lifecycle tracing, the
protocol flight recorder, the SLO engine, wire exposition. See
registry.py / trace.py / recorder.py / slo.py module docstrings and the
TECHNICAL.md "Observability" and "Fleet tracing & flight recorder"
sections for the contracts."""

from .recorder import FlightRecorder
from .registry import (
    Counter,
    CounterGroup,
    Gauge,
    Histogram,
    Registry,
)
from .slo import Objective, SloEngine, default_objectives, evaluate_point
from .trace import BROKER_STAGES, REJECTED, STAGES, TxTrace

__all__ = [
    "BROKER_STAGES",
    "Counter",
    "CounterGroup",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "Objective",
    "REJECTED",
    "Registry",
    "STAGES",
    "SloEngine",
    "TxTrace",
    "default_objectives",
    "evaluate_point",
]
