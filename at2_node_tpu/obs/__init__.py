"""Observability subsystem: metrics registry, lifecycle tracing, the
protocol flight recorder, wire exposition. See registry.py / trace.py /
recorder.py module docstrings and the TECHNICAL.md "Observability" and
"Fleet tracing & flight recorder" sections for the contracts."""

from .recorder import FlightRecorder
from .registry import (
    Counter,
    CounterGroup,
    Gauge,
    Histogram,
    Registry,
)
from .trace import REJECTED, STAGES, TxTrace

__all__ = [
    "Counter",
    "CounterGroup",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "REJECTED",
    "Registry",
    "STAGES",
    "TxTrace",
]
