"""Observability subsystem: metrics registry, lifecycle tracing, the
protocol flight recorder, the SLO engine, wire exposition. See
registry.py / trace.py / recorder.py / slo.py module docstrings and the
TECHNICAL.md "Observability" and "Fleet tracing & flight recorder"
sections for the contracts."""

from .audit import AUDIT_RANGES, FleetAuditor, LedgerDigest
from .profiler import (
    PHASES,
    PLANE_LEAF_PHASES,
    EventLoopLagProbe,
    PhaseAccounting,
    StackSampler,
    build_info,
)
from .recorder import FlightRecorder
from .registry import (
    Counter,
    CounterGroup,
    Gauge,
    Histogram,
    Registry,
)
from .slo import Objective, SloEngine, default_objectives, evaluate_point
from .trace import BROKER_STAGES, REJECTED, STAGES, TxTrace

__all__ = [
    "AUDIT_RANGES",
    "BROKER_STAGES",
    "Counter",
    "CounterGroup",
    "EventLoopLagProbe",
    "FleetAuditor",
    "FlightRecorder",
    "LedgerDigest",
    "Gauge",
    "Histogram",
    "Objective",
    "PHASES",
    "PLANE_LEAF_PHASES",
    "PhaseAccounting",
    "REJECTED",
    "Registry",
    "STAGES",
    "SloEngine",
    "StackSampler",
    "TxTrace",
    "build_info",
    "evaluate_point",
]
