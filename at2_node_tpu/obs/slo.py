"""Declarative SLOs evaluated with multi-window burn rates.

An *objective* is a machine-checkable service-level target over the
signals the obs stack already exports — commit-latency p99 ceiling,
committed-throughput floor, ingress rejection-rate ceiling, and a
quorum-stall budget. The :class:`SloEngine` turns a stream of periodic
*probe samples* (cumulative counters + the ingress→commit histogram's
cumulative buckets + a stall flag, all read from the node's
``Registry``/``TxTrace``) into burn-rate verdicts served at
``GET /sloz`` and folded into ``/healthz``.

Burn rate is the SRE book's alerting currency: ``burn = observed /
target`` (for ceilings) or ``target / observed`` (for floors), so
``burn > 1`` means the objective is being violated *at the current
rate*. One window is not a verdict — a single slow transaction spikes a
short window, a long window hides an outage for minutes — so every
objective is evaluated over TWO windows (fast + slow) and only flags
**breaching** when BOTH burn above 1.0. That multi-window AND is the
flap suppressor: transient spikes clear the fast window before the slow
window ever burns, and long-degraded states trip both.

Windowed values come from *deltas between samples*, never from
lifetime aggregates: throughput is Δcommitted/Δt, the rejection ratio
is Δrejected/(Δrejected+Δcommitted), and the windowed p99 is recovered
from the histogram's cumulative bucket counts by differencing the
oldest and newest sample in the window (the standard
``histogram_quantile(rate(...))`` construction, done locally). A window
with fewer than two samples reports ``no_data`` and can never breach —
a node that just booted is not in violation of anything.

Offline evaluation: :func:`evaluate_point` applies the same objectives
to a single aggregate measurement dict (throughput / p99 / rejection
ratio / stall fraction), which is how the scenario grid
(tools/scenario_grid.py) and banked bench JSON get re-checked without a
live engine. Everything here is pure, stdlib-only, and clock-injected,
so the verdict math is unit-testable to the edge cases.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Objective",
    "SloEngine",
    "evaluate_point",
    "default_objectives",
]

# A burn that would be infinite (activity with zero progress) is capped
# to stay JSON-serializable; anything at the cap reads as "maximally
# burning", which is all an alert needs to know.
BURN_CAP = 1e6

# Rejection-ratio windows need a minimum number of admission outcomes
# before the ratio means anything: 1 reject out of 1 attempt is not a
# 100%-rejection incident, it is one unlucky request.
MIN_RATIO_EVENTS = 16

KINDS = (
    "latency_p99",  # windowed ingress→commit p99 <= target (ms)
    "throughput_floor",  # windowed committed tx/s >= target
    "rejection_ratio",  # windowed rejected/(rejected+committed) <= target
    "stall_budget",  # fraction of window commit-stalled <= target
)


@dataclass(frozen=True)
class Objective:
    """One declarative target. ``target`` units depend on ``kind``:
    milliseconds for latency_p99, tx/s for throughput_floor, a [0,1]
    ratio for rejection_ratio and stall_budget."""

    name: str
    kind: str
    target: float

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"unknown objective kind {self.kind!r}")
        if self.target <= 0:
            raise ValueError(f"objective {self.name}: target must be > 0")


def default_objectives(
    *,
    latency_p99_ms: float = 2000.0,
    throughput_floor_tps: float = 0.0,
    rejection_ratio_max: float = 0.95,
    stall_budget: float = 0.5,
) -> List[Objective]:
    """The node's standing objectives. Defaults are deliberately
    lenient — they catch a node that is *broken* (everything rejected,
    commits stalled for most of a window, multi-second p99), not one
    that is merely slow; operators tighten per deployment via the
    ``[slo]`` config table. A target <= 0 disables that objective."""
    objectives = []
    if latency_p99_ms > 0:
        objectives.append(
            Objective("commit_latency_p99", "latency_p99", latency_p99_ms)
        )
    if throughput_floor_tps > 0:
        objectives.append(
            Objective("throughput_floor", "throughput_floor",
                      throughput_floor_tps)
        )
    if rejection_ratio_max > 0:
        objectives.append(
            Objective("rejection_ratio", "rejection_ratio",
                      rejection_ratio_max)
        )
    if stall_budget > 0:
        objectives.append(
            Objective("stall_budget", "stall_budget", stall_budget)
        )
    return objectives


class _FallbackClock:
    monotonic = staticmethod(time.monotonic)
    wall = staticmethod(time.time)


def _delta_p99_ms(
    old: Optional[Tuple[list, float, int]],
    new: Optional[Tuple[list, float, int]],
) -> Optional[float]:
    """Windowed p99 (ms) from two cumulative bucket snapshots
    (``Histogram.buckets()`` shape: ([(le, cum), ...], sum, count)).
    Returns None when the window saw no completions. The estimate is
    the upper bound of the bucket holding the 99th rank — deterministic
    and conservative; the +Inf bucket reports twice the last finite
    bound (there is no windowed max to clamp against)."""
    if new is None:
        return None
    new_pairs, _, new_count = new
    old_pairs, old_count = ([], 0)
    if old is not None:
        old_pairs, _, old_count = old
    total = new_count - old_count
    if total <= 0:
        return None
    old_cum = {le: cum for le, cum in old_pairs}
    rank = 0.99 * total
    last_finite = 0.0
    for le, cum in new_pairs:
        d = cum - old_cum.get(le, 0)
        if le != le or le == float("inf"):
            # +Inf bucket: everything lands here eventually
            if d >= rank:
                return round((last_finite or 1.0) * 2.0 * 1e3, 6)
            continue
        last_finite = le
        if d >= rank:
            return round(le * 1e3, 6)
    return round((last_finite or 1.0) * 2.0 * 1e3, 6)


def _eval_window(
    objective: Objective, samples: List[dict], window_s: float
) -> dict:
    """One objective over one window's samples (oldest..newest already
    filtered to the window). Returns {window_s, status, value, burn}
    with status in {"no_data", "idle", "ok", "breaching"}."""
    out = {"window_s": window_s, "status": "no_data", "value": None,
           "burn": 0.0}
    if len(samples) < 2:
        return out
    old, new = samples[0], samples[-1]
    span = new["t"] - old["t"]
    if span <= 0:
        return out
    d_committed = new["committed"] - old["committed"]
    d_rejected = new["rejected"] - old["rejected"]

    def verdict(value, burn) -> dict:
        burn = min(max(burn, 0.0), BURN_CAP)
        out["value"] = value
        out["burn"] = round(burn, 6)
        out["status"] = "breaching" if burn > 1.0 else "ok"
        return out

    if objective.kind == "latency_p99":
        p99 = _delta_p99_ms(old.get("latency"), new.get("latency"))
        if p99 is None:
            out["status"] = "idle"
            return out
        return verdict(p99, p99 / objective.target)
    if objective.kind == "throughput_floor":
        active = (
            d_committed > 0 or d_rejected > 0 or new.get("pending", 0) > 0
        )
        if not active:
            # a floor only applies under offered load: an idle node is
            # not violating a throughput objective
            out["status"] = "idle"
            return out
        rate = d_committed / span
        burn = BURN_CAP if rate <= 0 else objective.target / rate
        return verdict(round(rate, 6), burn)
    if objective.kind == "rejection_ratio":
        den = d_committed + d_rejected
        if den < MIN_RATIO_EVENTS:
            out["status"] = "idle"
            return out
        ratio = d_rejected / den
        return verdict(round(ratio, 6), ratio / objective.target)
    if objective.kind == "stall_budget":
        stalled = sum(1 for s in samples if s.get("stalled"))
        frac = stalled / len(samples)
        return verdict(round(frac, 6), frac / objective.target)
    raise AssertionError(f"unreachable kind {objective.kind}")


class SloEngine:
    """Bounded sample store + multi-window evaluation.

    ``observe`` one probe sample per tick (the Service's probe loop, or
    a test driving a fake clock); ``evaluate`` renders the full /sloz
    body; ``breaching`` is the healthz hook — the names of objectives
    burning above 1.0 in EVERY window. Single-threaded by contract
    (event-loop callbacks), like TxTrace."""

    def __init__(
        self,
        objectives: List[Objective],
        windows: Tuple[float, float] = (30.0, 300.0),
        clock=None,
    ) -> None:
        if not windows or any(w <= 0 for w in windows):
            raise ValueError("windows must be positive")
        self.objectives = list(objectives)
        self.windows = tuple(sorted(windows))
        self._clock = clock if clock is not None else _FallbackClock()
        self._samples: deque = deque()

    def observe(self, sample: dict) -> None:
        """Append one probe sample: ``{"t", "committed", "rejected",
        "pending", "stalled", "latency": Histogram.buckets()}``. Samples
        older than the slow window (plus one slot of slack) are pruned,
        so memory is bounded by window span / probe interval."""
        self._samples.append(sample)
        horizon = sample["t"] - self.windows[-1] - 1.0
        while self._samples and self._samples[0]["t"] < horizon:
            self._samples.popleft()

    @property
    def sample_count(self) -> int:
        return len(self._samples)

    def evaluate(self, now: Optional[float] = None) -> dict:
        """The /sloz body: per-objective per-window burn verdicts plus
        the overall breaching list. JSON-safe (no inf/nan)."""
        if now is None:
            now = self._clock.monotonic()
        per_window: Dict[float, List[dict]] = {}
        for w in self.windows:
            cutoff = now - w
            per_window[w] = [s for s in self._samples if s["t"] >= cutoff]
        objectives_out = []
        breaching = []
        for obj in self.objectives:
            windows_out = [
                _eval_window(obj, per_window[w], w) for w in self.windows
            ]
            statuses = [w["status"] for w in windows_out]
            if all(s == "breaching" for s in statuses):
                status = "breaching"
                breaching.append(obj.name)
            elif any(s == "no_data" for s in statuses):
                status = "no_data"
            elif all(s == "idle" for s in statuses):
                status = "idle"
            else:
                status = "ok"
            objectives_out.append(
                {
                    "name": obj.name,
                    "kind": obj.kind,
                    "target": obj.target,
                    "status": status,
                    "windows": windows_out,
                }
            )
        return {
            "windows_s": list(self.windows),
            "samples": len(self._samples),
            "objectives": objectives_out,
            "breaching": breaching,
        }

    def breaching(self, now: Optional[float] = None) -> List[str]:
        return self.evaluate(now)["breaching"]

    def fast_burns(self, now: Optional[float] = None) -> Dict[str, float]:
        """Per-objective burn over the FAST window only, as a flat
        ``{objective_name: burn}`` dict — the scrapeable form of the
        signal /sloz buries in JSON. This is both a /metrics gauge
        provider (Service registers it under ``slo_burn_``) and the
        overload controller's SLO input. Idle/no-data objectives read
        as 0.0 burn: no evidence is not pressure."""
        if now is None:
            now = self._clock.monotonic()
        fast = self.windows[0]
        cutoff = now - fast
        samples = [s for s in self._samples if s["t"] >= cutoff]
        out: Dict[str, float] = {}
        for obj in self.objectives:
            w = _eval_window(obj, samples, fast)
            out[obj.name] = w["burn"] if w["status"] in (
                "ok", "breaching"
            ) else 0.0
        return out


def evaluate_point(objectives: List[Objective], measures: dict) -> dict:
    """Offline single-point evaluation for banked artifacts: apply the
    objectives to one aggregate measurement dict with keys
    ``throughput_tps``, ``latency_p99_ms``, ``rejection_ratio``,
    ``stall_fraction`` (missing keys → that objective is skipped as
    "no_data"). Same burn semantics as the live engine, one window.
    Pure — re-runnable from BENCH_SCENARIOS.json alone."""
    key_for = {
        "latency_p99": "latency_p99_ms",
        "throughput_floor": "throughput_tps",
        "rejection_ratio": "rejection_ratio",
        "stall_budget": "stall_fraction",
    }
    out = []
    breaching = []
    for obj in objectives:
        value = measures.get(key_for[obj.kind])
        if value is None:
            out.append(
                {"name": obj.name, "kind": obj.kind, "target": obj.target,
                 "value": None, "burn": 0.0, "status": "no_data"}
            )
            continue
        if obj.kind == "throughput_floor":
            burn = BURN_CAP if value <= 0 else obj.target / value
        else:
            burn = value / obj.target
        burn = min(max(burn, 0.0), BURN_CAP)
        status = "breaching" if burn > 1.0 else "ok"
        if status == "breaching":
            breaching.append(obj.name)
        out.append(
            {"name": obj.name, "kind": obj.kind, "target": obj.target,
             "value": value, "burn": round(burn, 6), "status": status}
        )
    return {
        "objectives": out,
        "breaching": breaching,
        "ok": not breaching,
    }
