"""Per-transaction lifecycle tracing: ingress → … → committed.

``TxTrace`` answers the question the flat counters cannot: WHERE does a
transfer spend its time between arriving on the RPC surface and landing
in the ledger? Each traced transaction is stamped through the stage
ladder

    ingress → admitted → echoed → ready_quorum → delivered → committed

and every stamp feeds a ``tx_ingress_to_<stage>`` histogram measured
from the ingress timestamp, so ``/statusz`` can report p50/p99 for any
prefix of the pipeline (ingress→commit being the headline number).

Cardinality control — a tracer must never become the memory leak it is
supposed to find:

* **Sampling**: only every Nth transaction seen at ingress is traced
  (``sample_every``; 1 = all, 0 = disabled). Stamps for untraced keys
  are a single dict miss.
* **Cap**: at most ``cap`` live (uncommitted) traces; beginning a new
  one past the cap evicts the oldest, counted in ``tx_trace_evicted``.
  A transaction that never commits (rejected, byzantine, equivocated)
  therefore ages out instead of pinning memory forever.

Stamps are idempotent and order-tolerant: a duplicate or backwards stamp
(the batched plane can deliver before the per-entry echo bookkeeping
runs; retransmits re-echo) is ignored, so each histogram sees each
transaction at most once.

Keys are ``(sender_public_key, sequence)`` — the identity the broadcast
plane itself dedups on. Only transactions that entered through THIS
node's RPC ingress are traced (relayed traffic has no local ingress
time), so the percentiles are end-to-end client latency as this node's
clients experience it.
"""

from __future__ import annotations

import time

from .registry import Histogram, Registry

__all__ = ["STAGES", "TxTrace"]

STAGES: tuple[str, ...] = (
    "ingress",
    "admitted",
    "echoed",
    "ready_quorum",
    "delivered",
    "committed",
)
_STAGE_IDX = {s: i for i, s in enumerate(STAGES)}


class TxTrace:
    """Sampled, capped lifecycle tracker. Single-threaded by contract:
    every stamp site runs on the node's event loop (RPC handlers, the
    broadcast worker callbacks, the commit tail), so the live-trace dict
    needs no lock — only the histograms it feeds are thread-safe."""

    def __init__(
        self,
        registry: Registry,
        sample_every: int = 1,
        cap: int = 8192,
    ) -> None:
        if sample_every < 0:
            raise ValueError("sample_every must be >= 0 (0 disables)")
        if cap < 1:
            raise ValueError("cap must be >= 1")
        self._sample_every = sample_every
        self._cap = cap
        # key -> [highest_stage_idx, ingress_monotonic]
        self._live: dict[tuple, list] = {}
        self._seen = 0
        self._traced = registry.counter(
            "tx_traced", "transactions sampled into the lifecycle tracer"
        )
        self._completed = registry.counter(
            "tx_trace_completed", "traces that reached committed"
        )
        self._evicted = registry.counter(
            "tx_trace_evicted", "live traces evicted at the cardinality cap"
        )
        self._hists: dict[str, Histogram] = {
            s: registry.histogram(
                f"tx_ingress_to_{s}", f"latency from ingress to {s}"
            )
            for s in STAGES[1:]
        }

    @property
    def enabled(self) -> bool:
        return self._sample_every > 0

    def begin(self, key: tuple, now: float | None = None) -> None:
        """Record ingress for ``key`` if it wins the sampling lottery."""
        if not self._sample_every:
            return
        self._seen += 1
        if self._seen % self._sample_every:
            return
        if key in self._live:
            return  # client retry of an in-flight tx: keep first ingress
        if len(self._live) >= self._cap:
            # dicts iterate in insertion order: the first key is oldest
            self._live.pop(next(iter(self._live)))
            self._evicted.inc()
        self._live[key] = [0, time.monotonic() if now is None else now]
        self._traced.inc()

    def stamp(self, key: tuple, stage: str, now: float | None = None) -> None:
        rec = self._live.get(key)
        if rec is None:
            return
        idx = _STAGE_IDX[stage]
        if idx <= rec[0]:
            return  # duplicate or out-of-order: first arrival wins
        t = time.monotonic() if now is None else now
        self._hists[stage].observe(t - rec[1])
        rec[0] = idx
        if stage == "committed":
            del self._live[key]
            self._completed.inc()

    @property
    def live(self) -> int:
        return len(self._live)

    def snapshot(self) -> dict:
        """Per-stage histogram snapshots for /statusz."""
        out = {
            f"ingress_to_{s}": self._hists[s].snapshot() for s in STAGES[1:]
        }
        out["live_traces"] = len(self._live)
        return out
