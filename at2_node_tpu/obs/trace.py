"""Per-transaction lifecycle tracing: ingress → … → committed.

``TxTrace`` answers the question the flat counters cannot: WHERE does a
transfer spend its time between arriving on the RPC surface and landing
in the ledger? Each traced transaction is stamped through the stage
ladder

    ingress → admitted → echoed → ready_quorum → delivered → committed

and every stamp feeds a ``tx_ingress_to_<stage>`` histogram measured
from the ingress timestamp, so ``/statusz`` can report p50/p99 for any
prefix of the pipeline (ingress→commit being the headline number).

Fleet stitching (tools/trace_collect.py) needs more than the local
percentiles, so every stamp additionally retains BOTH timestamps:

* **monotonic** — the node's scheduling clock, good for intra-node
  durations but meaningless across hosts;
* **wall** — the node's wall clock, the only cross-node join axis.
  Under the deterministic simulator both come from the same virtual
  clock, so stitched timelines are exact (and reproducible bit-for-bit
  from a seed).

Terminal stamps (``committed``, or the out-of-ladder ``rejected`` that
admission control applies at the RPC boundary) retire the record into a
bounded *completed ring* (``done_cap`` newest completions) that
``/tracez`` exports next to the still-live records.

Cardinality control — a tracer must never become the memory leak it is
supposed to find:

* **Sampling**: only every Nth transaction seen at ingress is traced
  (``sample_every``; 1 = all, 0 = disabled). Stamps for untraced keys
  are a single dict miss.
* **Cap**: at most ``cap`` live (unterminated) traces; beginning a new
  one past the cap evicts the oldest, counted in ``tx_trace_evicted``.
  A transaction that never terminates (byzantine, equivocated)
  therefore ages out instead of pinning memory forever.

Stamps are idempotent and order-tolerant: a duplicate or backwards stamp
(the batched plane can deliver before the per-entry echo bookkeeping
runs; retransmits re-echo) is ignored, so each histogram sees each
transaction at most once.

Keys are ``(sender_public_key, sequence)`` — the identity the broadcast
plane itself dedups on, and therefore globally unique across the fleet.
Transactions that entered through THIS node's RPC ingress get *origin*
records (they carry the ``ingress`` stamp and feed the histograms);
relayed traffic gets *relay* records opened lazily at the first
non-terminal stamp (no local ingress time, no histogram contribution) —
those are the spans trace_collect joins across nodes. The local
percentiles therefore stay what they always were: end-to-end client
latency as this node's clients experience it.
"""

from __future__ import annotations

import time
from collections import deque

from .registry import Histogram, Registry

__all__ = [
    "BROKER_STAGES", "PHASE_MARKERS", "REJECTED", "STAGES", "TxTrace",
]

STAGES: tuple[str, ...] = (
    "ingress",
    "admitted",
    "echoed",
    "ready_quorum",
    "delivered",
    "committed",
)
_STAGE_IDX = {s: i for i, s in enumerate(STAGES)}

# Broker-hop relay stamps. The broker tier sits BEFORE node ingress on
# the distilled path (client → broker _collect → distill → node
# SendDistilledBatch), so its stages get negative ladder indices: they
# order ahead of ``ingress`` (index 0) for stitching, while the
# ``idx <= rec[_IDX]`` monotonicity guard makes node-side records (which
# start at index >= 0) ignore them for free. Brokers never call
# ``begin()`` — every broker record is a relay span opened by the keyed
# lottery, so all parties sample the SAME transactions and
# trace_collect joins client → broker → node → commit. Deliberately NOT
# appended to ``STAGES``: the ladder is the node-local happy path and
# its consumers (histogram construction, snapshot()) iterate it.
BROKER_STAGES: tuple[str, ...] = ("broker_rx", "broker_flush")
_STAGE_IDX["broker_rx"] = -2
_STAGE_IDX["broker_flush"] = -1

# Order-free phase markers (ISSUE 14 phase-overlap accounting). With
# [wan] overlap_ready on, a node emits its Ready in the same frame as
# its Echo — so "the echo quorum was observed" and "own Ready was sent"
# can land in EITHER order, which the ``idx <= rec[_IDX]`` ladder guard
# would silently truncate to whichever arrived first. Markers are
# therefore stamped OUTSIDE the ladder: appended once to the record's
# stamp list (first arrival wins per marker), never advancing the
# ladder index, never feeding a histogram, never opening a relay span.
# trace_collect.py reads them back as the per-slot echo→ready gap
# (negative = piggybacked). Deliberately NOT in ``STAGES`` for the same
# reason BROKER_STAGES is not.
PHASE_MARKERS: frozenset = frozenset({"echo_quorum", "ready_sent"})

# Out-of-ladder terminal: admission control refused the transaction at
# the RPC boundary (token-bucket throttle or failed pre-verification).
# Not a STAGES member — the ladder is the happy path and existing
# consumers iterate it — but it finalizes a record exactly like
# ``committed`` does.
REJECTED = "rejected"

# _live record layout (a list, mutated in place on the hot path)
_IDX = 0  # highest stage index stamped so far
_T0 = 1  # monotonic reference (ingress for origin, first stamp for relay)
_ORIGIN = 2  # True = entered through this node's RPC ingress
_STAMPS = 3  # [(stage, monotonic, wall), ...] in arrival order


class _FallbackClock:
    """time-module clock used when no clock seam is injected (direct
    TxTrace construction in tests/benchmarks)."""

    monotonic = staticmethod(time.monotonic)
    wall = staticmethod(time.time)


class TxTrace:
    """Sampled, capped lifecycle tracker. Single-threaded by contract:
    every stamp site runs on the node's event loop (RPC handlers, the
    broadcast worker callbacks, the commit tail), so the live-trace dict
    needs no lock — only the histograms it feeds are thread-safe."""

    def __init__(
        self,
        registry: Registry,
        sample_every: int = 1,
        cap: int = 8192,
        done_cap: int = 1024,
        clock=None,
        retire_at: str | None = None,
    ) -> None:
        if sample_every < 0:
            raise ValueError("sample_every must be >= 0 (0 disables)")
        if cap < 1:
            raise ValueError("cap must be >= 1")
        if done_cap < 1:
            raise ValueError("done_cap must be >= 1")
        if retire_at is not None and retire_at not in _STAGE_IDX:
            raise ValueError(f"unknown retire_at stage {retire_at!r}")
        self._sample_every = sample_every
        self._cap = cap
        # A non-terminal stage that retires records for THIS tracer.
        # The broker's tracer sets retire_at="broker_flush": its
        # custody of a transaction ends at flush, so the record moves to
        # the completed ring (and /tracez) instead of idling at the live
        # cap until eviction. Node tracers leave it None.
        self._retire_at = retire_at
        self._clock = clock if clock is not None else _FallbackClock()
        self._live: dict[tuple, list] = {}
        self._done: deque = deque(maxlen=done_cap)
        self._seen = 0
        self._traced = registry.counter(
            "tx_traced", "transactions sampled into the lifecycle tracer"
        )
        self._relayed = registry.counter(
            "tx_trace_relayed",
            "relay-side trace records opened for fleet stitching",
        )
        self._completed = registry.counter(
            "tx_trace_completed", "traces that reached committed"
        )
        self._rejected_c = registry.counter(
            "tx_trace_rejected", "traces terminated by admission rejection"
        )
        self._evicted = registry.counter(
            "tx_trace_evicted", "live traces evicted at the cardinality cap"
        )
        self._hists: dict[str, Histogram] = {
            s: registry.histogram(
                f"tx_ingress_to_{s}", f"latency from ingress to {s}"
            )
            for s in STAGES[1:]
        }
        self._hists[REJECTED] = registry.histogram(
            "tx_ingress_to_rejected", "latency from ingress to rejection"
        )

    @property
    def enabled(self) -> bool:
        return self._sample_every > 0

    def _evict_for_room(self) -> None:
        if len(self._live) >= self._cap:
            # dicts iterate in insertion order: the first key is oldest
            self._live.pop(next(iter(self._live)))
            self._evicted.inc()

    def begin(self, key: tuple, now: float | None = None) -> None:
        """Record ingress for ``key`` if it wins the sampling lottery."""
        if not self._sample_every:
            return
        self._seen += 1
        if self._seen % self._sample_every:
            return
        if key in self._live:
            return  # client retry of an in-flight tx: keep first ingress
        self._evict_for_room()
        t = self._clock.monotonic() if now is None else now
        self._live[key] = [0, t, True, [("ingress", t, self._clock.wall())]]
        self._traced.inc()

    def stamp(self, key: tuple, stage: str, now: float | None = None) -> None:
        rec = self._live.get(key)
        if stage in PHASE_MARKERS:
            # order-free annotation on an already-open record: no ladder
            # index, no histogram, no relay-span open
            if rec is None:
                return
            if any(s == stage for s, _, _ in rec[_STAMPS]):
                return  # first arrival wins
            t = self._clock.monotonic() if now is None else now
            rec[_STAMPS].append((stage, t, self._clock.wall()))
            return
        terminal = stage == "committed" or stage == REJECTED
        if rec is None:
            # Relay-side open: a stamp for a key this node never saw at
            # ingress starts a relay span (the cross-node half of a
            # stitched timeline) — but never from a terminal stamp
            # alone, a record holding nothing but its own tombstone is
            # useless. The relay lottery is keyed (not sequential) so
            # every node samples the SAME transactions and spans join.
            if terminal or not self._sample_every:
                return
            if self._sample_every > 1 and (
                (key[0][0] + key[1]) % self._sample_every
            ):
                return
            self._evict_for_room()
            t = self._clock.monotonic() if now is None else now
            self._live[key] = rec = [_STAGE_IDX[stage], t, False, []]
            rec[_STAMPS].append((stage, t, self._clock.wall()))
            self._relayed.inc()
            if stage == self._retire_at:
                # e.g. broker_flush for a record evicted between rx and
                # flush: retire the single-stamp span rather than leave
                # it live forever
                self._retire(key, rec, stage)
                self._completed.inc()
            return
        if stage == REJECTED:
            t = self._clock.monotonic() if now is None else now
            if rec[_ORIGIN]:
                self._hists[REJECTED].observe(t - rec[_T0])
            rec[_STAMPS].append((REJECTED, t, self._clock.wall()))
            self._retire(key, rec, REJECTED)
            self._rejected_c.inc()
            return
        idx = _STAGE_IDX[stage]
        if idx <= rec[_IDX]:
            return  # duplicate or out-of-order: first arrival wins
        t = self._clock.monotonic() if now is None else now
        if rec[_ORIGIN]:
            self._hists[stage].observe(t - rec[_T0])
        rec[_IDX] = idx
        rec[_STAMPS].append((stage, t, self._clock.wall()))
        if stage == "committed" or stage == self._retire_at:
            self._retire(key, rec, stage)
            self._completed.inc()

    def _retire(self, key: tuple, rec: list, terminal: str) -> None:
        del self._live[key]
        self._done.append(self._export(key, rec, terminal))

    @staticmethod
    def _export(key: tuple, rec: list, terminal: str | None) -> dict:
        return {
            "sender": key[0].hex(),
            "seq": key[1],
            "origin": rec[_ORIGIN],
            "terminal": terminal,
            "stages": [
                [s, round(m, 9), round(w, 9)] for s, m, w in rec[_STAMPS]
            ],
        }

    @property
    def live(self) -> int:
        return len(self._live)

    def snapshot(self) -> dict:
        """Per-stage histogram snapshots for /statusz."""
        out = {
            f"ingress_to_{s}": self._hists[s].snapshot() for s in STAGES[1:]
        }
        out["ingress_to_rejected"] = self._hists[REJECTED].snapshot()
        out["live_traces"] = len(self._live)
        return out

    def tracez(self, limit: int | None = None) -> dict:
        """Live + completed trace records for GET /tracez and the sim
        episode capture. ``limit`` keeps only the newest N completed
        records (the ring is already bounded by ``done_cap``)."""
        done = list(self._done)
        if limit is not None and limit >= 0:
            done = done[len(done) - limit:] if limit else []
        return {
            "live": [
                self._export(k, rec, None) for k, rec in self._live.items()
            ],
            "completed": done,
        }
