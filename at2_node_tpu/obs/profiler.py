"""Continuous profiler: sampling stack profiler, event-loop lag probe,
and the plane time-accounting seam.

Dependency-free (stdlib only), same contract as registry.py: the node
must stay deployable on a bare TPU VM image, so there is no py-spy /
yappi / opentelemetry here. Three coordinated parts:

* :class:`PhaseAccounting` — the plane time-accounting seam. Hot paths
  (both broadcast planes, the commit tail, verifier flush decisions)
  mark disjoint sequential segments against a fixed phase vocabulary;
  each segment lands in a per-phase ns Counter plus a ×2-log Histogram
  in the node's existing :class:`~.registry.Registry`, so ``/metrics``
  and ``/statusz`` export the decomposition for free. Timing uses
  ``time.perf_counter_ns()`` directly — NOT the clock seam — because
  the quantity being accounted is real elapsed host time (the thing
  ROADMAP item 1 needs decomposed), and because sim determinism is
  carried by wire traces, never by registry values: phase counters keep
  accumulating under the virtual clock without perturbing a schedule.

* :class:`StackSampler` — a sampling wall/CPU profiler: one daemon
  thread walks ``sys._current_frames()`` at a configurable Hz and
  aggregates frames into a bounded stack tree. Output is the tree as
  JSON or collapsed-stack ("folded") text for flamegraphs. The sampler
  is a REAL thread, so it never auto-starts under the simulator (sim
  time is virtual; a real thread would race the deterministic
  schedule) — it is started on demand via ``/profilez?start``, the
  healthz ok→degraded edge capture, or a bench harness.

* :class:`EventLoopLagProbe` — measures asyncio scheduling lag as the
  overshoot of a short cooperative sleep. Clock-seam aware: under the
  sim virtual clock a manually-driven :meth:`~EventLoopLagProbe.
  probe_once` measures exactly zero lag (virtual sleeps are exact) and
  never deadlocks the scheduler, while the standing background loop is
  reserved for served (real-time) nodes.

See TECHNICAL.md "Continuous profiling & plane time-accounting" for the
phase vocabulary and the overhead budget.
"""

from __future__ import annotations

import contextvars
import os
import platform
import subprocess
import sys
import threading
import time
from typing import Iterable, Optional, Sequence

from .registry import Histogram, Registry

__all__ = [
    "PHASES",
    "PLANE_LEAF_PHASES",
    "PHASE_BOUNDS",
    "EventLoopLagProbe",
    "PhaseAccounting",
    "ShardPhaseView",
    "StackSampler",
    "build_info",
    "merge_folded",
    "parse_folded",
]

# The fixed phase vocabulary. ``plane_total`` wraps a broadcast worker's
# whole drain cycle (parse + process); the PLANE_LEAF_PHASES are the
# disjoint sequential segments inside it, so
# ``sum(leaves) / plane_total`` is the decomposition coverage that
# profile_collect checks against its >=90% bar. ``slot_gc``,
# ``commit_tail`` and ``verifier_flush`` run outside the worker span and
# are reported as separate serial terms.
PLANE_LEAF_PHASES: tuple[str, ...] = (
    "rx_decode",       # frame parse + admission pre-checks
    "verify_wait",     # worker blocked on verifier.verify_many
    "echo_apply",      # content insert, relay, sieve echo construction
    "quorum_bitmap",   # attestation vote/bitmap ingestion
    "ready_deliver",   # quorum evaluation, ready send, delivery
    "entry_registry",  # (sender, seq) -> entry binding ops
)

PHASES: tuple[str, ...] = PLANE_LEAF_PHASES + (
    "plane_total",     # whole worker drain cycle (denominator)
    "slot_gc",         # per-sweep cost of the slot GC loop
    "commit_tail",     # per-commit bookkeeping after delivery
    "verifier_flush",  # batch verifier flush decision + dispatch
)

# Phase segments are routinely single-digit microseconds — far below the
# registry's default 100µs floor — so phase histograms get their own ×2
# ladder: 1µs .. ~33s in 26 buckets (+1 overflow).
PHASE_BOUNDS: tuple[float, ...] = tuple(1e-6 * 2.0**i for i in range(26))


class PhaseAccounting:
    """Per-phase elapsed-ns counters + ×2-log histograms.

    Hot-site idiom (one attribute read + one ``is not None`` when
    accounting is off, one ``perf_counter_ns`` pair per segment when
    on)::

        ph = self.phases
        t0 = ph.t() if ph is not None else 0
        ...work...
        if ph is not None:
            t0 = ph.add("echo_apply", t0)   # returns fresh t for chaining

    Counters are exact across threads (registry Counter.inc is
    lock-protected), which is what makes the decomposition shares
    trustworthy when 16 workers mark concurrently.

    The ``plane_total`` denominator goes through ``begin_plane`` /
    ``end_plane`` instead of a bare ``add_ns``: a drain cycle can
    re-enter the plane within the same logical task (e.g. a verifier
    fallback path kicked via ``rlc_ready_or_kick`` that pumps the inbox
    again), and the naive span-per-call accounting counted the nested
    cycle's wall time TWICE — once in its own span and once inside the
    outer one — inflating the denominator and deflating coverage. The
    guard is a contextvar depth counter, which gives exactly the right
    isolation on both runtimes: per-Task on the event loop (two worker
    tasks interleaving on one thread still account their own cycles) and
    per-thread on shard executors.
    """

    __slots__ = ("_counters", "_hists")

    def __init__(
        self, registry: Registry, phases: Sequence[str] = PHASES
    ) -> None:
        self._counters = {
            p: registry.counter(
                f"phase_{p}_ns", f"elapsed ns accounted to phase {p}"
            )
            for p in phases
        }
        self._hists = {
            p: registry.histogram(
                f"phase_{p}", f"per-segment latency of phase {p}",
                bounds=PHASE_BOUNDS,
            )
            for p in phases
        }

    @staticmethod
    def t() -> int:
        """A segment-open timestamp (ns)."""
        return time.perf_counter_ns()

    def add(self, phase: str, t0: int) -> int:
        """Close the segment opened at ``t0`` against ``phase``; returns
        a fresh timestamp so chained segments stay gap-free and
        disjoint (no double counting)."""
        t1 = time.perf_counter_ns()
        dt = t1 - t0
        if dt > 0:
            self._counters[phase].inc(dt)
            self._hists[phase].observe(dt * 1e-9)
        return t1

    def add_ns(self, phase: str, ns: int) -> None:
        """Account an externally-measured duration. Exists for callers
        that already hold a ns delta — and it makes counter exactness
        directly testable."""
        if ns > 0:
            self._counters[phase].inc(ns)
            self._hists[phase].observe(ns * 1e-9)

    def begin_plane(self) -> int:
        """Open a plane drain cycle. Returns the cycle-open timestamp,
        or -1 when this context is already inside a cycle (the nested
        cycle's span must NOT be added to ``plane_total`` again)."""
        depth = _plane_depth.get()
        _plane_depth.set(depth + 1)
        return time.perf_counter_ns() if depth == 0 else -1

    def end_plane(self, t0: int) -> None:
        """Close the cycle opened by the matching :meth:`begin_plane`;
        accounts ``plane_total`` only for the outermost cycle."""
        depth = _plane_depth.get()
        if depth > 0:
            _plane_depth.set(depth - 1)
        if t0 >= 0:
            self.add_ns("plane_total", time.perf_counter_ns() - t0)

    def shard_view(self, shard_id: int, registry: Registry) -> "ShardPhaseView":
        """A per-shard facade over this accounting: same marking API,
        but the six plane leaf phases additionally land in
        ``phase_<p>_shard<k>_ns`` counters on ``registry`` so /metrics
        can show where each shard's time goes. Base counters still get
        every mark — aggregate coverage math is unchanged."""
        return ShardPhaseView(self, shard_id, registry)

    def totals(self) -> dict[str, int]:
        """{phase: accumulated ns} — the raw decomposition vector."""
        return {p: c.value for p, c in self._counters.items()}


# Depth of nested plane drain cycles in the current context. Module-level
# (not per-instance) so a shard core's view and the owner's accounting
# agree on what "inside a cycle" means; contextvars give per-Task
# isolation on the loop and per-thread isolation on shard executors.
_plane_depth: contextvars.ContextVar[int] = contextvars.ContextVar(
    "at2_plane_depth", default=0
)


class ShardPhaseView:
    """Shard-labeled facade over a shared :class:`PhaseAccounting` (see
    :meth:`PhaseAccounting.shard_view`). Leaf-phase marks dual-write to
    the base counters and the shard's own ``phase_<p>_shard<k>_ns``
    counters; everything else delegates."""

    __slots__ = ("_base", "shard_id", "_shard_counters")

    def __init__(
        self, base: PhaseAccounting, shard_id: int, registry: Registry
    ) -> None:
        self._base = base
        self.shard_id = shard_id
        self._shard_counters = {
            p: registry.counter(
                f"phase_{p}_shard{shard_id}_ns",
                f"elapsed ns accounted to phase {p} on plane shard {shard_id}",
            )
            for p in PLANE_LEAF_PHASES
        }

    t = staticmethod(PhaseAccounting.t)

    def add(self, phase: str, t0: int) -> int:
        t1 = self._base.add(phase, t0)
        dt = t1 - t0
        sc = self._shard_counters.get(phase)
        if sc is not None and dt > 0:
            sc.inc(dt)
        return t1

    def add_ns(self, phase: str, ns: int) -> None:
        self._base.add_ns(phase, ns)
        sc = self._shard_counters.get(phase)
        if sc is not None and ns > 0:
            sc.inc(ns)

    def begin_plane(self) -> int:
        return self._base.begin_plane()

    def end_plane(self, t0: int) -> None:
        self._base.end_plane(t0)

    def totals(self) -> dict[str, int]:
        return self._base.totals()


# --------------------------------------------------------------------------
# Sampling stack profiler


class _StackNode:
    __slots__ = ("count", "children")

    def __init__(self) -> None:
        self.count = 0
        self.children: dict[str, _StackNode] = {}


_TRUNCATED = "(truncated)"


def _frame_label(filename: str, func: str) -> str:
    return f"{os.path.basename(filename)}:{func}"


class StackSampler:
    """Sampling profiler: a daemon thread walks every live thread's
    frame stack at ``hz`` and folds the stacks into a bounded tree.

    * Interior frames are labeled ``file:func``; the leaf frame carries
      its line number (``file:func:line``) so the hottest folded stack
      names an exact file:line — the attribution profile_collect puts
      in its decomposition report.
    * The tree is bounded at ``max_nodes``: once the budget is spent,
      paths that would create a new node collapse into a
      ``(truncated)`` child at the divergence point, so a pathological
      workload can never grow memory without bound.
    * ``start()/stop()`` are idempotent and the sampler is restartable;
      ``start(duration=...)`` self-stops (used by ``/profilez?start``
      and the healthz degraded-edge capture).
    * :meth:`ingest` is the deterministic test seam: it takes synthetic
      root-first stacks and is exactly the path real samples take.
    """

    def __init__(
        self,
        hz: float = 97.0,
        max_nodes: int = 20000,
        max_depth: int = 64,
    ) -> None:
        if hz <= 0:
            raise ValueError(f"sampler hz must be > 0, got {hz}")
        if max_nodes <= 0:
            raise ValueError(f"sampler max_nodes must be > 0, got {max_nodes}")
        self.hz = float(hz)
        self.max_nodes = int(max_nodes)
        self.max_depth = int(max_depth)
        self._lock = threading.Lock()
        self._root = _StackNode()
        self._nodes = 1
        self._samples = 0
        self._truncated = 0
        # code object -> "file:func": label construction (basename +
        # format) dominates per-sample cost, and the set of live code
        # objects is small and stable — caching it cuts the sampler's
        # per-tick cost ~4x, which is what keeps the whole tier inside
        # the 5% overhead budget on a 1-core host
        self._label_cache: dict = {}
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self._started_at: Optional[float] = None
        self._last_duration: Optional[float] = None

    # -- lifecycle -------------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self, duration: Optional[float] = None) -> bool:
        """Begin sampling; returns False (no-op) if already running.
        With ``duration`` the sampler stops itself after that many real
        seconds."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            self._stop_event = threading.Event()
            self._started_at = time.monotonic()
            self._last_duration = duration
            self._thread = threading.Thread(
                target=self._run,
                args=(self._stop_event, duration),
                name="at2-profiler",
                daemon=True,
            )
            self._thread.start()
            return True

    def stop(self) -> None:
        """Idempotent; joins the sampler thread."""
        with self._lock:
            thread = self._thread
            event = self._stop_event
            self._thread = None
        if thread is None:
            return
        event.set()
        if thread is not threading.current_thread():
            thread.join(timeout=5.0)

    def reset(self) -> None:
        with self._lock:
            self._root = _StackNode()
            self._nodes = 1
            self._samples = 0
            self._truncated = 0

    def _run(self, stop: threading.Event, duration: Optional[float]) -> None:
        interval = 1.0 / self.hz
        deadline = (
            time.monotonic() + duration if duration is not None else None
        )
        own = threading.get_ident()
        while not stop.wait(interval):
            if deadline is not None and time.monotonic() >= deadline:
                break
            self._sample_once(own)

    def _sample_once(self, own_tid: int) -> None:
        cache = self._label_cache
        if len(cache) > 65536:  # paranoia bound; code objects are finite
            cache.clear()
        stacks: list[list[str]] = []
        for tid, frame in sys._current_frames().items():
            if tid == own_tid:
                continue
            labels: list[str] = []
            leaf_lineno = frame.f_lineno
            f = frame
            while f is not None and len(labels) < self.max_depth:
                code = f.f_code
                label = cache.get(code)
                if label is None:
                    label = _frame_label(code.co_filename, code.co_name)
                    cache[code] = label
                labels.append(label)
                f = f.f_back
            labels.reverse()  # root-first; labels[-1] is the leaf
            labels[-1] = f"{labels[-1]}:{leaf_lineno}"
            stacks.append(labels)
        self._ingest_labeled(stacks)

    # -- aggregation -----------------------------------------------------

    def ingest(
        self, stacks: Iterable[Sequence[tuple[str, str, int]]]
    ) -> None:
        """Fold root-first ``(filename, func, lineno)`` stacks into the
        tree. One call = one sample tick (every stack in it shares the
        sample count bump). This is the path real samples take (modulo
        label caching), so tests can drive it with synthetic frames."""
        labeled: list[list[str]] = []
        for stack in stacks:
            labels = [_frame_label(fn, func) for fn, func, _ in stack]
            if labels:
                labels[-1] = f"{labels[-1]}:{stack[-1][2]}"
            labeled.append(labels)
        self._ingest_labeled(labeled)

    def _ingest_labeled(self, stacks: Iterable[Sequence[str]]) -> None:
        with self._lock:
            self._samples += 1
            for labels in stacks:
                if not labels:
                    continue
                node = self._root
                for label in labels:
                    child = node.children.get(label)
                    if child is None:
                        if self._nodes >= self.max_nodes:
                            self._truncated += 1
                            child = node.children.get(_TRUNCATED)
                            if child is None:
                                child = _StackNode()
                                node.children[_TRUNCATED] = child
                                self._nodes += 1
                            child.count += 1
                            break
                        child = _StackNode()
                        node.children[label] = child
                        self._nodes += 1
                    node = child
                else:
                    node.count += 1

    # -- views -----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "running": self.running,
                "samples": self._samples,
                "nodes": self._nodes,
                "truncated_paths": self._truncated,
                "hz": self.hz,
                "duration": self._last_duration,
            }

    def folded(self, limit: Optional[int] = None) -> str:
        """Collapsed-stack text (``frame;frame;frame count`` per line),
        ready for flamegraph.pl / speedscope. Deterministic ordering:
        count descending, then stack string — byte-identical for
        identical trees regardless of insertion order."""
        lines: list[tuple[int, str]] = []

        def walk(node: _StackNode, path: list[str]) -> None:
            if node.count and path:
                lines.append((node.count, ";".join(path)))
            for label in node.children:
                walk(node.children[label], path + [label])

        with self._lock:
            walk(self._root, [])
        lines.sort(key=lambda e: (-e[0], e[1]))
        if limit is not None:
            lines = lines[:limit]
        return "\n".join(f"{stack} {count}" for count, stack in lines)

    def tree(self) -> dict:
        """The stack tree as JSON-ready nested dicts, children sorted by
        count descending then label (deterministic)."""

        def render(label: str, node: _StackNode) -> dict:
            kids = sorted(
                node.children.items(),
                key=lambda kv: (-self._subtree_count(kv[1]), kv[0]),
            )
            out: dict = {"name": label, "count": node.count}
            if kids:
                out["children"] = [render(k, v) for k, v in kids]
            return out

        with self._lock:
            return render("root", self._root)

    @staticmethod
    def _subtree_count(node: _StackNode) -> int:
        total = node.count
        for child in node.children.values():
            total += StackSampler._subtree_count(child)
        return total


def parse_folded(text: str) -> dict[str, int]:
    """Collapsed-stack text -> ``{stack: count}``. Tolerant of blank
    lines; a malformed line (no trailing integer) is skipped rather than
    poisoning the merge — folded increments cross a process boundary."""
    out: dict[str, int] = {}
    for line in text.splitlines():
        stack, _, count = line.rpartition(" ")
        if not stack:
            continue
        try:
            n = int(count)
        except ValueError:
            continue
        out[stack] = out.get(stack, 0) + n
    return out


def merge_folded(
    parts: Iterable[tuple[str, "str | dict[str, int]"]],
    limit: Optional[int] = None,
) -> str:
    """Merge several collapsed-stack profiles into one folded text.

    ``parts`` is ``(prefix, folded)`` pairs where ``folded`` is either
    folded text or an already-parsed ``{stack: count}`` dict; a
    non-empty prefix is prepended to every stack in that part (the
    multi-process convention: worker frames arrive as ``shardN/...``).
    Ordering matches :meth:`StackSampler.folded`: count descending,
    then stack string — deterministic for identical inputs.
    """
    agg: dict[str, int] = {}
    for prefix, folded in parts:
        entries = (
            parse_folded(folded) if isinstance(folded, str) else folded
        )
        for stack, count in entries.items():
            key = f"{prefix}{stack}" if prefix else stack
            agg[key] = agg.get(key, 0) + count
    lines = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))
    if limit is not None:
        lines = lines[:limit]
    return "\n".join(f"{stack} {count}" for stack, count in lines)


# --------------------------------------------------------------------------
# Event-loop lag probe


class EventLoopLagProbe:
    """Asyncio scheduling-lag probe: sleep ``interval`` through the
    clock seam and record the overshoot into an ``event_loop_lag``
    histogram (so ``event_loop_lag_p99_ms`` lands in /statusz stats and
    /metrics automatically).

    Two driving modes:

    * :meth:`probe_once` — one measurement, awaitable from anywhere.
      Under the sim virtual clock the overshoot is exactly 0.0 and the
      call completes in zero virtual time steps beyond the sleep, so
      sim tests can drive it manually without ever parking a standing
      timer (standing timers blunt SimScheduler's deadlock detection).
    * :meth:`start` / :meth:`stop` — the standing background loop, for
      served real-time nodes only (Service gates it on ``serve_rpc``).
    """

    def __init__(
        self,
        registry: Registry,
        clock,
        interval: float = 0.05,
    ) -> None:
        if interval <= 0:
            raise ValueError(f"lag probe interval must be > 0, got {interval}")
        self.clock = clock
        self.interval = float(interval)
        self.hist: Histogram = registry.histogram(
            "event_loop_lag",
            "asyncio scheduling lag (sleep overshoot)",
            bounds=PHASE_BOUNDS,
        )
        self._task = None

    async def probe_once(self) -> float:
        t0 = self.clock.monotonic()
        await self.clock.sleep(self.interval)
        lag = max(0.0, self.clock.monotonic() - t0 - self.interval)
        self.hist.observe(lag)
        return lag

    def start(self) -> None:
        import asyncio

        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            await self.probe_once()

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except BaseException:
                pass


# --------------------------------------------------------------------------
# Build identity


_GIT_SHA: Optional[str] = None
_GIT_SHA_RESOLVED = False


def _git_sha() -> Optional[str]:
    global _GIT_SHA, _GIT_SHA_RESOLVED
    if not _GIT_SHA_RESOLVED:
        _GIT_SHA_RESOLVED = True
        try:
            out = subprocess.run(
                ["git", "rev-parse", "--short=12", "HEAD"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True,
                timeout=5,
            )
            if out.returncode == 0:
                sha = out.stdout.decode("ascii", "replace").strip()
                _GIT_SHA = sha or None
        except Exception:
            _GIT_SHA = None
    return _GIT_SHA


def build_info() -> dict:
    """The static half of the /statusz ``build`` block: what code is
    running. Service adds the dynamic half (config hash, start time,
    uptime). regress.py / profile_collect stamp reports with THIS dict
    only — it is stable across runs of the same checkout, which is what
    keeps their output byte-identical."""
    try:
        import jax

        jax_version = getattr(jax, "__version__", None)
    except Exception:
        jax_version = None
    return {
        "git_sha": _git_sha(),
        "python": platform.python_version(),
        "jax": jax_version,
    }
