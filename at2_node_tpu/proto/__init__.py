"""Generated protobuf messages + hand-written gRPC stubs for `at2.AT2`.

`at2_pb2` is generated from `at2.proto` by `protoc --python_out` (the
grpc_tools codegen plugin is unavailable in this environment, so the
service stubs in `rpc.py` are written by hand against `grpc.aio`'s generic
handler API — functionally identical to what `protoc-gen-grpc-python`
would emit).
"""

from . import at2_pb2

__all__ = ["at2_pb2"]
