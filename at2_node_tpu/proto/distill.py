"""Distilled transaction-batch wire format (the broker ingress frame).

Chop Chop's distillation insight (arXiv:2304.07081) applied to the AT2
ingress plane: once a client has registered its pubkey in the client
directory, a steady-state transfer no longer needs to carry the 32-byte
key through the RPC plane — a varint client-id is enough, and a broker
that collects many clients' transfers can strip all per-entry framing:

    frame := magic(1) version(1)
             varint n_groups
             varint n_entries                  (redundant, cross-checked)
             group*                            (sender ids strictly increasing)
             sig_block                         (n_entries x 64 bytes, columnar)

    group := varint id_delta                   (first group: the id itself;
                                                later groups: id - prev_id >= 1)
             varint n                          (entries in group, >= 1)
             entry*                            (seqs strictly increasing)

    entry := varint seq_delta                  (first entry: the seq itself,
                                                >= 1; later: seq - prev >= 1)
             varint rtag                       (0: raw 32-byte recipient key
                                                follows; k>=1: directory id k-1)
             [recipient_key(32) when rtag==0]
             varint amount

Sorted strictly-increasing deltas make within-batch duplicate
(sender, seq) pairs *unrepresentable*, so a byzantine broker cannot even
encode a duplicated entry inside one frame (cross-frame duplication is
caught by the node's dedup window, counted as ``dedup_drops``).
Signatures live in one columnar trailing block so the variable-length
head parses without touching them; each signature is the client's
ed25519 over the SAME canonical bytes the per-tx path signs — the v2
tagged transfer form (types.py ``transfer_signing_bytes``), which binds
sender AND sequence into the preimage. That binding is what keeps the
broker untrusted: it can censor or reorder, but it cannot re-encode a
captured signature at a fresh sequence (the preimage changes), so it
never forges — not even by replay.

This module is the pure-Python reference codec; ``native/at2_ingest.cpp``
carries the GIL-released bulk parse (`at2_distill_parse`) that the node
uses when the ingest library is available. The two are differential-
tested against each other in ``tests/test_distill.py``.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, Iterable, List, Optional, Sequence, Tuple, Union

MAGIC = 0xD5
VERSION = 0x01

# Hard cap on entries per distilled frame. Four full TxBatch slots: the
# node re-chunks at `batching.max_entries` anyway, and the cap bounds
# the work a single hostile RPC can demand before signature checks.
DISTILL_MAX_ENTRIES = 4096

ENTRY_WIRE = 140  # expanded body: sender(32) seq(4) recipient(32) amount(8) sig(64)
SIG_WIRE = 64

_BODY = struct.Struct("<32sI32sQ64s")

_U64_MAX = (1 << 64) - 1
_U32_MAX = (1 << 32) - 1


class DistillError(ValueError):
    """Malformed distilled frame (bounds, ordering, or count violations)."""


@dataclass(frozen=True)
class DistilledEntry:
    """One transfer inside a distilled frame.

    ``recipient`` is either an ``int`` directory id or a raw 32-byte
    pubkey (``bytes``) for recipients that never registered —
    directory-less clients stay first-class on both sides of a transfer.
    """

    sender_id: int
    sequence: int
    recipient: Union[int, bytes]
    amount: int
    signature: bytes


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0 or value > _U64_MAX:
        raise DistillError(f"varint out of range: {value}")
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, off: int) -> Tuple[int, int]:
    """Decode one LEB128 varint at ``off``; returns (value, new_off)."""
    result = 0
    shift = 0
    for _ in range(10):  # 10 * 7 = 70 bits covers u64
        if off >= len(buf):
            raise DistillError("truncated varint")
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            if result > _U64_MAX:
                raise DistillError("varint exceeds u64")
            return result, off
        shift += 7
    raise DistillError("varint longer than 10 bytes")


def encode(entries: Sequence[DistilledEntry]) -> bytes:
    """Encode entries (already sorted by (sender_id, sequence), strictly
    increasing — :func:`distill` produces that order) into one frame."""
    if not entries:
        raise DistillError("empty distilled frame")
    if len(entries) > DISTILL_MAX_ENTRIES:
        raise DistillError(f"too many entries: {len(entries)}")

    head = bytearray([MAGIC, VERSION])
    groups: List[List[DistilledEntry]] = []
    for e in entries:
        if groups and groups[-1][0].sender_id == e.sender_id:
            groups[-1].append(e)
        else:
            groups.append([e])

    _write_varint(head, len(groups))
    _write_varint(head, len(entries))
    sigs = bytearray()
    prev_id = None
    for group in groups:
        gid = group[0].sender_id
        if prev_id is not None and gid <= prev_id:
            raise DistillError("sender ids not strictly increasing")
        _write_varint(head, gid if prev_id is None else gid - prev_id)
        prev_id = gid
        _write_varint(head, len(group))
        prev_seq = 0
        for e in group:
            if e.sequence <= prev_seq or e.sequence > _U32_MAX:
                raise DistillError("sequences not strictly increasing u32")
            _write_varint(head, e.sequence - prev_seq)
            prev_seq = e.sequence
            if isinstance(e.recipient, int):
                _write_varint(head, e.recipient + 1)
            else:
                if len(e.recipient) != 32:
                    raise DistillError("raw recipient must be 32 bytes")
                _write_varint(head, 0)
                head += e.recipient
            _write_varint(head, e.amount)
            if len(e.signature) != SIG_WIRE:
                raise DistillError("signature must be 64 bytes")
            sigs += e.signature
    return bytes(head + sigs)


def decode(frame: bytes) -> List[DistilledEntry]:
    """Strict decode; raises :class:`DistillError` on any malformation
    (bad magic, non-increasing ids/seqs, count mismatch, trailing bytes)."""
    if len(frame) < 4:
        raise DistillError("frame too short")
    if frame[0] != MAGIC or frame[1] != VERSION:
        raise DistillError("bad magic/version")
    off = 2
    n_groups, off = _read_varint(frame, off)
    n_entries, off = _read_varint(frame, off)
    if n_groups == 0 or n_entries == 0:
        raise DistillError("empty distilled frame")
    if n_entries > DISTILL_MAX_ENTRIES or n_groups > n_entries:
        raise DistillError("entry/group count out of bounds")
    sig_len = n_entries * SIG_WIRE
    if len(frame) < off + sig_len:
        raise DistillError("frame shorter than signature block")
    sig_start = len(frame) - sig_len

    out: List[DistilledEntry] = []
    prev_id = None
    for _ in range(n_groups):
        delta, off = _read_varint(frame, off)
        if prev_id is None:
            gid = delta
        else:
            if delta == 0:
                raise DistillError("sender ids not strictly increasing")
            gid = prev_id + delta
            if gid > _U64_MAX:
                raise DistillError("sender id exceeds u64")
        prev_id = gid
        n, off = _read_varint(frame, off)
        if n == 0 or len(out) + n > n_entries:
            raise DistillError("group count out of bounds")
        prev_seq = 0
        for _ in range(n):
            sd, off = _read_varint(frame, off)
            if sd == 0:
                raise DistillError("sequences not strictly increasing")
            seq = prev_seq + sd
            if seq > _U32_MAX:
                raise DistillError("sequence exceeds u32")
            prev_seq = seq
            rtag, off = _read_varint(frame, off)
            recipient: Union[int, bytes]
            if rtag == 0:
                if off + 32 > sig_start:
                    raise DistillError("truncated raw recipient")
                recipient = frame[off : off + 32]
                off += 32
            else:
                recipient = rtag - 1
            amount, off = _read_varint(frame, off)
            if off > sig_start:
                raise DistillError("head overruns signature block")
            sig = frame[sig_start + len(out) * SIG_WIRE :][:SIG_WIRE]
            out.append(DistilledEntry(gid, seq, recipient, amount, sig))
    if len(out) != n_entries:
        raise DistillError("entry count mismatch")
    if off != sig_start:
        raise DistillError("trailing bytes between head and signatures")
    return out


def distill(
    entries: Iterable[DistilledEntry],
) -> Tuple[bytes, int]:
    """Broker-side build: sort by (sender_id, sequence), drop exact
    duplicate (sender_id, sequence) pairs (first submission wins), and
    encode. Returns ``(frame, n_duplicates_dropped)``."""
    ordered = sorted(entries, key=lambda e: (e.sender_id, e.sequence))
    kept: List[DistilledEntry] = []
    dropped = 0
    for e in ordered:
        if kept and kept[-1].sender_id == e.sender_id and kept[-1].sequence == e.sequence:
            dropped += 1
            continue
        kept.append(e)
    return encode(kept), dropped


def expand_py(
    frame: bytes,
    get_key: Callable[[int], Optional[bytes]],
) -> Tuple[bytearray, List[int], List[bool]]:
    """Pure-Python mirror of the native ``at2_distill_parse``: decode the
    frame and expand each entry to its 140-byte canonical body (the exact
    ``Payload.encode()[1:]`` bytes the batched broadcast plane carries).

    ``get_key(client_id)`` resolves a directory id to a 32-byte pubkey or
    ``None``. Returns ``(bodies, sender_ids, ok)`` where ``bodies`` is
    ``n * 140`` bytes; an entry whose sender or recipient id is unknown
    gets ``ok[i] = False`` (its unresolved fields are zeroed) — the
    caller counts those as ``directory_misses`` and drops them.
    """
    entries = decode(frame)
    bodies = bytearray(len(entries) * ENTRY_WIRE)
    ids: List[int] = []
    ok: List[bool] = []
    zero32 = b"\x00" * 32
    for i, e in enumerate(entries):
        sender = get_key(e.sender_id)
        if isinstance(e.recipient, int):
            recipient = get_key(e.recipient)
        else:
            recipient = e.recipient
        good = sender is not None and recipient is not None
        _BODY.pack_into(
            bodies,
            i * ENTRY_WIRE,
            sender if sender is not None else zero32,
            e.sequence,
            recipient if recipient is not None else zero32,
            e.amount,
            e.signature,
        )
        ids.append(e.sender_id)
        ok.append(good)
    return bodies, ids, ok
