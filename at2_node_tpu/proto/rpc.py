"""Hand-written grpc.aio service/client stubs for the `at2.AT2` service.

Replaces the codegen the reference gets from tonic-build
(`/root/reference/build.rs:2`, `/root/reference/src/proto.rs:1-6`): the
same four unary RPCs under the fully-qualified service name `at2.AT2`
(`/root/reference/src/at2.proto:4-9`), here registered via
`grpc.method_handlers_generic_handler` because the grpc_tools protoc
plugin is not available in this environment.
"""

from __future__ import annotations

import grpc

from . import at2_pb2 as pb
from . import finality_pb2 as fpb

SERVICE_NAME = "at2.AT2"

# method name -> (request type, reply type); mirrors at2.proto's service
# block one-to-one.
_METHODS = {
    "SendAsset": (pb.SendAssetRequest, pb.SendAssetReply),
    "SendAssetBatch": (pb.SendAssetBatchRequest, pb.SendAssetReply),
    "GetBalance": (pb.GetBalanceRequest, pb.GetBalanceReply),
    "GetLastSequence": (pb.GetLastSequenceRequest, pb.GetLastSequenceReply),
    "GetLatestTransactions": (
        pb.GetLatestTransactionsRequest,
        pb.GetLatestTransactionsReply,
    ),
    # Broker ingress tier (ISSUE 7): directory registration + distilled
    # batch submission (proto/distill.py wire format inside `frame`).
    "Register": (pb.RegisterRequest, pb.RegisterReply),
    "SendDistilledBatch": (pb.SendDistilledBatchRequest, pb.SendAssetReply),
    # Finality lane (finality/): the certificate chain + the serving
    # node's live commit frontier, for light clients and wait_final().
    "GetCertificate": (fpb.GetCertificateRequest, fpb.GetCertificateReply),
}


class At2Servicer:
    """Subclass and override the four handlers, then `add_to_server`."""

    async def SendAsset(self, request, context):
        raise NotImplementedError

    async def SendAssetBatch(self, request, context):
        raise NotImplementedError

    async def GetBalance(self, request, context):
        raise NotImplementedError

    async def GetLastSequence(self, request, context):
        raise NotImplementedError

    async def GetLatestTransactions(self, request, context):
        raise NotImplementedError

    async def Register(self, request, context):
        raise NotImplementedError

    async def SendDistilledBatch(self, request, context):
        raise NotImplementedError

    async def GetCertificate(self, request, context):
        raise NotImplementedError


def add_to_server(servicer: At2Servicer, server: grpc.aio.Server) -> None:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req.FromString,
            response_serializer=rep.SerializeToString,
        )
        for name, (req, rep) in _METHODS.items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


class At2Stub:
    """Async client stub over a `grpc.aio.Channel`."""

    def __init__(self, channel: grpc.aio.Channel) -> None:
        for name, (req, rep) in _METHODS.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{SERVICE_NAME}/{name}",
                    request_serializer=req.SerializeToString,
                    response_deserializer=rep.FromString,
                ),
            )
