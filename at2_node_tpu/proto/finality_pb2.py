# -*- coding: utf-8 -*-
"""Runtime-built protobuf messages for the finality RPC surface.

`at2_pb2.py` is a frozen protoc artifact (a serialized FileDescriptorProto
blob) and the grpc_tools protoc plugin is not available in this
environment, so the GetCertificate pair is described here with explicit
descriptor_pb2 construction and registered in the default pool at import
time — same wire semantics as if `finality.proto` had been compiled:

    message GetCertificateRequest {}
    message GetCertificateReply {
      bool   enabled       = 1;  // [finality] table on at the serving node
      uint64 epoch         = 2;  // serving node's current membership epoch
      uint64 node_commits  = 3;  // serving node's commit frontier NOW
      repeated bytes certificates = 4;  // Certificate.encode(), oldest first
    }
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

_FILE_NAME = "at2_finality.proto"
_PACKAGE = "at2"


def _build_file() -> descriptor_pb2.FileDescriptorProto:
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = _FILE_NAME
    fdp.package = _PACKAGE
    fdp.syntax = "proto3"

    fdp.message_type.add().name = "GetCertificateRequest"

    reply = fdp.message_type.add()
    reply.name = "GetCertificateReply"
    f = reply.field.add()
    f.name, f.number = "enabled", 1
    f.type = descriptor_pb2.FieldDescriptorProto.TYPE_BOOL
    f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
    f = reply.field.add()
    f.name, f.number = "epoch", 2
    f.type = descriptor_pb2.FieldDescriptorProto.TYPE_UINT64
    f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
    f = reply.field.add()
    f.name, f.number = "node_commits", 3
    f.type = descriptor_pb2.FieldDescriptorProto.TYPE_UINT64
    f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
    f = reply.field.add()
    f.name, f.number = "certificates", 4
    f.type = descriptor_pb2.FieldDescriptorProto.TYPE_BYTES
    f.label = descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
    return fdp


_pool = descriptor_pool.Default()
try:
    _file = _pool.Add(_build_file())
except Exception:
    # already registered (module reloaded, or a parallel import raced us)
    _file = _pool.FindFileByName(_FILE_NAME)


def _message_class(name: str):
    desc = _file.message_types_by_name[name]
    get = getattr(message_factory, "GetMessageClass", None)
    if get is not None:  # protobuf >= 4
        return get(desc)
    return message_factory.MessageFactory(_pool).GetPrototype(desc)


GetCertificateRequest = _message_class("GetCertificateRequest")
GetCertificateReply = _message_class("GetCertificateReply")
