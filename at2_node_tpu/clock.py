"""The clock seam: one injectable time source for every timed component.

The broadcast stack, mesh, service, and batch verifier used to call
`time.monotonic()` / `asyncio.sleep()` directly, which welds their timer
semantics to the wall clock and makes adversarial-schedule testing cost
real seconds. Every timed component now takes an optional ``clock``
(defaulting to :data:`SYSTEM_CLOCK`, which preserves the exact previous
behavior), and the deterministic simulator (`at2_node_tpu.sim`) injects
a virtual clock bound to its discrete-event scheduler.

Three operations cover every call site in the tree:

* ``monotonic()`` — interval timestamps (slot ages, retransmit pacing,
  token-bucket refills, pipeline latency stamps);
* ``wall()``     — wall-clock reads whose only job is uniqueness across
  restarts (the ingress batcher's batch_seq epoch);
* ``sleep(dt)``  — cooperative delays (GC ticks, redial backoff,
  catchup windows, flush timers).

Production code must route timed waits through these instead of
`time.monotonic` / `time.time` / `asyncio.sleep` so the simulator's
virtual time covers them.
"""

from __future__ import annotations

import asyncio
import time


class SystemClock:
    """Real time: the default for every production component."""

    def monotonic(self) -> float:
        return time.monotonic()

    def wall(self) -> float:
        return time.time()

    async def sleep(self, delay: float) -> None:
        await asyncio.sleep(delay)


SYSTEM_CLOCK = SystemClock()
