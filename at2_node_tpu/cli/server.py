"""`server` CLI: `config {new,get-node}` and `run`.

Same subcommand surface and stdin/stdout TOML piping as the reference
server binary (`/root/reference/src/bin/server/main.rs:17-140`):

    server config new <node_address> <rpc_address>   > node.toml
    server config get-node < node.toml               # shareable fragment
    server run < node.toml                           # serve forever

Peers are added by appending other nodes' `get-node` fragments to the
config, exactly the reference operator workflow
(`/root/reference/README.md:26-27`).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

from ..crypto.keys import ExchangeKeyPair, SignKeyPair
from ..node.config import Config


def cmd_config_new(args: argparse.Namespace) -> int:
    config = Config(
        node_address=args.node_address,
        rpc_address=args.rpc_address,
        sign_key=SignKeyPair.random(),
        network_key=ExchangeKeyPair.random(),
    )
    sys.stdout.write(config.dumps())
    return 0


def cmd_config_get_node(args: argparse.Namespace) -> int:
    config = Config.load(sys.stdin)
    sys.stdout.write(config.node_fragment())
    return 0


def cmd_run(args: argparse.Namespace) -> int:
    # WARN-level logging by default, like the reference's tracing setup
    # (`server/main.rs:94-99`); AT2_LOG overrides for debugging.
    import os

    logging.basicConfig(
        level=os.environ.get("AT2_LOG", "WARNING").upper(),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    # multi-host bring-up; a no-op returning immediately (and importing
    # no jax) unless AT2_COORDINATOR is configured, so single-host
    # CPU-verifier servers stay light at boot
    from ..parallel.multihost import maybe_initialize

    maybe_initialize()
    config = Config.load(sys.stdin)

    async def main() -> None:
        import signal

        from ..node.service import Service

        service = await Service.start(config)
        # SIGTERM (systemd/k8s stop, test harness kill) must shut down
        # gracefully like SIGINT: quiesce, drain deliveries, write the
        # final checkpoint. Default SIGTERM disposition would kill the
        # process mid-state with no snapshot.
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except (NotImplementedError, RuntimeError):
                pass  # non-unix / nested-loop fallback: SIGINT still works
        serve = asyncio.ensure_future(service.serve_forever())
        stopped = asyncio.ensure_future(stop.wait())
        try:
            await asyncio.wait(
                {serve, stopped}, return_when=asyncio.FIRST_COMPLETED
            )
        finally:
            # close() BEFORE cancelling serve: wait_for_termination shares
            # grpc's shutdown future — cancelling it first poisons the
            # stop() await inside close() with CancelledError.
            await service.close()
            for t in (serve, stopped):
                t.cancel()
            await asyncio.gather(serve, stopped, return_exceptions=True)
        if not stop.is_set() and serve.done() and not serve.cancelled():
            exc = serve.exception()
            if exc is not None:
                raise exc  # server crashed: surface it, exit nonzero

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        pass
    except OSError as exc:
        print(f"server: {exc}", file=sys.stderr)
        return 1
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="server", description="AT2 node")
    sub = parser.add_subparsers(dest="command", required=True)

    config = sub.add_parser("config", help="manage node configuration")
    config_sub = config.add_subparsers(dest="config_command", required=True)

    new = config_sub.add_parser("new", help="generate a fresh node config")
    new.add_argument("node_address", help="host:port of the node-to-node plane")
    new.add_argument("rpc_address", help="host:port of the client gRPC plane")
    new.set_defaults(func=cmd_config_new)

    get_node = config_sub.add_parser(
        "get-node", help="print this node's shareable [[nodes]] fragment"
    )
    get_node.set_defaults(func=cmd_config_get_node)

    run = sub.add_parser("run", help="run the node (config on stdin)")
    run.set_defaults(func=cmd_run)

    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
