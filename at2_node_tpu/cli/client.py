"""`client` CLI: wallet commands over the `at2.AT2` RPC surface.

Same subcommand surface, config schema, and output formats as the
reference client binary (`/root/reference/src/bin/client/main.rs:19-175`,
`/root/reference/src/bin/client/config.rs:7-13`):

    client config new <rpc_url>        > wallet.toml   # random keypair
    client config get-public-key       < wallet.toml   # hex public key
    client send-asset <seq> <recipient-hex> <amount>  < wallet.toml
    client get-balance                 < wallet.toml
    client get-last-sequence           < wallet.toml
    client get-latest-transactions     < wallet.toml

Config is `{rpc_address, private_key(hex)}` TOML on stdin; generated
config goes to stdout — pure shell-pipe plumbing like the reference.
"""

from __future__ import annotations

import argparse
import asyncio
import sys

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is the same parser
    import tomli as tomllib
from dataclasses import dataclass

from ..client import Client
from ..crypto.keys import SignKeyPair
from ..types import TransactionState


@dataclass
class WalletConfig:
    rpc_address: str
    private_key: SignKeyPair

    def dumps(self) -> str:
        return (
            f'rpc_address = "{self.rpc_address}"\n'
            f'private_key = "{self.private_key.to_hex()}"\n'
        )

    @staticmethod
    def load_stdin() -> "WalletConfig":
        doc = tomllib.loads(sys.stdin.read())
        return WalletConfig(
            rpc_address=doc["rpc_address"],
            private_key=SignKeyPair.from_hex(doc["private_key"]),
        )


def cmd_config_new(args: argparse.Namespace) -> int:
    sys.stdout.write(WalletConfig(args.rpc_address, SignKeyPair.random()).dumps())
    return 0


def cmd_config_get_public_key(args: argparse.Namespace) -> int:
    print(WalletConfig.load_stdin().private_key.public.hex())
    return 0


def _run(coro) -> int:
    try:
        asyncio.run(coro)
        return 0
    except Exception as exc:  # match the reference's single-line stderr exit
        print(f"error running cmd: {exc}", file=sys.stderr)
        return 1


def cmd_send_asset(args: argparse.Namespace) -> int:
    config = WalletConfig.load_stdin()

    async def go() -> None:
        async with Client(config.rpc_address) as client:
            await client.send_asset(
                config.private_key,
                args.sequence,
                bytes.fromhex(args.recipient),
                args.amount,
            )

    return _run(go())


def cmd_get_balance(args: argparse.Namespace) -> int:
    config = WalletConfig.load_stdin()

    async def go() -> None:
        async with Client(config.rpc_address) as client:
            print(await client.get_balance(config.private_key.public))

    return _run(go())


def cmd_get_last_sequence(args: argparse.Namespace) -> int:
    config = WalletConfig.load_stdin()

    async def go() -> None:
        async with Client(config.rpc_address) as client:
            print(await client.get_last_sequence(config.private_key.public))

    return _run(go())


_STATE_NAMES = {
    TransactionState.PENDING: "pending",
    TransactionState.SUCCESS: "success",
    TransactionState.FAILURE: "failure",
}


def cmd_get_latest_transactions(args: argparse.Namespace) -> int:
    config = WalletConfig.load_stdin()

    async def go() -> None:
        async with Client(config.rpc_address) as client:
            for tx in await client.get_latest_transactions():
                # same human format as client/main.rs:134-147
                print(
                    f"{tx.timestamp.isoformat()}: {tx.sender.hex()} send "
                    f"{tx.amount}¤ to {tx.recipient.hex()} "
                    f"({_STATE_NAMES[tx.state]})"
                )

    return _run(go())


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="client", description="AT2 wallet")
    sub = parser.add_subparsers(dest="command", required=True)

    config = sub.add_parser("config", help="manage wallet configuration")
    config_sub = config.add_subparsers(dest="config_command", required=True)
    new = config_sub.add_parser("new", help="generate a fresh wallet config")
    new.add_argument("rpc_address", help="node RPC url, e.g. http://host:port")
    new.set_defaults(func=cmd_config_new)
    gpk = config_sub.add_parser("get-public-key", help="print hex public key")
    gpk.set_defaults(func=cmd_config_get_public_key)

    send = sub.add_parser("send-asset", help="sign and submit a transfer")
    send.add_argument("sequence", type=int)
    send.add_argument("recipient", help="recipient public key (hex)")
    send.add_argument("amount", type=int)
    send.set_defaults(func=cmd_send_asset)

    sub.add_parser("get-balance").set_defaults(func=cmd_get_balance)
    sub.add_parser("get-last-sequence").set_defaults(func=cmd_get_last_sequence)
    sub.add_parser("get-latest-transactions").set_defaults(
        func=cmd_get_latest_transactions
    )

    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
