"""Authenticated encrypted TCP channels between nodes.

TPU-native-framework equivalent of drop's network plane
(`/root/reference/src/bin/server/rpc.rs:18,82-86`: `TcpListener::new(addr,
Exchanger)`, `ResolveConnector(TcpConnector).retry()`): asyncio TCP
streams with an X25519 key-exchange handshake and per-frame
ChaCha20-Poly1305 encryption, so a node only ever talks to peers it can
authenticate by their configured network public key
(`/root/reference/src/bin/server/config.rs:29-33`).

Handshake (one round trip):

1. each side sends its raw 32-byte X25519 public key followed by a fresh
   32-byte random nonce;
2. both compute the static-static ECDH shared secret (authenticating the
   peer) and derive two directional session keys via HKDF-SHA256, salted
   with BOTH random nonces — so every connection gets fresh keys even
   between the same long-term key pair (no (key, nonce) reuse across
   reconnects, and frames recorded from an old connection cannot be
   replayed into a new one); the `info` string binds each key to the
   initiator→responder / responder→initiator direction, so the two
   directions never share (key, nonce) space either;
3. every subsequent frame is `u32-LE ciphertext length || ciphertext`
   where ciphertext = ChaCha20-Poly1305(plaintext) under the sending
   direction's key with a little-endian frame-counter nonce.

The receiving side learns the peer's identity (its exchange public key)
from the handshake and the caller checks it against the configured peer
set — an unknown key is rejected before any frame is processed.
"""

from __future__ import annotations

import asyncio
import os
import struct
from dataclasses import dataclass, field

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    def _hkdf32(shared: bytes, salt: bytes, info: bytes) -> bytes:
        return HKDF(
            algorithm=hashes.SHA256(), length=32, salt=salt, info=info
        ).derive(shared)

except ImportError:  # image without the OpenSSL wheels: RFC fallback
    from ..crypto._fallback import ChaCha20Poly1305, InvalidTag

    def _hkdf32(shared: bytes, salt: bytes, info: bytes) -> bytes:
        from ..crypto._fallback import hkdf_sha256

        return hkdf_sha256(shared, salt, info, 32)

from ..crypto.keys import ExchangeKeyPair

MAX_FRAME = 16 * 1024 * 1024  # hard cap; a frame is at most a message batch

_LEN = struct.Struct("<I")
_NONCE = struct.Struct("<Q")


class HandshakeError(Exception):
    pass


class ChannelClosed(Exception):
    pass


def _derive(
    shared: bytes,
    initiator_pub: bytes,
    responder_pub: bytes,
    initiator_nonce: bytes,
    responder_nonce: bytes,
) -> tuple:
    """Two directional ChaCha20-Poly1305 keys from the ECDH secret; the
    per-connection nonces make the keys unique per connection."""

    def one(direction: bytes) -> bytes:
        return _hkdf32(
            shared,
            initiator_pub + responder_pub + initiator_nonce + responder_nonce,
            b"at2-node-tpu channel " + direction,
        )

    return one(b"i2r"), one(b"r2i")


@dataclass(eq=False)  # identity hash: channels live in a set
class Channel:
    """One encrypted, authenticated duplex connection to a peer."""

    reader: asyncio.StreamReader
    writer: asyncio.StreamWriter
    peer_public: bytes  # the peer's X25519 key, proven by the handshake
    _send_aead: ChaCha20Poly1305
    _recv_aead: ChaCha20Poly1305
    _send_ctr: int = 0
    _recv_ctr: int = 0
    _send_lock: asyncio.Lock = field(default_factory=asyncio.Lock)

    async def send(self, payload: bytes) -> None:
        async with self._send_lock:
            nonce = _NONCE.pack(self._send_ctr) + b"\x00\x00\x00\x00"
            self._send_ctr += 1
            ct = self._send_aead.encrypt(nonce, payload, None)
            self.writer.write(_LEN.pack(len(ct)) + ct)
            try:
                await self.writer.drain()
            except ConnectionError as exc:
                raise ChannelClosed(str(exc)) from exc

    async def recv(self) -> bytes:
        try:
            header = await self.reader.readexactly(_LEN.size)
            (length,) = _LEN.unpack(header)
            if length > MAX_FRAME:
                # post-handshake garbage (attacker or corruption), same
                # class as a bad AEAD tag below: channel-fatal, normal drop
                raise ChannelClosed(f"oversized frame: {length}")
            ct = await self.reader.readexactly(length)
        except (asyncio.IncompleteReadError, ConnectionError) as exc:
            raise ChannelClosed(str(exc)) from exc
        nonce = _NONCE.pack(self._recv_ctr) + b"\x00\x00\x00\x00"
        self._recv_ctr += 1
        try:
            return self._recv_aead.decrypt(nonce, ct, None)
        except InvalidTag as exc:
            # a frame failing the AEAD tag is wire corruption or an active
            # attacker: protocol-fatal for the channel, but NOT an internal
            # error — callers (the mesh) treat ChannelClosed as a normal
            # drop/redial, so on-path garbage cannot traceback-spam logs.
            # (ONLY InvalidTag: anything else here is a real bug and must
            # surface loudly, not be laundered into a silent redial.)
            raise ChannelClosed("integrity check failed") from exc

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass


async def _swap_hello(
    reader: asyncio.StreamReader, writer: asyncio.StreamWriter, own_public: bytes
) -> tuple:
    """Exchange (public key, connection nonce); returns the peer's pair."""
    own_nonce = os.urandom(32)
    writer.write(own_public + own_nonce)
    await writer.drain()
    try:
        hello = await reader.readexactly(64)
    except (asyncio.IncompleteReadError, ConnectionError) as exc:
        raise HandshakeError(f"peer closed during handshake: {exc}") from exc
    return own_nonce, hello[:32], hello[32:]


def _shared_or_raise(keypair: ExchangeKeyPair, peer_public: bytes) -> bytes:
    try:
        return keypair.exchange(peer_public)
    except ValueError as exc:  # low-order / malformed point
        raise HandshakeError(f"bad peer key: {exc}") from exc


def responder_session_keys(
    keypair: ExchangeKeyPair, own_nonce: bytes, hello: bytes
) -> tuple:
    """Responder-side key material from the peer's 64-byte hello: returns
    (peer_public, k_i2r, k_r2i). THE one implementation — used by both
    the asyncio accept path below and the native-reader accept path
    (net/peers.py), so the two inbound planes can never drift."""
    peer_public, peer_nonce = hello[:32], hello[32:64]
    shared = _shared_or_raise(keypair, peer_public)
    k_i2r, k_r2i = _derive(
        shared, peer_public, keypair.public, peer_nonce, own_nonce
    )
    return peer_public, k_i2r, k_r2i


async def connect(
    host: str, port: int, keypair: ExchangeKeyPair, timeout: float = 5.0
) -> Channel:
    """Dial a peer (initiator role). DNS names resolve via the OS — the
    equivalent of drop's ResolveConnector
    (`/root/reference/tests/server-config-resolve-addrs:5-8`)."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout
    )
    try:
        own_nonce, peer_public, peer_nonce = await asyncio.wait_for(
            _swap_hello(reader, writer, keypair.public), timeout
        )
        shared = _shared_or_raise(keypair, peer_public)
        k_i2r, k_r2i = _derive(
            shared, keypair.public, peer_public, own_nonce, peer_nonce
        )
    except Exception:
        writer.close()
        raise
    return Channel(
        reader,
        writer,
        peer_public,
        _send_aead=ChaCha20Poly1305(k_i2r),
        _recv_aead=ChaCha20Poly1305(k_r2i),
    )


async def accept(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    keypair: ExchangeKeyPair,
    timeout: float = 5.0,
) -> Channel:
    """Complete the responder side of the handshake on an inbound socket.
    On any failure the socket is closed before the error propagates."""
    try:
        own_nonce, peer_public, peer_nonce = await asyncio.wait_for(
            _swap_hello(reader, writer, keypair.public), timeout
        )
        peer_public, k_i2r, k_r2i = responder_session_keys(
            keypair, own_nonce, peer_public + peer_nonce
        )
    except Exception:
        writer.close()
        raise
    return Channel(
        reader,
        writer,
        peer_public,
        _send_aead=ChaCha20Poly1305(k_r2i),
        _recv_aead=ChaCha20Poly1305(k_i2r),
    )
