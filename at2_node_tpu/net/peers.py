"""Full-mesh peer connection manager.

Equivalent of drop's `System` / `SystemManager` / `NetworkSender`
(`/root/reference/src/bin/server/rpc.rs:19,88-125`): bring up an encrypted
listener, dial every configured peer, and expose send/broadcast keyed by
peer identity. Improvements over the reference consciously taken:

* dropped connections ARE re-dialed with exponential backoff — the
  reference leaves this as "TODO readd connections if dropped"
  (`rpc.rs:87`);
* inbound connections from unknown exchange keys are rejected at the
  handshake boundary (the reference relies on drop's Exchanger for the
  same property [dep-inferred]).

Each ordered pair of nodes uses one TCP connection: the initiator writes,
the responder reads. A full mesh of N nodes therefore carries N·(N−1)
connections, each authenticated by the X25519 handshake
(`at2_node_tpu.net.transport`).

Delivery is best-effort (murmur semantics, `/root/reference/technical.md:9-10`):
sends while a peer is down are buffered in a bounded queue and dropped
oldest-first on overflow.

Messages are coalesced: a wire frame is the plain concatenation of queued
messages (broadcast records are self-delimiting — see
`broadcast.messages.parse_frame`), so under load one AEAD seal and one
syscall carry up to MAX_BATCH_MSGS protocol messages — the amortization
that lets the broadcast plane keep pace with the TPU verifier's batch
throughput.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Awaitable, Callable, Dict, Iterable, List, Optional

from ..crypto.keys import ExchangeKeyPair
from . import transport

logger = logging.getLogger(__name__)

SEND_QUEUE_CAP = 4096
# Coalescing bounds: one wire frame carries up to MAX_BATCH_MSGS queued
# messages (one AEAD + one syscall for all of them). Broadcast messages
# are self-delimiting fixed-size records (broadcast.messages.parse_frame),
# so coalescing is plain concatenation — no extra framing layer. Batches
# form naturally under load: while a frame drains, the queue refills, so
# the next frame is bigger — idle traffic still goes out one message at a
# time with no added latency.
MAX_BATCH_MSGS = 1024
MAX_BATCH_BYTES = 4 * 1024 * 1024


@dataclass(frozen=True)
class Peer:
    """One row of the config's `[[nodes]]` table
    (`/root/reference/src/bin/server/config.rs:29-38` + this build's
    added `sign_public_key`)."""

    address: str  # "host:port" of the peer's node plane
    exchange_public: bytes  # 32-byte X25519 key (channel identity)
    sign_public: bytes  # 32-byte ed25519 key (Echo/Ready signing identity)

    def host_port(self) -> tuple:
        host, _, port = self.address.rpartition(":")
        return host, int(port)


class Mesh:
    """Maintains channels to all peers; calls back on every inbound frame."""

    def __init__(
        self,
        listen_addr: str,
        keypair: ExchangeKeyPair,
        peers: Iterable[Peer],
        on_frame: Callable[[Peer, bytes], Awaitable[None]],
    ) -> None:
        self.listen_addr = listen_addr
        self.keypair = keypair
        self.peers = [p for p in peers if p.exchange_public != keypair.public]
        self.by_exchange: Dict[bytes, Peer] = {
            p.exchange_public: p for p in self.peers
        }
        self.by_sign: Dict[bytes, Peer] = {p.sign_public: p for p in self.peers}
        self.on_frame = on_frame
        self._server: Optional[asyncio.base_events.Server] = None
        self._send_queues: Dict[bytes, asyncio.Queue] = {}
        self._tasks: list = []
        self._channels: set = set()  # live channels, closed on shutdown
        self._closed = False
        # observability counters (SURVEY.md §5): connection churn and
        # best-effort-plane drops are the operator's failure-detection
        # signals
        self.redials = 0  # established connections dropped + re-dialed
        self.dial_failures = 0  # connect/handshake attempts that failed
        self.send_overflows = 0

    def stats(self) -> dict:
        return {
            "channels": len(self._channels),
            "send_queue_depth": sum(
                q.qsize() for q in self._send_queues.values()
            ),
            "redials": self.redials,
            "dial_failures": self.dial_failures,
            "send_overflows": self.send_overflows,
        }

    async def start(self) -> None:
        host, _, port = self.listen_addr.rpartition(":")
        self._server = await asyncio.start_server(
            self._handle_inbound, host or "0.0.0.0", int(port)
        )
        for peer in self.peers:
            q: asyncio.Queue = asyncio.Queue(maxsize=SEND_QUEUE_CAP)
            self._send_queues[peer.exchange_public] = q
            self._tasks.append(asyncio.create_task(self._outbound_loop(peer, q)))

    async def close(self) -> None:
        self._closed = True
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        for channel in list(self._channels):
            channel.close()
        self._channels.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- sending ----------------------------------------------------------

    def send(self, peer: Peer, frame: bytes) -> None:
        """Queue a frame for one peer; never blocks (best-effort plane)."""
        q = self._send_queues.get(peer.exchange_public)
        if q is None:
            return
        while True:
            try:
                q.put_nowait(frame)
                return
            except asyncio.QueueFull:
                try:  # drop the oldest queued frame and retry
                    q.get_nowait()
                    self.send_overflows += 1
                    logger.warning("send queue overflow to %s", peer.address)
                except asyncio.QueueEmpty:
                    pass

    def broadcast(self, frame: bytes, exclude: Iterable[bytes] = ()) -> None:
        skip = set(exclude)
        for peer in self.peers:
            if peer.exchange_public not in skip:
                self.send(peer, frame)

    # -- connection maintenance -------------------------------------------

    async def _outbound_loop(self, peer: Peer, q: asyncio.Queue) -> None:
        backoff = 0.1
        host, port = peer.host_port()
        pending: Optional[List[bytes]] = None  # batch to resend after redial
        held: Optional[bytes] = None  # message deferred to the next frame
        while not self._closed:
            try:
                channel = await transport.connect(host, port, self.keypair)
            except (OSError, transport.HandshakeError, asyncio.TimeoutError):
                self.dial_failures += 1
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
                continue
            if channel.peer_public != peer.exchange_public:
                logger.warning(
                    "peer %s presented unexpected key %s",
                    peer.address,
                    channel.peer_public.hex(),
                )
                self.dial_failures += 1
                channel.close()
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
                continue
            backoff = 0.1
            self._channels.add(channel)
            try:
                while True:
                    if pending is None:
                        first = held if held is not None else await q.get()
                        held = None
                        batch = [first]
                        size = len(first)
                        # drain whatever accumulated while the last frame
                        # was in flight (bounded: the frame never exceeds
                        # MAX_BATCH_BYTES — an overflowing message is held
                        # for the next frame, not appended)
                        while len(batch) < MAX_BATCH_MSGS:
                            try:
                                m = q.get_nowait()
                            except asyncio.QueueEmpty:
                                break
                            if size + len(m) > MAX_BATCH_BYTES:
                                held = m
                                break
                            batch.append(m)
                            size += len(m)
                        pending = batch
                    await channel.send(b"".join(pending))
                    pending = None
            except (transport.ChannelClosed, ConnectionError):
                self.redials += 1
                logger.warning("connection to %s dropped; redialing", peer.address)
            finally:
                channel.close()
                self._channels.discard(channel)

    async def _handle_inbound(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            channel = await transport.accept(reader, writer, self.keypair)
        except (transport.HandshakeError, asyncio.TimeoutError, OSError):
            writer.close()
            return
        peer = self.by_exchange.get(channel.peer_public)
        if peer is None:
            logger.warning(
                "rejecting connection from unknown key %s",
                channel.peer_public.hex(),
            )
            channel.close()
            return
        self._channels.add(channel)
        try:
            while True:
                frame = await channel.recv()
                await self.on_frame(peer, frame)
        except (transport.ChannelClosed, ConnectionError):
            pass
        except Exception:
            logger.exception("inbound handler error from %s", peer.address)
        finally:
            channel.close()
            self._channels.discard(channel)
