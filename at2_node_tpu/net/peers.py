"""Full-mesh peer connection manager.

Equivalent of drop's `System` / `SystemManager` / `NetworkSender`
(`/root/reference/src/bin/server/rpc.rs:19,88-125`): bring up an encrypted
listener, dial every configured peer, and expose send/broadcast keyed by
peer identity. Improvements over the reference consciously taken:

* dropped connections ARE re-dialed with jittered exponential backoff —
  the reference leaves this as "TODO readd connections if dropped"
  (`rpc.rs:87`); successful re-dials after a drop are counted as
  `peer_reconnects` (distinct from `redials`, which counts the drops);
* inbound connections from unknown exchange keys are rejected at the
  handshake boundary (the reference relies on drop's Exchanger for the
  same property [dep-inferred]).

Each ordered pair of nodes uses one TCP connection: the initiator writes,
the responder reads. A full mesh of N nodes therefore carries N·(N−1)
connections, each authenticated by the X25519 handshake
(`at2_node_tpu.net.transport`).

Delivery is best-effort (murmur semantics, `/root/reference/technical.md:9-10`):
sends while a peer is down are buffered in a bounded queue and dropped
oldest-first on overflow.

Messages are coalesced: a wire frame is the plain concatenation of queued
messages (broadcast records are self-delimiting — see
`broadcast.messages.parse_frame`), so under load one AEAD seal and one
syscall carry up to MAX_BATCH_MSGS protocol messages — the amortization
that lets the broadcast plane keep pace with the TPU verifier's batch
throughput.
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket as socket_mod
from collections import deque
from dataclasses import dataclass
from typing import Awaitable, Callable, Dict, Iterable, List, Optional

from ..crypto.keys import ExchangeKeyPair
from . import transport

logger = logging.getLogger(__name__)

SEND_QUEUE_CAP = 4096
# Coalescing bounds: one wire frame carries up to MAX_BATCH_MSGS queued
# messages (one AEAD + one syscall for all of them). Broadcast messages
# are self-delimiting fixed-size records (broadcast.messages.parse_frame),
# so coalescing is plain concatenation — no extra framing layer. Batches
# form naturally under load: while a frame drains, the queue refills, so
# the next frame is bigger — idle traffic still goes out one message at a
# time with no added latency.
MAX_BATCH_MSGS = 1024
MAX_BATCH_BYTES = 4 * 1024 * 1024


@dataclass(frozen=True)
class Peer:
    """One row of the config's `[[nodes]]` table
    (`/root/reference/src/bin/server/config.rs:29-38` + this build's
    added `sign_public_key`)."""

    address: str  # "host:port" of the peer's node plane
    exchange_public: bytes  # 32-byte X25519 key (channel identity)
    sign_public: bytes  # 32-byte ed25519 key (Echo/Ready signing identity)
    region: str = ""  # optional region hint ([wan] fanout ordering)

    def host_port(self) -> tuple:
        host, _, port = self.address.rpartition(":")
        return host, int(port)


class Mesh:
    """Maintains channels to all peers; calls back on every inbound frame."""

    def __init__(
        self,
        listen_addr: str,
        keypair: ExchangeKeyPair,
        peers: Iterable[Peer],
        on_frame: Callable[[Peer, bytes], Awaitable[None]],
        clock=None,
        region_fanout: bool = False,
        region: str = "",
        capture_cap: int = 0,
    ) -> None:
        from ..clock import SYSTEM_CLOCK

        self.listen_addr = listen_addr
        self.keypair = keypair
        self.clock = SYSTEM_CLOCK if clock is None else clock
        # [wan] region-aware fanout: when on, broadcast() walks peers
        # nearest-first — same-region (declared hints) before far, RTT
        # EWMA (fed from dial timing) as the fine order within each tier
        self.region_fanout = region_fanout
        self.region = region
        self._rtt_ewma: Dict[bytes, float] = {}
        self.peers = [p for p in peers if p.exchange_public != keypair.public]
        self.by_exchange: Dict[bytes, Peer] = {
            p.exchange_public: p for p in self.peers
        }
        self.by_sign: Dict[bytes, Peer] = {p.sign_public: p for p in self.peers}
        self.on_frame = on_frame
        self._server: Optional[asyncio.base_events.Server] = None
        self._send_queues: Dict[bytes, asyncio.Queue] = {}
        self._tasks: list = []
        # outbound loops keyed by exchange key so membership removal can
        # cancel exactly one peer's dialer (node/membership.py)
        self._outbound_tasks: Dict[bytes, asyncio.Task] = {}
        self._channels: set = set()  # live channels, closed on shutdown
        self._closed = False
        # native-reader inbound plane (net docstring in native/reader.py):
        # wake-pipe read fd -> [peer, reader, sock, wake_write_fd, drops]
        self._native_by_fd: Dict[int, list] = {}
        self._listen_sock: Optional[socket_mod.socket] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # observability counters (SURVEY.md §5): connection churn and
        # best-effort-plane drops are the operator's failure-detection
        # signals
        self.redials = 0  # established connections dropped + re-dialed
        self.dial_failures = 0  # connect/handshake attempts that failed
        self.peer_reconnects = 0  # successful re-dials AFTER a drop
        self.send_overflows = 0
        self._reader_drops_closed = 0  # drops of already-closed readers
        # Inbound wire-capture ring (obs/audit.py plane, served on
        # /capturez, replayed by tools/capture_replay.py): a bounded
        # deque of (mono_ns, peer sign hex, first kind byte, frame hex)
        # records taken at the delivery boundary on BOTH inbound planes.
        # Kill-switched like the flight recorder: capture_cap=0 keeps the
        # hot path at a single attribute check.
        self.capture_cap = capture_cap
        self._capture = deque(maxlen=capture_cap) if capture_cap > 0 else None
        self.captured = 0  # cumulative frames captured (past the ring)

    def stats(self) -> dict:
        return {
            "channels": len(self._channels) + len(self._native_by_fd),
            "send_queue_depth": sum(
                q.qsize() for q in self._send_queues.values()
            ),
            "redials": self.redials,
            "dial_failures": self.dial_failures,
            "peer_reconnects": self.peer_reconnects,
            "send_overflows": self.send_overflows,
            "native_readers": len(self._native_by_fd),
            # cumulative like send_overflows: closed channels' drops must
            # not vanish from the operator's failure-detection signal
            "reader_drops": self._reader_drops_closed
            + sum(e[4] for e in self._native_by_fd.values()),
            "captured": self.captured,
        }

    def _capture_frame(self, peer: Peer, frame: bytes) -> None:
        self.captured += 1
        self._capture.append(
            (
                int(self.clock.monotonic() * 1e9),
                peer.sign_public.hex(),
                frame[0] if frame else 0,
                frame.hex(),
            )
        )

    def capture_dump(self) -> dict:
        """Snapshot of the inbound wire-capture ring (served on
        /capturez; the input format of tools/capture_replay.py)."""
        return {
            "cap": self.capture_cap,
            "captured": self.captured,
            "records": [list(r) for r in (self._capture or ())],
        }

    async def start(self) -> None:
        from ..native.reader import reader_available

        self._loop = asyncio.get_running_loop()
        host, _, port = self.listen_addr.rpartition(":")
        if reader_available():
            # native inbound plane: the listen socket is accepted manually
            # so the connection's fd can be handed to a C++ reader thread
            # wholesale after the handshake (asyncio never owns its
            # stream buffers). An EXPLICIT host resolves via getaddrinfo
            # like asyncio.start_server would (hostname/IPv6 listen_addrs
            # behave the same on both planes; first result wins — the
            # single-socket bind vs start_server's multi-bind is the one
            # documented divergence). An empty host keeps the historical
            # IPv4-any wildcard: getaddrinfo's wildcard ordering is
            # platform-dependent and an AF_INET6-first result with
            # bindv6only set would silently stop accepting IPv4 peers.
            if host:
                infos = await self._loop.getaddrinfo(
                    host,
                    int(port),
                    type=socket_mod.SOCK_STREAM,
                    flags=socket_mod.AI_PASSIVE,
                )
                family, stype, proto, _, sockaddr = infos[0]
            else:
                family, stype, proto = (
                    socket_mod.AF_INET, socket_mod.SOCK_STREAM, 0
                )
                sockaddr = ("0.0.0.0", int(port))
            s = socket_mod.socket(family, stype, proto)
            s.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1)
            s.bind(sockaddr)
            s.listen(128)
            s.setblocking(False)
            self._listen_sock = s
            self._tasks.append(
                asyncio.create_task(self._native_accept_loop())
            )
        else:
            self._server = await asyncio.start_server(
                self._handle_inbound, host or "0.0.0.0", int(port)
            )
        for peer in self.peers:
            self._start_outbound(peer)

    def _start_outbound(self, peer: Peer) -> None:
        q: asyncio.Queue = asyncio.Queue(maxsize=SEND_QUEUE_CAP)
        self._send_queues[peer.exchange_public] = q
        self._outbound_tasks[peer.exchange_public] = asyncio.create_task(
            self._outbound_loop(peer, q)
        )

    async def close(self) -> None:
        self._closed = True
        tasks = self._tasks + list(self._outbound_tasks.values())
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        self._tasks.clear()
        self._outbound_tasks.clear()
        for channel in list(self._channels):
            channel.close()
        self._channels.clear()
        for rfd in list(self._native_by_fd):
            self._native_close(rfd)
        if self._listen_sock is not None:
            self._listen_sock.close()
            self._listen_sock = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- membership (node/membership.py epoch transitions) -----------------

    def add_peer(self, peer: Peer) -> bool:
        """Register a peer joining the mesh (epoch reconfiguration). If
        the mesh is already running, its outbound dialer starts
        immediately; inbound connections authenticate as soon as the key
        is registered. Returns False for self or an already-known key."""
        if (
            peer.exchange_public == self.keypair.public
            or peer.exchange_public in self.by_exchange
        ):
            return False
        self.peers.append(peer)
        self.by_exchange[peer.exchange_public] = peer
        self.by_sign[peer.sign_public] = peer
        if self._loop is not None and not self._closed:
            self._start_outbound(peer)
        return True

    def remove_peer(self, sign_public: bytes) -> bool:
        """Evict a peer (epoch reconfiguration): cancel its outbound
        dialer, drop its queue, and forget its keys — NEW inbound
        handshakes from it are rejected like any unknown key. Channels
        it already holds drain until they close (the epoch grace window;
        stack-level epoch checks reject its stale messages meanwhile)."""
        peer = self.by_sign.pop(sign_public, None)
        if peer is None:
            return False
        self.by_exchange.pop(peer.exchange_public, None)
        self.peers = [
            p for p in self.peers
            if p.exchange_public != peer.exchange_public
        ]
        self._send_queues.pop(peer.exchange_public, None)
        task = self._outbound_tasks.pop(peer.exchange_public, None)
        if task is not None:
            task.cancel()
        return True

    # -- sending ----------------------------------------------------------

    def send(self, peer: Peer, frame: bytes) -> None:
        """Queue a frame for one peer; never blocks (best-effort plane)."""
        q = self._send_queues.get(peer.exchange_public)
        if q is None:
            return
        while True:
            try:
                q.put_nowait(frame)
                return
            except asyncio.QueueFull:
                try:  # drop the oldest queued frame and retry
                    q.get_nowait()
                    self.send_overflows += 1
                    logger.warning("send queue overflow to %s", peer.address)
                except asyncio.QueueEmpty:
                    pass

    def broadcast(self, frame: bytes, exclude: Iterable[bytes] = ()) -> None:
        skip = set(exclude)
        peers = self._fanout_order() if self.region_fanout else self.peers
        for peer in peers:
            if peer.exchange_public not in skip:
                self.send(peer, frame)

    def _fanout_order(self) -> List[Peer]:
        """Peers nearest-first: same-region (when both hints are set)
        before cross-region, measured RTT EWMA within each tier, config
        order as the stable tiebreak (sort stability keeps unmeasured
        peers in declared order)."""
        def key(p: Peer):
            far = 0 if (
                self.region and p.region and p.region == self.region
            ) else 1
            return (far, self._rtt_ewma.get(p.exchange_public, float("inf")))

        return sorted(self.peers, key=key)

    # -- connection maintenance -------------------------------------------

    async def _outbound_loop(self, peer: Peer, q: asyncio.Queue) -> None:
        import random

        backoff = 0.1
        host, port = peer.host_port()
        pending: Optional[List[bytes]] = None  # batch to resend after redial
        held: Optional[bytes] = None  # message deferred to the next frame
        dropped = False  # an established channel was lost (for reconnects)
        while not self._closed:
            # full jitter on the backoff sleep: N peers dropping together
            # (a switch reboot) must not re-dial in lockstep
            def nap() -> float:
                return backoff * random.uniform(0.5, 1.0)

            dial_t0 = self.clock.monotonic()
            try:
                channel = await transport.connect(host, port, self.keypair)
            except (OSError, transport.HandshakeError, asyncio.TimeoutError):
                self.dial_failures += 1
                await self.clock.sleep(nap())
                backoff = min(backoff * 2, 5.0)
                continue
            if channel.peer_public != peer.exchange_public:
                logger.warning(
                    "peer %s presented unexpected key %s",
                    peer.address,
                    channel.peer_public.hex(),
                )
                self.dial_failures += 1
                channel.close()
                await self.clock.sleep(nap())
                backoff = min(backoff * 2, 5.0)
                continue
            # the dial (TCP connect + X25519 handshake) is a live RTT
            # sample; EWMA it for region-aware fanout ordering
            rtt = self.clock.monotonic() - dial_t0
            prev_rtt = self._rtt_ewma.get(peer.exchange_public)
            self._rtt_ewma[peer.exchange_public] = (
                rtt if prev_rtt is None else 0.8 * prev_rtt + 0.2 * rtt
            )
            if dropped:
                self.peer_reconnects += 1
                dropped = False
            backoff = 0.1
            self._channels.add(channel)
            try:
                while True:
                    if pending is None:
                        first = held if held is not None else await q.get()
                        held = None
                        batch = [first]
                        size = len(first)
                        # drain whatever accumulated while the last frame
                        # was in flight (bounded: the frame never exceeds
                        # MAX_BATCH_BYTES — an overflowing message is held
                        # for the next frame, not appended)
                        while len(batch) < MAX_BATCH_MSGS:
                            try:
                                m = q.get_nowait()
                            except asyncio.QueueEmpty:
                                break
                            if size + len(m) > MAX_BATCH_BYTES:
                                held = m
                                break
                            batch.append(m)
                            size += len(m)
                        pending = batch
                    await channel.send(b"".join(pending))
                    pending = None
            except (transport.ChannelClosed, ConnectionError):
                self.redials += 1
                dropped = True
                logger.warning("connection to %s dropped; redialing", peer.address)
            finally:
                channel.close()
                self._channels.discard(channel)

    # -- native inbound plane (C++ reader threads) ------------------------

    async def _native_accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _addr = await self._loop.sock_accept(self._listen_sock)
            except (OSError, asyncio.CancelledError):
                return
            task = asyncio.create_task(self._native_inbound(sock))
            self._tasks.append(task)
            # prune on completion: inbound churn (a flapping peer
            # redialing for days) must not grow _tasks without bound
            task.add_done_callback(
                lambda t: self._tasks.remove(t) if t in self._tasks else None
            )

    async def _native_handshake(self, sock) -> tuple:
        """Responder handshake over the raw socket — same hello exchange
        as transport.accept (key derivation shared via
        transport.responder_session_keys), but leaving the socket's
        kernel buffer untouched past the 64 hello bytes so the C++
        reader starts from frame 0."""
        own_nonce = os.urandom(32)
        await self._loop.sock_sendall(sock, self.keypair.public + own_nonce)
        hello = b""
        while len(hello) < 64:
            chunk = await self._loop.sock_recv(sock, 64 - len(hello))
            if not chunk:
                raise transport.HandshakeError("peer closed during handshake")
            hello += chunk
        peer_public, k_i2r, _ = transport.responder_session_keys(
            self.keypair, own_nonce, hello
        )
        return peer_public, k_i2r

    async def _native_inbound(self, sock) -> None:
        from ..native.reader import NativeChannelReader

        sock.setblocking(False)
        try:
            peer_public, recv_key = await asyncio.wait_for(
                self._native_handshake(sock), 5.0
            )
        except (
            transport.HandshakeError,
            asyncio.TimeoutError,
            OSError,
            ConnectionError,
        ):
            sock.close()
            return
        except BaseException:
            # cancellation from Mesh.close() mid-handshake: the accepted
            # socket must not leak to GC finalization
            sock.close()
            raise
        peer = self.by_exchange.get(peer_public)
        if peer is None:
            logger.warning(
                "rejecting connection from unknown key %s", peer_public.hex()
            )
            sock.close()
            return
        # the C++ thread does blocking reads; the handshake needed the
        # socket non-blocking for the asyncio sock_* calls
        sock.setblocking(True)
        rfd, wfd = os.pipe()
        os.set_blocking(rfd, False)
        os.set_blocking(wfd, False)
        rdr = NativeChannelReader(sock.fileno(), recv_key, wfd)
        # entry: [peer, reader, sock, wake_write_fd, drops, last_delivery]
        self._native_by_fd[rfd] = [peer, rdr, sock, wfd, 0, None]
        self._loop.add_reader(rfd, self._native_wake, rfd)

    def _native_wake(self, rfd: int) -> None:
        """One wakeup per frame BATCH: drain the pipe, take every queued
        frame, deliver them through the normal on_frame path. Each
        delivery task CHAINS on the connection's previous one, so
        per-connection frame ordering holds even if on_frame ever gains
        an internal await (it currently doesn't — but ordering must not
        depend on that non-local property)."""
        from ..native.reader import STATUS_OPEN

        entry = self._native_by_fd.get(rfd)
        if entry is None:
            return
        peer, rdr, _sock, _wfd, _, prev = entry
        try:
            os.read(rfd, 65536)
        except (BlockingIOError, OSError):
            pass
        frames: list = []
        while True:
            batch, status, drops = rdr.take()
            frames.extend(batch)
            if not batch:
                break
        entry[4] = drops
        if frames:
            task = asyncio.ensure_future(
                self._deliver_frames(peer, frames, prev)
            )
            task.add_done_callback(self._log_deliver_error)
            entry[5] = task
        if status != STATUS_OPEN:
            # eof or protocol/decrypt failure: channel-fatal, normal drop
            # (the initiating side redials; same semantics as
            # transport.ChannelClosed on the asyncio path)
            self._native_close(rfd)

    async def _deliver_frames(
        self, peer: Peer, frames: list, prev: Optional[asyncio.Future] = None
    ) -> None:
        if prev is not None and not prev.done():
            try:
                await prev  # serialize behind the connection's last batch
            except Exception:
                pass  # already logged by its own done-callback
        for frame in frames:
            if self._capture is not None:
                self._capture_frame(peer, frame)
            await self.on_frame(peer, frame)

    @staticmethod
    def _log_deliver_error(task) -> None:
        if not task.cancelled() and task.exception() is not None:
            logger.exception(
                "inbound frame delivery failed", exc_info=task.exception()
            )

    def _native_close(self, rfd: int) -> None:
        entry = self._native_by_fd.pop(rfd, None)
        if entry is None:
            return
        _peer, rdr, sock, wfd, drops, _prev = entry
        self._reader_drops_closed += drops
        self._loop.remove_reader(rfd)
        rdr.stop()
        os.close(rfd)
        os.close(wfd)
        sock.close()

    async def _handle_inbound(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            channel = await transport.accept(reader, writer, self.keypair)
        except (transport.HandshakeError, asyncio.TimeoutError, OSError):
            writer.close()
            return
        peer = self.by_exchange.get(channel.peer_public)
        if peer is None:
            logger.warning(
                "rejecting connection from unknown key %s",
                channel.peer_public.hex(),
            )
            channel.close()
            return
        self._channels.add(channel)
        try:
            while True:
                frame = await channel.recv()
                if self._capture is not None:
                    self._capture_frame(peer, frame)
                await self.on_frame(peer, frame)
        except (transport.ChannelClosed, ConnectionError):
            pass
        except Exception:
            logger.exception("inbound handler error from %s", peer.address)
        finally:
            channel.close()
            self._channels.discard(channel)
