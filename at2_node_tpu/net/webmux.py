"""Single-port gRPC + grpc-web multiplexer (the browser surface).

The reference serves browsers and native clients on ONE port: tonic with
`accept_http1(true)` + `tonic_web::config().allow_all_origins()`
(`/root/reference/src/bin/server/main.rs:110-114`), so its wasm client can
call the node from a browser (`/root/reference/src/client.rs:45-46,58-61`).
grpc.aio has no HTTP/1 story, so this module recreates the capability the
transport-native way:

* ``PortMux`` listens on the node's public RPC address and sniffs each
  connection's first bytes. The HTTP/2 client preface (``PRI *
  HTTP/2.0``) marks a native gRPC client — the connection is spliced to
  the real grpc.aio server on an internal loopback port, bytes forwarded
  verbatim both ways.
* Anything else is treated as HTTP/1: an in-process grpc-web endpoint
  decodes the grpc-web framing (binary ``application/grpc-web+proto`` and
  base64 ``application/grpc-web-text+proto``), dispatches to the SAME
  servicer object the gRPC server uses, and answers with CORS-allow-all
  headers plus the grpc-web trailers frame — so any stock grpc-web client
  (including browsers) works against the node.

Only unary RPCs are implemented — exactly the surface `at2.proto` has.
"""

from __future__ import annotations

import asyncio
import base64
import logging
import time
from typing import Dict, Optional, Tuple

import grpc

from ..proto import at2_pb2 as pb
from ..proto import finality_pb2 as fpb

logger = logging.getLogger(__name__)

# grpc-web frame flags
_DATA_FRAME = 0x00
_TRAILER_FRAME = 0x80

_MAX_BODY = 4 * 1024 * 1024
_MAX_HEADER = 64 * 1024
# Splice-path bounds: each spliced native-gRPC connection costs two pump
# tasks, so the count is capped and fully-idle splices are reaped. Idle
# means NO traffic in EITHER direction for the whole window (a watchdog
# checks a shared last-activity stamp), so a long-running RPC whose
# client half is quiet — e.g. a SendAsset parked behind a saturated
# broadcast inbox — is never torn down while the server is replying.
_MAX_SPLICES = 256
_SPLICE_IDLE = 300.0
# keep-alive bounds: one HTTP/1 connection serves at most this many
# requests before the server closes it (resource rotation), and at most
# this many HTTP/1 connections are held concurrently — keep-alive must
# not let cheap idle sockets pin unbounded handler tasks on the public
# port (the splice path has _MAX_SPLICES for the same reason)
_MAX_REQUESTS_PER_CONN = 10_000
_MAX_HTTP1_CONNS = 512

# method name -> request message class (the service's reply types come
# back from the servicer call itself)
_REQUEST_TYPES: Dict[str, type] = {
    "SendAsset": pb.SendAssetRequest,
    "SendAssetBatch": pb.SendAssetBatchRequest,
    "GetBalance": pb.GetBalanceRequest,
    "GetLastSequence": pb.GetLastSequenceRequest,
    "GetLatestTransactions": pb.GetLatestTransactionsRequest,
    "GetCertificate": fpb.GetCertificateRequest,
}

_CORS_HEADERS = (
    "Access-Control-Allow-Origin: *\r\n"
    "Access-Control-Allow-Methods: POST, OPTIONS\r\n"
    "Access-Control-Allow-Headers: content-type, x-grpc-web, x-user-agent, grpc-timeout\r\n"
    "Access-Control-Expose-Headers: grpc-status, grpc-message\r\n"
)


class _TooLarge(ValueError):
    """Chunked body exceeded _MAX_BODY (maps to 413, not 400)."""


class _Abort(Exception):
    """Raised by the fake context to short-circuit a handler."""

    def __init__(self, code: grpc.StatusCode, details: str) -> None:
        super().__init__(details)
        self.code = code
        self.details = details


class _WebContext:
    """Minimal stand-in for grpc.aio.ServicerContext under grpc-web: the
    servicer methods use ``abort`` and ``peer`` (see node/service.py
    handlers — ``peer`` keys the per-source admission token bucket)."""

    def __init__(self, peer: str = "web:unknown") -> None:
        self._peer = peer

    def peer(self) -> str:
        return self._peer

    async def abort(self, code: grpc.StatusCode, details: str = "") -> None:
        raise _Abort(code, details)


def _frame(payload: bytes, flags: int = _DATA_FRAME) -> bytes:
    return bytes([flags]) + len(payload).to_bytes(4, "big") + payload


def _parse_frames(body: bytes) -> list:
    """Split a grpc-web body into (flags, payload) tuples."""
    out = []
    view = memoryview(body)
    while len(view) >= 5:
        flags = view[0]
        length = int.from_bytes(view[1:5], "big")
        if len(view) < 5 + length:
            raise ValueError("truncated grpc-web frame")
        out.append((flags, bytes(view[5 : 5 + length])))
        view = view[5 + length :]
    if len(view):
        raise ValueError("trailing bytes after grpc-web frames")
    return out


def _status_int(code: grpc.StatusCode) -> int:
    return code.value[0]


class PortMux:
    """The public RPC listener: native gRPC spliced through, grpc-web
    served in-process."""

    def __init__(
        self,
        listen_addr: str,
        grpc_port: int,
        servicer,
        grpc_host: str = "127.0.0.1",
    ) -> None:
        self.listen_addr = listen_addr
        self.grpc_host = grpc_host
        self.grpc_port = grpc_port
        self.servicer = servicer
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()  # live per-connection handler tasks
        self._n_splices = 0  # live spliced native-gRPC connections
        self._n_http1 = 0  # live keep-alive HTTP/1 connections
        self._http1_accepted = 0  # total accepted (observability/tests)

    def stats(self) -> dict:
        return {
            "splices": self._n_splices,
            "http1_conns": self._n_http1,
            "http1_accepted": self._http1_accepted,
        }

    async def start(self) -> None:
        host, _, port = self.listen_addr.rpartition(":")
        self._server = await asyncio.start_server(
            self._handle_conn, host or "0.0.0.0", int(port)
        )

    async def close(self) -> None:
        """Shutdown must not depend on clients hanging up: handler tasks
        (including gRPC splices held open by lingering client channels)
        are cancelled outright before the listener is awaited closed."""
        if self._server is not None:
            self._server.close()
        for task in list(self._conns):
            task.cancel()
        if self._conns:
            await asyncio.gather(*self._conns, return_exceptions=True)
        self._conns.clear()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conns.add(task)
        try:
            await self._handle(reader, writer)
        except asyncio.CancelledError:
            pass
        finally:
            if task is not None:
                self._conns.discard(task)

    # -- connection handling ---------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            # readexactly: a short first segment must not misroute a native
            # gRPC client whose HTTP/2 preface arrives in pieces
            head = await asyncio.wait_for(reader.readexactly(4), timeout=30)
        except (
            asyncio.TimeoutError,
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
        ):
            writer.close()
            return
        try:
            if head == b"PRI ":
                await self._splice_grpc(head, reader, writer)
            else:
                await self._http1_loop(head, reader, writer)
        except asyncio.TimeoutError:
            pass
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass
        except Exception:
            logger.exception("webmux connection error")
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _splice_grpc(
        self,
        head: bytes,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        """Bidirectional byte pipe to the internal grpc.aio port, bounded
        in count (cap) and lifetime (per-read idle timeout) so an
        idle-splice flood cannot pin pump tasks indefinitely."""
        if self._n_splices >= _MAX_SPLICES:
            logger.warning("splice cap reached (%d); rejecting", _MAX_SPLICES)
            writer.close()
            return
        self._n_splices += 1
        try:
            up_reader, up_writer = await asyncio.open_connection(
                self.grpc_host, self.grpc_port
            )
            up_writer.write(head)
            last_activity = time.monotonic()

            async def pipe(src: asyncio.StreamReader, dst: asyncio.StreamWriter):
                # bare read loop: the idle policy lives in the watchdog, so
                # the data plane pays no per-chunk timer machinery
                nonlocal last_activity
                try:
                    while True:
                        chunk = await src.read(65536)
                        if not chunk:
                            break
                        last_activity = time.monotonic()
                        dst.write(chunk)
                        await dst.drain()
                finally:
                    try:
                        dst.close()
                    except Exception:
                        pass

            async def watchdog():
                while True:
                    await asyncio.sleep(_SPLICE_IDLE / 4)
                    if time.monotonic() - last_activity > _SPLICE_IDLE:
                        for w in (writer, up_writer):
                            try:
                                w.close()  # pumps wake with EOF and exit
                            except Exception:
                                pass
                        return

            wd = asyncio.create_task(watchdog())
            try:
                await asyncio.gather(
                    pipe(reader, up_writer), pipe(up_reader, writer),
                    return_exceptions=True,
                )
            finally:
                wd.cancel()
        finally:
            self._n_splices -= 1

    # -- HTTP/1 grpc-web --------------------------------------------------

    async def _http1_loop(
        self,
        head: bytes,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        """Serve HTTP/1 requests on this connection until the client
        closes, asks to close, errors, or idles out — real keep-alive,
        like the reference's tonic HTTP/1 surface, so stock grpc-web
        clients reuse one connection across unary calls instead of
        paying a reconnect each. Each request (headers through response)
        gets a 30s bound: the same slowloris protection as before, now
        doubling as the idle-connection reaper between requests."""
        if self._n_http1 >= _MAX_HTTP1_CONNS:
            writer.write(
                b"HTTP/1.1 503 Service Unavailable\r\n"
                b"Content-Length: 0\r\nConnection: close\r\n\r\n"
            )
            await writer.drain()
            return
        self._n_http1 += 1
        self._http1_accepted += 1
        try:
            buf = bytearray(head)
            for i in range(_MAX_REQUESTS_PER_CONN):
                # the final allowed request must ADVERTISE close — a
                # pooled client told keep-alive would write its next
                # request into a dead socket
                last = i == _MAX_REQUESTS_PER_CONN - 1
                keep = await asyncio.wait_for(
                    self._serve_http1(buf, reader, writer, allow_keep=not last),
                    timeout=30,
                )
                if not keep:
                    return
        finally:
            self._n_http1 -= 1

    async def _serve_http1(
        self,
        buf: bytearray,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        allow_keep: bool = True,
    ) -> bool:
        """Serve ONE request whose leading bytes (possibly from the
        previous request's over-read) sit in ``buf``; leaves any trailing
        over-read in ``buf`` for the next request. Returns True when the
        connection should stay open."""
        while b"\r\n\r\n" not in buf:
            chunk = await reader.read(4096)
            if not chunk:
                return False  # client closed between/mid requests
            buf.extend(chunk)
            if len(buf) > _MAX_HEADER:
                await self._respond(
                    writer, "431 Request Header Fields Too Large",
                    "text/plain", b"",
                )
                return False
        sep = buf.find(b"\r\n\r\n")
        header_blob = bytes(buf[:sep])
        del buf[: sep + 4]
        try:
            request_line, headers = self._parse_headers(header_blob)
            method, path, version = request_line.split(" ", 2)
        except ValueError:
            await self._respond(writer, "400 Bad Request", "text/plain", b"bad request")
            return False

        # HTTP/1.1 defaults to keep-alive; 1.0 only opts in; either side
        # can force close. Connection is a comma-separated token list
        # (RFC 9110 §7.6.1) — compare whole tokens, not substrings, so a
        # token that merely CONTAINS "close"/"keep-alive" can't
        # misclassify the connection.
        conn_tokens = {
            t.strip()
            for t in headers.get("connection", "").lower().split(",")
            if t.strip()
        }
        keep = allow_keep and (
            "close" not in conn_tokens
            if version.strip().upper() == "HTTP/1.1"
            else "keep-alive" in conn_tokens
        )

        if method.upper() == "OPTIONS":
            # drain any body (preflights normally have none, but an
            # unconsumed body would desync the next request's framing)
            if "chunked" in headers.get("transfer-encoding", "").lower():
                keep = False  # not worth decoding for a preflight
            else:
                try:
                    opt_len = int(headers.get("content-length", "0"))
                except ValueError:
                    opt_len = -1
                if opt_len < 0 or opt_len > _MAX_BODY:
                    keep = False
                else:
                    while len(buf) < opt_len:
                        chunk = await reader.read(65536)
                        if not chunk:
                            return False
                        buf.extend(chunk)
                    del buf[:opt_len]
            # CORS preflight (allow-all, reference parity)
            writer.write(
                (
                    "HTTP/1.1 204 No Content\r\n"
                    + _CORS_HEADERS
                    + "Access-Control-Max-Age: 86400\r\n"
                    + "Content-Length: 0\r\n"
                    + f"Connection: {'keep-alive' if keep else 'close'}\r\n\r\n"
                ).encode()
            )
            await writer.drain()
            return keep

        if method.upper() == "GET":
            # Observability endpoints (/metrics /healthz /statusz),
            # answered by the servicer when it implements obs_http (duck-
            # typed: test doubles and bare servicers just 404). Riding
            # THIS loop — not a separate listener — is deliberate: GETs
            # share _MAX_HTTP1_CONNS, the per-connection request cap, and
            # the 30s per-request bound with grpc-web traffic, so a
            # scrape flood cannot pin handler tasks beyond what the
            # grpc-web path already tolerates.
            if "chunked" in headers.get("transfer-encoding", "").lower():
                keep = False  # a GET with a chunked body isn't worth decoding
            else:
                try:
                    get_len = int(headers.get("content-length", "0"))
                except ValueError:
                    get_len = -1
                if get_len < 0 or get_len > _MAX_BODY:
                    keep = False
                else:
                    while len(buf) < get_len:
                        chunk = await reader.read(65536)
                        if not chunk:
                            return False
                        buf.extend(chunk)
                    del buf[:get_len]
            handler = getattr(self.servicer, "obs_http", None)
            route = path.split("?", 1)[0]  # query-free form, for logging
            result = None
            if callable(handler):
                try:
                    # full path INCLUDING the query string: the handler
                    # parses parameters itself (e.g. /tracez?limit=N)
                    result = handler(path)
                except Exception:
                    logger.exception("obs handler failed for %s", route)
                    await self._respond(
                        writer, "500 Internal Server Error", "text/plain",
                        b"", keep=keep,
                    )
                    return keep
            if result is None:
                await self._respond(
                    writer, "404 Not Found", "text/plain", b"not found",
                    keep=keep,
                )
                return keep
            status, content_type, body = result
            reason = {200: "OK", 503: "Service Unavailable"}.get(status, "OK")
            await self._respond(
                writer, f"{status} {reason}", content_type, body, keep=keep
            )
            return keep

        if method.upper() != "POST":
            await self._respond(writer, "405 Method Not Allowed", "text/plain", b"")
            return False

        # curl (bodies >1KB and streaming uploads) sends Expect:
        # 100-continue and stalls ~1s waiting for the interim response;
        # answer it before any body read so real streaming clients
        # never pay that latency
        if "100-continue" in headers.get("expect", "").lower():
            writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            await writer.drain()

        if "chunked" in headers.get("transfer-encoding", "").lower():
            # real client stacks (curl/httpx streaming bodies) DO send
            # unary grpc-web requests chunked; ignoring the body here
            # would silently decode an EMPTY request — wrong answer, not
            # even an error (round-3 interop finding)
            try:
                body = await self._read_chunked(reader, buf)
            except _TooLarge:
                await self._respond(
                    writer, "413 Payload Too Large", "text/plain", b""
                )
                return False
            except ValueError:
                await self._respond(
                    writer, "400 Bad Request", "text/plain", b""
                )
                return False
        else:
            try:
                length = int(headers.get("content-length", "0"))
            except ValueError:
                length = -1
            if length < 0:
                # malformed/negative Content-Length answers 400 instead of
                # falling into the generic handler (which would log a full
                # traceback per junk request on the public port)
                await self._respond(writer, "400 Bad Request", "text/plain", b"")
                return False
            if length > _MAX_BODY:
                await self._respond(
                    writer, "413 Payload Too Large", "text/plain", b""
                )
                return False
            while len(buf) < length:
                chunk = await reader.read(65536)
                if not chunk:
                    return False  # closed mid-body
                buf.extend(chunk)
            body = bytes(buf[:length])
            del buf[:length]  # over-read stays for the next request

        content_type = headers.get("content-type", "")
        text_mode = "grpc-web-text" in content_type
        if "grpc-web" not in content_type:
            await self._respond(
                writer, "415 Unsupported Media Type", "text/plain", b""
            )
            return False
        if text_mode:
            try:
                body = base64.b64decode(body)
            except Exception:
                await self._respond(writer, "400 Bad Request", "text/plain", b"")
                return False

        # key admission buckets by HOST only: HTTP/1 connections churn
        # ephemeral ports, and a per-port bucket would reset on reconnect
        peername = writer.get_extra_info("peername")
        peer = (
            f"web:{peername[0]}"
            if isinstance(peername, tuple) and peername
            else "web:unknown"
        )
        status, message, reply_bytes = await self._dispatch(path, body, peer)

        payload = b""
        if reply_bytes is not None:
            payload += _frame(reply_bytes)
        trailer = f"grpc-status: {status}\r\n"
        if message:
            trailer += f"grpc-message: {message}\r\n"
        payload += _frame(trailer.encode(), _TRAILER_FRAME)
        if text_mode:
            payload = base64.b64encode(payload)
            reply_type = "application/grpc-web-text+proto"
        else:
            reply_type = "application/grpc-web+proto"
        await self._respond(writer, "200 OK", reply_type, payload, keep=keep)
        return keep

    async def _dispatch(
        self, path: str, body: bytes, peer: str = "web:unknown"
    ) -> Tuple[int, str, Optional[bytes]]:
        """Decode the request, run the servicer method, encode the reply.
        Returns (grpc-status, grpc-message, reply bytes or None)."""
        parts = path.strip("/").split("/")
        if len(parts) != 2 or parts[0] != "at2.AT2":
            return _status_int(grpc.StatusCode.UNIMPLEMENTED), "unknown service", None
        method_name = parts[1]
        req_type = _REQUEST_TYPES.get(method_name)
        handler = getattr(self.servicer, method_name, None)
        if req_type is None or handler is None:
            return _status_int(grpc.StatusCode.UNIMPLEMENTED), "unknown method", None
        try:
            frames = _parse_frames(body)
            data = b"".join(p for f, p in frames if f == _DATA_FRAME)
            request = req_type.FromString(data)
        except Exception:
            return (
                _status_int(grpc.StatusCode.INVALID_ARGUMENT),
                "malformed request",
                None,
            )
        try:
            reply = await handler(request, _WebContext(peer))
        except _Abort as abort:
            return _status_int(abort.code), abort.details, None
        except Exception:
            logger.exception("grpc-web handler error in %s", method_name)
            return _status_int(grpc.StatusCode.INTERNAL), "internal error", None
        return 0, "", reply.SerializeToString()

    # -- small HTTP helpers ----------------------------------------------

    @staticmethod
    async def _read_chunked(
        reader: asyncio.StreamReader, buf: bytearray
    ) -> bytes:
        """Decode a Transfer-Encoding: chunked body (bounded by _MAX_BODY)
        from the connection's shared buffer: consumed bytes are removed,
        over-read bytes stay in ``buf`` for the next keep-alive request."""

        async def fill(n: int) -> None:
            while len(buf) < n:
                chunk = await reader.read(65536)
                if not chunk:
                    raise ValueError("connection closed mid-chunk")
                buf.extend(chunk)
                if len(buf) > _MAX_BODY + 4096:
                    raise _TooLarge("chunked body too large")

        async def read_line() -> bytes:
            while True:
                idx = buf.find(b"\r\n")
                if idx >= 0:
                    line = bytes(buf[:idx])
                    del buf[: idx + 2]
                    return line
                await fill(len(buf) + 1)

        body = bytearray()
        while True:
            size_token = (await read_line()).split(b";", 1)[0]
            # RFC 9112 chunk-size is 1*HEXDIG only — int(x, 16) alone
            # would also take '+3'/' 3'/'0x3', framing every other
            # server rejects
            if not size_token or any(
                c not in b"0123456789abcdefABCDEF" for c in size_token
            ):
                raise ValueError(f"bad chunk size {size_token[:16]!r}")
            size = int(size_token, 16)
            if len(body) + size > _MAX_BODY:
                raise _TooLarge("chunked body too large")
            if size == 0:
                # trailers (if any) up to the final blank line
                while await read_line():
                    pass
                return bytes(body)
            await fill(size + 2)
            body += buf[:size]
            if bytes(buf[size : size + 2]) != b"\r\n":
                raise ValueError("missing chunk terminator")
            del buf[: size + 2]

    @staticmethod
    def _parse_headers(raw: bytes) -> Tuple[str, Dict[str, str]]:
        header_blob = raw.split(b"\r\n\r\n", 1)[0].decode("latin-1")
        lines = header_blob.split("\r\n")
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        return lines[0], headers

    @staticmethod
    async def _respond(
        writer: asyncio.StreamWriter,
        status_line: str,
        content_type: str,
        body: bytes,
        keep: bool = False,
    ) -> None:
        """Error responses default to Connection: close (the request's
        framing can't be trusted past a parse failure); successful
        grpc-web replies pass keep=True to hold the connection open."""
        conn = "keep-alive" if keep else "close"
        writer.write(
            (
                f"HTTP/1.1 {status_line}\r\n"
                f"Content-Type: {content_type}\r\n"
                + _CORS_HEADERS
                + f"Content-Length: {len(body)}\r\nConnection: {conn}\r\n\r\n"
            ).encode()
            + body
        )
        await writer.drain()
