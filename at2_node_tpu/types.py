"""Shared transaction types and canonical wire serialization.

TPU-native re-design of the reference's shared types
(`/root/reference/src/lib.rs:17-50`): ``ThinTransaction`` (who gets how
much — signed as part of :func:`transfer_signing_bytes`),
``TransactionState`` and ``FullTransaction`` (what the
recent-transactions ring stores).

Canonical byte layout
---------------------
The reference signs/ships bincode-serialized Rust structs
(`/root/reference/src/client.rs:77-87`). bincode compatibility is not
required — the whole stack is replaced — but client and server must agree
on a canonical layout, so we define one explicitly:

* public keys / signatures: raw bytes (32 / 64), no length prefix when the
  field width is fixed;
* integers: little-endian fixed width (u32 for sequence numbers mirroring
  ``sieve::Sequence`` = u32 at `/root/reference/src/at2.proto:13`, u64 for
  amounts);
* the *signed* form of a transfer is :func:`transfer_signing_bytes`:
  ``tag || sender(32) || sequence(4, LE) || recipient(32) || amount(8, LE)``.

The signed form is a DELIBERATE divergence from the reference, which signs
only ``ThinTransaction{recipient, amount}`` and leaves the sequence to be
bound by the broadcast layer (`client.rs:77-78`, SURVEY.md C13). That
binding only holds when the signer runs its own broadcast instance; here
clients submit through an RPC front (a node, or an UNTRUSTED broker —
see broker.py), so an unbound signature would let any middleman re-submit
one observed transfer at sequence last+1, last+2, ... and drain the
sender. Binding ``sender`` and ``sequence`` into the signed bytes (under a
versioned domain tag, so no other protocol message can collide) makes a
captured signature valid for exactly one ledger slot: a byzantine broker
or ingress node can censor, reorder, or duplicate-within-one-slot, but
never author a transfer the client did not sign.
"""

from __future__ import annotations

import datetime
import enum
import struct
from dataclasses import dataclass

Sequence = int  # u32, mirrors sieve::Sequence (at2.proto:13)

PUBLIC_KEY_LEN = 32
SIGNATURE_LEN = 64

# Domain tag of the transfer signature (v2: sender + sequence bound in;
# v1 — the reference's recipient||amount form — is not accepted anywhere).
TRANSFER_SIG_TAG = b"at2-node-tpu/transfer/v2"


def transfer_signing_bytes(
    sender: bytes, sequence: int, recipient: bytes, amount: int
) -> bytes:
    """Canonical preimage of a client transfer signature.

    ``tag || sender || sequence(LE u32) || recipient || amount(LE u64)``
    — byte-identical to ``TRANSFER_SIG_TAG`` + the first 76 bytes of the
    wire payload body (broadcast/messages.py ``_PAYLOAD``), so bulk
    verifiers can slice the preimage straight out of parsed frames."""
    if len(sender) != PUBLIC_KEY_LEN or len(recipient) != PUBLIC_KEY_LEN:
        raise ValueError("sender/recipient must be 32-byte public keys")
    return (
        TRANSFER_SIG_TAG
        + sender
        + struct.pack("<I", sequence)
        + recipient
        + struct.pack("<Q", amount)
    )


class TransactionState(enum.Enum):
    """Processing status of a transaction (`lib.rs:26-33`)."""

    PENDING = 0
    SUCCESS = 1
    FAILURE = 2


@dataclass(frozen=True)
class ThinTransaction:
    """Who gets how much (`lib.rs:15-24`); signed together with the
    sender and sequence (:func:`transfer_signing_bytes`)."""

    recipient: bytes  # 32-byte ed25519 public key
    amount: int  # u64

    def __post_init__(self) -> None:
        if len(self.recipient) != PUBLIC_KEY_LEN:
            raise ValueError("recipient must be a 32-byte public key")
        if not 0 <= self.amount < 1 << 64:
            raise ValueError("amount must fit in u64")


@dataclass
class FullTransaction:
    """A transaction as committed to the recent ring (`lib.rs:37-50`)."""

    timestamp: datetime.datetime
    sender: bytes  # 32-byte ed25519 public key
    sender_sequence: Sequence
    recipient: bytes
    amount: int
    state: TransactionState


def rfc3339(ts: datetime.datetime) -> str:
    """RFC 3339 timestamp string, like chrono's ``to_rfc3339``
    (`/root/reference/src/bin/server/rpc.rs:327`). Naive datetimes are
    taken as UTC so the output always carries an offset."""
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=datetime.timezone.utc)
    return ts.isoformat()


def parse_rfc3339(s: str) -> datetime.datetime:
    """Inverse of :func:`rfc3339` (`/root/reference/src/client.rs:129-131`).

    Accepts the ``Z`` suffix explicitly so peers emitting the canonical
    RFC 3339 form parse on every supported Python version.
    """
    if s.endswith(("Z", "z")):
        s = s[:-1] + "+00:00"
    return datetime.datetime.fromisoformat(s)
