"""Shared transaction types and canonical wire serialization.

TPU-native re-design of the reference's shared types
(`/root/reference/src/lib.rs:17-50`): ``ThinTransaction`` (the payload the
sender signs), ``TransactionState`` and ``FullTransaction`` (what the
recent-transactions ring stores).

Canonical byte layout
---------------------
The reference signs/ships bincode-serialized Rust structs
(`/root/reference/src/client.rs:77-87`). bincode compatibility is not
required — the whole stack is replaced — but client and server must agree
on a canonical layout, so we define one explicitly:

* public keys / signatures: raw bytes (32 / 64), no length prefix when the
  field width is fixed;
* integers: little-endian fixed width (u32 for sequence numbers mirroring
  ``sieve::Sequence`` = u32 at `/root/reference/src/at2.proto:13`, u64 for
  amounts);
* the *signed* form of a ``ThinTransaction`` is ``recipient(32) ||
  amount(8, LE)`` — note that like the reference the sequence number is NOT
  part of the signed struct (`/root/reference/src/client.rs:77-78`); it is
  bound to the payload by the broadcast layer.
"""

from __future__ import annotations

import datetime
import enum
import struct
from dataclasses import dataclass

Sequence = int  # u32, mirrors sieve::Sequence (at2.proto:13)

PUBLIC_KEY_LEN = 32
SIGNATURE_LEN = 64


class TransactionState(enum.Enum):
    """Processing status of a transaction (`lib.rs:26-33`)."""

    PENDING = 0
    SUCCESS = 1
    FAILURE = 2


@dataclass(frozen=True)
class ThinTransaction:
    """The signed wire payload: who gets how much (`lib.rs:15-24`)."""

    recipient: bytes  # 32-byte ed25519 public key
    amount: int  # u64

    def __post_init__(self) -> None:
        if len(self.recipient) != PUBLIC_KEY_LEN:
            raise ValueError("recipient must be a 32-byte public key")
        if not 0 <= self.amount < 1 << 64:
            raise ValueError("amount must fit in u64")

    def signing_bytes(self) -> bytes:
        """Canonical byte form the sender signs (`client.rs:77-78`)."""
        return self.recipient + struct.pack("<Q", self.amount)


@dataclass
class FullTransaction:
    """A transaction as committed to the recent ring (`lib.rs:37-50`)."""

    timestamp: datetime.datetime
    sender: bytes  # 32-byte ed25519 public key
    sender_sequence: Sequence
    recipient: bytes
    amount: int
    state: TransactionState


def rfc3339(ts: datetime.datetime) -> str:
    """RFC 3339 timestamp string, like chrono's ``to_rfc3339``
    (`/root/reference/src/bin/server/rpc.rs:327`). Naive datetimes are
    taken as UTC so the output always carries an offset."""
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=datetime.timezone.utc)
    return ts.isoformat()


def parse_rfc3339(s: str) -> datetime.datetime:
    """Inverse of :func:`rfc3339` (`/root/reference/src/client.rs:129-131`).

    Accepts the ``Z`` suffix explicitly so peers emitting the canonical
    RFC 3339 form parse on every supported Python version.
    """
    if s.endswith(("Z", "z")):
        s = s[:-1] + "+00:00"
    return datetime.datetime.fromisoformat(s)
