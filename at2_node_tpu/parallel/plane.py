"""PlaneExecutor seam: where sharded broadcast drain work runs.

The sharded plane (broadcast/shards.py) partitions slot state by origin
key and needs two things from the runtime: a place to run each shard's
drain closure, and a bounded handoff lane for the effects a shard
produces (outbound frames, delivered payloads, stall kicks) that must be
applied on the owner event loop. This module provides both behind a
seam small enough that the sim can substitute a synchronous executor
and keep the whole plane deterministic:

- ``InlinePlaneExecutor`` runs shard closures synchronously on the
  caller. One logical worker, no threads, no reordering — this is what
  ``SimScheduler``-driven nodes use, and why the same-seed campaign
  hash is identical at shards=1 and shards=4.
- ``ThreadPlaneExecutor`` pins one OS thread per shard (single-thread
  pool each, so shard state is confined to exactly one thread for its
  lifetime). Python-level work still serializes on the GIL; the
  scaling comes from the native quorum/parse kernels releasing it.
  Process or subinterpreter executors slot in behind the same protocol
  later without touching the plane.
- ``SPSCQueue`` is the bounded single-producer single-consumer lane a
  shard uses to hand effects back to the owner loop. Bounded so a
  stalled owner exerts backpressure instead of growing without limit;
  instrumented so /metrics can show depth and handoff latency.
"""

from __future__ import annotations

import concurrent.futures
import time
from collections import deque
from typing import Any, Callable, List, Optional, Tuple


class SPSCQueue:
    """Bounded single-producer single-consumer handoff queue.

    One shard thread puts, the owner loop drains. Under CPython's GIL a
    deque's append/popleft are atomic, so no lock is needed for the
    1-producer/1-consumer discipline this class documents. ``put``
    returns False when the queue is full — the producer decides whether
    to spin, drop, or run the effect degraded; it must not block the
    shard drain loop on the owner.
    """

    __slots__ = ("_q", "_cap", "_dropped")

    def __init__(self, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError("SPSCQueue capacity must be positive")
        self._q: deque = deque()
        self._cap = capacity
        self._dropped = 0

    def put(self, item: Any) -> bool:
        if len(self._q) >= self._cap:
            self._dropped += 1
            return False
        self._q.append((time.perf_counter_ns(), item))
        return True

    def drain(self, max_items: int = 0) -> Tuple[List[Any], int]:
        """Pop up to ``max_items`` entries (0 = all currently visible).

        Returns ``(items, max_handoff_ns)`` where the second element is
        the oldest enqueue-to-drain latency seen in this drain — the
        number /metrics reports as ``plane_shard_handoff_ns``.
        """
        out: List[Any] = []
        worst = 0
        now = time.perf_counter_ns()
        n = len(self._q) if max_items <= 0 else min(max_items, len(self._q))
        for _ in range(n):
            try:
                t0, item = self._q.popleft()
            except IndexError:  # racing producer-side len() snapshot
                break
            dt = now - t0
            if dt > worst:
                worst = dt
            out.append(item)
        return out, worst

    def __len__(self) -> int:
        return len(self._q)

    @property
    def dropped(self) -> int:
        return self._dropped


class InlinePlaneExecutor:
    """Synchronous executor: shard closures run on the caller, in call
    order. This is the deterministic path — the sim drives every shard
    from one logical worker, so wire behavior is byte-identical to the
    monolithic plane."""

    name = "inline"

    def __init__(self, shards: int = 1):
        self.shards = shards

    def submit(
        self, shard_id: int, fn: Callable[..., Any], *args: Any
    ) -> "concurrent.futures.Future":
        fut: concurrent.futures.Future = concurrent.futures.Future()
        try:
            fut.set_result(fn(*args))
        except BaseException as exc:  # noqa: BLE001 - mirrored to future
            fut.set_exception(exc)
        return fut

    def shutdown(self) -> None:
        pass


class ThreadPlaneExecutor:
    """One OS thread per shard. Each shard gets its own single-thread
    pool so its slot state is only ever touched from that thread —
    confinement, not locking, is the memory model. The owner loop
    awaits the returned futures (wrapped via asyncio) and applies the
    shard's queued effects afterwards."""

    name = "thread"

    def __init__(self, shards: int):
        if shards <= 0:
            raise ValueError("ThreadPlaneExecutor needs >= 1 shard")
        self.shards = shards
        self._pools = [
            concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"plane-shard-{i}"
            )
            for i in range(shards)
        ]

    def submit(
        self, shard_id: int, fn: Callable[..., Any], *args: Any
    ) -> "concurrent.futures.Future":
        return self._pools[shard_id].submit(fn, *args)

    def shutdown(self) -> None:
        for p in self._pools:
            p.shutdown(wait=False, cancel_futures=True)


def make_plane_executor(kind: str, shards: int):
    """Factory behind the config seam: ``[plane] executor = ...``."""
    if kind == "inline":
        return InlinePlaneExecutor(shards)
    if kind == "thread":
        return ThreadPlaneExecutor(shards)
    raise ValueError(f"unknown plane executor {kind!r}")
